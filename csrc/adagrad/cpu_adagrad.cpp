// Host Adagrad step (reference csrc/adagrad/cpu_adagrad.cpp) for offloaded
// optimizer state. In-place over contiguous fp32 shards; C ABI for ctypes.

#include <cstdint>

#include "../includes/ds_simd.h"
#include "../includes/ds_threading.h"

extern "C" {

void ds_cpu_adagrad_step(float* params, float* grads, float* exp_avg_sq,
                         int64_t n, float lr, float eps, float weight_decay) {
  ds::parallel_for(
      static_cast<size_t>(n), DS_SIMD_WIDTH, [&](size_t begin, size_t end) {
        ds::vecf vlr = ds::vecf::set1(-lr);
        ds::vecf veps = ds::vecf::set1(eps);
        ds::vecf vwd = ds::vecf::set1(weight_decay);
        size_t i = begin;
        const size_t vend =
            begin + ((end - begin) / DS_SIMD_WIDTH) * DS_SIMD_WIDTH;
        for (; i < vend; i += DS_SIMD_WIDTH) {
          ds::vecf grad = ds::vecf::load(grads + i);
          ds::vecf param = ds::vecf::load(params + i);
          if (weight_decay != 0.0f) grad = ds::fma(param, vwd, grad);
          ds::vecf var = ds::fma(grad, grad, ds::vecf::load(exp_avg_sq + i));
          param = param + (vlr * grad) / (ds::sqrt(var) + veps);
          var.store(exp_avg_sq + i);
          param.store(params + i);
        }
        for (; i < end; ++i) {
          float grad = grads[i];
          if (weight_decay != 0.0f) grad += params[i] * weight_decay;
          exp_avg_sq[i] += grad * grad;
          params[i] -= lr * grad / (std::sqrt(exp_avg_sq[i]) + eps);
        }
      });
}

}  // extern "C"
