// Host Adam/AdamW step for the ZeRO-Offload path.
//
// TPU-native analogue of the reference csrc/adam/{cpu_adam.cpp,
// cpu_adam_impl.cpp} (AVX-vectorized DeepSpeedCPUAdam). Operates in-place on
// contiguous fp32 shards: params (fp32 master), grads, exp_avg, exp_avg_sq.
// Exposed as a C ABI for ctypes (no pybind11 in this image). bf16 "copy back"
// is handled Python-side (the device copy is jnp.asarray of the updated
// master shard cast to bf16).

#include <cstdint>
#include <cstring>

#include "../includes/ds_simd.h"
#include "../includes/ds_threading.h"

namespace {

struct AdamHyper {
  float lr;
  float beta1;
  float beta2;
  float eps;
  float weight_decay;
  int adamw_mode;  // 1: decoupled decay (AdamW); 0: L2-into-grad (Adam)
  int bias_correction;
};

inline void adam_range(float* p, float* g, float* m, float* v, size_t begin,
                       size_t end, const AdamHyper& h, float bc1, float bc2) {
  const float step_size = h.lr / bc1;
  const float bc2_sqrt = bc2;  // already sqrt'ed by caller
  ds::vecf vb1 = ds::vecf::set1(h.beta1);
  ds::vecf vb1m = ds::vecf::set1(1.0f - h.beta1);
  ds::vecf vb2 = ds::vecf::set1(h.beta2);
  ds::vecf vb2m = ds::vecf::set1(1.0f - h.beta2);
  // -step * m / (sqrt(v)/bc2 + eps)  ==  (-step*bc2) * m / (sqrt(v) + eps*bc2)
  ds::vecf veps = ds::vecf::set1(h.eps * bc2_sqrt);
  ds::vecf vstep = ds::vecf::set1(-step_size * bc2_sqrt);
  ds::vecf vwd = ds::vecf::set1(h.weight_decay);
  ds::vecf vlrwd = ds::vecf::set1(1.0f - h.lr * h.weight_decay);

  size_t i = begin;
  const size_t vec_end = begin + ((end - begin) / DS_SIMD_WIDTH) * DS_SIMD_WIDTH;
  for (; i < vec_end; i += DS_SIMD_WIDTH) {
    ds::vecf grad = ds::vecf::load(g + i);
    ds::vecf param = ds::vecf::load(p + i);
    if (!h.adamw_mode && h.weight_decay != 0.0f)
      grad = ds::fma(param, vwd, grad);
    ds::vecf mom = ds::fma(vb1, ds::vecf::load(m + i), vb1m * grad);
    ds::vecf var = ds::fma(vb2, ds::vecf::load(v + i), vb2m * (grad * grad));
    if (h.adamw_mode && h.weight_decay != 0.0f) param = param * vlrwd;
    // p += -step/bc2_sqrt * m / (sqrt(v) + eps*bc2_sqrt)
    //    == p - step * (m/bc1') / (sqrt(v)/bc2_sqrt + eps)
    param = param + (vstep * mom) / (ds::sqrt(var) + veps);
    mom.store(m + i);
    var.store(v + i);
    param.store(p + i);
  }
  for (; i < end; ++i) {
    float grad = g[i];
    if (!h.adamw_mode && h.weight_decay != 0.0f) grad += p[i] * h.weight_decay;
    m[i] = h.beta1 * m[i] + (1.0f - h.beta1) * grad;
    v[i] = h.beta2 * v[i] + (1.0f - h.beta2) * grad * grad;
    float param = p[i];
    if (h.adamw_mode && h.weight_decay != 0.0f)
      param *= (1.0f - h.lr * h.weight_decay);
    p[i] = param - step_size * m[i] / (std::sqrt(v[i]) / bc2_sqrt + h.eps);
  }
}

}  // namespace

extern "C" {

// One fused Adam(W) step over a flat shard. `step` is 1-based.
void ds_cpu_adam_step(float* params, float* grads, float* exp_avg,
                      float* exp_avg_sq, int64_t n, int64_t step, float lr,
                      float beta1, float beta2, float eps, float weight_decay,
                      int adamw_mode, int bias_correction) {
  AdamHyper h{lr, beta1, beta2, eps, weight_decay, adamw_mode, bias_correction};
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
    bc2 = std::sqrt(1.0f - std::pow(beta2, static_cast<float>(step)));
  }
  ds::parallel_for(static_cast<size_t>(n), DS_SIMD_WIDTH,
                   [&](size_t b, size_t e) {
                     adam_range(params, grads, exp_avg, exp_avg_sq, b, e, h,
                                bc1, bc2);
                   });
}

int ds_simd_width() { return DS_SIMD_WIDTH; }

}  // extern "C"
