// Chunked parallel-for over std::thread for host optimizer steps.
//
// The reference parallelizes cpu_adam with OpenMP (#pragma omp parallel for
// in csrc/adam/cpu_adam_impl.cpp); we use a plain std::thread fan-out so the
// build has no OpenMP runtime dependency.  Chunks are cache-line aligned
// multiples of the SIMD width.
#pragma once

#include <algorithm>
#include <cstddef>
#include <functional>
#include <thread>
#include <vector>

namespace ds {

inline size_t default_threads() {
  unsigned hw = std::thread::hardware_concurrency();
  return hw ? static_cast<size_t>(hw) : 4;
}

// Invoke fn(begin, end) over [0, n) in parallel chunks; chunk boundaries are
// multiples of `align` so SIMD bodies never straddle a boundary.
inline void parallel_for(size_t n, size_t align,
                         const std::function<void(size_t, size_t)>& fn,
                         size_t min_chunk = 1 << 16) {
  size_t nthreads = std::min(default_threads(),
                             std::max<size_t>(1, n / min_chunk));
  if (nthreads <= 1) {
    fn(0, n);
    return;
  }
  size_t chunk = (n + nthreads - 1) / nthreads;
  chunk = ((chunk + align - 1) / align) * align;
  std::vector<std::thread> workers;
  workers.reserve(nthreads);
  for (size_t t = 0; t < nthreads; ++t) {
    size_t begin = t * chunk;
    if (begin >= n) break;
    size_t end = std::min(n, begin + chunk);
    workers.emplace_back([&fn, begin, end] { fn(begin, end); });
  }
  for (auto& w : workers) w.join();
}

}  // namespace ds
