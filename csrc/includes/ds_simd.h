// SIMD helpers for host-side optimizer kernels.
//
// TPU-native analogue of the reference's csrc/includes/simd.h (AVX512/AVX256
// wrappers used by cpu_adam/cpu_lion/cpu_adagrad). The offload path runs the
// optimizer step on the host CPU while the TPU computes the next micro-batch,
// so the host step must keep up with HBM->host gradient streaming: that means
// vectorized FMA over contiguous fp32 shards plus multi-threaded chunking
// (see ds_threading.h).
#pragma once

#include <cstddef>
#include <cmath>

#if defined(__AVX2__)
#include <immintrin.h>
#define DS_SIMD_WIDTH 8

namespace ds {
struct vecf {
  __m256 v;
  static inline vecf load(const float* p) { return {_mm256_loadu_ps(p)}; }
  static inline vecf set1(float x) { return {_mm256_set1_ps(x)}; }
  inline void store(float* p) const { _mm256_storeu_ps(p, v); }
  inline vecf operator+(const vecf& o) const { return {_mm256_add_ps(v, o.v)}; }
  inline vecf operator-(const vecf& o) const { return {_mm256_sub_ps(v, o.v)}; }
  inline vecf operator*(const vecf& o) const { return {_mm256_mul_ps(v, o.v)}; }
  inline vecf operator/(const vecf& o) const { return {_mm256_div_ps(v, o.v)}; }
};
// a*b + c
static inline vecf fma(const vecf& a, const vecf& b, const vecf& c) {
  return {_mm256_fmadd_ps(a.v, b.v, c.v)};
}
static inline vecf sqrt(const vecf& a) { return {_mm256_sqrt_ps(a.v)}; }
// sign(a): +1.0f / -1.0f / 0.0f
static inline vecf sign(const vecf& a) {
  __m256 zero = _mm256_setzero_ps();
  __m256 pos = _mm256_and_ps(_mm256_cmp_ps(a.v, zero, _CMP_GT_OQ),
                             _mm256_set1_ps(1.0f));
  __m256 neg = _mm256_and_ps(_mm256_cmp_ps(a.v, zero, _CMP_LT_OQ),
                             _mm256_set1_ps(-1.0f));
  return {_mm256_add_ps(pos, neg)};
}
}  // namespace ds

#else  // scalar fallback (portable; also what non-x86 hosts get)
#define DS_SIMD_WIDTH 1

namespace ds {
struct vecf {
  float v;
  static inline vecf load(const float* p) { return {*p}; }
  static inline vecf set1(float x) { return {x}; }
  inline void store(float* p) const { *p = v; }
  inline vecf operator+(const vecf& o) const { return {v + o.v}; }
  inline vecf operator-(const vecf& o) const { return {v - o.v}; }
  inline vecf operator*(const vecf& o) const { return {v * o.v}; }
  inline vecf operator/(const vecf& o) const { return {v / o.v}; }
};
static inline vecf fma(const vecf& a, const vecf& b, const vecf& c) {
  return {a.v * b.v + c.v};
}
static inline vecf sqrt(const vecf& a) { return {std::sqrt(a.v)}; }
static inline vecf sign(const vecf& a) {
  return {a.v > 0.0f ? 1.0f : (a.v < 0.0f ? -1.0f : 0.0f)};
}
}  // namespace ds
#endif
