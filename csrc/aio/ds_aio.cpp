// Async file I/O thread pool for NVMe offload.
//
// TPU-native analogue of the reference csrc/aio/ (libaio-based
// deepspeed_aio_thread.cpp + deepspeed_py_aio_handle): a pool of worker
// threads servicing pread/pwrite requests against host buffers, so optimizer
// shards and partitioned params can stream to/from NVMe while the TPU
// computes. libaio's O_DIRECT ring is replaced by plain positional I/O on
// worker threads — on modern kernels with page cache this saturates NVMe for
// the large sequential shards this path moves, and it needs no alignment
// dance for the caller. C ABI for ctypes (no pybind11 in this image).

#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Request {
  bool is_write;
  std::string path;
  char* buffer;
  int64_t nbytes;
  int64_t offset;
  // result: >=0 bytes transferred, <0 -errno
  int64_t result = 0;
  bool done = false;
};

class AioHandle {
 public:
  AioHandle(int nthreads, int block_size)
      : block_size_(block_size > 0 ? block_size : (1 << 20)), stop_(false) {
    if (nthreads <= 0) nthreads = 4;
    for (int t = 0; t < nthreads; ++t)
      workers_.emplace_back([this] { worker(); });
  }

  ~AioHandle() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

  int64_t submit(bool is_write, const char* path, char* buf, int64_t nbytes,
                 int64_t offset) {
    std::unique_lock<std::mutex> lk(mu_);
    int64_t id = next_id_++;
    auto req = std::make_shared<Request>();
    req->is_write = is_write;
    req->path = path;
    req->buffer = buf;
    req->nbytes = nbytes;
    req->offset = offset;
    inflight_[id] = req;
    queue_.push_back(id);
    cv_.notify_one();
    return id;
  }

  int64_t wait(int64_t id) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = inflight_.find(id);
    if (it == inflight_.end()) return -EINVAL;
    auto req = it->second;
    done_cv_.wait(lk, [&] { return req->done; });
    inflight_.erase(id);
    return req->result;
  }

  // Returns 0 if all inflight requests completed OK, else first error code.
  int64_t wait_all() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [&] {
      for (auto& kv : inflight_)
        if (!kv.second->done) return false;
      return true;
    });
    int64_t rc = 0;
    for (auto& kv : inflight_)
      if (kv.second->result < 0 && rc == 0) rc = kv.second->result;
    inflight_.clear();
    return rc;
  }

 private:
  void worker() {
    for (;;) {
      std::shared_ptr<Request> req;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        int64_t id = queue_.front();
        queue_.pop_front();
        req = inflight_[id];
      }
      req->result = execute(*req);
      {
        std::unique_lock<std::mutex> lk(mu_);
        req->done = true;
      }
      done_cv_.notify_all();
    }
  }

  int64_t execute(const Request& req) {
    int flags = req.is_write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(req.path.c_str(), flags, 0644);
    if (fd < 0) return -errno;
    int64_t moved = 0;
    while (moved < req.nbytes) {
      int64_t chunk = std::min<int64_t>(block_size_, req.nbytes - moved);
      ssize_t rc =
          req.is_write
              ? ::pwrite(fd, req.buffer + moved, chunk, req.offset + moved)
              : ::pread(fd, req.buffer + moved, chunk, req.offset + moved);
      if (rc < 0) {
        int64_t err = -errno;
        ::close(fd);
        return err;
      }
      if (rc == 0) break;  // EOF on read
      moved += rc;
    }
    ::close(fd);
    return moved;
  }

  const int block_size_;
  bool stop_;
  int64_t next_id_ = 1;
  std::mutex mu_;
  std::condition_variable cv_;       // work available
  std::condition_variable done_cv_;  // completions
  std::deque<int64_t> queue_;
  std::unordered_map<int64_t, std::shared_ptr<Request>> inflight_;
  std::vector<std::thread> workers_;
};

}  // namespace

extern "C" {

void* ds_aio_create(int nthreads, int block_size) {
  return new AioHandle(nthreads, block_size);
}

void ds_aio_destroy(void* handle) { delete static_cast<AioHandle*>(handle); }

int64_t ds_aio_pwrite(void* handle, const char* path, char* buf,
                      int64_t nbytes, int64_t offset) {
  return static_cast<AioHandle*>(handle)->submit(true, path, buf, nbytes,
                                                 offset);
}

int64_t ds_aio_pread(void* handle, const char* path, char* buf, int64_t nbytes,
                     int64_t offset) {
  return static_cast<AioHandle*>(handle)->submit(false, path, buf, nbytes,
                                                 offset);
}

int64_t ds_aio_wait(void* handle, int64_t request_id) {
  return static_cast<AioHandle*>(handle)->wait(request_id);
}

int64_t ds_aio_wait_all(void* handle) {
  return static_cast<AioHandle*>(handle)->wait_all();
}

}  // extern "C"
