// Async file I/O thread pool for NVMe offload.
//
// TPU-native analogue of the reference csrc/aio/ (libaio-based
// deepspeed_aio_thread.cpp + deepspeed_py_aio_handle, aio config keys
// block_size / queue_depth / thread_count / single_submit /
// overlap_events).  libaio's O_DIRECT ring is replaced by positional I/O
// on worker threads — this image ships no libaio/liburing headers — but
// the throughput-relevant structure is kept:
//
//  * one large request is STRIPED into block_size parts serviced by all
//    workers concurrently (the reference splits a tensor across its
//    thread ring the same way); submit() returns immediately — workers
//    claim parts from per-request cursors, so the caller overlaps I/O
//    with device compute (the module's purpose);
//  * queue_depth bounds the parts of ONE request in flight at once (the
//    reference's per-ring in-flight bound);
//  * optional O_DIRECT (page-cache bypass) when buffer/offset/length meet
//    the 4096-byte alignment contract, falling back to buffered I/O
//    per-request otherwise (no alignment dance forced on callers).
//
// C ABI for ctypes (no pybind11 in this image).

#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kDirectAlign = 4096;

struct Request {
  bool is_write;
  std::string path;
  char* buffer;
  int64_t nbytes;
  int64_t offset;
  bool use_direct;
  int fd = -1;
  int nparts = 0;
  std::atomic<int> next_part{0};      // claim cursor
  std::atomic<int> running_parts{0};  // queue_depth bound
  std::atomic<int64_t> moved{0};
  std::atomic<int64_t> error{0};  // first -errno
  std::atomic<int> parts_left{0};
  bool done = false;
};

class AioHandle {
 public:
  AioHandle(int nthreads, int block_size, int queue_depth, bool use_direct)
      : block_size_(block_size > 0 ? block_size : (1 << 20)),
        queue_depth_(queue_depth > 0 ? queue_depth : 128),
        use_direct_(use_direct),
        stop_(false) {
    if (nthreads <= 0) nthreads = 4;
    for (int t = 0; t < nthreads; ++t)
      workers_.emplace_back([this] { worker(); });
  }

  ~AioHandle() {
    // 1. Stop part claims and join workers FIRST: once they are gone,
    //    no thread touches request buffers — a waiter woken below may
    //    have its caller free the buffer immediately, which must not
    //    race a worker's in-flight pread/pwrite.  Each worker finishes
    //    at most its current block_size part, so the join is bounded.
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
      active_.clear();
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
    // 2. Requests with unclaimed parts can now never reach done — mark
    //    them done with a cancellation error so threads blocked in
    //    wait()/wait_all() wake up instead of hanging forever, and
    //    drain those waiters before members are destroyed (they still
    //    take mu_ / erase from inflight_ on their way out).
    std::unique_lock<std::mutex> lk(mu_);
    for (auto& kv : inflight_) {
      if (!kv.second->done) {
        int64_t expected = 0;
        kv.second->error.compare_exchange_strong(expected, -ECANCELED);
        kv.second->done = true;
      }
    }
    done_cv_.notify_all();
    drained_cv_.wait(lk, [&] { return waiters_ == 0; });
    for (auto& kv : inflight_) close_req(*kv.second);
  }

  int64_t submit(bool is_write, const char* path, char* buf, int64_t nbytes,
                 int64_t offset) {
    auto req = std::make_shared<Request>();
    req->is_write = is_write;
    req->path = path;
    req->buffer = buf;
    req->nbytes = nbytes;
    req->offset = offset;
    // O_DIRECT only when the whole transfer AND every striped part meet
    // the alignment contract (parts start at multiples of block_size_)
    req->use_direct =
        use_direct_ && (reinterpret_cast<uintptr_t>(buf) % kDirectAlign == 0) &&
        (offset % kDirectAlign == 0) && (nbytes % kDirectAlign == 0) &&
        (block_size_ % kDirectAlign == 0);

    int flags = is_write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    if (req->use_direct) flags |= O_DIRECT;
    req->fd = ::open(path, flags, 0644);
    if (req->fd < 0 && req->use_direct) {  // fs may refuse O_DIRECT
      req->use_direct = false;
      req->fd = ::open(path, is_write ? (O_WRONLY | O_CREAT) : O_RDONLY, 0644);
    }
    if (req->fd < 0) return -errno;

    int nparts =
        static_cast<int>(std::max<int64_t>(1, (nbytes + block_size_ - 1) /
                                                  block_size_));
    req->nparts = nparts;
    req->parts_left.store(nparts);

    std::unique_lock<std::mutex> lk(mu_);
    int64_t id = next_id_++;
    inflight_[id] = req;
    active_.push_back(req);
    cv_.notify_all();
    return id;  // immediately: workers claim parts from the cursor
  }

  int64_t wait(int64_t id) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = inflight_.find(id);
    if (it == inflight_.end()) return -EINVAL;
    auto req = it->second;
    ++waiters_;
    done_cv_.wait(lk, [&] { return req->done; });
    --waiters_;
    drained_cv_.notify_all();
    inflight_.erase(id);
    close_req(*req);  // cancelled requests never ran their last part
    int64_t err = req->error.load();
    return err < 0 ? err : req->moved.load();
  }

  // Returns 0 if all inflight requests completed OK, else first error code.
  int64_t wait_all() {
    std::unique_lock<std::mutex> lk(mu_);
    ++waiters_;
    done_cv_.wait(lk, [&] {
      for (auto& kv : inflight_)
        if (!kv.second->done) return false;
      return true;
    });
    --waiters_;
    drained_cv_.notify_all();
    int64_t rc = 0;
    for (auto& kv : inflight_) {
      if (kv.second->error.load() < 0 && rc == 0) rc = kv.second->error.load();
      close_req(*kv.second);  // cancelled requests never closed their fd
    }
    inflight_.clear();
    return rc;
  }

 private:
  static void close_req(Request& req) {
    if (req.fd >= 0) {
      ::close(req.fd);
      req.fd = -1;
    }
  }

  void worker() {
    for (;;) {
      std::shared_ptr<Request> req;
      int part_idx = -1;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return stop_ || claimable(lk, req, part_idx); });
        if (req == nullptr) {
          if (stop_) return;
          continue;
        }
      }
      int64_t off = static_cast<int64_t>(part_idx) * block_size_;
      int64_t rc = execute(*req, off,
                           std::min<int64_t>(block_size_, req->nbytes - off));
      req->running_parts.fetch_sub(1);
      if (rc < 0) {
        int64_t expected = 0;
        req->error.compare_exchange_strong(expected, rc);
      } else {
        req->moved.fetch_add(rc);
      }
      bool last = req->parts_left.fetch_sub(1) == 1;
      {
        std::unique_lock<std::mutex> lk(mu_);
        if (last) {
          close_req(*req);
          req->done = true;
          done_cv_.notify_all();
        }
        cv_.notify_one();  // a queue_depth slot freed up
      }
    }
  }

  // Claim the next part of the earliest active request with spare
  // queue_depth slots; prunes fully-claimed requests.  A depth-capped
  // request no longer blocks the whole line — workers scan past it so a
  // later request's parts proceed (FIFO preference, not FIFO blocking).
  // mu_ held.
  bool claimable(std::unique_lock<std::mutex>&, std::shared_ptr<Request>& req,
                 int& part_idx) {
    for (auto it = active_.begin(); it != active_.end();) {
      auto& cand = *it;
      if (cand->next_part.load() >= cand->nparts) {
        it = active_.erase(it);
        continue;
      }
      if (cand->running_parts.load() >= queue_depth_) {
        ++it;  // depth-capped: scan past, don't head-of-line block
        continue;
      }
      int p = cand->next_part.fetch_add(1);
      if (p >= cand->nparts) {  // lost the race to the last part
        ++it;
        continue;
      }
      cand->running_parts.fetch_add(1);
      req = cand;
      part_idx = p;
      return true;
    }
    return false;
  }

  static int64_t execute(Request& req, int64_t part_off, int64_t nbytes) {
    int64_t moved = 0;
    while (moved < nbytes) {
      char* buf = req.buffer + part_off + moved;
      int64_t want = nbytes - moved;
      int64_t pos = req.offset + part_off + moved;
      ssize_t rc = req.is_write ? ::pwrite(req.fd, buf, want, pos)
                                : ::pread(req.fd, buf, want, pos);
      if (rc < 0) return -errno;
      if (rc == 0) break;  // EOF on read
      moved += rc;
    }
    return moved;
  }

  const int64_t block_size_;
  const int queue_depth_;
  const bool use_direct_;
  bool stop_;
  int64_t next_id_ = 1;
  std::mutex mu_;
  std::condition_variable cv_;       // parts claimable
  std::condition_variable done_cv_;  // completions
  std::condition_variable drained_cv_;  // destructor: waiters all left
  int waiters_ = 0;  // threads inside wait()/wait_all() (mu_ held)
  std::deque<std::shared_ptr<Request>> active_;  // requests with parts left
  std::unordered_map<int64_t, std::shared_ptr<Request>> inflight_;
  std::vector<std::thread> workers_;
};

}  // namespace

extern "C" {

void* ds_aio_create(int nthreads, int block_size) {
  return new AioHandle(nthreads, block_size, /*queue_depth=*/128,
                       /*use_direct=*/false);
}

void* ds_aio_create2(int nthreads, int block_size, int queue_depth,
                     int use_direct) {
  return new AioHandle(nthreads, block_size, queue_depth, use_direct != 0);
}

void ds_aio_destroy(void* handle) { delete static_cast<AioHandle*>(handle); }

// Null-handle guards: the ctypes wrapper clears its handle on close(),
// so calls issued AFTER close() returns get -EINVAL instead of a null
// deref.  (A call truly concurrent with ds_aio_destroy remains the
// caller's race to avoid — the check cannot see a delete that lands
// between it and the method body.)
int64_t ds_aio_pwrite(void* handle, const char* path, char* buf,
                      int64_t nbytes, int64_t offset) {
  if (!handle) return -EINVAL;
  return static_cast<AioHandle*>(handle)->submit(true, path, buf, nbytes,
                                                 offset);
}

int64_t ds_aio_pread(void* handle, const char* path, char* buf, int64_t nbytes,
                     int64_t offset) {
  if (!handle) return -EINVAL;
  return static_cast<AioHandle*>(handle)->submit(false, path, buf, nbytes,
                                                 offset);
}

int64_t ds_aio_wait(void* handle, int64_t request_id) {
  if (!handle) return -EINVAL;
  return static_cast<AioHandle*>(handle)->wait(request_id);
}

int64_t ds_aio_wait_all(void* handle) {
  if (!handle) return -EINVAL;
  return static_cast<AioHandle*>(handle)->wait_all();
}

}  // extern "C"
