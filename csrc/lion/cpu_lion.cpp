// Host Lion step (reference csrc/lion/) for offloaded optimizer state.
// update = sign(beta1*m + (1-beta1)*g); m = beta2*m + (1-beta2)*g
// In-place over contiguous fp32 shards; C ABI for ctypes.

#include <cstdint>

#include "../includes/ds_simd.h"
#include "../includes/ds_threading.h"

extern "C" {

void ds_cpu_lion_step(float* params, float* grads, float* exp_avg, int64_t n,
                      float lr, float beta1, float beta2, float weight_decay) {
  ds::parallel_for(
      static_cast<size_t>(n), DS_SIMD_WIDTH, [&](size_t begin, size_t end) {
        ds::vecf vb1 = ds::vecf::set1(beta1);
        ds::vecf vb1m = ds::vecf::set1(1.0f - beta1);
        ds::vecf vb2 = ds::vecf::set1(beta2);
        ds::vecf vb2m = ds::vecf::set1(1.0f - beta2);
        ds::vecf vlr = ds::vecf::set1(-lr);
        ds::vecf vdecay = ds::vecf::set1(1.0f - lr * weight_decay);
        size_t i = begin;
        const size_t vend =
            begin + ((end - begin) / DS_SIMD_WIDTH) * DS_SIMD_WIDTH;
        for (; i < vend; i += DS_SIMD_WIDTH) {
          ds::vecf grad = ds::vecf::load(grads + i);
          ds::vecf mom = ds::vecf::load(exp_avg + i);
          ds::vecf param = ds::vecf::load(params + i);
          ds::vecf update = ds::sign(ds::fma(vb1, mom, vb1m * grad));
          if (weight_decay != 0.0f) param = param * vdecay;
          param = ds::fma(vlr, update, param);
          mom = ds::fma(vb2, mom, vb2m * grad);
          mom.store(exp_avg + i);
          param.store(params + i);
        }
        for (; i < end; ++i) {
          float grad = grads[i];
          float mom = exp_avg[i];
          float u = beta1 * mom + (1.0f - beta1) * grad;
          float update = u > 0.0f ? 1.0f : (u < 0.0f ? -1.0f : 0.0f);
          float param = params[i];
          if (weight_decay != 0.0f) param *= (1.0f - lr * weight_decay);
          params[i] = param - lr * update;
          exp_avg[i] = beta2 * mom + (1.0f - beta2) * grad;
        }
      });
}

}  // extern "C"
