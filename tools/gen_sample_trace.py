#!/usr/bin/env python
"""Generate the checked-in sample workload trace (ISSUE 9 CI satellite).

Runs a deterministic 200-request mixed workload — four shared system
prompts (2-4 full pages each), a bimodal suffix-length distribution,
mostly-greedy sampling, submissions in waves so arrival offsets are
non-trivial — through a small-page debug FastGen engine with workload
capture on, and writes the resulting content-free ledger to
``tools/traces/sample_200.jsonl``.  Regenerate after a ledger schema
change::

    python tools/gen_sample_trace.py [--out tools/traces/sample_200.jsonl]

The trace is the fixture for the ``BENCH_REPLAY=1`` bench leg and the
``tools/ci.sh`` replay smoke.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

N_REQUESTS = 200
PAGE = 16


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(
        REPO_ROOT, "tools", "traces", "sample_200.jsonl"))
    ap.add_argument("--requests", type=int, default=N_REQUESTS)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax.core import meta as flax_meta
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference.v2 import (
        FastGenScheduler, InferenceEngineV2, KVCacheConfig,
        RaggedInferenceEngineConfig, RaggedInferenceModel,
        SamplingParams, StateManagerConfig)
    from deepspeed_tpu.models.llama import LlamaForCausalLM

    model_def = LlamaForCausalLM("debug", max_seq_len=256,
                                 dtype=jnp.float32)
    cfg = model_def.cfg
    params = flax_meta.unbox(model_def.init_params(jax.random.key(0)))
    kv_cfg = KVCacheConfig(num_layers=cfg.num_layers,
                           kv_heads=cfg.kv_heads,
                           head_dim=cfg.dims_per_head, page_size=PAGE,
                           num_pages=512, dtype=jnp.float32)
    model = RaggedInferenceModel(cfg, params, kv_config=kv_cfg)
    eng = InferenceEngineV2(model, RaggedInferenceEngineConfig(
        state_manager=StateManagerConfig(
            max_tracked_sequences=32, max_ragged_sequence_count=32,
            max_ragged_batch_size=256)))

    rng = np.random.default_rng(9)
    systems = [rng.integers(0, cfg.vocab_size, pages * PAGE)
               for pages in (2, 2, 3, 4)]

    def prompt(i):
        sys_p = systems[int(rng.integers(0, len(systems)))]
        # bimodal suffix: short chat turns vs long few-shot tails
        sfx = int(rng.integers(3, 9) if rng.random() < 0.6
                  else rng.integers(24, 40))
        return np.concatenate(
            [sys_p, rng.integers(0, cfg.vocab_size, sfx)]).tolist()

    tmp = args.out + ".gen"
    if os.path.exists(tmp):
        os.unlink(tmp)
    wt = telemetry.get_workload_trace()
    wt.configure(tmp)
    sched = FastGenScheduler(eng)
    uid = 0
    # waves of 20 with the scheduler stepping in between, so arrival
    # offsets (and queue waits) are non-degenerate
    while uid < args.requests or sched.has_work:
        for _ in range(20):
            if uid >= args.requests:
                break
            greedy = rng.random() < 0.8
            sp = SamplingParams(
                max_new_tokens=int(rng.integers(4, 11)),
                temperature=0.0 if greedy else 0.8,
                top_k=0 if greedy else 40)
            sched.submit(uid, prompt(uid), sp)
            uid += 1
        for _ in range(6):
            if sched.has_work:
                sched.step()
    wt.close()
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    os.replace(tmp, args.out)

    from replay_trace import load_trace
    trace = load_trace(args.out)
    ok = sum(1 for r in trace["requests"]
             if r.get("outcome") == "ok")
    print(f"gen_sample_trace: {args.out}: "
          f"{len(trace['requests'])} requests ({ok} ok), "
          f"{len(trace['key_counts'])} distinct step keys, "
          f"{len(trace['compiles'])} on-path compiles, "
          f"{os.path.getsize(args.out)} bytes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
