#!/usr/bin/env bash
# Single CI entrypoint (ISSUE 8 satellite).  Runs, in order:
#
#   0. dslint        — tools/dslint static contract checks (ISSUE 15):
#                      hot-path d2h/sync lint, config parity, lock
#                      discipline, disabled-path cost, catalog closure
#                      (metrics + chaos sites + flight events + DS_*
#                      env docs).  Strict: any unsuppressed finding or
#                      stale baseline entry fails BEFORE the test
#                      tiers, so a contract break is named fast
#   1. tier-1        — the ROADMAP verify tier (-m 'not slow'; includes
#                      the heavy tier and the chaos suite)
#   2. chaos tier    — every fault-injection test alone (-m chaos), so
#                      a chaos regression is named even when tier-1's
#                      summary is long
#   3. replay smoke  — tools/replay_trace.py --check over the first 32
#                      requests of the checked-in sample trace: a
#                      captured workload must replay with matching
#                      request count / lengths / share structure; the
#                      --spec pass replays the same workload with
#                      speculative decoding on and checks the SAME
#                      structural parity (speculation may change only
#                      throughput/metrics, ISSUE 10); a second arm
#                      replays with --drafter model (ISSUE 17) so the
#                      in-program draft head passes the same parity bar
#   3a. shard smoke  — tools/replay_trace.py --tp 2 --check
#                      (ISSUE 18): the same 32 requests replayed on a
#                      2-way simulated tensor-parallel mesh (host
#                      device count forced before jax loads); asserts
#                      the base structural parity PLUS zero on-path
#                      compiles and zero structured errors — sharding
#                      may change wire bytes, nothing the user sees
#   4. fleet smoke   — tools/fleetctl.py --smoke (ISSUE 11): spin two
#                      debug serving replicas on ephemeral metrics
#                      ports, scrape both, and assert the federated
#                      /fleet view is EXACTLY the sum of its parts
#                      (counters and histogram bucket counts)
#   5. pool smoke    — tools/fleetctl.py --pool-smoke (ISSUE 12): two
#                      in-process replicas behind the prefix-affinity
#                      router replay the first 32 requests of the
#                      checked-in trace; one replica is drain-migrated
#                      away mid-replay; asserts exact gen-length parity
#                      and ZERO lost requests
#   3b. tier smoke   — tools/replay_trace.py --tier --check
#                      (ISSUE 16): the first 24 requests replayed
#                      TWICE on one device-starved engine (a 4-page
#                      device cache request, clamped to the smallest
#                      schedulable pool) backed by a tiny host ring
#                      spilling to a disk tier; asserts structural
#                      parity, demotions + disk spills + promotions
#                      actually happened, warm-from-tier tokens ==
#                      cold tokens (keyed sampling), and the store's
#                      host+disk+inflight == indexed accounting
#   5b. disagg smoke — tools/replay_trace.py --disagg --check
#                      (ISSUE 13): the same 32 requests through the
#                      two-pool prefill/decode scheduler with
#                      committed-page KV streaming handoffs; asserts
#                      structural parity AND zero lost requests
#   5d. journey smoke — tools/replay_trace.py --disagg --journeys
#                      --check (ISSUE 19): the same 32 requests with
#                      request journeys on; asserts every completed
#                      request reconstructs a GAP-FREE segment chain
#                      whose segments sum to its measured e2e latency,
#                      and that zero handoff fragments were orphaned
#   5c. cold-start smoke — tools/coldstart_smoke.py --check
#                      (ISSUE 14): process A mines a lattice artifact
#                      from the checked-in trace, precompiles it into
#                      a persistent compile cache, and snapshots a
#                      partially-served run; a COLD process B restores
#                      with lattice="auto:…" against the warm cache
#                      and replays — asserting tokenwise parity,
#                      compile_on_path_total == 0, and ZERO true
#                      compiles (cache loads only)
#   6. bench gate    — tools/check_bench.py --strict (latest vs
#                      previous BENCH_r*.json; throughput -10% /
#                      latency +15% tolerances, cross-backend rounds
#                      downgraded to notes, fleet keys ±30/40%)
#
# Usage: tools/ci.sh [extra pytest args for the tier-1 leg]
# Environment: JAX_PLATFORMS defaults to cpu (the CI mesh);
#              DS_CI_TIMEOUT (seconds, default 870) bounds tier-1.

set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
TIMEOUT="${DS_CI_TIMEOUT:-870}"

echo "== dslint static contract checks =="
python -m tools.dslint --strict

echo "== tier-1 (timeout ${TIMEOUT}s) =="
timeout -k 10 "$TIMEOUT" python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly "$@"

echo "== chaos tier =="
python -m pytest tests/ -q -m chaos -p no:cacheprovider

echo "== workload replay smoke (incl. speculative pass) =="
python tools/replay_trace.py --trace tools/traces/sample_200.jsonl \
    --limit 32 --spec --check > /dev/null

echo "== model-drafted speculative replay smoke (ISSUE 17) =="
python tools/replay_trace.py --trace tools/traces/sample_200.jsonl \
    --limit 32 --spec --drafter model --check > /dev/null

echo "== sharded replay smoke (tp=2 simulated mesh, ISSUE 18) =="
python tools/replay_trace.py --trace tools/traces/sample_200.jsonl \
    --limit 32 --tp 2 --check > /dev/null

echo "== tiered-KV smoke (4-page device cache forcing demotion) =="
python tools/replay_trace.py --trace tools/traces/sample_200.jsonl \
    --limit 24 --tier --tier-device-pages 4 --check > /dev/null

echo "== fleetctl federation smoke =="
python tools/fleetctl.py --smoke

echo "== replica-pool router smoke (migrate mid-replay) =="
python tools/fleetctl.py --pool-smoke

echo "== disaggregated two-pool smoke (KV-streaming handoffs) =="
python tools/replay_trace.py --trace tools/traces/sample_200.jsonl \
    --limit 32 --disagg --check > /dev/null

echo "== request-journey smoke (gap-free chains, 0 orphans) =="
python tools/replay_trace.py --trace tools/traces/sample_200.jsonl \
    --limit 32 --disagg --journeys --check > /dev/null

echo "== cold-start smoke (persistent compile cache + auto lattice) =="
python tools/coldstart_smoke.py --check --limit 16 > /dev/null

echo "== memory observatory smoke (ledger validate + OOM forensics) =="
python tools/plan_capacity.py --trace tools/traces/sample_200.jsonl \
    --limit 20 --validate --oom-smoke --check > /dev/null

# (the former standalone metric-lint leg is leg 0's metric-catalog
# rule now; tools/check_metrics.py remains as a local/CI-transition
# shim over the same implementation)

echo "== bench regression gate =="
python tools/check_bench.py --strict

echo "ci.sh: all gates green"
