#!/usr/bin/env python
"""Bench regression gate (ISSUE 5 satellite).

Compares the latest ``BENCH_r*.json`` artifact's ``parsed`` metrics
against the previous round with per-metric-class tolerances:

- **throughput** keys (``value``, ``*_tok_s``, ``*_req_s``,
  ``*_hit_rate``, ``*goodput*``) may not DROP more than 10%;
- **latency / SLO** keys (``*_ms`` — p50/p99 TTFT, ITL, queue wait,
  step time) may not GROW more than 15%.

Warn-only by default (CPU bench numbers carry ±20% run-to-run noise and
a TPU→CPU-fallback round is not a regression); ``--strict`` exits
non-zero for CI.  Rounds measured on different backends (one
``cpu_fallback``, one not) are compared but every finding is
downgraded to a cross-backend note.

Usage::

    python tools/check_bench.py            # warn-only, repo root
    python tools/check_bench.py --strict   # non-zero exit on regression
    python tools/check_bench.py --dir /path/to/artifacts
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

THROUGHPUT_DROP_TOL = 0.10   # throughput may not drop >10%
LATENCY_GROW_TOL = 0.15      # SLO latencies may not grow >15%
#: fastgen_fleet_* and pool_* keys span a deliberate replica-kill
#: chaos event (ISSUE 11/12) — kill timing jitter moves them far more
#: than steady legs, so they get their own wider tolerances
FLEET_DROP_TOL = 0.30
FLEET_GROW_TOL = 0.40

_THROUGHPUT_RE = re.compile(
    r"(^value$|_tok_s$|_req_s$|_hit_rate$|goodput|_speedup_)")
_LATENCY_RE = re.compile(r"_ms$")
#: disagg_* rides the fleet tolerances too: its handoff latency and
#: per-pool rates are scheduling-interleave sensitive on CPU debug;
#: coldstart_* spans subprocess spawns + disk I/O (ISSUE 14) — the
#: in-round coldstart_findings gate carries the hard invariants;
#: tier_* spans disk AIO + replica-to-replica transfer timing
#: (ISSUE 16) — its hard invariants live in tier_findings;
#: fastgen_shard_* times shard arithmetic on oversubscribed host cores
#: (a simulated mesh, ISSUE 18) — its hard invariants (parity, wire
#: bytes, on-path compiles) live in shard_findings
_FLEET_RE = re.compile(
    r"^(fastgen_fleet_|fastgen_shard_|pool_|disagg_|coldstart_|tier_)")
#: parsed keys that are not a measured quantity at all
_SKIP_RE = re.compile(
    r"(^metric$|^unit$|error|^cpu_fallback$|_model$|_path$|_policy$|"
    r"^micro_bs$|estimated|^swept|^vs_baseline$|_total$|compile_s$)")


def _round_files(art_dir: str) -> List[str]:
    files = glob.glob(os.path.join(art_dir, "BENCH_r*.json"))

    def round_no(path):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        return int(m.group(1)) if m else -1
    return sorted((f for f in files if round_no(f) >= 0), key=round_no)


def _load_parsed(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"check_bench: cannot read {path}: {e}", file=sys.stderr)
        return None
    parsed = doc.get("parsed")
    return parsed if isinstance(parsed, dict) else None


def classify(key: str) -> Optional[str]:
    """'throughput' | 'latency' | None (ignored)."""
    if _SKIP_RE.search(key):
        return None
    if _THROUGHPUT_RE.search(key):
        return "throughput"
    if _LATENCY_RE.search(key):
        return "latency"
    return None


def compare(prev: Dict, cur: Dict) -> List[Tuple[str, str]]:
    """Return [(severity, message)]; severity is 'regression' or
    'note'."""
    findings: List[Tuple[str, str]] = []
    cross_backend = bool(prev.get("cpu_fallback")) != bool(
        cur.get("cpu_fallback"))
    if cross_backend:
        findings.append((
            "note",
            "backends differ between rounds (cpu_fallback flag flipped) "
            "— deltas below are cross-backend notes, not regressions"))
    for key in sorted(set(prev) & set(cur)):
        kind = classify(key)
        if kind is None:
            continue
        p, c = prev[key], cur[key]
        if not (isinstance(p, (int, float)) and isinstance(c, (int, float))):
            continue
        if p <= 0:
            continue    # nothing to ratio against
        rel = (c - p) / p
        fleet = bool(_FLEET_RE.search(key))
        drop_tol = FLEET_DROP_TOL if fleet else THROUGHPUT_DROP_TOL
        grow_tol = FLEET_GROW_TOL if fleet else LATENCY_GROW_TOL
        if kind == "throughput" and rel < -drop_tol:
            findings.append((
                "note" if cross_backend else "regression",
                f"{key}: {p} -> {c} ({rel * 100:+.1f}%; throughput "
                f"tolerance -{drop_tol * 100:.0f}%)"))
        elif kind == "latency" and rel > grow_tol:
            findings.append((
                "note" if cross_backend else "regression",
                f"{key}: {p} -> {c} ({rel * 100:+.1f}%; latency "
                f"tolerance +{grow_tol * 100:.0f}%)"))
    return findings


def spec_findings(cur: Dict) -> List[str]:
    """In-round speculative-decoding gate (ISSUE 10 + 17): on the
    HIGH-repetition workload the n-gram spec leg exists to be faster —
    warn when it measured slower than the spec-off leg of the same
    round.  The n-gram LOW-repetition leg is exempt (there the prompt-
    lookup drafter backs off; break-even is the contract), but the
    MODEL-drafted low-repetition leg is not: the draft head exists
    precisely for traffic n-gram loses, so it must win >= 1.5x there
    and compile nothing on-path."""
    on = cur.get("fastgen_spec_decode_tok_s")
    off = cur.get("fastgen_spec_off_decode_tok_s")
    if not (isinstance(on, (int, float)) and isinstance(off, (int, float))
            and off > 0):
        return []
    out: List[str] = []
    if on < off:
        rate = cur.get("fastgen_spec_accept_rate")
        out.append(
            f"speculative decoding is SLOWER than spec-off on the "
            f"high-repetition leg ({on} vs {off} tok/s, accept rate "
            f"{rate}) — check the drafter/accept path before "
            f"enabling serving_optimization.speculative")
    # model-drafted leg (ISSUE 17): on the LOW-repetition workload —
    # where the n-gram drafter backs off — the in-program draft head
    # must still win >= 1.5x (self-draft acceptance is repetition-
    # independent; the win is dispatch amortization) and its fused
    # draft+verify programs must all come from the warmed lattice
    m_on = cur.get("fastgen_spec_model_decode_tok_s")
    m_off = cur.get("fastgen_spec_model_off_decode_tok_s")
    if (isinstance(m_on, (int, float)) and isinstance(m_off, (int, float))
            and m_off > 0 and m_on < 1.5 * m_off):
        rate = cur.get("fastgen_spec_model_accept_rate")
        out.append(
            f"model-drafted speculation only {round(m_on / m_off, 3)}x "
            f"spec-off on the low-repetition leg ({m_on} vs {m_off} "
            f"tok/s, accept rate {rate}; target >= 1.5x) — check the "
            f"draft-KV catch-up path and the draft loop's accept math")
    m_comp = cur.get("fastgen_spec_model_compile_on_path_total")
    if isinstance(m_comp, (int, float)) and m_comp > 0:
        out.append(
            f"model-drafted spec leg hit {int(m_comp)} on-path XLA "
            "compile(s) — the draft_spec/draft_fill lattice no longer "
            "covers the workload's step keys")
    return out


def pool_findings(cur: Dict) -> List[str]:
    """In-round replica-pool gate (ISSUE 12): the kill/add demo's
    invariants — no request may be lost across a migration, the
    two-replica affinity pool should beat a single replica by >= 1.5x,
    and affinity routing's prefix hit rate must be strictly above the
    round-robin control arm on the shared-prefix trace."""
    out: List[str] = []
    lost = cur.get("pool_lost_requests")
    if isinstance(lost, (int, float)) and lost > 0:
        out.append(f"replica-pool kill/add demo LOST {lost} request(s) "
                   "— migration must end every request as tokens or a "
                   "structured error")
    sp = cur.get("pool_speedup_vs_single")
    if isinstance(sp, (int, float)) and sp < 1.5:
        out.append(f"pool aggregate tok/s only {sp}x a single replica "
                   "across the kill/add event (target >= 1.5x)")
    aff = cur.get("pool_prefix_hit_rate_affinity")
    rr = cur.get("pool_prefix_hit_rate_round_robin")
    if (isinstance(aff, (int, float)) and isinstance(rr, (int, float))
            and aff <= rr):
        out.append(f"affinity routing's prefix hit rate ({aff}) is not "
                   f"above round-robin's ({rr}) on the shared-prefix "
                   "trace — check hint publication / router matching")
    return out


def disagg_findings(cur: Dict) -> List[str]:
    """In-round disaggregation gate (ISSUE 13): the acceptance
    invariants of the two-pool leg — nothing lost, output tokenwise
    identical to the fused engine, zero on-path compiles, each pool's
    compiled-program count strictly below the fused engine's, and the
    specialization inequalities (prefill-pool MFU and decode-pool HBM
    rate strictly above the fused baseline's gauges)."""
    out: List[str] = []
    if "disagg_lost_requests" not in cur:
        return out      # leg didn't run this round
    lost = cur.get("disagg_lost_requests")
    if isinstance(lost, (int, float)) and lost > 0:
        out.append(f"disagg leg LOST {lost} request(s) — every handoff "
                   "must end as tokens or a structured error")
    if cur.get("disagg_tokenwise_identical") in (0, False):
        out.append("disagg two-pool output is NOT tokenwise identical "
                   "to the fused engine (keyed sampling / handoff "
                   "residual state broken?)")
    comp = cur.get("disagg_compile_on_path_total")
    if isinstance(comp, (int, float)) and comp > 0:
        out.append(f"disagg measured run compiled {comp} program(s) "
                   "on-path (warmup no longer covers the two-pool key "
                   "sequence)")
    for pool in ("prefill", "decode"):
        progs = cur.get(f"disagg_programs_{pool}")
        fused = cur.get("disagg_programs_fused")
        if (isinstance(progs, (int, float))
                and isinstance(fused, (int, float)) and progs >= fused):
            out.append(f"disagg {pool} pool compiled {progs} programs, "
                       f"not below the fused engine's {fused} — the "
                       "role lattice shrink regressed")
    mfu, fmfu = cur.get("disagg_prefill_mfu"), cur.get("disagg_fused_mfu")
    if (isinstance(mfu, (int, float)) and isinstance(fmfu, (int, float))
            and fmfu > 0 and mfu <= fmfu):
        out.append(f"prefill-pool MFU ({mfu:.3g}) is not above the "
                   f"fused baseline's ({fmfu:.3g}) on the replayed "
                   "trace")
    hbm, fhbm = (cur.get("disagg_decode_hbm_gb_s"),
                 cur.get("disagg_fused_hbm_gb_s"))
    if (isinstance(hbm, (int, float)) and isinstance(fhbm, (int, float))
            and fhbm > 0 and hbm <= fhbm):
        out.append(f"decode-pool HBM GB/s ({hbm:.3g}) is not above the "
                   f"fused baseline's ({fhbm:.3g}) on the replayed "
                   "trace")
    return out


def tier_findings(cur: Dict) -> List[str]:
    """In-round tiered-KV gate (ISSUE 16): int8 pages must fund >=
    1.7x resident sequences at the same device byte budget, TTFT p99
    with int8 on must stay flat (not grow >15% over the fp baseline
    at that budget), the warm wave must actually hit the host/disk
    tier (a returning prefix is a promotion, not a recompute, and not
    a silent corruption — the replay asserts tokenwise parity
    upstream), a cross-replica fetch must beat recomputing the same
    prefix, and the measured passes must not compile on-path."""
    out: List[str] = []
    if "tier_resident_seq_ratio" not in cur:
        return out      # leg didn't run this round
    ratio = cur.get("tier_resident_seq_ratio")
    if isinstance(ratio, (int, float)) and ratio < 1.7:
        out.append(f"int8 KV pages fund only {ratio}x resident "
                   "sequences at an equal device byte budget "
                   "(target >= 1.7x) — check "
                   "KVCacheConfig.bytes_per_page accounting")
    before = cur.get("tier_ttft_p99_before_ms")
    after = cur.get("tier_ttft_p99_after_ms")
    if (isinstance(before, (int, float)) and before > 0
            and isinstance(after, (int, float))
            and after > before * 1.15):
        out.append(f"TTFT p99 with int8 KV is {after / before:.2f}x "
                   f"the fp baseline at the same byte budget "
                   f"({after} vs {before} ms; target <= 1.15x) — "
                   "dequantization is eating the capacity win")
    host = cur.get("tier_host_hit_rate")
    disk = cur.get("tier_disk_hit_rate")
    if (isinstance(host, (int, float)) and isinstance(disk, (int, float))
            and host + disk <= 0):
        out.append("the warm wave never hit the host/disk tier — "
                   "returning prefixes are recomputing instead of "
                   "promoting (demotion or digest chaining broken?)")
    promoted = cur.get("tier_promoted_pages")
    if isinstance(promoted, (int, float)) and promoted <= 0:
        out.append("the tiered engine promoted zero pages across the "
                   "warm waves — the device-starved replay should "
                   "force promotions")
    fetch = cur.get("tier_fetch_ttft_ms")
    rec = cur.get("tier_recompute_ttft_ms")
    if (isinstance(fetch, (int, float)) and isinstance(rec, (int, float))
            and rec > 0 and fetch >= rec):
        out.append(f"cross-replica page fetch ({fetch} ms TTFT) did "
                   f"not beat recompute-prefill ({rec} ms) on an "
                   "affinity-miss — streaming committed pages should "
                   "be cheaper than re-prefilling the prefix")
    comp = cur.get("tier_compile_on_path_total")
    if isinstance(comp, (int, float)) and comp > 0:
        out.append(f"tier bench measured passes compiled {comp} "
                   "program(s) on-path (warmup no longer covers the "
                   "quantized/tier-warmed key set)")
    return out


def shard_findings(cur: Dict) -> List[str]:
    """In-round sharded-serving gate (ISSUE 18): the acceptance
    invariants of the BENCH_SHARD leg — the tp-way fp arm tokenwise
    identical to tp=1 on every row (sampled included; the GSPMD
    all-gather is bit-exact), the int8 arm tokenwise identical on the
    greedy rows (bounded quantization error may flip a keyed draw that
    thresholds on exact logit values — agreement there is a reported
    rate, not a gate), the int8 collective moving STRICTLY fewer wire
    bytes than the fp-equivalent of the same dispatches, and zero
    on-path compiles across the measured passes (tp is in the
    compile-cache digest: a mesh change is a MISS, never a wrong
    executable — but the warmed lattice must still cover every sharded
    step key)."""
    out: List[str] = []
    if "fastgen_shard_tp" not in cur:
        return out      # leg didn't run this round
    if cur.get("fastgen_shard_parity_fp") in (0, False):
        out.append("sharded fp arm is NOT tokenwise identical to tp=1 "
                   "— the one-program step's sharding leaks into "
                   "results (kv partitioning / keyed sampling / "
                   "collective placement broken?)")
    if cur.get("fastgen_shard_parity_int8") in (0, False):
        out.append("int8-collective arm is NOT tokenwise identical to "
                   "tp=1 on the GREEDY rows — the top-1 margin should "
                   "dominate the per-shard quantization step on the "
                   "debug model; check the block-scale/dequant math")
    wire = cur.get("fastgen_shard_int8_wire_bytes")
    fp = cur.get("fastgen_shard_int8_wire_fp_bytes")
    if (isinstance(wire, (int, float)) and isinstance(fp, (int, float))
            and not (0 < wire < fp)):
        out.append(f"int8 collective wire bytes ({wire}) are not "
                   f"strictly below the fp-equivalent ({fp}) — the "
                   "quantized encoding stopped paying for itself")
    comp = cur.get("fastgen_shard_compile_on_path_total")
    if isinstance(comp, (int, float)) and comp > 0:
        out.append(f"shard bench measured passes compiled {int(comp)} "
                   "program(s) on-path (warmup no longer covers the "
                   "sharded step-key set)")
    return out


def coldstart_findings(cur: Dict) -> List[str]:
    """In-round cold-start gate (ISSUE 14).  The recompile-proof
    invariants (zero on-path compiles, zero true compiles, tokenwise
    parity, manifest loads) live in ONE place —
    ``coldstart_smoke.coldstart_gates`` — and are consumed here; only
    the timing-ratio checks are bench-side: the warm-cache
    restore-to-first-token must sit within 25% of the warm-process
    control.  The timing gate is honest about the CPU-debug tier:
    there lowering (not XLA compile) dominates, so the 25% target is
    reported against the no-cache cold leg too (the cache's actual
    win)."""
    out: List[str] = []
    if "coldstart_replay_compile_on_path" not in cur:
        return out      # leg didn't run this round
    try:
        from .coldstart_smoke import coldstart_gates
    except ImportError:              # run as a script: tools/ on path
        from coldstart_smoke import coldstart_gates
    out.extend(coldstart_gates(cur))
    warm = cur.get("coldstart_restore_ttft_warm_ms")
    cached = cur.get("coldstart_restore_ttft_warmcache_ms")
    nocache = cur.get("coldstart_restore_ttft_nocache_ms")
    if (isinstance(warm, (int, float)) and warm > 0
            and isinstance(cached, (int, float))):
        ratio = cached / warm
        if ratio > 1.25:
            msg = (f"coldstart warm-cache restore-to-first-token is "
                   f"{ratio:.2f}x the warm control "
                   f"({cached:.0f} vs {warm:.0f} ms; target <= 1.25x)")
            if isinstance(nocache, (int, float)) and nocache > cached:
                msg += (f" — still {nocache / cached:.2f}x faster than "
                        f"the no-cache cold restore ({nocache:.0f} ms)")
            out.append(msg)
    if (isinstance(nocache, (int, float)) and
            isinstance(cached, (int, float)) and cached >= nocache > 0):
        out.append(f"coldstart warm-cache restore ({cached:.0f} ms) is "
                   f"not faster than the no-cache cold restore "
                   f"({nocache:.0f} ms) — the compile cache bought "
                   "nothing")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=REPO_ROOT,
                    help="directory holding BENCH_r*.json artifacts")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on a regression (CI mode)")
    args = ap.parse_args(argv)

    rounds = _round_files(args.dir)
    if len(rounds) < 2:
        print(f"check_bench: need >= 2 BENCH_r*.json rounds under "
              f"{args.dir} ({len(rounds)} found) — nothing to compare")
        return 0
    cur_path = rounds[-1]
    cur = _load_parsed(cur_path)
    if cur is None:
        print(f"check_bench: latest round {os.path.basename(cur_path)} "
              "has no usable 'parsed' metrics — skipping comparison")
        return 0
    # the previous round may have failed outright (parsed: null) — walk
    # back to the most recent round that actually measured something
    prev_path, prev = None, None
    for cand in reversed(rounds[:-1]):
        prev = _load_parsed(cand)
        if prev is not None:
            prev_path = cand
            break
    if prev is None:
        print("check_bench: no earlier round with usable 'parsed' "
              "metrics — nothing to compare")
        return 0

    findings = compare(prev, cur)
    findings += [("note", m) for m in spec_findings(cur)]
    findings += [("note", m) for m in pool_findings(cur)]
    findings += [("note", m) for m in disagg_findings(cur)]
    findings += [("note", m) for m in tier_findings(cur)]
    findings += [("note", m) for m in shard_findings(cur)]
    findings += [("note", m) for m in coldstart_findings(cur)]
    regressions = [m for sev, m in findings if sev == "regression"]
    notes = [m for sev, m in findings if sev == "note"]
    label = (f"{os.path.basename(prev_path)} -> "
             f"{os.path.basename(cur_path)}")
    for m in notes:
        print(f"check_bench [note] {label}: {m}")
    for m in regressions:
        print(f"check_bench [REGRESSION] {label}: {m}",
              file=sys.stderr)
    if not findings:
        print(f"check_bench: {label}: no regressions within tolerances")
    if regressions and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
