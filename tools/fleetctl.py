#!/usr/bin/env python
"""fleetctl (ISSUE 11/12): see and drive N serving replicas as one
fleet.

A stdlib-only CLI over the federation layer
(``deepspeed_tpu/telemetry/federation.py``): scrape each replica's
``/snapshot?raw=1``, merge (counters sum, gauges roll up min/max/sum,
log-bucketed histograms merge EXACTLY), and print status / JSON /
Prometheus text.  Also hosts the two-replica smoke used by
``tools/ci.sh``, the replica-kill fleet bench behind bench.py's
``BENCH_FLEET=1`` leg, and the ISSUE 12 replica-pool legs: the CI
pool smoke (two in-process replicas behind the prefix-affinity router,
one migrated mid-replay) and the ``BENCH_POOL=1`` kill/add demo.

Usage::

    python tools/fleetctl.py --targets 127.0.0.1:9001,127.0.0.1:9002
        [status|json|metrics|digests] [--watch SECONDS]
    python tools/fleetctl.py --targets ... journey <uid>
                                           # scrape every replica's
                                           # /journey?uid= records and
                                           # stitch one cross-process
                                           # segment chain (ISSUE 19)
    python tools/fleetctl.py --targets ... mem
                                           # per-replica ds_mem_*
                                           # subsystem table, fleet
                                           # totals, headroom min/sum
                                           # (ISSUE 20)
    python tools/fleetctl.py --smoke       # CI: two debug replicas,
                                           # merged counters == sum
    python tools/fleetctl.py --kill-demo   # bench: two replicas, one
                                           # killed mid-replay via the
                                           # serving.preempt chaos site
    python tools/fleetctl.py --pool-smoke  # CI: replica pool, affinity
                                           # router, migrate mid-replay
    python tools/fleetctl.py --pool-demo   # bench: pool kill/add demo
                                           # (BENCH_POOL keys)

``digests`` prints each target's ``/snapshot?digests=1`` prefix-cache
affinity hint — the subprocess-mode routing input (ISSUE 12).
Targets are ``[label=]host:port`` (labels default to r0, r1, ...).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

REPLICA = os.path.join(REPO_ROOT, "tools", "fleet_replica.py")


# -- replica process management (smoke / kill-demo / bench) ------------------
class ReplicaProc:
    """A fleet_replica.py child with a line-buffered stdout reader."""

    def __init__(self, label: str, args: Optional[List[str]] = None,
                 env_extra: Optional[Dict[str, str]] = None):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(env_extra or {})
        self.label = label
        self.proc = subprocess.Popen(
            [sys.executable, REPLICA, "--label", label] + (args or []),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env, start_new_session=True)
        self.lines: List[str] = []
        self._t = threading.Thread(target=self._read, daemon=True)
        self._t.start()

    def _read(self) -> None:
        for line in self.proc.stdout:
            self.lines.append(line.rstrip("\n"))

    def wait_line(self, needle: str, timeout: float) -> Optional[str]:
        deadline = time.monotonic() + timeout
        seen = 0
        while time.monotonic() < deadline:
            while seen < len(self.lines):
                if needle in self.lines[seen]:
                    return self.lines[seen]
                seen += 1
            if self.proc.poll() is not None and seen >= len(self.lines):
                return None
            time.sleep(0.05)
        return None

    def port(self, timeout: float = 120.0) -> int:
        line = self.wait_line("FLEET_REPLICA ready", timeout)
        if line is None:
            raise RuntimeError(
                f"replica {self.label} never reported ready "
                f"(exit={self.proc.poll()})")
        return int(line.split("port=")[1].split()[0])

    def terminate(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def _federation(targets: List[Tuple[str, int]], stale_after_s=None):
    from deepspeed_tpu.telemetry.federation import Federation
    fed = Federation() if stale_after_s is None else Federation(
        stale_after_s=stale_after_s)
    for label, port in targets:
        fed.add_http(label, f"127.0.0.1:{port}")
    return fed


# -- CI smoke ----------------------------------------------------------------
def run_smoke() -> int:
    """Spin two debug replicas, scrape, assert the merged fleet view IS
    the sum of its parts (counters and histogram counts, exactly)."""
    reps = [ReplicaProc("r0", ["--rounds", "1", "--seed", "0"]),
            ReplicaProc("r1", ["--rounds", "1", "--seed", "1"])]
    try:
        targets = [(r.label, r.port()) for r in reps]
        for r in reps:
            if r.wait_line("FLEET_REPLICA done", 180.0) is None:
                raise RuntimeError(
                    f"replica {r.label} did not finish its round")
        fed = _federation(targets)
        view = fed.scrape()
        if view["stale"]:
            raise RuntimeError(f"stale replicas in smoke: "
                               f"{view['replicas']}")
        parts = []
        import urllib.request
        for label, port in targets:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/snapshot?raw=1",
                    timeout=5) as resp:
                parts.append(json.loads(resp.read().decode()))
        for name, merged in sorted(view["counters"].items()):
            want = sum(p["counters"].get(name, 0) for p in parts)
            if merged != want:
                raise RuntimeError(
                    f"merged counter {name}: {merged} != sum of parts "
                    f"{want}")
        for name, h in sorted(view["hists"].items()):
            want = sum(p["hists"][name]["count"] for p in parts
                       if name in p.get("hists", {}))
            if h["count"] != want:
                raise RuntimeError(
                    f"merged histogram {name}: count {h['count']} != "
                    f"sum of parts {want}")
        toks = view["counters"].get("ds_fastgen_tokens_total", 0)
        if toks <= 0:
            raise RuntimeError("no tokens counted across the fleet")
        print(f"fleetctl smoke: OK — 2 replicas, "
              f"{len(view['counters'])} merged counters == sum of "
              f"parts, {len(view['hists'])} histograms merged exactly, "
              f"{toks} fleet tokens")
        return 0
    finally:
        for r in reps:
            r.terminate()


# -- replica-kill fleet event (BENCH_FLEET) ----------------------------------
def run_kill_demo(step_sleep_s: float = 0.05, rounds: int = 150,
                  kill_at_step: int = 90,
                  sample_every_s: float = 0.2,
                  run_s: float = 20.0) -> Dict[str, Any]:
    """Two live replicas replaying the checked-in CAPTURED trace
    (``tools/traces/sample_200.jsonl``, anonymized prompt synthesis
    per replica seed); one is killed mid-replay through the
    ``serving.preempt`` chaos site.  The parent federates both,
    samples a FLEET time-series ring, and runs the SLO burn-rate
    evaluator over it — returns the ``fastgen_fleet_*`` bench keys
    (aggregate tok/s, merged p99 TTFT across the kill event, the page
    verdict and its advice)."""
    from deepspeed_tpu.telemetry.registry import percentile_from_counts
    from deepspeed_tpu.telemetry.slo import SLOEvaluator
    from deepspeed_tpu.telemetry.timeseries import TimeSeries

    # --trace-limit 4 keeps per-step compute small relative to the
    # pacing sleep, so the token rate tracks the number of LIVE
    # replicas (the signal) rather than CPU contention (noise)
    common = ["--trace",
              os.path.join(REPO_ROOT, "tools", "traces",
                           "sample_200.jsonl"),
              "--trace-limit", "4",
              "--rounds", str(rounds),
              "--step-sleep-s", str(step_sleep_s)]
    reps = [
        ReplicaProc("r0", common + ["--seed", "0"]),
        ReplicaProc("r1", common + ["--seed", "1"],
                    env_extra={
                        "DS_CHAOS": f"serving.preempt:at={kill_at_step}"}),
    ]
    try:
        targets = [(r.label, r.port()) for r in reps]
        fed = _federation(targets, stale_after_s=2.0)
        ts = TimeSeries(source=fed.merged_raw)
        ts.configure(interval_s=sample_every_s, retention_s=600.0)
        ev = SLOEvaluator()
        ev.attach(timeseries=ts, federation=fed)

        # let both replicas pass their compile warmup (round 0) before
        # measuring the both-alive rate the objective is set from
        for r in reps:
            if r.wait_line("round=0 done", 300.0) is None:
                raise RuntimeError(
                    f"replica {r.label} never finished round 0 "
                    f"(exit={r.proc.poll()})")
        ts.sample_now()
        time.sleep(max(4 * sample_every_s, 2.4))
        ts.sample_now()
        warm_rate = ts.counter_rate("ds_fastgen_tokens_total", 5.0) or 0.0
        if warm_rate <= 0:
            # min_per_s = 0 would be rejected by the objective
            # validator anyway — fail with the real story instead
            raise RuntimeError(
                "no fleet tokens observed in the warm window — "
                "replicas too slow for the demo pacing?")
        if reps[1].proc.poll() is not None:
            raise RuntimeError(
                "replica r1 died before the both-alive rate was "
                "measured — raise kill_at_step")
        ev.configure([{
            "name": "fleet_goodput", "kind": "throughput_min",
            "counter": "ds_fastgen_tokens_total",
            "min_per_s": 0.8 * warm_rate, "budget": 0.1,
            "fast_window_s": 2.0, "slow_window_s": 4.0,
            "page_burn": 2.0, "warn_burn": 0.5,
        }])

        t0 = time.monotonic()
        tok0 = (fed.scrape()["counters"]
                .get("ds_fastgen_tokens_total", 0))
        paged = advice = surv_rate = None
        kill_seen_at = None
        while time.monotonic() - t0 < run_s:
            time.sleep(sample_every_s)
            ts.sample_now()     # evaluator rides the on-sample hook
            if kill_seen_at is None and reps[1].proc.poll() is not None:
                kill_seen_at = round(time.monotonic() - t0, 2)
            cur = ev.current()
            if paged is None and cur["status"] == "page":
                v = cur["objectives"]["fleet_goodput"]
                paged = round(time.monotonic() - t0, 2)
                advice = v["advice"]
                # the survivor's rate AT page time, while it still runs
                surv_rate = ts.counter_rate(
                    "ds_fastgen_tokens_total", 2.0)
                break
            if (reps[0].proc.poll() is not None
                    or any("FLEET_REPLICA done" in ln
                           for ln in reps[0].lines)):
                # the survivor finished its workload — stop before the
                # end-of-traffic rate drop masquerades as the kill
                break
        wall = time.monotonic() - t0
        view = fed.scrape()
        toks = view["counters"].get("ds_fastgen_tokens_total", 0) - tok0
        th = view["hists"].get("ds_fastgen_ttft_ms")
        ttft_p99 = (round(percentile_from_counts(
            th["bounds"], th["counts"], th["count"], 99), 2)
            if th and th["count"] else None)
        return {
            "fastgen_fleet_tok_s": round(toks / wall, 1),
            "fastgen_fleet_ttft_p99_ms": ttft_p99,
            "fastgen_fleet_warm_tok_s": round(warm_rate, 1),
            "fastgen_fleet_survivor_tok_s": (
                round(surv_rate, 1) if surv_rate is not None else None),
            "fastgen_fleet_replicas": len(reps),
            "fastgen_fleet_stale": view["stale"],
            "fastgen_fleet_kill_observed_s": kill_seen_at,
            "fastgen_fleet_paged_at_s": paged,
            "fastgen_fleet_advice": advice,
        }
    finally:
        for r in reps:
            r.terminate()


# -- replica pool (ISSUE 12): CI smoke + BENCH_POOL kill/add demo ------------
SAMPLE_TRACE = os.path.join(REPO_ROOT, "tools", "traces",
                            "sample_200.jsonl")


def _pool_workload(limit: int):
    """Load the checked-in captured trace and synthesize the anonymized
    shared-prefix prompts (the ISSUE 9 machinery) — the replayed
    workload every pool leg drives."""
    from tools.replay_trace import load_trace, synthesize_prompts
    trace = load_trace(SAMPLE_TRACE)
    requests = [r for r in trace["requests"]
                if r.get("outcome") == "ok"][:limit]
    meta = trace["meta"]
    page = int(meta.get("page_size", 16))
    vocab = int(meta.get("vocab_size", 128))
    prompts = synthesize_prompts(requests, page, vocab, seed=0)
    return meta, requests, prompts


def _pool_factory(meta, requests, engines: Dict[str, Any],
                  max_seqs: int = 8):
    """A ReplicaPool factory that caches one engine per label (so a
    warmup pass can pre-compile the engines a later measured pass —
    including its post-kill scale_up — will use)."""
    from deepspeed_tpu.inference.v2 import FastGenScheduler
    from tools.replay_trace import build_replay_engine

    def factory(label: str):
        eng = engines.get(label)
        if eng is None:
            eng = build_replay_engine(meta, requests, max_seqs=max_seqs)
            engines[label] = eng
        return FastGenScheduler(eng)

    return factory


def _pool_params(requests):
    from deepspeed_tpu.inference.v2 import SamplingParams
    return [SamplingParams(
        temperature=float(r.get("temperature", 0.0)),
        top_k=int(r.get("top_k", 0)), top_p=float(r.get("top_p", 1.0)),
        max_new_tokens=max(1, int(r["gen_len"]))) for r in requests]


def _reset_engines(engines: Dict[str, Any]) -> None:
    from tools.replay_trace import _reset_engine
    for eng in engines.values():
        _reset_engine(eng)


def run_pool_smoke(limit: int = 32) -> int:
    """CI leg (ISSUE 12): two in-process replicas behind the
    prefix-affinity router replay the first ``limit`` requests of the
    checked-in captured trace; one replica is drain-migrated away
    mid-replay.  Asserts structural parity (request count + exact
    generated lengths) and ZERO lost requests (every request ends as
    tokens or a structured error — here: tokens), with the pool
    counters monotone through the membership change."""
    from deepspeed_tpu.serving import ReplicaPool
    from deepspeed_tpu.telemetry import metrics as tm

    meta, requests, prompts = _pool_workload(limit)
    params = _pool_params(requests)
    engines: Dict[str, Any] = {}
    pool = ReplicaPool(_pool_factory(meta, requests, engines),
                       replicas=2)
    routed0 = tm.POOL_ROUTED.value
    migrated0 = tm.POOL_MIGRATED.value
    for i in range(len(requests)):
        verdict = pool.submit(i, prompts[i], params[i])
        if verdict is not None:
            raise RuntimeError(
                f"pool smoke: request {i} rejected at submit: "
                f"{verdict.code}")
    for _ in range(6):      # let both replicas get in-flight work
        pool.step()
    gone = pool.scale_down()
    if gone is None:
        raise RuntimeError("pool smoke: scale_down refused with two "
                           "live replicas")
    pool.run_to_completion()
    results = pool.results()
    problems = []
    if pool.errors:
        problems.append(f"structured errors: "
                        f"{ {u: e.code for u, e in pool.errors.items()} }")
    if len(results) != len(requests):
        problems.append(f"request count: {len(results)} completed vs "
                        f"{len(requests)} submitted")
    for i, rec in enumerate(requests):
        want = max(1, int(rec["gen_len"]))
        got = len(results.get(i, []))
        if got != want:
            problems.append(f"req {i}: gen_len {got} vs recorded {want}")
    routed = tm.POOL_ROUTED.value - routed0
    migrated = tm.POOL_MIGRATED.value - migrated0
    if routed < len(requests):
        problems.append(f"routed counter not monotone/complete: "
                        f"{routed} < {len(requests)}")
    if migrated < 1:
        problems.append("no request migrated across the scale_down")
    if len(pool.labels) != 1:
        problems.append(f"expected 1 surviving replica, have "
                        f"{pool.labels}")
    if problems:
        for p in problems:
            print(f"fleetctl pool smoke: {p}", file=sys.stderr)
        raise RuntimeError("pool smoke failed")
    print(f"fleetctl pool smoke: OK — {len(requests)} requests through "
          f"2 replicas, {gone} drain-migrated mid-replay "
          f"({migrated} requests re-homed, partial tokens kept), "
          f"0 lost, exact gen-length parity")
    return 0


def _pool_run_pass(meta, requests, prompts, params, engines,
                   n_replicas: int, policy: str, pace_s: float,
                   wave: int, wave_gap_s: float,
                   kill_add: bool = False,
                   timeout_s: float = 180.0) -> Dict[str, Any]:
    """One measured pool pass over the replayed workload: threaded
    replicas, wave-paced submission (so earlier group members commit
    and warm the cache before later ones arrive — time-scaled pacing
    split across the router).  With ``kill_add``, the busiest replica
    is killed abruptly once ~40% of requests completed and a fresh
    replica is added shortly after."""
    from deepspeed_tpu.serving import ReplicaPool
    from deepspeed_tpu.telemetry import metrics as tm
    from tools.replay_trace import percentile

    # hint_every=1: publish affinity hints every step so placement is
    # timing-insensitive (export_digests is O(top_k) host work)
    pool = ReplicaPool(_pool_factory(meta, requests, engines),
                       replicas=n_replicas, policy=policy,
                       hint_every=1)
    look0 = tm.SERVING_PREFIX_LOOKUP_TOKENS.value
    hit0 = tm.SERVING_PREFIX_HIT_TOKENS.value
    migr0 = tm.POOL_MIGRATED.value
    pool.start(pace_s=pace_s)
    t0 = time.monotonic()
    kill_done = add_done = False
    kill_mono = None
    i = 0
    try:
        while True:
            now = time.monotonic()
            due = min(len(requests), (int((now - t0) / wave_gap_s) + 1)
                      * wave)
            while i < due:
                pool.submit(i, prompts[i], params[i])
                i += 1
            stats = pool.stats()
            if (kill_add and not kill_done
                    and stats["completed"] >= 0.4 * len(requests)):
                victim = max(stats["backlogs"] or {"": 0},
                             key=lambda lb: stats["backlogs"].get(lb, 0))
                if victim:
                    pool.kill(victim)
                    kill_mono = time.monotonic()
                    kill_done = True
            if (kill_done and not add_done
                    and time.monotonic() - kill_mono > 0.3):
                pool.scale_up()
                add_done = True
            if i >= len(requests) and pool.serve_until_idle(0.05):
                break
            if time.monotonic() - t0 > timeout_s:
                raise RuntimeError(f"pool pass timed out "
                                   f"({policy}, kill_add={kill_add})")
            time.sleep(0.005)
    finally:
        pool.stop()
    wall = time.monotonic() - t0
    reqs = [pool.request(u) for u in range(len(requests))]
    toks = sum(len(r.tokens) for r in reqs if r is not None)
    ttft = [(r.first_token_mono - r.submit_mono) * 1e3 for r in reqs
            if r is not None and r.first_token_mono]
    out = {
        "tok_s": round(toks / wall, 1) if wall else None,
        "wall_s": round(wall, 3),
        "completed": sum(1 for r in reqs if r is not None and r.done),
        "lost": sum(1 for r in reqs
                    if r is None or not r.finalized),
        "errors": {u: e.code for u, e in pool.errors.items()},
        "ttft_p99_ms": percentile(ttft, 99),
        "hit_rate": round(
            (tm.SERVING_PREFIX_HIT_TOKENS.value - hit0)
            / max(tm.SERVING_PREFIX_LOOKUP_TOKENS.value - look0, 1), 4),
        "migrated": tm.POOL_MIGRATED.value - migr0,
    }
    if kill_add and kill_mono is not None:
        before = [(r.first_token_mono - r.submit_mono) * 1e3
                  for r in reqs if r is not None and r.first_token_mono
                  and r.first_token_mono <= kill_mono]
        after = [(r.first_token_mono - r.submit_mono) * 1e3
                 for r in reqs if r is not None and r.first_token_mono
                 and r.first_token_mono > kill_mono]
        out["ttft_p99_ms_before_kill"] = percentile(before, 99)
        out["ttft_p99_ms_after_kill"] = percentile(after, 99)
        out["kill_at_s"] = round(kill_mono - t0, 3)
    return out


def run_pool_demo(limit: int = 24, pace_s: float = 0.01,
                  wave: int = 4, wave_gap_s: float = 0.15
                  ) -> Dict[str, Any]:
    """The BENCH_POOL leg (ISSUE 12): the replayed shared-prefix trace
    driven through (a) one replica, (b) two replicas under round-robin
    routing (the affinity control arm), (c) two replicas under the
    prefix-affinity router, and (d) the affinity pool with an abrupt
    replica KILL mid-replay followed by a scale-up ADD — emitting the
    acceptance keys: aggregate tok/s vs single replica, affinity vs
    round-robin prefix hit rate, p99 TTFT before/after the kill, and
    migrated-request/lost-request counts.  Every pass runs on
    pre-warmed engines (one untimed warmup pass over three labels, so
    even the post-kill replica is born compiled) with per-step pacing
    as the simulated device budget — the signal is live parallelism
    and cache placement, not CPU contention."""
    meta, requests, prompts = _pool_workload(limit)
    params = _pool_params(requests)
    engines: Dict[str, Any] = {}

    # untimed warmup: drive the FULL workload through each engine
    # alone (r0..r2 — r2 is the post-kill scale_up home) so every
    # engine compiles its largest slot buckets up front; measured
    # passes then show placement/parallelism, not XLA compiles.  Reset
    # to cold caches afterwards.
    factory = _pool_factory(meta, requests, engines)
    from tools.replay_trace import replay
    for label in ("r0", "r1", "r2"):
        factory(label)      # build + cache the engine
        replay(engines[label], requests, prompts, speed=0.0)
    _reset_engines(engines)

    single = _pool_run_pass(meta, requests, prompts, params, engines,
                            1, "affinity", pace_s, wave, wave_gap_s)
    _reset_engines(engines)
    rr = _pool_run_pass(meta, requests, prompts, params, engines,
                        2, "round_robin", pace_s, wave, wave_gap_s)
    _reset_engines(engines)
    aff = _pool_run_pass(meta, requests, prompts, params, engines,
                         2, "affinity", pace_s, wave, wave_gap_s)
    _reset_engines(engines)
    kill = _pool_run_pass(meta, requests, prompts, params, engines,
                          2, "affinity", pace_s, wave, wave_gap_s,
                          kill_add=True)
    return {
        "pool_requests": len(requests),
        "pool_single_tok_s": single["tok_s"],
        "pool_rr_tok_s": rr["tok_s"],
        "pool_affinity_tok_s": aff["tok_s"],
        "pool_agg_tok_s": kill["tok_s"],
        "pool_speedup_vs_single": (
            round(kill["tok_s"] / single["tok_s"], 3)
            if single["tok_s"] else None),
        "pool_prefix_hit_rate_affinity": aff["hit_rate"],
        "pool_prefix_hit_rate_round_robin": rr["hit_rate"],
        "pool_ttft_p99_ms_before_kill": kill.get(
            "ttft_p99_ms_before_kill"),
        "pool_ttft_p99_ms_after_kill": kill.get(
            "ttft_p99_ms_after_kill"),
        "pool_kill_at_s": kill.get("kill_at_s"),
        "pool_migrated_requests": kill["migrated"],
        "pool_lost_requests": kill["lost"],
    }


def _journey_text(targets: List[Tuple[str, str]], uid: int) -> str:
    """Cross-process journey reconstruction (ISSUE 19): scrape every
    target's ``/journey?uid=`` records and stitch them into one
    chronological segment chain by journey id — the "explain a slow
    request" runbook's fleet view.  ``targets`` are (label, host:port)
    pairs; unreachable replicas degrade to a line, never an abort."""
    import urllib.request
    from deepspeed_tpu.telemetry import journey as jn
    records: List[Dict[str, Any]] = []
    lines = []
    for label, target in targets:
        try:
            with urllib.request.urlopen(
                    f"http://{target}/journey?uid={int(uid)}",
                    timeout=5) as resp:
                doc = json.loads(resp.read().decode())
        except Exception as e:  # noqa: BLE001 — any replica may be down
            lines.append(f"{label:<8} UNREACHABLE ({e})")
            continue
        comp, frag = doc.get("completed", []), doc.get("fragments", [])
        lines.append(f"{label:<8} {len(comp)} completed, "
                     f"{len(frag)} fragment(s)")
        records.extend(comp + frag)
    if not records:
        lines.append(f"uid {uid}: no journey records on any target "
                     "(telemetry off, or the rings rolled over)")
        return "\n".join(lines)
    stitched = jn.stitch(records)
    total = sum(s["ms"] for s in stitched["segments"])
    lines.append(f"journey {stitched['jid']} uid={uid} "
                 f"outcome={stitched.get('outcome')} "
                 f"sources={stitched['sources']} "
                 f"total={round(total, 2)}ms")
    for s in stitched["segments"]:
        at = f" @{s['at']}" if s.get("at") else ""
        lines.append(f"  {s['seg']:<16} {s['ms']:>10.3f} ms{at}")
    for finding in jn.chain_gaps(stitched, eps_ms=5.0):
        lines.append(f"  GAP: {finding}")
    return "\n".join(lines)


def _digests_text(targets: List[Tuple[str, str]], top_k: int = 8) -> str:
    """Per-target ``/snapshot?digests=1`` affinity hints (the
    subprocess-mode router input, ISSUE 12).  ``targets`` are
    (label, host:port) pairs — the host passes through untouched."""
    from deepspeed_tpu.serving import fetch_remote_hints
    lines = []
    for label, target in targets:
        try:
            doc = fetch_remote_hints(target, top_k=top_k)
            digests = doc.get("digests", [])
            lines.append(f"{label:<8} page_size={doc.get('page_size')} "
                         f"digests={len(digests)}")
            for d in digests:
                lines.append(f"  {d}")
        except Exception as e:  # noqa: BLE001 — any replica may be down
            lines.append(f"{label:<8} UNREACHABLE ({e})")
    return "\n".join(lines)


#: fleet memory table columns (ISSUE 20): subsystem -> gauge name,
#: the ledger's own publication order
_MEM_COLUMNS = (
    ("weights", "ds_mem_weights_bytes"),
    ("kv_pages", "ds_mem_kv_pages_bytes"),
    ("draft_kv", "ds_mem_draft_kv_bytes"),
    ("tier_host", "ds_mem_tier_host_bytes"),
    ("tier_disk", "ds_mem_tier_disk_bytes"),
    ("offload", "ds_mem_offload_bytes"),
    ("staging", "ds_mem_staging_bytes"),
    ("telemetry", "ds_mem_telemetry_bytes"),
)


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return (f"{n:.0f}{unit}" if unit == "B"
                    else f"{n:.1f}{unit}")
        n /= 1024.0
    return f"{n:.1f}GiB"


def _mem_text(view: Dict[str, Any]) -> str:
    """Fleet memory rollup (ISSUE 20): one row per replica over the
    ``ds_mem_*`` subsystem gauges, fleet totals from the federation's
    sum rollup, and the capacity signal — fleet headroom is the SUM of
    per-replica ``ds_mem_headroom_seqs`` (what the fleet can still
    admit) while the MIN names the replica to stop routing to."""
    gauges = view.get("gauges", {})
    labels = sorted(view.get("replicas", {}))
    cols = [(s, gauges.get(g, {}).get("per_replica", {}))
            for s, g in _MEM_COLUMNS]
    out = ["replica   " + "".join(f"{s:>11}" for s, _ in cols)
           + f"{'unacct':>11}{'headroom':>10}"]
    unacct = gauges.get("ds_mem_unaccounted_bytes",
                        {}).get("per_replica", {})
    head = gauges.get("ds_mem_headroom_seqs", {})
    head_pr = head.get("per_replica", {})
    for label in labels:
        row = f"{label:<10}"
        for _, pr in cols:
            row += f"{_fmt_bytes(pr.get(label)):>11}"
        row += f"{_fmt_bytes(unacct.get(label)):>11}"
        h = head_pr.get(label)
        row += f"{(int(h) if h is not None else '-'):>10}"
        out.append(row)
    total = f"{'fleet':<10}"
    for s, g in _MEM_COLUMNS:
        total += f"{_fmt_bytes(gauges.get(g, {}).get('sum')):>11}"
    total += f"{_fmt_bytes(gauges.get('ds_mem_unaccounted_bytes', {}).get('sum')):>11}"
    hs = head.get("sum")
    total += f"{(int(hs) if hs is not None else '-'):>10}"
    out.append(total)
    if head_pr:
        hmin = min((v, k) for k, v in head_pr.items())
        out.append(f"headroom: fleet={int(hs or 0)} seqs admissible, "
                   f"min={int(hmin[0])} on {hmin[1]}")
    else:
        out.append("headroom: no ds_mem_headroom_seqs published — "
                   "replicas predate the memory observatory or "
                   "telemetry is off")
    return "\n".join(out)


# -- CLI ---------------------------------------------------------------------
def _status_text(view: Dict[str, Any]) -> str:
    lines = [f"fleet: {view['live']} live, {view['stale']} stale"]
    for label, st in sorted(view["replicas"].items()):
        mark = "STALE" if st["stale"] else "up"
        err = f" ({st['error']})" if st["error"] else ""
        lines.append(f"  {label:<8} {mark:<6} {st['target']}"
                     f" age={st['age_s']}s{err}")
    c = view["counters"]
    for key in ("ds_fastgen_tokens_total", "ds_serving_steps_total",
                "ds_fastgen_shed_total"):
        if key in c:
            lines.append(f"  {key} = {c[key]}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("command", nargs="?", default="status",
                    choices=["status", "json", "metrics", "digests",
                             "journey", "mem"])
    ap.add_argument("uid", nargs="?", type=int,
                    help="journey command: the request uid to stitch "
                    "across the fleet")
    ap.add_argument("--targets", default="",
                    help="comma-separated [label=]host:port replica "
                    "list (or DS_FLEET_TARGETS)")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="repeat every N seconds")
    ap.add_argument("--smoke", action="store_true",
                    help="spin two debug replicas and assert the "
                    "merged view == sum of parts (CI)")
    ap.add_argument("--kill-demo", action="store_true",
                    help="two replicas, one killed mid-replay; print "
                    "the fleet bench keys")
    ap.add_argument("--pool-smoke", action="store_true",
                    help="replica pool CI smoke: 2 in-process replicas "
                    "behind the affinity router, one drain-migrated "
                    "mid-replay; assert parity and zero lost requests")
    ap.add_argument("--pool-demo", action="store_true",
                    help="replica pool kill/add demo; print the "
                    "BENCH_POOL keys")
    ap.add_argument("--limit", type=int, default=0,
                    help="pool legs: replay only the first N trace "
                    "requests (0 = leg default)")
    args = ap.parse_args(argv)

    if args.smoke:
        try:
            return run_smoke()
        except RuntimeError as e:
            print(f"fleetctl smoke: FAILED — {e}", file=sys.stderr)
            return 1
    if args.pool_smoke:
        try:
            return run_pool_smoke(**({"limit": args.limit}
                                     if args.limit else {}))
        except RuntimeError as e:
            print(f"fleetctl pool smoke: FAILED — {e}", file=sys.stderr)
            return 1
    if args.kill_demo:
        print(json.dumps(run_kill_demo(), indent=1))
        return 0
    if args.pool_demo:
        print(json.dumps(run_pool_demo(**({"limit": args.limit}
                                          if args.limit else {})),
                         indent=1))
        return 0

    targets = args.targets or os.environ.get("DS_FLEET_TARGETS", "")
    if not targets:
        print("fleetctl: no --targets (or DS_FLEET_TARGETS)",
              file=sys.stderr)
        return 2
    from deepspeed_tpu.telemetry.federation import Federation
    fed = Federation()
    fed.configure_targets(targets)
    if args.command in ("digests", "journey"):
        pairs = []
        for i, entry in enumerate(t.strip() for t in
                                  targets.split(",") if t.strip()):
            label, _, tgt = (entry.partition("=") if "=" in entry
                             else (f"r{i}", "", entry))
            pairs.append((label.strip(), tgt.strip()))
        if args.command == "journey":
            if args.uid is None:
                print("fleetctl: journey needs a uid "
                      "(fleetctl --targets ... journey <uid>)",
                      file=sys.stderr)
                return 2
            print(_journey_text(pairs, args.uid))
            return 0
        while True:
            print(_digests_text(pairs))
            if not args.watch:
                return 0
            time.sleep(args.watch)
    while True:
        if args.command == "json":
            print(json.dumps(fed.snapshot_json(), indent=1))
        elif args.command == "metrics":
            print(fed.prometheus_text(), end="")
        elif args.command == "mem":
            print(_mem_text(fed.scrape()))
        else:
            print(_status_text(fed.scrape()))
        if not args.watch:
            return 0
        time.sleep(args.watch)


if __name__ == "__main__":
    sys.exit(main())
