#!/usr/bin/env python
"""fleetctl (ISSUE 11): see N serving replicas as one fleet.

A stdlib-only CLI over the federation layer
(``deepspeed_tpu/telemetry/federation.py``): scrape each replica's
``/snapshot?raw=1``, merge (counters sum, gauges roll up min/max/sum,
log-bucketed histograms merge EXACTLY), and print status / JSON /
Prometheus text.  Also hosts the two-replica smoke used by
``tools/ci.sh`` and the replica-kill fleet bench behind bench.py's
``BENCH_FLEET=1`` leg.

Usage::

    python tools/fleetctl.py --targets 127.0.0.1:9001,127.0.0.1:9002
        [status|json|metrics] [--watch SECONDS]
    python tools/fleetctl.py --smoke       # CI: two debug replicas,
                                           # merged counters == sum
    python tools/fleetctl.py --kill-demo   # bench: two replicas, one
                                           # killed mid-replay via the
                                           # serving.preempt chaos site

Targets are ``[label=]host:port`` (labels default to r0, r1, ...).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

REPLICA = os.path.join(REPO_ROOT, "tools", "fleet_replica.py")


# -- replica process management (smoke / kill-demo / bench) ------------------
class ReplicaProc:
    """A fleet_replica.py child with a line-buffered stdout reader."""

    def __init__(self, label: str, args: Optional[List[str]] = None,
                 env_extra: Optional[Dict[str, str]] = None):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(env_extra or {})
        self.label = label
        self.proc = subprocess.Popen(
            [sys.executable, REPLICA, "--label", label] + (args or []),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env, start_new_session=True)
        self.lines: List[str] = []
        self._t = threading.Thread(target=self._read, daemon=True)
        self._t.start()

    def _read(self) -> None:
        for line in self.proc.stdout:
            self.lines.append(line.rstrip("\n"))

    def wait_line(self, needle: str, timeout: float) -> Optional[str]:
        deadline = time.monotonic() + timeout
        seen = 0
        while time.monotonic() < deadline:
            while seen < len(self.lines):
                if needle in self.lines[seen]:
                    return self.lines[seen]
                seen += 1
            if self.proc.poll() is not None and seen >= len(self.lines):
                return None
            time.sleep(0.05)
        return None

    def port(self, timeout: float = 120.0) -> int:
        line = self.wait_line("FLEET_REPLICA ready", timeout)
        if line is None:
            raise RuntimeError(
                f"replica {self.label} never reported ready "
                f"(exit={self.proc.poll()})")
        return int(line.split("port=")[1].split()[0])

    def terminate(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def _federation(targets: List[Tuple[str, int]], stale_after_s=None):
    from deepspeed_tpu.telemetry.federation import Federation
    fed = Federation() if stale_after_s is None else Federation(
        stale_after_s=stale_after_s)
    for label, port in targets:
        fed.add_http(label, f"127.0.0.1:{port}")
    return fed


# -- CI smoke ----------------------------------------------------------------
def run_smoke() -> int:
    """Spin two debug replicas, scrape, assert the merged fleet view IS
    the sum of its parts (counters and histogram counts, exactly)."""
    reps = [ReplicaProc("r0", ["--rounds", "1", "--seed", "0"]),
            ReplicaProc("r1", ["--rounds", "1", "--seed", "1"])]
    try:
        targets = [(r.label, r.port()) for r in reps]
        for r in reps:
            if r.wait_line("FLEET_REPLICA done", 180.0) is None:
                raise RuntimeError(
                    f"replica {r.label} did not finish its round")
        fed = _federation(targets)
        view = fed.scrape()
        if view["stale"]:
            raise RuntimeError(f"stale replicas in smoke: "
                               f"{view['replicas']}")
        parts = []
        import urllib.request
        for label, port in targets:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/snapshot?raw=1",
                    timeout=5) as resp:
                parts.append(json.loads(resp.read().decode()))
        for name, merged in sorted(view["counters"].items()):
            want = sum(p["counters"].get(name, 0) for p in parts)
            if merged != want:
                raise RuntimeError(
                    f"merged counter {name}: {merged} != sum of parts "
                    f"{want}")
        for name, h in sorted(view["hists"].items()):
            want = sum(p["hists"][name]["count"] for p in parts
                       if name in p.get("hists", {}))
            if h["count"] != want:
                raise RuntimeError(
                    f"merged histogram {name}: count {h['count']} != "
                    f"sum of parts {want}")
        toks = view["counters"].get("ds_fastgen_tokens_total", 0)
        if toks <= 0:
            raise RuntimeError("no tokens counted across the fleet")
        print(f"fleetctl smoke: OK — 2 replicas, "
              f"{len(view['counters'])} merged counters == sum of "
              f"parts, {len(view['hists'])} histograms merged exactly, "
              f"{toks} fleet tokens")
        return 0
    finally:
        for r in reps:
            r.terminate()


# -- replica-kill fleet event (BENCH_FLEET) ----------------------------------
def run_kill_demo(step_sleep_s: float = 0.05, rounds: int = 150,
                  kill_at_step: int = 90,
                  sample_every_s: float = 0.2,
                  run_s: float = 20.0) -> Dict[str, Any]:
    """Two live replicas replaying the checked-in CAPTURED trace
    (``tools/traces/sample_200.jsonl``, anonymized prompt synthesis
    per replica seed); one is killed mid-replay through the
    ``serving.preempt`` chaos site.  The parent federates both,
    samples a FLEET time-series ring, and runs the SLO burn-rate
    evaluator over it — returns the ``fastgen_fleet_*`` bench keys
    (aggregate tok/s, merged p99 TTFT across the kill event, the page
    verdict and its advice)."""
    from deepspeed_tpu.telemetry.registry import percentile_from_counts
    from deepspeed_tpu.telemetry.slo import SLOEvaluator
    from deepspeed_tpu.telemetry.timeseries import TimeSeries

    # --trace-limit 4 keeps per-step compute small relative to the
    # pacing sleep, so the token rate tracks the number of LIVE
    # replicas (the signal) rather than CPU contention (noise)
    common = ["--trace",
              os.path.join(REPO_ROOT, "tools", "traces",
                           "sample_200.jsonl"),
              "--trace-limit", "4",
              "--rounds", str(rounds),
              "--step-sleep-s", str(step_sleep_s)]
    reps = [
        ReplicaProc("r0", common + ["--seed", "0"]),
        ReplicaProc("r1", common + ["--seed", "1"],
                    env_extra={
                        "DS_CHAOS": f"serving.preempt:at={kill_at_step}"}),
    ]
    try:
        targets = [(r.label, r.port()) for r in reps]
        fed = _federation(targets, stale_after_s=2.0)
        ts = TimeSeries(source=fed.merged_raw)
        ts.configure(interval_s=sample_every_s, retention_s=600.0)
        ev = SLOEvaluator()
        ev.attach(timeseries=ts, federation=fed)

        # let both replicas pass their compile warmup (round 0) before
        # measuring the both-alive rate the objective is set from
        for r in reps:
            if r.wait_line("round=0 done", 300.0) is None:
                raise RuntimeError(
                    f"replica {r.label} never finished round 0 "
                    f"(exit={r.proc.poll()})")
        ts.sample_now()
        time.sleep(max(4 * sample_every_s, 2.4))
        ts.sample_now()
        warm_rate = ts.counter_rate("ds_fastgen_tokens_total", 5.0) or 0.0
        if warm_rate <= 0:
            # min_per_s = 0 would be rejected by the objective
            # validator anyway — fail with the real story instead
            raise RuntimeError(
                "no fleet tokens observed in the warm window — "
                "replicas too slow for the demo pacing?")
        if reps[1].proc.poll() is not None:
            raise RuntimeError(
                "replica r1 died before the both-alive rate was "
                "measured — raise kill_at_step")
        ev.configure([{
            "name": "fleet_goodput", "kind": "throughput_min",
            "counter": "ds_fastgen_tokens_total",
            "min_per_s": 0.8 * warm_rate, "budget": 0.1,
            "fast_window_s": 2.0, "slow_window_s": 4.0,
            "page_burn": 2.0, "warn_burn": 0.5,
        }])

        t0 = time.monotonic()
        tok0 = (fed.scrape()["counters"]
                .get("ds_fastgen_tokens_total", 0))
        paged = advice = surv_rate = None
        kill_seen_at = None
        while time.monotonic() - t0 < run_s:
            time.sleep(sample_every_s)
            ts.sample_now()     # evaluator rides the on-sample hook
            if kill_seen_at is None and reps[1].proc.poll() is not None:
                kill_seen_at = round(time.monotonic() - t0, 2)
            cur = ev.current()
            if paged is None and cur["status"] == "page":
                v = cur["objectives"]["fleet_goodput"]
                paged = round(time.monotonic() - t0, 2)
                advice = v["advice"]
                # the survivor's rate AT page time, while it still runs
                surv_rate = ts.counter_rate(
                    "ds_fastgen_tokens_total", 2.0)
                break
            if (reps[0].proc.poll() is not None
                    or any("FLEET_REPLICA done" in ln
                           for ln in reps[0].lines)):
                # the survivor finished its workload — stop before the
                # end-of-traffic rate drop masquerades as the kill
                break
        wall = time.monotonic() - t0
        view = fed.scrape()
        toks = view["counters"].get("ds_fastgen_tokens_total", 0) - tok0
        th = view["hists"].get("ds_fastgen_ttft_ms")
        ttft_p99 = (round(percentile_from_counts(
            th["bounds"], th["counts"], th["count"], 99), 2)
            if th and th["count"] else None)
        return {
            "fastgen_fleet_tok_s": round(toks / wall, 1),
            "fastgen_fleet_ttft_p99_ms": ttft_p99,
            "fastgen_fleet_warm_tok_s": round(warm_rate, 1),
            "fastgen_fleet_survivor_tok_s": (
                round(surv_rate, 1) if surv_rate is not None else None),
            "fastgen_fleet_replicas": len(reps),
            "fastgen_fleet_stale": view["stale"],
            "fastgen_fleet_kill_observed_s": kill_seen_at,
            "fastgen_fleet_paged_at_s": paged,
            "fastgen_fleet_advice": advice,
        }
    finally:
        for r in reps:
            r.terminate()


# -- CLI ---------------------------------------------------------------------
def _status_text(view: Dict[str, Any]) -> str:
    lines = [f"fleet: {view['live']} live, {view['stale']} stale"]
    for label, st in sorted(view["replicas"].items()):
        mark = "STALE" if st["stale"] else "up"
        err = f" ({st['error']})" if st["error"] else ""
        lines.append(f"  {label:<8} {mark:<6} {st['target']}"
                     f" age={st['age_s']}s{err}")
    c = view["counters"]
    for key in ("ds_fastgen_tokens_total", "ds_serving_steps_total",
                "ds_fastgen_shed_total"):
        if key in c:
            lines.append(f"  {key} = {c[key]}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("command", nargs="?", default="status",
                    choices=["status", "json", "metrics"])
    ap.add_argument("--targets", default="",
                    help="comma-separated [label=]host:port replica "
                    "list (or DS_FLEET_TARGETS)")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="repeat every N seconds")
    ap.add_argument("--smoke", action="store_true",
                    help="spin two debug replicas and assert the "
                    "merged view == sum of parts (CI)")
    ap.add_argument("--kill-demo", action="store_true",
                    help="two replicas, one killed mid-replay; print "
                    "the fleet bench keys")
    args = ap.parse_args(argv)

    if args.smoke:
        try:
            return run_smoke()
        except RuntimeError as e:
            print(f"fleetctl smoke: FAILED — {e}", file=sys.stderr)
            return 1
    if args.kill_demo:
        print(json.dumps(run_kill_demo(), indent=1))
        return 0

    targets = args.targets or os.environ.get("DS_FLEET_TARGETS", "")
    if not targets:
        print("fleetctl: no --targets (or DS_FLEET_TARGETS)",
              file=sys.stderr)
        return 2
    from deepspeed_tpu.telemetry.federation import Federation
    fed = Federation()
    fed.configure_targets(targets)
    while True:
        if args.command == "json":
            print(json.dumps(fed.snapshot_json(), indent=1))
        elif args.command == "metrics":
            print(fed.prometheus_text(), end="")
        else:
            print(_status_text(fed.scrape()))
        if not args.watch:
            return 0
        time.sleep(args.watch)


if __name__ == "__main__":
    sys.exit(main())
