#!/usr/bin/env python
"""Metric-namespace lint (ISSUE 4 CI satellite).

Asserts that every metric registered in the telemetry registry

- matches the ``ds_<area>_<name>`` naming convention with a known area
  (counters additionally end in ``_total``), and
- is documented in docs/DESIGN.md's "Telemetry" metric table,

so the namespace cannot silently drift: adding a metric without
documenting it (or with an off-convention name) fails tier-1
(tests/test_telemetry.py runs :func:`check`) and this script
(``python tools/check_metrics.py``) exits non-zero.
"""

from __future__ import annotations

import os
import re
import sys
from typing import List

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

AREAS = ("serving", "comm", "kv", "train", "fastgen", "chaos")
NAME_RE = re.compile(
    r"^ds_(%s)_[a-z][a-z0-9_]*$" % "|".join(AREAS))


def check(design_path: str = None) -> List[str]:
    """Return a list of lint errors (empty = clean)."""
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    from deepspeed_tpu.telemetry import Counter, get_registry
    from deepspeed_tpu.telemetry import metrics  # noqa: F401 — mint catalog

    if design_path is None:
        design_path = os.path.join(REPO_ROOT, "docs", "DESIGN.md")
    with open(design_path) as f:
        design = f.read()

    errors = []
    registered = get_registry().all_metrics()
    if not registered:
        errors.append("no metrics registered — catalog import broken?")
    for name, metric in sorted(registered.items()):
        if not NAME_RE.match(name):
            errors.append(
                f"{name}: does not match ds_<area>_<name> "
                f"(area in {AREAS}, lowercase [a-z0-9_])")
        if isinstance(metric, Counter) and not name.endswith("_total"):
            errors.append(f"{name}: counters must end in _total")
        if f"`{name}`" not in design:
            errors.append(
                f"{name}: not documented in docs/DESIGN.md "
                "(add a row to the Telemetry metric table)")
        if not metric.help:
            errors.append(f"{name}: registered without help text")
    return errors


def main() -> int:
    errors = check()
    for e in errors:
        print(f"check_metrics: {e}", file=sys.stderr)
    if errors:
        print(f"check_metrics: {len(errors)} error(s)", file=sys.stderr)
        return 1
    from deepspeed_tpu.telemetry import get_registry
    print(f"check_metrics: {len(get_registry().all_metrics())} metrics OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
