#!/usr/bin/env python
"""Metric-namespace lint — thin CLI shim (ISSUE 15).

The implementation moved into the dslint framework
(``tools/dslint/metrics_catalog.py``, run in CI as dslint's
``metric-catalog`` rule).  This shim keeps the historical CLI and
module surface — ``check()`` returning message strings, ``NAME_RE``,
``AREAS``, exit code 1 on any error — so ``tools/ci.sh`` and
tests/test_telemetry.py keep working during the transition.
"""

from __future__ import annotations

import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(_TOOLS)
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.dslint import metrics_catalog as _impl            # noqa: E402
from tools.dslint.metrics_catalog import (AREAS,             # noqa: F401,E402
                                          NAME_RE, RECORD_METHODS,
                                          SCAN_ROOTS)

#: module-level seams kept monkeypatchable (the catalog-relocation
#: test seam from ISSUE 9) — read at call time, not import time
CATALOG = _impl.CATALOG


def check(design_path: str = None):
    """List of lint error strings (empty = clean); delegates to
    tools/dslint/metrics_catalog with this module's seams."""
    return _impl.check(design_path=design_path, repo_root=REPO_ROOT,
                       catalog=CATALOG)


def main() -> int:
    errors = check()
    for e in errors:
        print(f"check_metrics: {e}", file=sys.stderr)
    if errors:
        print(f"check_metrics: {len(errors)} error(s)", file=sys.stderr)
        return 1
    from deepspeed_tpu.telemetry import get_registry
    print(f"check_metrics: {len(get_registry().all_metrics())} metrics OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
