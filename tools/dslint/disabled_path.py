"""Pass 4 — disabled-path cost (rule ``disabled-path-guard``).

The telemetry spine's standing promise, re-tested per module since
PR 4: with telemetry off, every instrumentation entry point costs one
attribute read (<5µs), never an allocation, f-string, or call.  This
pass checks the SHAPE that promise requires: a function marked
``# dslint: disabled-path`` must begin (docstring aside) with a single
guard

    if <attribute/flag expression>: return <trivial>

whose test is built only from names, attributes, ``not``, ``and`` /
``or``, and comparisons over those (``state.enabled``,
``self.active``, ``not (state.enabled and self.enabled)``) — no
calls, no f-strings, no subscripts — and whose early return is a bare
``return`` or a pre-built constant/name/attribute (the shared no-op
span/track objects).  Anything before or inside the guard that
allocates or calls would be paid on EVERY disabled invocation.

Coverage is required per module (REQUIRED_MODULES): each instrumented
telemetry module must annotate at least one entry point, so the
contract can't silently age out of a rewrite.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from .core import Finding, Project, SourceFile, register_rules

register_rules("disabled-path-guard")

#: modules that must each carry >=1 annotated disabled-path function
REQUIRED_MODULES: Tuple[str, ...] = (
    "deepspeed_tpu/telemetry/tracer.py",
    "deepspeed_tpu/telemetry/flight_recorder.py",
    "deepspeed_tpu/telemetry/timeseries.py",
    "deepspeed_tpu/telemetry/workload_trace.py",
    "deepspeed_tpu/telemetry/watchdog.py",
    "deepspeed_tpu/telemetry/memory.py",
    "deepspeed_tpu/runtime/fault_injection.py",
)


def _attr_only(node: ast.AST) -> bool:
    """True when the expression is names/attributes/constants combined
    with not/and/or/comparisons — one-attribute-read territory."""
    if isinstance(node, (ast.Name, ast.Constant)):
        return True
    if isinstance(node, ast.Attribute):
        return _attr_only(node.value)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
        return _attr_only(node.operand)
    if isinstance(node, ast.BoolOp):
        return all(_attr_only(v) for v in node.values)
    if isinstance(node, ast.Compare):
        return _attr_only(node.left) and all(
            _attr_only(c) for c in node.comparators)
    return False


def _trivial_return(stmt: ast.stmt) -> bool:
    if not isinstance(stmt, ast.Return):
        return False
    v = stmt.value
    return v is None or isinstance(v, (ast.Constant, ast.Name)) or (
        isinstance(v, ast.Attribute) and _attr_only(v))


def check_guard(func: ast.AST) -> Optional[str]:
    """None when the guard shape holds, else why it doesn't."""
    body = list(func.body)
    if body and isinstance(body[0], ast.Expr) and isinstance(
            body[0].value, ast.Constant) and isinstance(
            body[0].value.value, str):
        body = body[1:]     # docstring
    if not body:
        return "empty body"
    first = body[0]
    if not isinstance(first, ast.If):
        return (f"first statement is {type(first).__name__}, not the "
                "disabled guard — work precedes the enabled check")
    if not _attr_only(first.test):
        return ("guard test is not a pure attribute/flag read "
                f"(`{ast.unparse(first.test)}`) — a call or subscript "
                "in the guard is paid on every disabled invocation")
    if first.orelse:
        return "guard has an else branch — not an early return"
    if len(first.body) != 1 or not _trivial_return(first.body[0]):
        return ("guard body must be exactly one trivial return "
                "(bare / constant / pre-built no-op object)")
    return None


def run(project: Project,
        required=REQUIRED_MODULES) -> List[Finding]:
    findings: List[Finding] = []
    for rel in required:
        sf = project.file(rel)
        if sf is None:
            findings.append(Finding(
                "disabled-path-guard", rel, 0,
                "required disabled-path module missing from scan",
                detail="missing-module"))
            continue
        if not any(sf.func_annotated(f, "disabled-path")
                   for f in sf.functions()):
            findings.append(Finding(
                "disabled-path-guard", rel, 0,
                "no '# dslint: disabled-path' annotated function in "
                "this instrumented module — the <5µs contract has no "
                "checked entry point here",
                detail="no-annotation"))
    for sf in project.files():
        for func in sf.functions():
            if not sf.func_annotated(func, "disabled-path"):
                continue
            why = check_guard(func)
            if why is not None and not sf.suppressed(
                    "disabled-path-guard", func.lineno):
                findings.append(Finding(
                    "disabled-path-guard", sf.rel, func.lineno,
                    f"{func.name}() is documented <5µs disabled but "
                    f"does not start with a single attribute-read "
                    f"guard: {why}",
                    detail=func.name))
    return findings
