"""CLI: ``python -m tools.dslint`` (run from the repo root).

Exit codes: 0 clean, 1 findings (or, with ``--strict``, stale baseline
entries), 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from tools.dslint import (DEFAULT_BASELINE, PASSES, RULE_TO_PASS,  # noqa: E402
                          run_all)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dslint",
        description="repo-native static contract checker")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on stale baseline entries (the "
                    "debt ledger may only shrink); CI runs this")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default {DEFAULT_BASELINE}; "
                    "'' disables)")
    ap.add_argument("--only", default="",
                    help="comma-separated pass or rule names to run")
    ap.add_argument("--skip", default="",
                    help="comma-separated pass or rule names to skip")
    ap.add_argument("--list-rules", action="store_true",
                    help="print rule ids and owning passes, then exit")
    ap.add_argument("--root", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, owner in sorted(RULE_TO_PASS.items()):
            print(f"{rule:24s} ({owner})")
        print("framework rules: bare-suppression, parse-error")
        return 0

    known = set(PASSES) | set(RULE_TO_PASS)
    only = [s for s in args.only.split(",") if s]
    skip = [s for s in args.skip.split(",") if s]
    for name in only + skip:
        if name not in known:
            print(f"dslint: unknown pass/rule {name!r} "
                  f"(known: {sorted(known)})", file=sys.stderr)
            return 2

    try:
        report = run_all(root=args.root, baseline_path=args.baseline,
                         only=only or None, skip=skip or None)
    except Exception as e:
        print(f"dslint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    for err in report.baseline_errors:
        print(f"dslint: baseline error: {err}", file=sys.stderr)
    for f in report.findings:
        print(f"dslint: {f.format()}", file=sys.stderr)
    for e in report.stale_baseline:
        print("dslint: stale baseline entry "
              f"{e.get('rule')}::{e.get('path')}::{e.get('detail')} — "
              "the finding no longer exists; remove it",
              file=sys.stderr)

    failed = bool(report.findings or report.baseline_errors)
    if args.strict and report.stale_baseline:
        failed = True
    if failed:
        n = len(report.findings)
        print(f"dslint: {n} finding(s)"
              + (f", {len(report.stale_baseline)} stale baseline "
                 "entr(ies)" if report.stale_baseline else ""),
              file=sys.stderr)
        return 1
    suffix = (f" ({len(report.baselined)} baselined)"
              if report.baselined else "")
    print(f"dslint: clean{suffix}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
