"""Pass 5 — catalog closure (rules ``metric-catalog``, ``chaos-site``,
``flight-event``, ``env-doc``).

Every name-shaped registry in the system must be CLOSED: a name used
anywhere in the production tree must be registered, and a registered
name must be used — otherwise the catalogs rot in both directions
(phantom names that silently no-op; dead entries that document nothing).

- ``metric-catalog``: the absorbed ``tools/check_metrics.py`` lint
  (naming convention, DESIGN.md documentation, help text, dead-metric
  scan) — see :mod:`tools.dslint.metrics_catalog`.
- ``chaos-site``: every site name passed to the fault-injection
  registry (``fire`` / ``has_site`` / ``maybe_raise`` /
  ``site_value``) must exist in ``fault_injection.SITES``, and every
  registered site must be exercised somewhere outside the registry —
  a ``DS_CHAOS`` spec naming an unknown site already raises at arm
  time; this closes the static side so the name can't drift in code.
- ``flight-event``: every literal event kind recorded into the flight
  recorder (``.record("...")`` / ``._record("...")`` /
  ``._record_event("...")``) must be registered in
  ``flight_recorder.EVENT_KINDS``, and every registered kind must be
  recorded somewhere — postmortem consumers grep by kind.
- ``env-doc``: every ``DS_*`` environment variable the production
  tree reads must appear in docs/DESIGN.md or README.md — an
  undocumented env knob is an unsupported one.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Project, register_rules

register_rules("metric-catalog", "chaos-site", "flight-event",
               "env-doc")

FAULT_INJECTION = "deepspeed_tpu/runtime/fault_injection.py"
FLIGHT_RECORDER = "deepspeed_tpu/telemetry/flight_recorder.py"
DOC_PATHS = ("docs/DESIGN.md", "README.md")

_SITE_METHODS = {"fire", "has_site", "maybe_raise", "site_value"}
_EVENT_METHODS = {"record", "_record", "_record_event"}


def _literal_str_arg(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) and \
            isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _dict_literal_keys(tree: ast.AST, name: str) -> Optional[Set[str]]:
    """String keys of a module-level ``NAME: ... = {...}`` dict."""
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            target, value = node.target.id, node.value
        if target == name and isinstance(value, ast.Dict):
            return {k.value for k in value.keys
                    if isinstance(k, ast.Constant) and
                    isinstance(k.value, str)}
    return None


def _set_literal(tree: ast.AST, name: str) -> Optional[Set[str]]:
    """String members of a module-level ``NAME = frozenset({...})`` /
    ``NAME = {...}`` set literal."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name):
            continue
        value = node.value
        if isinstance(value, ast.Call) and \
                getattr(value.func, "id", "") == "frozenset" and \
                value.args:
            value = value.args[0]
        if isinstance(value, (ast.Set,)):
            return {e.value for e in value.elts
                    if isinstance(e, ast.Constant) and
                    isinstance(e.value, str)}
    return None


# -- chaos sites -------------------------------------------------------------
def check_chaos_sites(project: Project,
                      registry_path: str = FAULT_INJECTION
                      ) -> List[Finding]:
    out: List[Finding] = []
    reg = project.file(registry_path)
    if reg is None:
        return [Finding("chaos-site", registry_path, 0,
                        "fault-injection registry missing from scan",
                        detail="missing-module")]
    sites = _dict_literal_keys(reg.tree, "SITES")
    if sites is None:
        return [Finding("chaos-site", registry_path, 0,
                        "SITES dict literal not found — the site "
                        "catalog must stay statically readable",
                        detail="no-SITES")]
    used: Set[str] = set()
    for sf in project.files():
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr in _SITE_METHODS):
                continue
            site = _literal_str_arg(node)
            if site is None:
                continue    # dynamic dispatch: runtime validation owns it
            if sf.rel != registry_path:
                used.add(site)
            if site not in sites and not sf.suppressed(
                    "chaos-site", node.lineno):
                out.append(Finding(
                    "chaos-site", sf.rel, node.lineno,
                    f"unknown fault-injection site {site!r} — register "
                    f"it in {registry_path}:SITES (known: "
                    f"{sorted(sites)})",
                    detail=f"unknown:{site}"))
    for site in sorted(sites - used):
        out.append(Finding(
            "chaos-site", registry_path, 0,
            f"site {site!r} is registered in SITES but never "
            "exercised (fire/has_site/maybe_raise/site_value) in the "
            "production tree — dead chaos coverage",
            detail=f"dead:{site}"))
    return out


# -- flight events -----------------------------------------------------------
def check_flight_events(project: Project,
                        recorder_path: str = FLIGHT_RECORDER
                        ) -> List[Finding]:
    out: List[Finding] = []
    rec = project.file(recorder_path)
    if rec is None:
        return [Finding("flight-event", recorder_path, 0,
                        "flight recorder missing from scan",
                        detail="missing-module")]
    kinds = _set_literal(rec.tree, "EVENT_KINDS")
    if kinds is None:
        return [Finding("flight-event", recorder_path, 0,
                        "EVENT_KINDS set literal not found — the "
                        "event-kind catalog must stay statically "
                        "readable", detail="no-EVENT_KINDS")]
    used: Set[str] = set()
    for sf in project.files():
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr in _EVENT_METHODS):
                continue
            kind = _literal_str_arg(node)
            if kind is None:
                continue    # wrappers forward a variable; their
                # literal callers are collected instead
            used.add(kind)
            if kind not in kinds and not sf.suppressed(
                    "flight-event", node.lineno):
                out.append(Finding(
                    "flight-event", sf.rel, node.lineno,
                    f"flight event kind {kind!r} is not registered in "
                    f"{recorder_path}:EVENT_KINDS — postmortem "
                    "consumers grep by kind; register it (with the "
                    "DESIGN.md event taxonomy) before recording it",
                    detail=f"unknown:{kind}"))
    for kind in sorted(kinds - used):
        out.append(Finding(
            "flight-event", recorder_path, 0,
            f"event kind {kind!r} is registered in EVENT_KINDS but "
            "never recorded in the production tree — dead catalog "
            "entry", detail=f"dead:{kind}"))
    return out


# -- env vars ----------------------------------------------------------------
def _env_reads(tree: ast.AST) -> List[Tuple[str, int]]:
    """(name, line) for every DS_* environment read: os.getenv /
    os.environ.get / os.environ[...] / `"DS_X" in os.environ`."""
    reads: List[Tuple[str, int]] = []

    def _is_environ(node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute) and
                node.attr == "environ") or (
            isinstance(node, ast.Name) and node.id == "environ")

    for node in ast.walk(tree):
        name = None
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "getenv") \
                    or (isinstance(f, ast.Name) and f.id == "getenv"):
                name = _const_str(node.args[0]) if node.args else None
            elif isinstance(f, ast.Attribute) and f.attr == "get" and \
                    _is_environ(f.value):
                name = _const_str(node.args[0]) if node.args else None
        elif isinstance(node, ast.Subscript) and _is_environ(node.value):
            name = _const_str(node.slice)
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                _is_environ(node.comparators[0]):
            name = _const_str(node.left)
        if name and name.startswith("DS_"):
            reads.append((name, node.lineno))
    return reads


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def check_env_docs(project: Project,
                   doc_paths: Tuple[str, ...] = DOC_PATHS
                   ) -> List[Finding]:
    docs = "\n".join(project.doc(p) for p in doc_paths)
    #: word-boundary set of documented names: a raw substring test
    #: would let DS_WORKLOAD ride on DS_WORKLOAD_TRACE's documentation
    documented = set(re.findall(r"\bDS_[A-Z0-9_]+\b", docs))
    out: List[Finding] = []
    seen: Set[str] = set()
    for sf in project.files():
        for name, line in _env_reads(sf.tree):
            if name in seen:
                continue
            if name in documented:
                seen.add(name)
                continue
            if sf.suppressed("env-doc", line):
                seen.add(name)
                continue
            seen.add(name)
            out.append(Finding(
                "env-doc", sf.rel, line,
                f"environment variable {name} is read here but "
                f"documented in neither of {doc_paths} — an "
                "undocumented env knob is an unsupported one",
                detail=name))
    return out


# -- the absorbed metric lint ------------------------------------------------
def check_metric_catalog(project: Project) -> List[Finding]:
    from . import metrics_catalog
    try:
        errors = metrics_catalog.check(repo_root=project.root)
    except Exception as e:     # import failure IS a catalog failure
        return [Finding("metric-catalog",
                        "deepspeed_tpu/telemetry/metrics.py", 0,
                        f"metric catalog check failed to run: "
                        f"{type(e).__name__}: {e}",
                        detail=f"error:{type(e).__name__}")]
    return [Finding("metric-catalog",
                    "deepspeed_tpu/telemetry/metrics.py", 0, err,
                    detail=err.split(":")[0])
            for err in errors]


def run(project: Project) -> List[Finding]:
    out: List[Finding] = []
    out.extend(check_chaos_sites(project))
    out.extend(check_flight_events(project))
    out.extend(check_env_docs(project))
    out.extend(check_metric_catalog(project))
    return out
