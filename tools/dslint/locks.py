"""Pass 3 — lock discipline (rules ``telemetry-rlock``,
``lock-held-io``).

Two lock contracts, both paid for in review rounds (PR 5: a
non-reentrant lock reachable from the SIGTERM postmortem handler would
deadlock the dying process; PR 11: an HTTP fetch under the federation
registry lock serialized every scrape behind the network):

- ``telemetry-rlock``: the telemetry spine and the fault-injection
  registry may only mint ``threading.RLock()`` — any code path can be
  interrupted by the postmortem signal handler, which re-enters the
  same locks to dump state.
- ``lock-held-io``: no I/O (file ``open``, ``urlopen``, sockets,
  ``requests``) or blocking call (``time.sleep``, ``subprocess``,
  ``.join()`` on threads) may be *syntactically reachable* while a
  telemetry lock is held.  Reachability is the ``with <...lock>:``
  block body plus same-module helpers it calls (``self._foo()`` /
  module-level ``foo()``), transitively — the exact shape of the PR 11
  bug, where the fetch hid one call deep.

Intentional holders (the workload ledger's append-under-lock design)
carry ``# dslint: disable=lock-held-io -- <why>`` on the ``with``
header, which covers the block.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, List, Optional, Set, Tuple

from .core import (Finding, Project, SourceFile, register_rules,
                   root_name as _root_name)

register_rules("telemetry-rlock", "lock-held-io")

#: modules bound by the lock contracts (glob on repo-relative path)
LOCK_SCOPED_FILES = (
    "deepspeed_tpu/telemetry/*.py",
    "deepspeed_tpu/runtime/fault_injection.py",
)

#: blocking/I-O callables flagged under a held lock: (root, attr) with
#: None as wildcard
_BLOCKING_ATTRS = {
    ("time", "sleep"), (None, "urlopen"), (None, "urlretrieve"),
    ("socket", "socket"), ("socket", "create_connection"),
    ("requests", "get"), ("requests", "post"), ("requests", "request"),
    ("subprocess", "run"), ("subprocess", "Popen"),
    ("subprocess", "call"), ("subprocess", "check_call"),
    ("subprocess", "check_output"), ("os", "system"),
}
_BLOCKING_NAMES = {"open", "urlopen"}


def _in_scope(rel: str) -> bool:
    return any(fnmatch.fnmatch(rel, pat) for pat in LOCK_SCOPED_FILES)


def _blocking_reason(call: ast.Call) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Name) and func.id in _BLOCKING_NAMES:
        return f"{func.id}()"
    if isinstance(func, ast.Attribute):
        root = _root_name(func.value)
        for r, a in _BLOCKING_ATTRS:
            if func.attr == a and (r is None or r == root):
                return f"{root}.{func.attr}()" if root else \
                    f".{func.attr}()"
    return None


def _is_lock_expr(node: ast.AST) -> bool:
    """``with self._lock:`` / ``with _lock:`` / any name or attribute
    ending in 'lock'."""
    if isinstance(node, ast.Name):
        return node.id.endswith("lock")
    if isinstance(node, ast.Attribute):
        return node.attr.endswith("lock")
    return False


def _local_callables(sf: SourceFile) -> Dict[str, ast.AST]:
    """name -> FunctionDef for every function in the module (methods
    keyed by bare name: reachability is name-based, same-module)."""
    out: Dict[str, ast.AST] = {}
    for func in sf.functions():
        out.setdefault(func.name, func)
    return out


def _called_local_names(node: ast.AST) -> Set[str]:
    """Names of same-module callables invoked from ``node``:
    ``self._foo(...)`` and bare ``foo(...)``."""
    names: Set[str] = set()
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self":
            names.add(f.attr)
        elif isinstance(f, ast.Name):
            names.add(f.id)
    return names


def _scan_held_block(sf: SourceFile, with_node: ast.With,
                     local: Dict[str, ast.AST]) -> List[Finding]:
    """BFS from the with-body through same-module callees, flagging
    blocking calls anywhere reachable."""
    out: List[Finding] = []
    seen: Set[str] = set()
    #: (node to scan, via-chain description)
    queue: List[Tuple[ast.AST, str]] = [(stmt, "")
                                        for stmt in with_node.body]
    while queue:
        node, via = queue.pop(0)
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                reason = _blocking_reason(n)
                if reason is not None:
                    line = n.lineno
                    if sf.suppressed("lock-held-io", line) or \
                            sf.suppressed("lock-held-io",
                                          with_node.lineno):
                        continue
                    where = f" (via {via})" if via else ""
                    out.append(Finding(
                        "lock-held-io", sf.rel, line,
                        f"{reason} reachable while the lock taken at "
                        f"line {with_node.lineno} is held{where} — "
                        "stage I/O outside the critical section, or "
                        "suppress on the I/O line with a reason",
                        detail=f"{_ctx(sf, n)}:{reason}"))
        for name in sorted(_called_local_names(node)):
            if name in seen or name not in local:
                continue
            seen.add(name)
            queue.append((local[name],
                          f"{via} -> {name}()" if via else f"{name}()"))
    return out


def run(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for sf in project.files():
        if not _in_scope(sf.rel):
            continue
        local = _local_callables(sf)
        for node in ast.walk(sf.tree):
            # (a) RLock-only minting
            if isinstance(node, ast.Call):
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else \
                    getattr(f, "id", "")
                root = _root_name(f.value) if isinstance(
                    f, ast.Attribute) else None
                if name == "Lock" and root in (None, "threading"):
                    if not sf.suppressed("telemetry-rlock",
                                         node.lineno):
                        out.append(Finding(
                            "telemetry-rlock", sf.rel, node.lineno,
                            "threading.Lock() in a telemetry-scoped "
                            "module — the postmortem SIGTERM handler "
                            "re-enters these locks; use "
                            "threading.RLock()",
                            detail=f"Lock@{_ctx(sf, node)}"))
            # (b) I/O reachable under a held lock
            if isinstance(node, ast.With) and any(
                    _is_lock_expr(item.context_expr)
                    for item in node.items):
                out.extend(_scan_held_block(sf, node, local))
    return out


def _ctx(sf: SourceFile, node: ast.AST) -> str:
    """Enclosing function name for a stable baseline detail."""
    best = "<module>"
    for func in sf.functions():
        if func.lineno <= node.lineno <= getattr(func, "end_lineno",
                                                 func.lineno):
            best = func.name
    return best
