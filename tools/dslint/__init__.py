"""dslint — the repo-native static contract checker (ISSUE 15).

Five passes over the production tree, each encoding a written
contract; see docs/DESIGN.md "Static contracts" for the rule table.

    python -m tools.dslint [--strict] [--only RULES] [--skip RULES]

Library entry point: :func:`run_all` -> :class:`~tools.dslint.core.Report`.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from .core import (DEFAULT_BASELINE, RULE_IDS, Finding,  # noqa: F401
                   Project, Report, SourceFile, apply_baseline,
                   load_baseline)
from . import hotpath, config_parity, locks, disabled_path, catalog

#: name -> pass entry point, in report order.  Importing the modules
#: above also registers every rule id, so suppression validation in
#: core sees the full vocabulary before any file parses.
PASSES: Dict[str, Callable[[Project], List[Finding]]] = {
    "hotpath": hotpath.run,
    "config-parity": config_parity.run,
    "locks": locks.run,
    "disabled-path": disabled_path.run,
    "catalog": catalog.run,
}

#: rule id -> owning pass name (for --only/--skip by rule id)
RULE_TO_PASS: Dict[str, str] = {
    "hot-path-sync": "hotpath", "hot-path-d2h-shape": "hotpath",
    "hot-path-missing": "hotpath",
    "config-parity": "config-parity",
    "telemetry-rlock": "locks", "lock-held-io": "locks",
    "disabled-path-guard": "disabled-path",
    "metric-catalog": "catalog", "chaos-site": "catalog",
    "flight-event": "catalog", "env-doc": "catalog",
}


def _select(only: Optional[Sequence[str]],
            skip: Optional[Sequence[str]]) -> List[str]:
    names = list(PASSES)
    alias = dict(RULE_TO_PASS)
    if only:
        wanted = {alias.get(n, n) for n in only}
        names = [n for n in names if n in wanted]
    if skip:
        dropped = {alias.get(n, n) for n in skip}
        names = [n for n in names if n not in dropped]
    return names


def run_all(root: Optional[str] = None,
            baseline_path: Optional[str] = None,
            only: Optional[Sequence[str]] = None,
            skip: Optional[Sequence[str]] = None) -> Report:
    """Run the selected passes and fold in the baseline.  ``root``
    defaults to the repo; ``baseline_path=''`` disables the baseline
    entirely (every finding reports as new)."""
    import os
    from .core import REPO_ROOT
    root = root or REPO_ROOT
    project = Project(root)
    findings: List[Finding] = list(project.parse_findings)
    for sf in project.files():
        findings.extend(sf.comment_findings)
    for name in _select(only, skip):
        findings.extend(PASSES[name](project))

    if baseline_path is None:
        baseline_path = os.path.join(root, DEFAULT_BASELINE)
    # dedup: one I/O line reachable from several lock blocks (or one
    # defect seen by overlapping sub-checks) reports once
    seen = set()
    findings = [f for f in findings
                if (k := (f.rule, f.path, f.line, f.detail)) not in seen
                and not seen.add(k)]

    entries, errors = ([], []) if baseline_path == "" else \
        load_baseline(baseline_path)
    new, old, stale = apply_baseline(findings, entries)
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(findings=new, baselined=old, stale_baseline=stale,
                  baseline_errors=errors)
