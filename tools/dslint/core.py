"""dslint core: the repo-native static contract checker's framework
(ISSUE 15).

The serving stack's load-bearing invariants — one compiled program +
token-sized d2h, mirrored config blocks, RLock-only telemetry, <5µs
disabled paths, closed metric/chaos/event/env catalogs — were enforced
by prose and review until now.  dslint turns each written contract into
an AST pass over the production tree so a contract break fails CI
instead of shipping.

Vocabulary (parsed from ``# dslint:`` comments, found via
:mod:`tokenize` so string literals can't false-trigger):

- ``# dslint: disable=<rule>[,<rule>...] -- <reason>`` — suppress the
  named rules on this line; placed on a compound statement's header
  line (``with``/``for``/``if``/``def``) it covers the whole block.
  The reason string is REQUIRED: a bare disable is itself a finding
  (rule ``bare-suppression``), as is disabling an unknown rule.
- ``# dslint: hot-path`` — marks a serving hot-path function (on the
  ``def`` line or the line above): the hot-path pass lints its body
  for host syncs.
- ``# dslint: disabled-path`` — marks a function documented "<5µs
  disabled": the disabled-path pass checks its guard shape.
- ``# dslint: d2h <shape>`` — declares an intentional device→host
  transfer on this line (e.g. ``[S] int32``); the hot-path pass allows
  it only when ``<shape>`` appears in docs/DESIGN.md's transfer
  contract.

Baseline file (``tools/dslint/baseline.json``): grandfathered findings
carried as ``{"rule", "path", "detail", "reason"}`` records (matched on
the first three; ``reason`` is required — the baseline is a debt
ledger, not a mute button).  ``--strict`` also fails on stale entries
so the ledger can only shrink.  Empty at merge.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: the production tree dslint walks (tests are deliberately excluded —
#: contracts bind shipped code; tools/dslint itself is excluded so the
#: linter's own pattern tables stay out of its jurisdiction)
SCAN_ROOTS = ("deepspeed_tpu", "tools", "bench.py")
EXCLUDE_DIRS = ("__pycache__", os.path.join("tools", "dslint"))

#: every rule id a ``disable=`` may name (passes register theirs at
#: import; the two framework rules are always present)
RULE_IDS: Set[str] = {"bare-suppression", "parse-error"}

DEFAULT_BASELINE = os.path.join("tools", "dslint", "baseline.json")

_TAG_RE = re.compile(r"dslint:\s*(?P<body>.+?)\s*$")
_DISABLE_RE = re.compile(
    r"^disable=(?P<rules>[a-z0-9-]+(?:\s*,\s*[a-z0-9-]+)*)"
    r"(?:\s+--\s+(?P<reason>.+?))?$")


def register_rules(*ids: str) -> None:
    """Pass modules declare their rule ids so suppressions validate."""
    RULE_IDS.update(ids)


def root_name(node: ast.AST) -> Optional[str]:
    """The base Name of a dotted call/attr/subscript chain
    (``jnp.sum(x)[0]`` -> ``jnp``), or None — shared by the hot-path
    and lock passes so their idea of a call's root can't drift."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = node.func if isinstance(node, ast.Call) else node.value
    return node.id if isinstance(node, ast.Name) else None


@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation.  ``detail`` is the line-number-free
    component of the baseline key, so a finding keeps matching its
    baseline entry across unrelated edits to the same file."""
    rule: str
    path: str           # repo-relative, forward slashes
    line: int           # 1-based; 0 = file- or project-scope
    message: str
    detail: str = ""

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.detail or self.message)

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class _Suppression:
    line: int           # the comment's line
    end: int            # last line it covers (inclusive)
    rules: Set[str]
    reason: Optional[str]


@dataclasses.dataclass
class _Annotation:
    line: int
    kind: str           # "hot-path" | "disabled-path" | "d2h"
    arg: str            # d2h shape text, "" otherwise
    end: int = 0        # statement coverage for d2h (inclusive)


class SourceFile:
    """One parsed production file: AST + raw lines + dslint comments."""

    def __init__(self, rel: str, text: str):
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)     # caller handles SyntaxError
        self.suppressions: List[_Suppression] = []
        self.annotations: List[_Annotation] = []
        self.comment_findings: List[Finding] = []
        self._stmt_span: Dict[int, int] = {}   # lineno -> end_lineno
        for node in ast.walk(self.tree):
            if isinstance(node, ast.stmt) and hasattr(node, "end_lineno"):
                # widest statement starting on this line wins
                prev = self._stmt_span.get(node.lineno, 0)
                self._stmt_span[node.lineno] = max(prev, node.end_lineno)
        self._parse_comments()

    # -- comment vocabulary --------------------------------------------------
    def _parse_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            comments = [(t.start[0], t.string) for t in toks
                        if t.type == tokenize.COMMENT]
        except tokenize.TokenError:
            comments = []
        for line, comment in comments:
            m = _TAG_RE.search(comment)
            if not m:
                continue
            body = m.group("body")
            if body.startswith("disable="):
                dm = _DISABLE_RE.match(body)
                if not dm:
                    self.comment_findings.append(Finding(
                        "bare-suppression", self.rel, line,
                        f"malformed dslint disable comment: {body!r} "
                        "(want: disable=<rule>[,<rule>] -- <reason>)",
                        detail=body))
                    continue
                rules = {r.strip() for r in dm.group("rules").split(",")}
                reason = dm.group("reason")
                unknown = rules - RULE_IDS
                if unknown:
                    self.comment_findings.append(Finding(
                        "bare-suppression", self.rel, line,
                        f"dslint disable names unknown rule(s) "
                        f"{sorted(unknown)} (known: {sorted(RULE_IDS)})",
                        detail=f"unknown:{','.join(sorted(unknown))}"))
                if not reason or not reason.strip():
                    self.comment_findings.append(Finding(
                        "bare-suppression", self.rel, line,
                        "dslint disable without a reason — suppressions "
                        "must say why ('disable=<rule> -- <reason>')",
                        detail=f"bare:{','.join(sorted(rules))}"))
                    continue    # a bare disable does not suppress
                self.suppressions.append(_Suppression(
                    line, self._coverage_end(line), rules & RULE_IDS,
                    reason.strip()))
            elif body == "hot-path" or body == "disabled-path":
                self.annotations.append(_Annotation(line, body, ""))
            elif body.startswith("d2h"):
                shape = body[len("d2h"):].strip()
                self.annotations.append(_Annotation(
                    line, "d2h", shape, end=self._coverage_end(line)))
            # unknown tags are ignored: forward compatibility with
            # newer vocab in older checkouts

    def _coverage_end(self, line: int) -> int:
        """A tag on a statement's first line covers the statement's
        whole span (so one disable on a ``with``/``for`` header covers
        the block); on a comment-only line it skips any further
        comment lines and covers the NEXT statement's span."""
        end = self._stmt_span.get(line)
        if end:
            return end
        stripped = (self.lines[line - 1].lstrip()
                    if line - 1 < len(self.lines) else "")
        if not stripped.startswith("#"):
            return line
        nxt = line + 1
        while nxt - 1 < len(self.lines) and (
                not self.lines[nxt - 1].strip()
                or self.lines[nxt - 1].lstrip().startswith("#")):
            nxt += 1
        return self._stmt_span.get(nxt, nxt)

    # -- queries -------------------------------------------------------------
    def suppressed(self, rule: str, line: int) -> bool:
        return any(rule in s.rules and s.line <= line <= s.end
                   for s in self.suppressions)

    def func_annotated(self, func: ast.AST, kind: str) -> bool:
        """Whether a FunctionDef carries ``# dslint: <kind>`` on its
        ``def`` line, the line above it, or the line above its first
        decorator."""
        candidates = {func.lineno, func.lineno - 1}
        if getattr(func, "decorator_list", None):
            candidates.add(func.decorator_list[0].lineno - 1)
        return any(a.kind == kind and a.line in candidates
                   for a in self.annotations)

    def d2h_annotation(self, line: int) -> Optional[str]:
        """The declared d2h shape covering ``line``, or None."""
        for a in self.annotations:
            if a.kind == "d2h" and a.line <= line <= (a.end or a.line):
                return a.arg
        return None

    def functions(self) -> Iterable[ast.AST]:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node


class Project:
    """The scanned production tree plus doc files, shared by all
    passes.  ``root`` defaults to the repo; tests point it at fixture
    trees (every pass must work on an arbitrary root)."""

    def __init__(self, root: str = REPO_ROOT,
                 scan_roots: Sequence[str] = SCAN_ROOTS):
        self.root = root
        self.scan_roots = tuple(scan_roots)
        self._files: Dict[str, SourceFile] = {}
        self._docs: Dict[str, str] = {}
        self.parse_findings: List[Finding] = []
        self._load()

    def _load(self) -> None:
        paths: List[str] = []
        for sr in self.scan_roots:
            full = os.path.join(self.root, sr)
            if os.path.isfile(full):
                paths.append(sr)
                continue
            for dirpath, dirs, files in os.walk(full):
                rel_dir = os.path.relpath(dirpath, self.root)
                if any(x in rel_dir for x in EXCLUDE_DIRS):
                    continue
                for name in sorted(files):
                    if name.endswith(".py"):
                        paths.append(os.path.normpath(
                            os.path.join(rel_dir, name)))
        for rel in sorted(set(paths)):
            try:
                with open(os.path.join(self.root, rel),
                          encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                continue
            try:
                self._files[rel.replace(os.sep, "/")] = SourceFile(rel,
                                                                   text)
            except SyntaxError as e:
                self.parse_findings.append(Finding(
                    "parse-error", rel.replace(os.sep, "/"),
                    getattr(e, "lineno", 0) or 0,
                    f"cannot parse: {e.msg}", detail=str(e.msg)))

    def files(self) -> List[SourceFile]:
        return list(self._files.values())

    def file(self, rel: str) -> Optional[SourceFile]:
        return self._files.get(rel)

    def doc(self, rel: str) -> str:
        """A doc file's text ("" when absent), cached."""
        if rel not in self._docs:
            try:
                with open(os.path.join(self.root, rel),
                          encoding="utf-8") as f:
                    self._docs[rel] = f.read()
            except OSError:
                self._docs[rel] = ""
        return self._docs[rel]


# -- baseline ----------------------------------------------------------------
def load_baseline(path: str) -> Tuple[List[dict], List[str]]:
    """Parse the baseline file -> (entries, format errors)."""
    if not os.path.exists(path):
        return [], []
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [], [f"{path}: unreadable baseline: {e}"]
    errors = []
    entries = doc.get("findings", []) if isinstance(doc, dict) else []
    if not isinstance(entries, list):
        return [], [f"{path}: 'findings' must be a list"]
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or not all(
                isinstance(e.get(k), str) and e.get(k)
                for k in ("rule", "path", "detail", "reason")):
            errors.append(
                f"{path}: findings[{i}] must carry non-empty string "
                "rule/path/detail/reason fields")
    return entries, errors


def apply_baseline(findings: List[Finding], entries: List[dict]
                   ) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """-> (new findings, baselined findings, stale entries)."""
    index = {(e.get("rule"), e.get("path"), e.get("detail")): e
             for e in entries}
    new, old, hit = [], [], set()
    for f in findings:
        e = index.get(f.key)
        if e is None:
            new.append(f)
        else:
            old.append(f)
            hit.add(f.key)
    stale = [e for k, e in index.items() if k not in hit]
    return new, old, stale


@dataclasses.dataclass
class Report:
    findings: List[Finding]             # unsuppressed, not baselined
    baselined: List[Finding]
    stale_baseline: List[dict]
    baseline_errors: List[str]

    @property
    def clean(self) -> bool:
        return not self.findings and not self.baseline_errors
