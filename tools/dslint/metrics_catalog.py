"""Metric-namespace catalog (absorbed ``tools/check_metrics.py``,
ISSUE 4 naming/docs lint + ISSUE 9 dead-metric pass; ISSUE 15 moved
the implementation here so it is one dslint rule among many —
``tools/check_metrics.py`` remains as a thin CLI shim over this
module).

Asserts that every metric registered in the telemetry registry

- matches the ``ds_<area>_<name>`` naming convention with a known area
  (counters additionally end in ``_total``),
- is documented in docs/DESIGN.md's "Telemetry" metric table, and
- is actually RECORDED somewhere in the production tree (a
  ``.inc(`` / ``.observe(`` / ``.set(`` / ``.bind(`` on the minted
  object outside ``telemetry/metrics.py``) — a metric minted but never
  fed is a dead series that scrapes as a forever-zero and rots the
  dashboard.

Unlike the pure-AST passes this one imports the live registry (the
catalog is the process's metric namespace, not a source artifact), so
it carries the telemetry import cost — CI pays it once.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

AREAS = ("serving", "comm", "kv", "train", "fastgen", "chaos",
         "fleet", "slo", "telemetry", "pool", "disagg", "journey",
         "mem")
NAME_RE = re.compile(
    r"^ds_(%s)_[a-z][a-z0-9_]*$" % "|".join(AREAS))

#: where metric objects are minted — excluded from the recording scan
CATALOG = os.path.join("deepspeed_tpu", "telemetry", "metrics.py")
#: the production tree the recording scan walks (tests are deliberately
#: excluded: a metric recorded only by its test is still dead)
SCAN_ROOTS = ("deepspeed_tpu", "tools", "bench.py")
#: a minted identifier counts as recorded when one of these is called
#: on it anywhere in the scanned tree
RECORD_METHODS = ("inc", "observe", "set", "bind")


def _minted_identifiers(repo_root: str,
                        catalog: str = None) -> Dict[str, str]:
    """{metric name: python identifier} parsed from the catalog."""
    path = os.path.join(repo_root, catalog or CATALOG)
    with open(path) as f:
        src = f.read()
    out: Dict[str, str] = {}
    for m in re.finditer(
            r"^(?P<ident>[A-Z][A-Z0-9_]*) = registry\.\w+\(\s*\n?\s*"
            r"\"(?P<name>ds_[a-z0-9_]+)\"", src, re.MULTILINE):
        out[m.group("name")] = m.group("ident")
    return out


def _scan_recordings(repo_root: str, catalog: str = None) -> str:
    """Concatenated source of every production .py file outside the
    catalog (one pass; the per-metric check is a regex over it)."""
    chunks: List[str] = []
    for root in SCAN_ROOTS:
        full = os.path.join(repo_root, root)
        if os.path.isfile(full):
            with open(full) as f:
                chunks.append(f.read())
            continue
        for dirpath, _dirs, files in os.walk(full):
            if "__pycache__" in dirpath:
                continue
            for name in files:
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                if path.endswith(catalog or CATALOG):
                    continue
                with open(path) as f:
                    chunks.append(f.read())
    return "\n".join(chunks)


def check(design_path: str = None,
          repo_root: str = REPO_ROOT,
          catalog: str = None) -> List[str]:
    """Return a list of lint errors (empty = clean).  The string
    messages are the stable interface ``tools/check_metrics.py`` and
    tests/test_telemetry.py consume."""
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from deepspeed_tpu.telemetry import Counter, get_registry
    from deepspeed_tpu.telemetry import metrics  # noqa: F401 — mint catalog

    if design_path is None:
        design_path = os.path.join(repo_root, "docs", "DESIGN.md")
    with open(design_path) as f:
        design = f.read()

    errors = []
    registered = get_registry().all_metrics()
    if not registered:
        errors.append("no metrics registered — catalog import broken?")
    idents = _minted_identifiers(repo_root, catalog)
    source = _scan_recordings(repo_root, catalog)
    for name, metric in sorted(registered.items()):
        if not NAME_RE.match(name):
            errors.append(
                f"{name}: does not match ds_<area>_<name> "
                f"(area in {AREAS}, lowercase [a-z0-9_])")
        if isinstance(metric, Counter) and not name.endswith("_total"):
            errors.append(f"{name}: counters must end in _total")
        if f"`{name}`" not in design:
            errors.append(
                f"{name}: not documented in docs/DESIGN.md "
                "(add a row to the Telemetry metric table)")
        if not metric.help:
            errors.append(f"{name}: registered without help text")
        # dead-metric pass (ISSUE 9): minted in the catalog but never
        # fed anywhere in the production tree.  Metrics registered
        # OUTSIDE the catalog (tests minting throwaways) are skipped —
        # the naming/docs lints above already police them.
        ident = idents.get(name)
        if ident is not None and not re.search(
                r"\b%s\s*\.\s*(%s)\s*\(" % (ident,
                                            "|".join(RECORD_METHODS)),
                source):
            errors.append(
                f"{name}: dead metric — minted as {ident} in "
                f"{catalog or CATALOG} but never recorded "
                f"(.{'/.'.join(RECORD_METHODS)}) anywhere in "
                f"{SCAN_ROOTS}")
    return errors
