"""Pass 2 — config parity (rule ``config-parity``).

The engine has two config surfaces: the training-side pydantic models
in ``runtime/config.py`` and the serving-side dataclasses in
``inference/v2/config.py``.  Three blocks are mirrored by hand every
PR — ``serving_optimization``, ``telemetry``, ``fault_injection`` —
and a field added to one but not the other silently becomes a knob
that half the stack ignores.  This pass compares the mirrored classes
structurally (pure AST, no imports):

- field SETS must match (modulo a per-pair allowed-extra set: the
  runtime ``ServingOptimizationConfig.enabled`` master escape hatch is
  consumed by ``from_dict`` rather than mirrored),
- field DEFAULTS must match (``Field(default_factory=X)`` and
  ``dataclasses.field(default_factory=X)`` normalize to the same
  spelling),
- every runtime ``ServingOptimizationConfig`` field must survive
  ``to_v2_dict`` (key present, value ``self.<same name>``) — the
  bridge every serving engine build rides.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, Project, register_rules

register_rules("config-parity")

RUNTIME_CONFIG = "deepspeed_tpu/runtime/config.py"
V2_CONFIG = "deepspeed_tpu/inference/v2/config.py"

#: (class name, extras allowed on the runtime side, extras allowed on
#: the v2 side)
PAIRS: Tuple[Tuple[str, frozenset, frozenset], ...] = (
    # `enabled` is the master escape hatch: from_dict consumes it to
    # flip the per-flag defaults, it is not a mirrored field
    ("ServingOptimizationConfig", frozenset({"enabled"}), frozenset()),
    ("TelemetryConfig", frozenset(), frozenset()),
    ("FaultInjectionConfig", frozenset(), frozenset()),
)


def _normalize_default(node: Optional[ast.expr]) -> str:
    """Comparable spelling of a field default: factory calls collapse
    to ``factory:<fn>`` whether spelled ``Field(default_factory=X)``
    or ``dataclasses.field(default_factory=X)``."""
    if node is None:
        return "<required>"
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else \
            getattr(fn, "id", "")
        if name in ("Field", "field"):
            for kw in node.keywords:
                if kw.arg == "default_factory":
                    return f"factory:{ast.unparse(kw.value)}"
            if node.args:
                return ast.unparse(node.args[0])
            return "<field()>"
    return ast.unparse(node)


def class_fields(tree: ast.AST, cls_name: str
                 ) -> Optional[Dict[str, str]]:
    """{field: normalized default} of a class's annotated assignments
    (the shape both pydantic models and dataclasses share); None when
    the class is absent."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            fields: Dict[str, str] = {}
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name) and \
                        not stmt.target.id.startswith("_"):
                    fields[stmt.target.id] = _normalize_default(
                        stmt.value)
            return fields
    return None


def to_v2_dict_keys(tree: ast.AST, cls_name: str
                    ) -> Optional[Dict[str, str]]:
    """{key: value source} of the dict literal ``to_v2_dict`` returns,
    or None when class/method/dict-literal-return is absent."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef) and node.name == cls_name):
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.FunctionDef) and \
                    stmt.name == "to_v2_dict":
                for ret in ast.walk(stmt):
                    if isinstance(ret, ast.Return) and \
                            isinstance(ret.value, ast.Dict):
                        out = {}
                        for k, v in zip(ret.value.keys,
                                        ret.value.values):
                            if isinstance(k, ast.Constant) and \
                                    isinstance(k.value, str):
                                out[k.value] = ast.unparse(v)
                        return out
    return None


def compare_pair(tree_a: ast.AST, tree_b: ast.AST, cls: str,
                 extra_a: frozenset, extra_b: frozenset,
                 path_a: str, path_b: str) -> List[Finding]:
    """Parity findings for one mirrored class (exposed for the
    seeded-violation tests)."""
    out: List[Finding] = []
    fa = class_fields(tree_a, cls)
    fb = class_fields(tree_b, cls)
    if fa is None:
        return [Finding("config-parity", path_a, 0,
                        f"mirrored class {cls} not found",
                        detail=f"{cls}:missing-class")]
    if fb is None:
        return [Finding("config-parity", path_b, 0,
                        f"mirrored class {cls} not found",
                        detail=f"{cls}:missing-class")]
    for name in sorted(set(fa) - set(fb) - extra_a):
        out.append(Finding(
            "config-parity", path_b, 0,
            f"{cls}.{name} exists in {path_a} but not here — mirror "
            "the field (same name, same default) or allow it "
            "explicitly in tools/dslint/config_parity.py:PAIRS",
            detail=f"{cls}.{name}:missing"))
    for name in sorted(set(fb) - set(fa) - extra_b):
        out.append(Finding(
            "config-parity", path_a, 0,
            f"{cls}.{name} exists in {path_b} but not here — mirror "
            "the field (same name, same default) or allow it "
            "explicitly in tools/dslint/config_parity.py:PAIRS",
            detail=f"{cls}.{name}:missing"))
    for name in sorted(set(fa) & set(fb)):
        if fa[name] != fb[name]:
            out.append(Finding(
                "config-parity", path_b, 0,
                f"{cls}.{name} default drift: {path_a} has "
                f"{fa[name]!r}, {path_b} has {fb[name]!r}",
                detail=f"{cls}.{name}:default"))
    return out


def check_to_v2_dict(tree: ast.AST, cls: str, path: str
                     ) -> List[Finding]:
    out: List[Finding] = []
    fields = class_fields(tree, cls)
    keys = to_v2_dict_keys(tree, cls)
    if fields is None:
        return out      # compare_pair already reported it
    if keys is None:
        return [Finding(
            "config-parity", path, 0,
            f"{cls}.to_v2_dict must return a dict literal the parity "
            "pass can read", detail=f"{cls}:to_v2_dict-shape")]
    for name in sorted(set(fields) - set(keys)):
        out.append(Finding(
            "config-parity", path, 0,
            f"{cls}.{name} does not survive to_v2_dict — the serving "
            "engine build would silently drop it",
            detail=f"{cls}.{name}:to_v2_dict"))
    for name in sorted(set(keys) - set(fields)):
        out.append(Finding(
            "config-parity", path, 0,
            f"to_v2_dict emits {name!r} which is not a {cls} field",
            detail=f"{cls}.{name}:to_v2_dict-extra"))
    for name in sorted(set(keys) & set(fields)):
        if keys[name] != f"self.{name}":
            out.append(Finding(
                "config-parity", path, 0,
                f"to_v2_dict[{name!r}] is {keys[name]} (expected "
                f"self.{name}) — a cross-wired key survives the "
                "field-set check but ships the wrong value",
                detail=f"{cls}.{name}:to_v2_dict-value"))
    return out


def run(project: Project) -> List[Finding]:
    sfa = project.file(RUNTIME_CONFIG)
    sfb = project.file(V2_CONFIG)
    if sfa is None or sfb is None:
        missing = RUNTIME_CONFIG if sfa is None else V2_CONFIG
        return [Finding("config-parity", missing, 0,
                        "config module missing from scan",
                        detail="missing-module")]
    out: List[Finding] = []
    for cls, extra_a, extra_b in PAIRS:
        out.extend(compare_pair(sfa.tree, sfb.tree, cls, extra_a,
                                extra_b, sfa.rel, sfb.rel))
    out.extend(check_to_v2_dict(sfa.tree, "ServingOptimizationConfig",
                                sfa.rel))
    return [f for f in out
            if not _suppressed(project, f)]


def _suppressed(project: Project, f: Finding) -> bool:
    sf = project.file(f.path)
    return sf is not None and f.line and sf.suppressed(f.rule, f.line)
