"""Pass 1 — hot-path d2h/sync lint (rules ``hot-path-sync``,
``hot-path-d2h-shape``, ``hot-path-missing``).

The PR 2 serving contract: one scheduler step = ONE compiled device
program + ONE token-sized device→host transfer.  This pass verifies it
instead of asserting it:

- Functions marked ``# dslint: hot-path`` (scheduler dispatch/drain,
  ``model._*_step_impl``, engine commit) may not contain host-sync
  constructs: ``np.asarray``/``np.array`` on non-literal arguments,
  ``.item()``/``.tolist()``/``.block_until_ready()``,
  ``jax.device_get``, or ``float()``/``int()``/``bool()`` forcing a
  ``jnp``/``jax`` computation or a ``*_dev`` value to the host.
- The ONLY exceptions are lines carrying a structured
  ``# dslint: d2h <shape>`` annotation (the promoted form of the old
  ``# the ONLY d2h`` comments) whose shape appears verbatim in
  docs/DESIGN.md's transfer contract — so the allowlist itself is
  cross-checked against the documented contract, and an undocumented
  shape cannot be waved through.
- Coverage is closed both ways: every function matching the
  REQUIRED_HOT_PATHS table must carry the annotation (a new
  ``_*_step_impl`` cannot silently opt out), and a table entry that no
  longer matches any function fails too (a rename must update the
  table, keeping it honest).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from .core import (Finding, Project, SourceFile, register_rules,
                   root_name as _root_name)

register_rules("hot-path-sync", "hot-path-d2h-shape", "hot-path-missing")

#: (file, function-name regex): every match must be hot-path annotated
REQUIRED_HOT_PATHS: Tuple[Tuple[str, str], ...] = (
    ("deepspeed_tpu/inference/v2/scheduler.py",
     r"^(_drain_impl|_step_impl|_dispatch_chain|_dispatch_spec"
     r"|_dispatch_draft_spec)$"),
    ("deepspeed_tpu/inference/v2/model.py",
     r"^(_\w*step_impl|_assemble_logits)$"),
    ("deepspeed_tpu/inference/v2/engine.py",
     r"^(_commit_batch|commit_spec)$"),
)

DESIGN_PATH = "docs/DESIGN.md"
#: shapes validate against THIS section when present (a shape string
#: appearing in unrelated prose must not legitimize a transfer);
#: docs without the section (fixtures) fall back to the whole text
CONTRACT_HEADING = "### The transfer contract"

#: builtin casts that force a device value to the host when applied to
#: a fresh jax computation
_CASTS = {"float", "int", "bool"}
#: host-func roots whose results are never device values (keeps
#: ``int(getattr(...))``-style code out of the cast check)
_DEVICE_ROOTS = {"jnp", "jax"}


def _is_dev_expr(node: ast.AST) -> bool:
    """Names/attributes following the ``*_dev`` device-value naming
    convention (``tokens_dev``, ``out_dev``)."""
    if isinstance(node, ast.Name):
        return node.id.endswith("_dev")
    if isinstance(node, ast.Attribute):
        return node.attr.endswith("_dev")
    return False


def _sync_reason(call: ast.Call) -> Optional[str]:
    """Why this call is a host sync, or None."""
    func = call.func
    if isinstance(func, ast.Attribute):
        root = _root_name(func.value)
        if func.attr in ("asarray", "array") and root in ("np", "numpy"):
            arg = call.args[0] if call.args else None
            if arg is None or isinstance(
                    arg, (ast.List, ast.Tuple, ast.Constant)):
                return None     # host-literal construction, not a sync
            return f"np.{func.attr}() on a potentially device value"
        if func.attr in ("item", "tolist") and not call.args:
            return f".{func.attr}() host sync"
        if func.attr == "block_until_ready":
            return ".block_until_ready() host sync"
        if func.attr == "device_get" and root == "jax":
            return "jax.device_get() host sync"
        return None
    if isinstance(func, ast.Name) and func.id in _CASTS \
            and len(call.args) == 1:
        arg = call.args[0]
        if isinstance(arg, ast.Call) and _root_name(arg) in _DEVICE_ROOTS:
            return (f"{func.id}() forces a {_root_name(arg)} "
                    "computation to the host")
        if _is_dev_expr(arg):
            return f"{func.id}() on a device value"
    return None


def contract_text(design: str) -> str:
    """The transfer-contract section of the design doc (up to the next
    heading), or the whole text when the heading is absent."""
    start = design.find(CONTRACT_HEADING)
    if start < 0:
        return design
    m = re.search(r"\n#{2,3} ", design[start + len(CONTRACT_HEADING):])
    end = start + len(CONTRACT_HEADING) + (m.start() if m
                                           else len(design))
    return design[start:end]


def _lint_function(sf: SourceFile, func: ast.AST, design: str
                   ) -> List[Finding]:
    out: List[Finding] = []
    qual = func.name
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        reason = _sync_reason(node)
        if reason is None:
            continue
        line = node.lineno
        shape = sf.d2h_annotation(line)
        snippet = (sf.lines[line - 1].split("#")[0].strip()
                   if line - 1 < len(sf.lines) else "")
        if shape is not None:
            # declared transfer: allowed iff the shape is part of the
            # documented contract
            if shape and shape in design:
                continue
            out.append(Finding(
                "hot-path-d2h-shape", sf.rel, line,
                f"declared d2h shape {shape!r} in {qual}() is not in "
                f"the {DESIGN_PATH} transfer contract — token-sized "
                "transfers must be documented before they ship",
                detail=f"{qual}:{shape}"))
            continue
        if sf.suppressed("hot-path-sync", line):
            continue
        out.append(Finding(
            "hot-path-sync", sf.rel, line,
            f"host sync in hot path {qual}(): {reason} "
            f"[`{snippet}`] — annotate an intentional token-sized "
            "transfer with '# dslint: d2h <shape>' or suppress with "
            "a reason",
            detail=f"{qual}:{snippet}"))
    return out


def run(project: Project,
        required=REQUIRED_HOT_PATHS,
        design_path: str = DESIGN_PATH) -> List[Finding]:
    findings: List[Finding] = []
    design = contract_text(project.doc(design_path))

    # coverage: the contract functions must be annotated
    for rel, pattern in required:
        sf = project.file(rel)
        if sf is None:
            findings.append(Finding(
                "hot-path-missing", rel, 0,
                f"hot-path contract file missing from the scan "
                f"(expected functions matching {pattern!r})",
                detail=f"file:{pattern}"))
            continue
        rx = re.compile(pattern)
        matched = False
        for func in sf.functions():
            if not rx.match(func.name):
                continue
            matched = True
            if not sf.func_annotated(func, "hot-path") \
                    and not sf.suppressed("hot-path-missing",
                                          func.lineno):
                findings.append(Finding(
                    "hot-path-missing", sf.rel, func.lineno,
                    f"{func.name}() matches the serving hot-path "
                    f"contract ({pattern!r}) but is not annotated "
                    "'# dslint: hot-path' — the d2h lint cannot see "
                    "it",
                    detail=func.name))
        if not matched:
            findings.append(Finding(
                "hot-path-missing", sf.rel, 0,
                f"no function matches hot-path contract {pattern!r} — "
                "renamed hot paths must update "
                "tools/dslint/hotpath.py:REQUIRED_HOT_PATHS",
                detail=f"none:{pattern}"))

    # the lint itself: every annotated function, required or not
    for sf in project.files():
        for func in sf.functions():
            if sf.func_annotated(func, "hot-path"):
                findings.extend(_lint_function(sf, func, design))
    return findings
