#!/usr/bin/env python
"""Sharded-serving bench leg (ISSUE 18): tp=1 vs tp=N fp vs tp=N int8.

Three debug engines serve the same shared-prefix workload (greedy AND
keyed-sampled rows) on a simulated ``--xla_force_host_platform_device_
count`` mesh: the unsharded baseline, the tp-way sharded engine with
the GSPMD fp logits all-gather, and the tp-way engine with the int8
block-scaled in-program collective.  The leg emits, per arm, measured
decode tok/s over a warmed pass, tokenwise parity against the tp=1
baseline, the analytic collective wire bytes alongside what the same
dispatches would have moved at fp, and the on-path compile count of
the measured pass (must be 0 — warmup covers the key set).

check_bench's ``shard_findings`` gates on: the fp arm tokenwise
identical to tp=1 on EVERY row (sampled included), the int8 arm
tokenwise identical on the greedy rows (a keyed draw thresholds on
exact logit values, so the bounded int8 error may legitimately flip a
sampled token — the sampled-row agreement is reported as a rate), int8
wire bytes STRICTLY below fp wire bytes, and zero on-path compiles.
Numbers are CPU-debug-relative — the simulated
mesh times shard arithmetic on host cores, so tok/s across arms is a
sanity band, not a speedup claim; the wire-byte ratio is exact.

bench.py's jax is already initialized single-device by the time the
BENCH_SHARD leg runs, so ``run_shard_bench`` re-execs this file as a
``--worker`` subprocess with the forced device count in XLA_FLAGS and
reads one JSON object from its stdout.

Usage::

    BENCH_SHARD=1 python bench.py          # as a bench leg
    python tools/shard_bench.py            # standalone (spawns worker)
    python tools/shard_bench.py --worker   # in a forced-mesh process
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def run_shard_bench() -> Dict[str, Any]:
    """Spawn the forced-mesh worker and return its ``fastgen_shard_*``
    metrics.  A subprocess is not optional: the host device count is
    read once at jax import, and the parent bench process imported jax
    long ago with the default single device."""
    tp = max(2, int(os.environ.get("BENCH_SHARD_TP", "2")))
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={tp}")
    env.setdefault("JAX_PLATFORMS", "cpu")
    budget = float(os.environ.get("BENCH_SHARD_TIMEOUT", "600"))
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker"],
        stdout=subprocess.PIPE, stderr=sys.stderr, env=env, text=True,
        timeout=budget)
    if proc.returncode != 0:
        raise RuntimeError(
            f"shard bench worker exited {proc.returncode}")
    # the worker prints exactly one JSON object as its last line
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _worker() -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax.core import meta as flax_meta

    from deepspeed_tpu.inference.v2 import (
        InferenceEngineV2, KVCacheConfig, RaggedInferenceEngineConfig,
        RaggedInferenceModel, SamplingParams, ServingOptimizationConfig,
        StateManagerConfig, generate)
    from deepspeed_tpu.models.llama import LlamaForCausalLM
    from deepspeed_tpu.telemetry import metrics as tm
    from tools.replay_trace import _reset_engine

    tp = max(2, int(os.environ.get("BENCH_SHARD_TP", "2")))
    n_req = int(os.environ.get("BENCH_SHARD_REQS", "12"))
    max_new = int(os.environ.get("BENCH_SHARD_NEW_TOKENS", "24"))

    model_def = LlamaForCausalLM("debug", max_seq_len=256,
                                 dtype=jnp.float32)
    cfg = model_def.cfg
    params = flax_meta.unbox(model_def.init_params(jax.random.key(0)))

    # shared-prefix workload, greedy and keyed-sampled rows interleaved
    # — parity must hold on SAMPLED requests too (keyed sampling is
    # schedule- and shard-invariant by construction)
    rng = np.random.default_rng(0)
    prefix = [int(t) for t in rng.integers(0, cfg.vocab_size, 24)]
    prompts, sampling = [], []
    greedy = SamplingParams(max_new_tokens=max_new)
    keyed = SamplingParams(temperature=0.8, top_k=20,
                           max_new_tokens=max_new)
    for i in range(n_req):
        tail = [int(t)
                for t in rng.integers(0, cfg.vocab_size, 4 + (i % 13))]
        prompts.append(prefix + tail)
        sampling.append(keyed if i % 2 else greedy)

    def build(serving):
        kv_cfg = KVCacheConfig(num_layers=cfg.num_layers,
                               kv_heads=cfg.kv_heads,
                               head_dim=cfg.dims_per_head, page_size=16,
                               num_pages=128, dtype=jnp.float32)
        model = RaggedInferenceModel(cfg, params, kv_config=kv_cfg)
        econf = RaggedInferenceEngineConfig(
            state_manager=StateManagerConfig(
                max_tracked_sequences=8,
                max_ragged_sequence_count=8,
                max_ragged_batch_size=256))
        econf.serving = serving
        return InferenceEngineV2(model, econf)

    arms = [
        ("tp1", ServingOptimizationConfig(keyed_sampling=True)),
        ("fp", ServingOptimizationConfig(keyed_sampling=True,
                                         tp_degree=tp)),
        ("int8", ServingOptimizationConfig(
            keyed_sampling=True, tp_degree=tp,
            tp_collective_quantization="int8")),
    ]
    out: Dict[str, Any] = {
        "fastgen_shard_tp": tp,
        "fastgen_shard_reqs": n_req,
        "fastgen_shard_new_tokens": max_new,
    }
    tokens_by_arm: Dict[str, Any] = {}
    compile_on_path = 0
    for name, serving in arms:
        engine = build(serving)
        generate(engine, prompts, sampling)      # untimed shape warmup
        _reset_engine(engine)    # measured pass starts from cold state
        b0 = tm.FASTGEN_SHARD_COLLECTIVE_BYTES.value
        f0 = tm.FASTGEN_SHARD_COLLECTIVE_FP_BYTES.value
        c0 = tm.FASTGEN_COMPILE_ON_PATH.value
        t0 = time.perf_counter()
        toks = generate(engine, prompts, sampling)
        wall = time.perf_counter() - t0
        tokens_by_arm[name] = toks
        gen = sum(len(t) for t in toks)
        out[f"fastgen_shard_{name}_decode_tok_s"] = round(
            gen / wall, 2) if wall > 0 else 0.0
        compile_on_path += int(tm.FASTGEN_COMPILE_ON_PATH.value - c0)
        if name != "tp1":
            out[f"fastgen_shard_{name}_wire_bytes"] = int(
                tm.FASTGEN_SHARD_COLLECTIVE_BYTES.value - b0)
            out[f"fastgen_shard_{name}_wire_fp_bytes"] = int(
                tm.FASTGEN_SHARD_COLLECTIVE_FP_BYTES.value - f0)
    # the fp all-gather is bit-identical — parity over EVERY row,
    # sampled included.  The int8 collective admits a bounded logit
    # error, and a keyed draw thresholds on exact values, so its
    # parity-grade bar is the greedy rows (argmax stable whenever the
    # top-1 margin exceeds the per-shard quantization step); sampled-
    # row agreement is reported as a rate, not gated
    out["fastgen_shard_parity_fp"] = int(
        tokens_by_arm["fp"] == tokens_by_arm["tp1"])
    g = [i for i in range(n_req) if not i % 2]
    out["fastgen_shard_parity_int8"] = int(
        [tokens_by_arm["int8"][i] for i in g]
        == [tokens_by_arm["tp1"][i] for i in g])
    s = [i for i in range(n_req) if i % 2]
    out["fastgen_shard_int8_sampled_agree_rate"] = round(
        sum(tokens_by_arm["int8"][i] == tokens_by_arm["tp1"][i]
            for i in s) / len(s), 4) if s else None
    fp_wire = out["fastgen_shard_fp_wire_bytes"]
    int8_wire = out["fastgen_shard_int8_wire_bytes"]
    out["fastgen_shard_wire_ratio"] = (
        round(int8_wire / fp_wire, 4) if fp_wire else None)
    out["fastgen_shard_compile_on_path_total"] = compile_on_path
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", action="store_true",
                    help="run the measurement in THIS process (the "
                    "forced-mesh subprocess mode)")
    args = ap.parse_args(argv)
    out = _worker() if args.worker else run_shard_bench()
    print(json.dumps(out, indent=None if args.worker else 1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
