#!/usr/bin/env python
"""Predict serving memory capacity from config + a mined workload
trace, and validate the plan against the live memory ledger (ISSUE 20).

Offline planning: the recorded request lengths give the per-sequence
page distribution; the model preset + page geometry give bytes per
page; together they predict how many resident sequences a device pool
of ``--kv-pages`` admits, the headroom left at the trace's observed
concurrency, and a host/disk tier split (hot shared prefix pages want
the host ring, cold once-seen prefixes want disk).

``--validate`` builds the same replay engine ``tools/replay_trace.py``
would and replays the trace TWICE with telemetry on, then checks the
live ledger against the plan:

- every ``ds_mem_*`` subsystem accountant is registered and readable;
- the accounted-vs-measured residual (``ds_mem_unaccounted_bytes``)
  stays within ``--tolerance`` of the measured device total;
- steady state is leak-free: pass-2 measured bytes match pass-1
  within the same tolerance;
- the predicted capacity agrees with the live headroom basis
  (``engine.headroom()`` pages / mined p90 pages-per-seq) within one
  sequence or 10%, whichever is larger.

``--oom-smoke`` is the forensics chaos leg: arm the ``kv.alloc_oom``
injection site, replay, and assert the evidence chain end-to-end — a
``mem.breakdown`` flight-recorder event with per-rung pages-freed
accounting, and a postmortem ``memory.json`` naming the dominant
subsystem.

``--check`` turns any failed assertion into a non-zero exit (the
ci.sh contract).

Usage::

    python tools/plan_capacity.py --trace trace.jsonl
        [--kv-pages 4096] [--max-seqs 32] [--validate] [--oom-smoke]
        [--tolerance 0.10] [--check] [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
from typing import Any, Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

try:
    from . import replay_trace
except ImportError:                      # run as a script: tools/ on path
    import replay_trace

_pct = replay_trace.percentile


# -- trace mining (shared with tools/analyze_trace.py) -----------------------
def mine_memory(requests: List[Dict[str, Any]], page: int,
                concurrency: int = 0) -> Dict[str, Any]:
    """The per-sequence page facts a capacity plan needs, mined from
    recorded request records: the pages-per-sequence distribution
    (prompt + generation, ceil pages — exactly what the allocator
    charges), and the prefix-page reuse structure from the recorded
    digest chains (a page referenced by >1 request is HOT: it earns a
    host-ring slot; a once-seen page is COLD: disk is fine).  The one
    implementation behind plan_capacity, the analyze_trace ``memory``
    section, and ``engine.headroom()``'s trace basis can't disagree on
    ceil conventions because they all charge whole pages."""
    pages = [-(-(int(r["prompt_len"]) + int(r.get("gen_len", 0)))
               // page) for r in requests]
    digest_refs: Dict[str, int] = {}
    for r in requests:
        for d in r.get("digests", ()):
            digest_refs[d] = digest_refs.get(d, 0) + 1
    distinct = len(digest_refs)
    hot = sum(1 for n in digest_refs.values() if n > 1)
    return {
        "page_size": page,
        "pages_per_seq": {
            "p50": _pct(pages, 50), "p90": _pct(pages, 90),
            "p99": _pct(pages, 99), "max": max(pages) if pages else 0,
        },
        "total_pages": sum(pages),
        "distinct_prefix_pages": distinct,
        "hot_prefix_pages": hot,
        "cold_prefix_pages": distinct - hot,
        "concurrency_estimate": int(concurrency),
        "note": (None if digest_refs else
                 "no prefix digest chains in this trace — tier-split "
                 "recommendation degrades to the length distribution "
                 "only (recapture with the workload ledger to mine "
                 "page reuse)"),
    }


def plan(mined: Dict[str, Any], kv_pages: int,
         bytes_per_page: int = 0, max_seqs: int = 0) -> Dict[str, Any]:
    """Config + mined facts -> the prediction: resident-sequence
    capacity of the pool (pages / p90 pages-per-seq, slot-clamped —
    the same admissibility model ``engine.headroom()`` serves live),
    headroom at the observed concurrency, and the tier split."""
    p90 = max(int(mined["pages_per_seq"]["p90"] or 0), 1)
    conc = int(mined["concurrency_estimate"])
    cap = kv_pages // p90 if kv_pages else 0
    if max_seqs:
        cap = min(cap, max_seqs)
    hot = int(mined["hot_prefix_pages"])
    cold = int(mined["cold_prefix_pages"])
    return {
        "kv_pages": int(kv_pages),
        "bytes_per_page": int(bytes_per_page),
        "kv_pool_bytes": (int(bytes_per_page) * (kv_pages + 1)
                          if bytes_per_page else None),
        "capacity_seqs": cap,
        "seqs_per_1k_pages": 1000 // p90,
        "bound": ("slots" if max_seqs and kv_pages // p90 >= max_seqs
                  else "kv_pages"),
        "headroom_at_observed_concurrency": cap - conc,
        "tier_split": {
            # the device pool must hold the ACTIVE working set (one
            # p90 sequence per concurrent request plus its landing
            # page); the host ring earns the hot reuse set; disk takes
            # the cold tail
            "device_pages_needed": conc * (p90 + 1),
            "host_pages_recommended": hot,
            "disk_pages_recommended": cold,
            "note": mined["note"],
        },
    }


def _bytes_per_page(page: int, model_size: str = "debug") -> int:
    """The page footprint of the preset's KV geometry, without
    building an engine (KVCacheConfig is pure arithmetic)."""
    import jax.numpy as jnp
    from deepspeed_tpu.inference.v2 import KVCacheConfig
    from deepspeed_tpu.models.llama import LlamaForCausalLM
    cfg = LlamaForCausalLM(model_size, max_seq_len=64,
                           dtype=jnp.float32).cfg
    return KVCacheConfig(
        num_layers=cfg.num_layers, kv_heads=cfg.kv_heads,
        head_dim=cfg.dims_per_head, page_size=page, num_pages=1,
        dtype=jnp.float32).bytes_per_page


def run_plan(trace_path: str, limit: int = 0, kv_pages: int = 0,
             max_seqs: int = 32,
             model_size: str = "debug") -> Dict[str, Any]:
    """The offline leg: load -> mine -> predict.  ``kv_pages=0``
    plans for the pool replay_trace's auto-sizing would build, so the
    --validate comparison is against the engine actually constructed."""
    trace = replay_trace.load_trace(trace_path)
    requests = [r for r in trace["requests"]
                if r.get("outcome") == "ok"]
    if limit:
        requests = requests[:limit]
    if not requests:
        raise ValueError(f"{trace_path}: no replayable requests")
    meta = trace["meta"]
    page = int(meta.get("page_size", 16))
    # analyze_trace owns the interval-overlap concurrency estimator
    # (lazy import: analyze_trace imports THIS module for mine_memory)
    try:
        from . import analyze_trace
    except ImportError:
        import analyze_trace
    conc = max(analyze_trace._concurrency_estimate(requests), 1)
    mined = mine_memory(requests, page, concurrency=conc)
    if not kv_pages:
        # replay_trace._build_engine's auto-size: max_seqs worst-case
        # sequences, floored at 256
        need = max(int(r["prompt_len"]) + max(1, int(r["gen_len"]))
                   for r in requests) + page
        kv_pages = max(256, max_seqs * -(-need // page))
    bpp = _bytes_per_page(page, model_size)
    return {
        "trace": trace_path,
        "requests": len(requests),
        "memory": mined,
        "plan": plan(mined, kv_pages, bytes_per_page=bpp,
                     max_seqs=max_seqs),
        "_requests": requests,      # stripped before printing
        "_meta": meta,
    }


# -- validation against the live ledger --------------------------------------
def validate(report: Dict[str, Any], seed: int = 0,
             tolerance: float = 0.10,
             model_size: str = "debug",
             max_seqs: int = 32) -> Dict[str, Any]:
    """Replay the planned trace twice on a real engine with telemetry
    on and hold the plan to the ledger's account of what happened."""
    import deepspeed_tpu.telemetry as dstel
    from deepspeed_tpu.telemetry.memory import (SUBSYSTEMS,
                                                get_memory_ledger)

    requests, meta = report["_requests"], report["_meta"]
    page = int(meta.get("page_size", 16))
    planned = report["plan"]
    ledger = get_memory_ledger()
    ledger.reset()
    engine = replay_trace.build_replay_engine(
        meta, requests, model_size=model_size,
        num_pages=planned["kv_pages"], max_seqs=max_seqs)
    vocab = min(int(meta.get("vocab_size", 0))
                or engine.model.cfg.vocab_size,
                engine.model.cfg.vocab_size)
    prompts = replay_trace.synthesize_prompts(requests, page, vocab,
                                              seed=seed)
    prev = dstel.enabled()
    dstel.enable()
    try:
        replay_trace.replay(engine, requests, prompts)
        bd1 = ledger.breakdown()
        replay_trace._reset_engine(engine)
        replay_trace.replay(engine, requests, prompts)
        bd2 = ledger.breakdown()
        replay_trace._reset_engine(engine)
        head = engine.headroom()
    finally:
        dstel.set_enabled(bool(prev))

    problems: List[str] = []
    missing = sorted(set(SUBSYSTEMS) - set(bd2["subsystems"]))
    if missing:
        problems.append(
            f"[ledger] subsystem accountant(s) never registered: "
            f"{missing}")
    dead = sorted(s for s in ("weights", "kv_pages")
                  if not bd2["subsystems"].get(s, 0))
    if dead:
        problems.append(
            f"[ledger] {dead} read zero bytes after a replay — the "
            "accountant callbacks are dead")
    measured = int(bd2["measured_bytes"])
    resid = abs(int(bd2["unaccounted_bytes"]))
    if measured > 0 and resid > tolerance * measured:
        problems.append(
            f"[residual] unaccounted {resid} bytes exceeds "
            f"{tolerance:.0%} of measured {measured} "
            f"(source={bd2['measured_source']}) — a device-resident "
            "subsystem is missing an accountant")
    drift = abs(int(bd2["measured_bytes"]) - int(bd1["measured_bytes"]))
    if bd1["measured_bytes"] and drift > tolerance * bd1["measured_bytes"]:
        problems.append(
            f"[leak] measured bytes drifted {drift} between two "
            "identical replays — steady state is not leak-free")
    p90 = max(int(report["memory"]["pages_per_seq"]["p90"] or 0), 1)
    live_cap = max(min(int(head["headroom_pages"]) // p90,
                       int(head["slot_headroom"])), 0)
    want = int(planned["capacity_seqs"])
    if abs(live_cap - want) > max(1, int(0.10 * max(want, 1))):
        problems.append(
            f"[capacity] plan predicted {want} resident seqs but the "
            f"drained engine's headroom admits {live_cap} at the "
            "mined p90 — the plan and the live pool disagree")
    return {
        "pass1": bd1, "pass2": bd2,
        "headroom": head,
        "live_capacity_seqs": live_cap,
        "predicted_capacity_seqs": want,
        "problems": problems, "ok": not problems,
    }


# -- OOM forensics chaos leg -------------------------------------------------
def oom_smoke(report: Dict[str, Any], seed: int = 0,
              model_size: str = "debug",
              max_seqs: int = 8) -> Dict[str, Any]:
    """Arm ``kv.alloc_oom``, replay, and assert the forensics chain:
    the degrade ladder must leave a ``mem.breakdown`` event (with its
    per-rung pages-freed accounting) in the flight recorder, and
    ``dump_postmortem`` must ship a ``memory.json`` naming the
    dominant subsystem."""
    import deepspeed_tpu.telemetry as dstel
    from deepspeed_tpu.runtime.fault_injection import get_fault_injector
    from deepspeed_tpu.telemetry.flight_recorder import (
        dump_postmortem, get_flight_recorder)
    from deepspeed_tpu.telemetry.memory import get_memory_ledger

    requests, meta = report["_requests"], report["_meta"]
    page = int(meta.get("page_size", 16))
    get_memory_ledger().reset()
    engine = replay_trace.build_replay_engine(
        meta, requests, model_size=model_size, max_seqs=max_seqs)
    vocab = min(int(meta.get("vocab_size", 0))
                or engine.model.cfg.vocab_size,
                engine.model.cfg.vocab_size)
    prompts = replay_trace.synthesize_prompts(requests, page, vocab,
                                              seed=seed)
    rec = get_flight_recorder()
    rec.clear()
    inj = get_fault_injector()
    prev = dstel.enabled()
    dstel.enable()
    # fire once, early: the scheduler's degrade ladder catches the
    # injected KVAllocationError and must leave the breakdown behind
    inj.configure({"kv.alloc_oom": {"at": "2", "max": 1}}, seed=seed)
    dump_dir = tempfile.mkdtemp(prefix="ds_mem_smoke_")
    try:
        replay_trace.replay(engine, requests, prompts)
        fired = inj.stats().get("kv.alloc_oom", {}).get("fires", 0)
        events = [e for e in rec.events()
                  if e.get("kind") == "mem.breakdown"]
        paths = dump_postmortem(dump_dir)
        mem_doc = None
        if "memory.json" in paths:
            with open(paths["memory.json"]) as f:
                mem_doc = json.load(f)
    finally:
        inj.disarm()
        dstel.set_enabled(bool(prev))
        shutil.rmtree(dump_dir, ignore_errors=True)

    problems: List[str] = []
    if not fired:
        problems.append("[chaos] kv.alloc_oom never fired — the "
                        "replay made no KV allocations?")
    if not events:
        problems.append("[forensics] no mem.breakdown event in the "
                        "flight recorder after an injected OOM")
    else:
        ev = events[-1]
        if not ev.get("dominant"):
            problems.append("[forensics] mem.breakdown names no "
                            "dominant subsystem")
        if not isinstance(ev.get("rungs"), list):
            problems.append("[forensics] mem.breakdown carries no "
                            "per-rung pages-freed accounting")
    if mem_doc is None:
        problems.append("[postmortem] dump_postmortem shipped no "
                        "memory.json although the ledger was armed")
    elif not mem_doc.get("dominant"):
        problems.append("[postmortem] memory.json names no dominant "
                        "subsystem")
    return {
        "injected_fires": fired,
        "breakdown_events": len(events),
        "dominant": (events[-1].get("dominant") if events else None),
        "memory_json": (sorted(mem_doc) if mem_doc else None),
        "problems": problems, "ok": not problems,
    }


# -- CLI ---------------------------------------------------------------------
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", required=True, help="workload JSONL path")
    ap.add_argument("--limit", type=int, default=0,
                    help="plan over only the first N ok requests")
    ap.add_argument("--kv-pages", type=int, default=0,
                    help="device KV pool to plan for (0 = the pool "
                    "the replay engine would auto-size)")
    ap.add_argument("--max-seqs", type=int, default=32,
                    help="tracked-sequence slots of the target config")
    ap.add_argument("--model-size", default="debug",
                    help="llama preset for page-byte geometry and the "
                    "--validate engine")
    ap.add_argument("--seed", type=int, default=0,
                    help="prompt-synthesis seed")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="accounted-vs-measured residual and leak "
                    "bound for --validate (fraction of measured)")
    ap.add_argument("--validate", action="store_true",
                    help="replay the trace twice and hold the plan to "
                    "the live memory ledger")
    ap.add_argument("--oom-smoke", action="store_true",
                    help="chaos leg: injected kv.alloc_oom must leave "
                    "mem.breakdown forensics and a memory.json "
                    "postmortem")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on any failed assertion")
    ap.add_argument("--json", default="",
                    help="also write the report to this path")
    args = ap.parse_args(argv)

    try:
        report = run_plan(args.trace, limit=args.limit,
                          kv_pages=args.kv_pages,
                          max_seqs=args.max_seqs,
                          model_size=args.model_size)
    except ValueError as e:
        print(f"plan_capacity: {e}", file=sys.stderr)
        return 1
    problems: List[str] = []
    if args.validate:
        v = validate(report, seed=args.seed, tolerance=args.tolerance,
                     model_size=args.model_size,
                     max_seqs=args.max_seqs)
        report["validate"] = v
        problems += v["problems"]
    if args.oom_smoke:
        s = oom_smoke(report, seed=args.seed,
                      model_size=args.model_size)
        report["oom_smoke"] = s
        problems += s["problems"]
    report.pop("_requests", None)
    report.pop("_meta", None)
    print(json.dumps(report, indent=1, default=str))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, default=str)
    if args.check and problems:
        print("plan_capacity: CAPACITY PLAN FAILED", file=sys.stderr)
        for p in problems:
            print(f"plan_capacity:   {p}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
