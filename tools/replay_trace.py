#!/usr/bin/env python
"""Replay a workload trace against a live FastGenScheduler (ISSUE 9).

Loads a JSONL ledger captured by ``telemetry/workload_trace.py``,
synthesizes **anonymized** token-id prompts that reproduce each
request's recorded length and prefix-sharing structure (a prompt page's
tokens are derived deterministically from its recorded chained digest,
so two requests share a synthesized page exactly when they shared a
page at capture time — the content is new, the structure is identical),
re-issues the requests with original or time-scaled arrival pacing, and
diffs the resulting SLO percentiles and recompile counters against the
recorded run.

This is the harness behind ROADMAP item 5's success metric
(``ds_fastgen_compile_on_path_total == 0`` over a replayed production
trace): capture production traffic, replay it against a candidate
config/lattice, and read the counters.

Usage::

    python tools/replay_trace.py --trace trace.jsonl [--speed 2.0]
        [--limit N] [--tolerance 4] [--check] [--json out.json]

``--speed 0`` (default) replays as fast as the scheduler drains (no
arrival pacing); ``--speed 1`` paces at recorded arrival offsets,
``--speed 2`` twice as fast, etc.  ``--check`` exits non-zero when
structural parity (request count / lengths / share structure / arrival
order) fails — the CI smoke mode.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


# -- simulated-mesh prelude (ISSUE 18) ---------------------------------------
def _tp_from_argv(argv) -> int:
    """Peek ``--tp N`` out of raw argv.  The host platform's device
    count is an env knob jax reads at import, so it must be set before
    argparse runs (argparse imports nothing, but the first lazy
    ``import jax`` below it wins the race otherwise)."""
    for i, a in enumerate(argv):
        if a == "--tp" and i + 1 < len(argv):
            try:
                return int(argv[i + 1])
            except ValueError:
                return 1
        if a.startswith("--tp="):
            try:
                return int(a.split("=", 1)[1])
            except ValueError:
                return 1
    return 1


if __name__ == "__main__":
    _tp_pre = _tp_from_argv(sys.argv[1:])
    if _tp_pre > 1 and "jax" not in sys.modules:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={_tp_pre}")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")


def percentile(vals, q: float):
    """Nearest-rank percentile over values (None entries dropped);
    None when empty.  The one implementation the replay report, the
    recorded-side diff, and tools/analyze_trace.py all share — a
    rounding change can't silently skew the recorded-vs-replayed
    ratio from one side only."""
    vals = sorted(v for v in vals if v is not None)
    if not vals:
        return None
    k = min(len(vals) - 1, int(round(q / 100.0 * (len(vals) - 1))))
    return round(float(vals[k]), 3)


# -- trace loading -----------------------------------------------------------
def load_trace(path: str) -> Dict[str, Any]:
    """Parse a workload-trace JSONL ledger into
    ``{"meta", "requests", "compiles", "key_counts"}``.  Records of the
    rotated generation (``<path>.1``) are NOT read — the caller decides
    whether to concatenate generations.  The parser itself is the ONE
    in-package implementation (``inference.v2.lattice.load_trace_facts``
    — engine build mines raw ledgers through it too); replay
    additionally requires request records."""
    from deepspeed_tpu.inference.v2.lattice import load_trace_facts
    trace = load_trace_facts(path)
    if not trace["requests"]:
        raise ValueError(f"{path}: no request records")
    return trace


# -- anonymized prompt synthesis ---------------------------------------------
def synthesize_prompts(requests: List[Dict[str, Any]], page_size: int,
                       vocab_size: int, seed: int = 0
                       ) -> List[np.ndarray]:
    """One int32 prompt per request (by record order), reproducing the
    recorded lengths and the prefix-sharing structure: a full page's
    tokens are a pure function of its recorded cumulative digest (equal
    digests — i.e. equal cumulative prefixes at capture — yield equal
    synthesized pages; distinct digests yield distinct pages w.h.p.),
    and the trailing partial page is unique per request (partial pages
    are never shared by the prefix cache's copy-on-write rule, so
    uniqueness there cannot change the structure)."""
    blocks: Dict[str, np.ndarray] = {}
    prompts: List[np.ndarray] = []
    for idx, rec in enumerate(requests):
        parts: List[np.ndarray] = []
        for digest in rec["digests"]:
            blk = blocks.get(digest)
            if blk is None:
                rng = np.random.default_rng(
                    (int(digest[:15], 16) << 17) ^ (seed & 0x1FFFF))
                blk = rng.integers(0, vocab_size, page_size,
                                   dtype=np.int64).astype(np.int32)
                blocks[digest] = blk
            parts.append(blk)
        rem = int(rec["prompt_len"]) - len(parts) * page_size
        if rem > 0:
            rng = np.random.default_rng(
                (seed << 24) ^ (idx * 2654435761 & 0x7FFFFFFF) ^ 0x5A5A)
            parts.append(rng.integers(0, vocab_size, rem,
                                      dtype=np.int64).astype(np.int32))
        prompts.append(np.concatenate(parts) if parts
                       else np.zeros(0, np.int32))
    return prompts


def share_signature_recorded(requests: List[Dict[str, Any]]
                             ) -> List[tuple]:
    """Canonical sharing structure of the RECORDED prompts: digests
    renamed to first-occurrence ordinals, one tuple per request."""
    ids: Dict[str, int] = {}
    return [tuple(ids.setdefault(d, len(ids)) for d in r["digests"])
            for r in requests]


def share_signature_prompts(prompts: List[np.ndarray], page_size: int
                            ) -> List[tuple]:
    """The same canonical structure recomputed from actual token-id
    prompts via the prefix cache's own chained hash."""
    from deepspeed_tpu.inference.v2.ragged.prefix_cache import PrefixCache
    ids: Dict[bytes, int] = {}
    sigs = []
    for p in prompts:
        d = b""
        sig = []
        for i in range(len(p) // page_size):
            d = PrefixCache.chain(d, p[i * page_size:(i + 1) * page_size])
            sig.append(ids.setdefault(d, len(ids)))
        sigs.append(tuple(sig))
    return sigs


# -- engine construction -----------------------------------------------------
def _replay_model_parts(meta: Dict[str, Any],
                        requests: List[Dict[str, Any]],
                        model_size: str = "debug"):
    """(cfg, params, page, need): the model geometry every replay
    engine shares — factored out so the disagg mode can build TWO
    engines over ONE weight tree (tokenwise-identical continuations
    need identical weights across the pools)."""
    import jax
    import jax.numpy as jnp
    from flax.core import meta as flax_meta
    from deepspeed_tpu.models.llama import LlamaForCausalLM

    page = int(meta.get("page_size", 16))
    need = max(int(r["prompt_len"]) + max(1, int(r["gen_len"]))
               for r in requests) + page
    max_seq = 1
    while max_seq < need:
        max_seq *= 2
    model_def = LlamaForCausalLM(model_size, max_seq_len=max(max_seq, 64),
                                 dtype=jnp.float32)
    params = flax_meta.unbox(model_def.init_params(jax.random.key(0)))
    return model_def.cfg, params, page, need


def _build_engine(cfg, params, page: int, need: int, num_pages: int,
                  max_seqs: int, serving=None):
    import jax.numpy as jnp
    from deepspeed_tpu.inference.v2 import (
        InferenceEngineV2, KVCacheConfig, RaggedInferenceEngineConfig,
        RaggedInferenceModel, StateManagerConfig)
    if not num_pages:
        # pool sized for max_seqs concurrent worst-case sequences
        per_seq = -(-need // page)
        num_pages = max(256, max_seqs * per_seq)
    kv_cfg = KVCacheConfig(num_layers=cfg.num_layers,
                           kv_heads=cfg.kv_heads,
                           head_dim=cfg.dims_per_head, page_size=page,
                           num_pages=num_pages, dtype=jnp.float32)
    model = RaggedInferenceModel(cfg, params, kv_config=kv_cfg)
    econf = RaggedInferenceEngineConfig(
        state_manager=StateManagerConfig(
            max_tracked_sequences=max_seqs,
            max_ragged_sequence_count=max_seqs,
            max_ragged_batch_size=max(256, 4 * page)))
    if serving is not None:
        econf.serving = serving
    return InferenceEngineV2(model, econf)


def build_replay_engine(meta: Dict[str, Any],
                        requests: List[Dict[str, Any]],
                        model_size: str = "debug",
                        num_pages: int = 0,
                        max_seqs: int = 32,
                        serving=None):
    """A small engine whose geometry (page size, context, KV pool) fits
    the trace.  The replay measures SCHEDULING/shape behavior — lattice
    coverage, share structure, relative SLOs — so the weights are
    random-init and the model family is the debug config unless a
    larger one is requested."""
    cfg, params, page, need = _replay_model_parts(meta, requests,
                                                  model_size)
    return _build_engine(cfg, params, page, need, num_pages, max_seqs,
                         serving=serving)


def build_disagg_engines(meta: Dict[str, Any],
                         requests: List[Dict[str, Any]],
                         model_size: str = "debug",
                         max_seqs: int = 32,
                         keyed: bool = True):
    """(prefill_engine, decode_engine) for the two-pool replay
    (ISSUE 13): one weight tree, two engines, each with its serving
    role; ``keyed`` turns on schedule-invariant sampling on both so
    sampled requests replay tokenwise identical to the fused engine.
    The decode engine runs a 2x WIDER slot geometry than the prefill
    engine — per-row decode cost is tiny, so the decode pool batches
    far more concurrent sequences per program than a fused engine
    whose one geometry must also fit prompt chunks (exactly the
    per-pool batch-shape freedom disaggregation exists to buy)."""
    from deepspeed_tpu.inference.v2 import ServingOptimizationConfig
    cfg, params, page, need = _replay_model_parts(meta, requests,
                                                  model_size)
    pre = _build_engine(
        cfg, params, page, need, 0, max_seqs,
        serving=ServingOptimizationConfig(role="prefill",
                                          keyed_sampling=keyed))
    dec = _build_engine(
        cfg, params, page, need, 0, 2 * max_seqs,
        serving=ServingOptimizationConfig(role="decode",
                                          keyed_sampling=keyed))
    return pre, dec


# -- the replay loop ---------------------------------------------------------
def replay(engine, requests: List[Dict[str, Any]],
           prompts: List[np.ndarray], speed: float = 0.0,
           token_budget: Optional[int] = None,
           serving=None, on_token=None,
           capture: bool = False) -> Dict[str, Any]:
    """Re-issue the trace against a fresh FastGenScheduler on
    ``engine``.  ``speed=0`` submits everything up front (as fast as
    the scheduler drains); ``speed>0`` paces submissions at the
    recorded arrival offsets divided by ``speed``.  Request ``i``
    replays with ``max_new_tokens = gen_len_i`` (and no stop token), so
    generated lengths reproduce exactly regardless of sampled values.
    Returns the replayed facts: per-request gen lengths, TTFT/queue
    percentiles, decode tok/s, and the measured-window recompile
    counters.  ``capture=True`` leaves the workload ledger LIVE for
    the drive — the caller has configured a private ledger and wants
    the replay's own request records (the tier bench mines the
    per-request ``hit_device/host/disk/remote`` attribution exactly
    the way tools/analyze_trace.py would)."""
    from deepspeed_tpu.inference.v2 import FastGenScheduler, SamplingParams
    from deepspeed_tpu.telemetry import metrics as tm
    from deepspeed_tpu.telemetry.workload_trace import get_workload_trace

    if capture:
        return _replay_impl(FastGenScheduler, SamplingParams, tm,
                            engine, requests, prompts, speed,
                            token_budget, serving, on_token)
    # a live ledger (DS_WORKLOAD_TRACE still exported on the capture
    # machine) must not record the replay's own synthetic traffic into
    # the trace being studied — capture is suspended for the drive
    with get_workload_trace().suspended():
        return _replay_impl(FastGenScheduler, SamplingParams, tm,
                            engine, requests, prompts, speed,
                            token_budget, serving, on_token)


def _replay_impl(FastGenScheduler, SamplingParams, tm, engine, requests,
                 prompts, speed, token_budget, serving,
                 user_on_token=None) -> Dict[str, Any]:
    order = sorted(range(len(requests)),
                   key=lambda i: float(requests[i].get("arrival_s", 0.0)))
    params = [SamplingParams(
        temperature=float(r.get("temperature", 0.0)),
        top_k=int(r.get("top_k", 0)), top_p=float(r.get("top_p", 1.0)),
        max_new_tokens=max(1, int(r["gen_len"]))) for r in requests]

    sched = FastGenScheduler(engine, token_budget=token_budget,
                             serving=serving)
    miss0 = tm.FASTGEN_STEP_CACHE_MISS.value
    comp0 = tm.FASTGEN_COMPILE_ON_PATH.value

    submit_t: Dict[int, float] = {}
    first_t: Dict[int, float] = {}
    gen: Dict[int, int] = {}
    submitted: List[int] = []
    token_count = [0]
    busy_s = 0.0
    nxt = 0
    stalls = 0

    def on_token(uid: int, tok: int) -> None:
        # per-token accounting MUST ride the callback: a speculative
        # step commits a whole accepted block per row per step, so the
        # step() return dict (one entry per uid) undercounts
        token_count[0] += 1
        gen[uid] = gen.get(uid, 0) + 1
        first_t.setdefault(uid, time.perf_counter())
        if user_on_token is not None:
            user_on_token(uid, tok)

    t0 = time.perf_counter()
    while nxt < len(order) or sched.has_work:
        now = time.perf_counter()
        elapsed = (now - t0) * (speed if speed > 0 else 1.0)
        while nxt < len(order) and (
                speed <= 0
                or float(requests[order[nxt]].get("arrival_s", 0.0))
                <= elapsed):
            i = order[nxt]
            verdict = sched.submit(i, prompts[i], params[i])
            if verdict is None:
                submit_t[i] = time.perf_counter()
                submitted.append(i)
            nxt += 1
        if sched.has_work:
            t_step = time.perf_counter()
            out = sched.step(on_token=on_token)
            busy_s += time.perf_counter() - t_step
            stalls = (stalls + 1 if sched.last_step_scheduled == 0
                      and not out else 0)
            if stalls > 64:
                raise RuntimeError(
                    "replay stalled: requests unschedulable (trace "
                    "needs a larger KV pool / context than the replay "
                    "engine has)")
        elif nxt < len(order):
            if speed > 0:
                gap = (float(requests[order[nxt]].get("arrival_s", 0.0))
                       - elapsed) / speed
                time.sleep(min(max(gap, 0.0), 0.01))
    total = time.perf_counter() - t0

    ttfts = [(first_t[i] - submit_t[i]) * 1e3
             for i in submitted if i in first_t]
    return {
        "requests_submitted": len(submitted),
        "submit_order": submitted,
        "gen_lens": {i: gen.get(i, 0) for i in submitted},
        "errors": {int(u): e.code for u, e in sched.errors.items()},
        "wall_s": round(total, 4),
        "busy_s": round(busy_s, 4),
        "decode_tok_s": (round(token_count[0] / total, 1) if total
                         else None),
        "ttft_p50_ms": percentile(ttfts, 50),
        "ttft_p99_ms": percentile(ttfts, 99),
        "step_cache_miss": tm.FASTGEN_STEP_CACHE_MISS.value - miss0,
        "compile_on_path": tm.FASTGEN_COMPILE_ON_PATH.value - comp0,
        "spec_drafted": sched._spec_drafted_cum,
        "spec_accepted": sched._spec_accepted_cum,
        "spec_draft_drafted": sched._spec_draft_drafted_cum,
        "spec_draft_accepted": sched._spec_draft_accepted_cum,
    }


# -- the two-pool (disaggregated) replay loop --------------------------------
def replay_disagg(prefill_engine, decode_engine,
                  requests: List[Dict[str, Any]],
                  prompts: List[np.ndarray],
                  speed: float = 0.0,
                  threaded: bool = False,
                  on_token=None,
                  journeys: bool = False) -> Dict[str, Any]:
    """Re-issue the trace through a fresh :class:`DisaggPool` over the
    two prebuilt engines (ISSUE 13).  Same submission/pacing contract
    and report shape as :func:`replay`, so ``diff_replay`` diffs both
    modes; extra keys carry the handoff facts (count/bytes/latency,
    streamed-vs-shared pages), the per-pool cost facts (prefill-pool
    MFU captured the moment the prefill pool drains — its busy window,
    not the whole run — and decode-pool HBM GB/s over the run), and
    ``lost`` (requests neither completed nor structurally errored; the
    CI smoke asserts 0).  ``threaded`` drives the pool through its
    ``start()`` stepper threads so the two pools genuinely overlap
    (the bench mode; keyed sampling keeps token values deterministic
    regardless of thread interleaving).  ``journeys`` (ISSUE 19)
    enables telemetry for the measured run and verifies request
    journeys end-to-end: every completed request must reconstruct a
    gap-free segment chain that sums to its measured e2e latency, with
    zero orphaned handoff fragments — findings land in the report's
    ``journeys`` block (and in ``--check`` problems)."""
    from deepspeed_tpu.inference.v2 import (FastGenScheduler,
                                            SamplingParams)
    from deepspeed_tpu.serving import DisaggPool
    from deepspeed_tpu.telemetry import metrics as tm
    from deepspeed_tpu.telemetry.workload_trace import get_workload_trace

    order = sorted(range(len(requests)),
                   key=lambda i: float(requests[i].get("arrival_s", 0.0)))
    params = [SamplingParams(
        temperature=float(r.get("temperature", 0.0)),
        top_k=int(r.get("top_k", 0)), top_p=float(r.get("top_p", 1.0)),
        max_new_tokens=max(1, int(r["gen_len"]))) for r in requests]

    submit_t: Dict[int, float] = {}
    first_t: Dict[int, float] = {}
    gen: Dict[int, int] = {}
    submitted: List[int] = []
    token_count = [0]

    def _tap(uid: int, tok: int) -> None:
        token_count[0] += 1
        gen[uid] = gen.get(uid, 0) + 1
        first_t.setdefault(uid, time.perf_counter())
        if on_token is not None:
            on_token(uid, tok)

    pool = DisaggPool(
        lambda: FastGenScheduler(prefill_engine),
        lambda: FastGenScheduler(decode_engine),
        on_token=_tap)

    miss0 = tm.FASTGEN_STEP_CACHE_MISS.value
    comp0 = tm.FASTGEN_COMPILE_ON_PATH.value
    hand0 = tm.DISAGG_HANDOFFS.value
    bytes0 = tm.DISAGG_HANDOFF_BYTES.value
    stream0 = tm.DISAGG_PAGES_STREAMED.value
    share0 = tm.DISAGG_PAGES_SHARED.value
    handoff_ms: List[float] = []
    pool._on_handoff_ms = handoff_ms.append

    jlog = prev_enabled = None
    if journeys:
        # journeys gate on the telemetry switch (mint() is the
        # disabled-path read); enable for the measured window only and
        # start from an empty log so the verdicts below see exactly
        # this run
        import deepspeed_tpu.telemetry as dstel
        from deepspeed_tpu.telemetry import journey as dsjourney
        jlog = dsjourney.get_journey_log()
        jlog.clear()
        prev_enabled = dstel.enabled()
        dstel.enable()

    nxt = 0
    stalls = 0
    with get_workload_trace().suspended():
        t0 = time.perf_counter()
        if threaded:
            pool.start()
        try:
            while nxt < len(order) or not pool.idle:
                now = time.perf_counter()
                elapsed = (now - t0) * (speed if speed > 0 else 1.0)
                while nxt < len(order) and (
                        speed <= 0
                        or float(requests[order[nxt]]
                                 .get("arrival_s", 0.0)) <= elapsed):
                    i = order[nxt]
                    verdict = pool.submit(i, prompts[i], params[i])
                    if verdict is None:
                        submit_t[i] = time.perf_counter()
                        submitted.append(i)
                    nxt += 1
                if threaded:
                    if pool.idle and nxt >= len(order):
                        break
                    time.sleep(0.002)
                    continue
                if not pool.idle:
                    before = token_count[0]
                    pool.step()
                    stalls = (stalls + 1 if token_count[0] == before
                              else 0)
                    if stalls > 512:
                        raise RuntimeError(
                            "disagg replay stalled: requests "
                            "unschedulable (trace needs a larger KV "
                            "pool than the replay engines have)")
                elif nxt < len(order) and speed > 0:
                    gap = (float(requests[order[nxt]]
                                 .get("arrival_s", 0.0)) - elapsed) / speed
                    time.sleep(min(max(gap, 0.0), 0.01))
            total = time.perf_counter() - t0
        finally:
            if threaded:
                pool.stop()
            if journeys:
                import deepspeed_tpu.telemetry as dstel
                dstel.set_enabled(bool(prev_enabled))
    # per-pool cost over each pool's BUSY window (seconds inside its
    # own scheduler steps): the specialization claim is about what a
    # role-shrunk program mix does with the hardware while it runs,
    # independent of how the two pools share a host/thread schedule.
    # ONE implementation (the pool's gauge refresh) feeds both the
    # ds_disagg_* gauges and this report
    cost = pool.refresh_cost_gauges()

    ttfts = [(first_t[i] - submit_t[i]) * 1e3
             for i in submitted if i in first_t]
    lost = [i for i in submitted
            if not pool.request(i).finalized]

    journeys_report = None
    if journeys:
        from deepspeed_tpu.telemetry import journey as dsjourney
        completed = {r["uid"]: r for r in jlog.completed()}
        jproblems: List[str] = []
        for i in submitted:
            preq = pool.request(i)
            if preq is None or not preq.done:
                continue
            rec = completed.get(i)
            if rec is None:
                jproblems.append(f"uid {i}: completed request has no "
                                 "flushed journey")
                continue
            for g in dsjourney.chain_gaps(rec, eps_ms=5.0):
                jproblems.append(f"uid {i}: {g}")
            e2e_ms = (preq.finished_mono - preq.submit_mono) * 1e3
            seg_ms = sum(s["ms"] for s in rec["segments"])
            # ε: the drain mark fires on the scheduler's finish sweep,
            # up to one step after the pool ledger saw the last token
            if abs(seg_ms - e2e_ms) > max(75.0, 0.10 * e2e_ms):
                jproblems.append(
                    f"uid {i}: journey segments sum "
                    f"{round(seg_ms, 1)}ms vs measured e2e "
                    f"{round(e2e_ms, 1)}ms")
        orphans = jlog.orphans()
        if orphans:
            jproblems.append(f"{len(orphans)} orphaned journey "
                             f"fragment(s): {orphans[:4]}")
        journeys_report = {
            "completed_journeys": len(completed),
            "fragments": len(jlog.fragments()),
            "orphans": len(orphans),
            "problems": jproblems,
        }

    return {
        "requests_submitted": len(submitted),
        "submit_order": submitted,
        "gen_lens": {i: gen.get(i, 0) for i in submitted},
        "errors": {int(u): e.code for u, e in pool.errors.items()},
        "lost": len(lost),
        "wall_s": round(total, 4),
        "decode_tok_s": (round(token_count[0] / total, 1) if total
                         else None),
        "ttft_p50_ms": percentile(ttfts, 50),
        "ttft_p99_ms": percentile(ttfts, 99),
        "step_cache_miss": tm.FASTGEN_STEP_CACHE_MISS.value - miss0,
        "compile_on_path": tm.FASTGEN_COMPILE_ON_PATH.value - comp0,
        "spec_drafted": 0,
        "spec_accepted": 0,
        "spec_draft_drafted": 0,
        "spec_draft_accepted": 0,
        "handoffs": tm.DISAGG_HANDOFFS.value - hand0,
        "handoff_bytes": tm.DISAGG_HANDOFF_BYTES.value - bytes0,
        "handoff_p50_ms": percentile(handoff_ms, 50),
        "pages_streamed": tm.DISAGG_PAGES_STREAMED.value - stream0,
        "pages_shared": tm.DISAGG_PAGES_SHARED.value - share0,
        "prefill_mfu": float(cost["prefill_mfu"]),
        "prefill_busy_s": round(pool.prefill_busy_s, 4),
        "decode_hbm_gb_s": float(cost["decode_hbm_gb_s"]),
        "decode_busy_s": round(pool.decode_busy_s, 4),
        "programs_prefill": len(prefill_engine.model._step_cache),
        "programs_decode": len(decode_engine.model._step_cache),
        "journeys": journeys_report,
    }


def run_replay_disagg(trace_path: str, limit: int = 0,
                      include_errors: bool = False, speed: float = 0.0,
                      model_size: str = "debug", seed: int = 0,
                      warmup: bool = True, tolerance: float = 4.0,
                      keyed: bool = True,
                      journeys: bool = False) -> Dict[str, Any]:
    """load → synthesize → (shape-warmup) → measured two-pool replay →
    structural diff: the disagg counterpart of :func:`run_replay`,
    behind the CI disagg smoke and bench.py's BENCH_DISAGG leg."""
    trace = load_trace(trace_path)
    requests = trace["requests"]
    if not include_errors:
        requests = [r for r in requests if r.get("outcome") == "ok"]
    if limit:
        requests = requests[:limit]
    if not requests:
        raise ValueError(f"{trace_path}: no replayable requests")
    meta = trace["meta"]
    page = int(meta.get("page_size", 16))
    pre_eng, dec_eng = build_disagg_engines(meta, requests,
                                            model_size=model_size,
                                            keyed=keyed)
    vocab = min(int(meta.get("vocab_size", 0))
                or pre_eng.model.cfg.vocab_size,
                pre_eng.model.cfg.vocab_size)
    prompts = synthesize_prompts(requests, page, vocab, seed=seed)
    if warmup:
        replay_disagg(pre_eng, dec_eng, requests, prompts, speed=0.0)
        _reset_engine(pre_eng)
        _reset_engine(dec_eng)
    report = replay_disagg(pre_eng, dec_eng, requests, prompts,
                           speed=speed, journeys=journeys)
    verdict = diff_replay(requests, prompts, page, report,
                          tolerance=tolerance)
    return {"trace": trace_path, "meta": meta,
            "requests": len(requests),
            "replay": report, "diff": verdict}


def run_disagg_bench(trace_path: Optional[str] = None,
                     limit: Optional[int] = None) -> Dict[str, Any]:
    """The BENCH_DISAGG leg (ISSUE 13): the same replayed mixed trace
    through (a) the fused single-pool scheduler and (b) the two-pool
    disaggregated scheduler, both with keyed sampling so the
    output-identity claim covers the trace's SAMPLED requests too.
    Both passes run SINGLE-threaded: the step/handoff sequence is then
    deterministic (warmup covers exactly the measured keys — 0
    on-path compiles by construction) and the per-pool MFU/HBM
    numbers come from busy-window accounting, so they measure program-
    mix specialization, not thread overlap (the threaded serve path is
    covered by tests/test_disagg.py).  Emits the acceptance numbers:
    prefill-pool MFU and decode-pool HBM GB/s vs the fused baseline's
    corresponding gauges, per-pool compiled/enumerated program counts
    vs the fused lattice's, handoff p50 ms, aggregate tok/s ratio,
    on-path compiles, lost requests, and tokenwise identity."""
    from deepspeed_tpu.inference.v2 import ServingOptimizationConfig
    from deepspeed_tpu.inference.v2.engine import lattice_keys
    from deepspeed_tpu.telemetry import metrics as tm

    if trace_path is None:
        trace_path = os.environ.get(
            "BENCH_DISAGG_TRACE",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "traces", "sample_200.jsonl"))
    if limit is None:
        limit = int(os.environ.get("BENCH_DISAGG_LIMIT", "64"))
    trace = load_trace(trace_path)
    requests = [r for r in trace["requests"]
                if r.get("outcome") == "ok"]
    if limit:
        requests = requests[:limit]
    # decode-weighted variant of the trace: disaggregation is built
    # for workloads with a real steady-state decode phase, and the
    # captured sample's gen lengths (~4 tokens) end before the decode
    # pool's chain warms up — scale them (prompts/sharing/arrivals
    # untouched; both arms serve the SAME scaled workload)
    gen_scale = int(os.environ.get("BENCH_DISAGG_GEN_SCALE", "4"))
    if gen_scale > 1:
        requests = [dict(r, gen_len=int(r["gen_len"]) * gen_scale)
                    for r in requests]
    meta = trace["meta"]
    page = int(meta.get("page_size", 16))

    # -- fused single-pool baseline (keyed, like the disagg pools) ----
    fused_eng = build_replay_engine(
        meta, requests,
        serving=ServingOptimizationConfig(keyed_sampling=True))
    vocab = min(int(meta.get("vocab_size", 0))
                or fused_eng.model.cfg.vocab_size,
                fused_eng.model.cfg.vocab_size)
    prompts = synthesize_prompts(requests, page, vocab)
    replay(fused_eng, requests, prompts)            # shape warmup
    _reset_engine(fused_eng)
    fused_eng.model.reset_cost_window()
    comp0 = tm.FASTGEN_COMPILE_ON_PATH.value
    fused_tokens: Dict[int, List[int]] = {}
    fused_rep = replay(
        fused_eng, requests, prompts,
        on_token=lambda u, t: fused_tokens.setdefault(u, []).append(t))
    # SAME busy-window accounting as the disagg pools (seconds inside
    # scheduler steps), so the specialization inequalities compare
    # like with like
    from deepspeed_tpu.inference.v2.model import serving_peak_flops
    fused_cost = fused_eng.model.cost_summary()
    fused_busy = max(float(fused_rep.get("busy_s") or 0.0), 1e-9)
    fused_mfu = (float(fused_cost.get("flops_dispatched", 0.0))
                 / fused_busy / serving_peak_flops())
    fused_hbm = (float(fused_cost.get("bytes_dispatched", 0.0))
                 / fused_busy / 1e9)
    fused_compiles = tm.FASTGEN_COMPILE_ON_PATH.value - comp0

    # -- two-pool disaggregated run -----------------------------------
    pre_eng, dec_eng = build_disagg_engines(meta, requests)
    replay_disagg(pre_eng, dec_eng, requests, prompts)  # shape warmup
    _reset_engine(pre_eng)
    _reset_engine(dec_eng)
    pre_eng.model.reset_cost_window()
    dec_eng.model.reset_cost_window()
    # measured pass single-threaded: the step/handoff sequence is then
    # DETERMINISTIC, so the warmup compiled exactly the keys the
    # measured run forms (0 on-path compiles by construction, the
    # acceptance bar) and the busy-window MFU/HBM numbers are stable
    disagg_tokens: Dict[int, List[int]] = {}
    rep = replay_disagg(
        pre_eng, dec_eng, requests, prompts,
        on_token=lambda u, t: disagg_tokens.setdefault(u, []).append(t))

    identical = all(fused_tokens.get(i) == disagg_tokens.get(i)
                    for i in range(len(requests)))
    # enumerated (not just exercised) lattice sizes, each with ITS
    # engine's geometry (the decode pool's wider slot range included):
    # the compile-time claim each pool's kinds= filter buys
    def lat(engine):
        sm = engine._config.state_manager
        return dict(
            max_prompt=max(int(r["prompt_len"]) for r in requests),
            max_new_tokens=max(int(r["gen_len"]) for r in requests),
            max_concurrency=sm.max_ragged_sequence_count,
            page_size=page,
            max_ragged_batch_size=sm.max_ragged_batch_size,
            has_fresh=getattr(engine.model, "_fresh_attention",
                              None) is not None,
            sampling=True, spec_max_draft=0)
    out = {
        "disagg_requests": len(requests),
        "disagg_agg_tok_s": rep["decode_tok_s"],
        "disagg_fused_tok_s": fused_rep["decode_tok_s"],
        "disagg_speedup_vs_fused": (
            round(rep["decode_tok_s"] / fused_rep["decode_tok_s"], 3)
            if fused_rep["decode_tok_s"] else None),
        "disagg_prefill_mfu": round(rep["prefill_mfu"], 9),
        "disagg_fused_mfu": round(fused_mfu, 9),
        "disagg_decode_hbm_gb_s": round(rep["decode_hbm_gb_s"], 4),
        "disagg_fused_hbm_gb_s": round(fused_hbm, 4),
        "disagg_handoff_p50_ms": rep["handoff_p50_ms"],
        "disagg_handoffs": rep["handoffs"],
        "disagg_handoff_bytes": rep["handoff_bytes"],
        "disagg_pages_streamed": rep["pages_streamed"],
        "disagg_pages_shared": rep["pages_shared"],
        "disagg_programs_prefill": rep["programs_prefill"],
        "disagg_programs_decode": rep["programs_decode"],
        "disagg_programs_fused": len(fused_eng.model._step_cache),
        "disagg_lattice_prefill": len(lattice_keys(
            kinds=("prefill", "decode"), **lat(pre_eng))),
        "disagg_lattice_decode": len(lattice_keys(
            kinds=("decode", "chain", "spec"), **lat(dec_eng))),
        "disagg_lattice_fused": len(lattice_keys(**lat(fused_eng))),
        "disagg_compile_on_path_total": rep["compile_on_path"],
        "disagg_fused_compile_on_path_total": fused_compiles,
        "disagg_lost_requests": rep["lost"],
        "disagg_tokenwise_identical": int(identical),
        "disagg_ttft_p50_ms": rep["ttft_p50_ms"],
        "disagg_fused_ttft_p50_ms": fused_rep["ttft_p50_ms"],
    }
    return out


# -- the tiered-KV replay legs (ISSUE 16) ------------------------------------
def build_tier_engine(meta: Dict[str, Any],
                      requests: List[Dict[str, Any]],
                      device_pages: int = 4,
                      host_pages: int = 8,
                      disk_pages: int = 256,
                      tier_dir: str = "",
                      model_size: str = "debug",
                      max_seqs: int = 2,
                      quant: str = "none"):
    """A deliberately device-starved replay engine backed by the
    host/disk prefix tier: the device pool is clamped to the smallest
    SCHEDULABLE size >= ``device_pages`` (one worst-case sequence plus
    a landing page — a 7-page request cannot run inside a literal
    4-page pool), so parked prefix pages are evicted -> DEMOTED almost
    immediately and a returning prefix must come back through tier
    promotion, not a device hit.  Keyed sampling makes replayed token
    values schedule-invariant, so callers can assert warm-from-tier ==
    cold tokenwise even on the trace's sampled requests."""
    from deepspeed_tpu.inference.v2 import ServingOptimizationConfig
    cfg, params, page, need = _replay_model_parts(meta, requests,
                                                  model_size)
    per_seq = -(-need // page)
    # every ADMITTED sequence pins its matched/promoted prefix pages,
    # so the schedulable floor is the worst-case active set, not one
    # sequence: below it, warm admissions livelock holding each
    # other's landing pages
    num_pages = max(int(device_pages), max_seqs * (per_seq + 1))
    serving = ServingOptimizationConfig(
        keyed_sampling=True, kv_quantization=quant,
        kv_tier_host_pages=host_pages, kv_tier_disk_pages=disk_pages,
        kv_tier_dir=tier_dir)
    return _build_engine(cfg, params, page, need, num_pages, max_seqs,
                         serving=serving)


def run_tier_smoke(trace_path: str, limit: int = 0,
                   include_errors: bool = False,
                   device_pages: int = 4, host_pages: int = 8,
                   disk_pages: int = 256,
                   model_size: str = "debug", seed: int = 0,
                   tolerance: float = 4.0) -> Dict[str, Any]:
    """The CI tier smoke (ISSUE 16): two replays of the same trace on
    ONE device-starved tiered engine.  Wave 1 prefills cold and every
    parked prefix page demotes (device -> host ring -> disk via AIO);
    wave 2 resubmits the same requests, so every returning prefix must
    be served back through promotion.  ``diff`` carries the usual
    structural-parity verdict plus the tier invariants ``--check``
    enforces: demotions and disk spills actually happened, wave 2
    promoted pages back, wave-2 tokens are exactly wave-1's (keyed
    sampling: warm-from-tier == cold), and the store's accounting
    (host + disk + inflight == indexed) holds."""
    import shutil
    import tempfile

    trace = load_trace(trace_path)
    requests = trace["requests"]
    if not include_errors:
        requests = [r for r in requests if r.get("outcome") == "ok"]
    if limit:
        requests = requests[:limit]
    if not requests:
        raise ValueError(f"{trace_path}: no replayable requests")
    meta = trace["meta"]
    page = int(meta.get("page_size", 16))
    tier_dir = tempfile.mkdtemp(prefix="ds_tier_smoke_")
    engine = None
    try:
        engine = build_tier_engine(
            meta, requests, device_pages=device_pages,
            host_pages=host_pages, disk_pages=disk_pages,
            tier_dir=tier_dir, model_size=model_size)
        vocab = min(int(meta.get("vocab_size", 0))
                    or engine.model.cfg.vocab_size,
                    engine.model.cfg.vocab_size)
        prompts = synthesize_prompts(requests, page, vocab, seed=seed)
        tok1: Dict[int, List[int]] = {}
        tok2: Dict[int, List[int]] = {}
        rep1 = replay(engine, requests, prompts,
                      on_token=lambda u, t: tok1.setdefault(
                          u, []).append(t))
        tiers = engine.state_manager.tiers
        stats1 = tiers.stats()
        rep2 = replay(engine, requests, prompts,
                      on_token=lambda u, t: tok2.setdefault(
                          u, []).append(t))
        stats2 = tiers.stats()
        verdict = diff_replay(requests, prompts, page, rep2,
                              tolerance=tolerance)
        problems = list(verdict["problems"])
        if stats1["demoted_pages"] <= 0:
            problems.append(
                "[tier] wave 1 demoted no pages — the device-starved "
                "pool should have evicted every parked prefix page "
                "into the host tier")
        if disk_pages > 0 and stats2["spilled_pages"] <= 0:
            problems.append(
                "[tier] nothing spilled host -> disk although a disk "
                "tier was configured and the host ring is tiny")
        if stats2["promoted_pages"] <= stats1["promoted_pages"]:
            problems.append(
                "[tier] wave 2 promoted no pages — returning prefixes "
                "recomputed instead of warming from the tier")
        if tok2 != tok1:
            diff_uids = sorted(u for u in tok1
                               if tok1.get(u) != tok2.get(u))
            problems.append(
                f"[tier] warm-from-tier tokens differ from cold for "
                f"request(s) {diff_uids[:8]} — promotion corrupted "
                "page contents")
        try:
            tiers.check_invariants()
        except RuntimeError as e:
            problems.append(f"[tier] store accounting broken: {e}")
        verdict = dict(verdict, problems=problems,
                       structural_ok=not problems)
        return {"trace": trace_path, "meta": meta,
                "requests": len(requests),
                "device_pages": engine.model.kv_config.num_pages,
                "wave1": rep1, "replay": rep2,
                "tier": stats2, "diff": verdict}
    finally:
        if engine is not None:
            engine.state_manager.close()
        shutil.rmtree(tier_dir, ignore_errors=True)


def run_tier_bench(trace_path: Optional[str] = None,
                   limit: Optional[int] = None) -> Dict[str, Any]:
    """The BENCH_TIER leg (ISSUE 16), three sub-legs over one replayed
    multi-user trace:

    1. **Capacity + quantization overhead**: int8 pages at the SAME
       device byte budget as the fp pool — resident-sequence counts
       from the honest ``bytes_per_page`` accounting (the >= 1.7x
       check_bench gate) — and a measured fp-vs-int8 replay for the
       TTFT p99 before/after comparison (the flat-within-15% gate).
    2. **Host/disk tier**: a device-starved tiered engine replays the
       trace twice; wave 2's per-request tier attribution is captured
       into a private workload ledger and mined for the fleet-wide
       prefix hit rate split by tier, plus promote-batch p50 ms.
    3. **Cross-replica fetch**: a 2-replica pool serves the same
       warm-prefix request once with page fetch on (affinity loses to
       least-backlog, pages stream replica-to-replica) and once cold
       with fetch off under an identical backlog shape — fetch TTFT
       must beat recompute-prefill TTFT."""
    import dataclasses as _dc
    import shutil
    import tempfile

    import jax.numpy as jnp
    from deepspeed_tpu.inference.v2 import (FastGenScheduler,
                                            SamplingParams,
                                            ServingOptimizationConfig)
    from deepspeed_tpu.inference.v2.lattice import load_trace_facts
    from deepspeed_tpu.inference.v2.ragged.kv_cache import (
        KVCacheConfig, pages_for_memory)
    from deepspeed_tpu.serving import ReplicaPool
    from deepspeed_tpu.telemetry import metrics as tm
    from deepspeed_tpu.telemetry.workload_trace import get_workload_trace

    if trace_path is None:
        trace_path = os.environ.get(
            "BENCH_TIER_TRACE",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "traces", "sample_200.jsonl"))
    if limit is None:
        limit = int(os.environ.get("BENCH_TIER_LIMIT", "48"))
    trace = load_trace(trace_path)
    requests = [r for r in trace["requests"]
                if r.get("outcome") == "ok"]
    if limit:
        requests = requests[:limit]
    if not requests:
        raise ValueError(f"{trace_path}: no replayable requests")
    meta = trace["meta"]
    cfg, params, page, need = _replay_model_parts(meta, requests)
    per_seq = -(-need // page)
    max_seqs = 32

    # -- capacity at equal device bytes (the honest accounting the
    # allocator itself sizes pools with — pages_for_memory).  The
    # byte budget is deliberately CONSTRAINED (8 worst-case fp
    # sequences for a 32-request wave): KV capacity, not FLOPs, is
    # what caps concurrency, so the before/after TTFT comparison must
    # run where that constraint binds — the fp pool queues on pages
    # while the int8 pool holds ~3x the sequences in the same bytes
    fp_pages = 8 * (per_seq + 1)
    fp_kv = KVCacheConfig(num_layers=cfg.num_layers,
                          kv_heads=cfg.kv_heads,
                          head_dim=cfg.dims_per_head, page_size=page,
                          num_pages=fp_pages, dtype=jnp.float32)
    budget = fp_pages * fp_kv.bytes_per_page
    q_pages = pages_for_memory(_dc.replace(fp_kv, quantization="int8"),
                               budget)
    out: Dict[str, Any] = {
        "tier_requests": len(requests),
        "tier_device_budget_mb": round(budget / 1e6, 2),
        "tier_resident_seqs_fp": fp_pages // per_seq,
        "tier_resident_seqs_int8": q_pages // per_seq,
        "tier_resident_seq_ratio": round(
            (q_pages // per_seq) / max(fp_pages // per_seq, 1), 3),
    }

    vocab = min(int(meta.get("vocab_size", 0)) or cfg.vocab_size,
                cfg.vocab_size)
    prompts = synthesize_prompts(requests, page, vocab)

    # -- leg 1: fp baseline vs int8 at the same byte budget ----------
    fp_eng = _build_engine(cfg, params, page, need, fp_pages, max_seqs)
    replay(fp_eng, requests, prompts)            # shape warmup
    _reset_engine(fp_eng)
    before = replay(fp_eng, requests, prompts)
    q_eng = _build_engine(
        cfg, params, page, need, q_pages, max_seqs,
        serving=ServingOptimizationConfig(kv_quantization="int8"))
    replay(q_eng, requests, prompts)             # shape warmup
    _reset_engine(q_eng)
    after = replay(q_eng, requests, prompts)
    out.update({
        "tier_ttft_p99_before_ms": before["ttft_p99_ms"],
        "tier_ttft_p99_after_ms": after["ttft_p99_ms"],
        "tier_fp_decode_tok_s": before["decode_tok_s"],
        "tier_int8_decode_tok_s": after["decode_tok_s"],
        "tier_fp_compile_on_path": before["compile_on_path"],
        "tier_int8_compile_on_path": after["compile_on_path"],
        "tier_compile_on_path_total": (before["compile_on_path"]
                                       + after["compile_on_path"]),
    })

    # -- leg 2: host/disk tier, warm wave mined from its own ledger --
    tier_dir = tempfile.mkdtemp(prefix="ds_tier_bench_")
    t_eng = None
    try:
        t_eng = build_tier_engine(meta, requests, device_pages=4,
                                  host_pages=max(8, per_seq),
                                  disk_pages=4096, tier_dir=tier_dir)
        cold = replay(t_eng, requests, prompts)  # wave 1: demotes
        # wave 2 is the WARM-shape warmup: promotion-warmed requests
        # form mixed-kind step keys a cold wave never dispatches, so
        # measuring wave 2 would eat their XLA compiles on-path.  The
        # tier state cycles (promote -> park -> demote again), so wave
        # 3 re-forms the same matched-page counts = the same keys.
        replay(t_eng, requests, prompts)
        # wave 3 measured, into a PRIVATE ledger: the per-request
        # tier-hit attribution is then mined exactly the way
        # tools/analyze_trace.py mines a production capture
        ledger = os.path.join(tier_dir, "tier_warm_wave.jsonl")
        wt = get_workload_trace()
        wt.configure(ledger)
        try:
            warm = replay(t_eng, requests, prompts, capture=True)
        finally:
            wt.close()
        stats = t_eng.state_manager.tiers.stats()
        recs = load_trace_facts(ledger)["requests"]
        prompt_tokens = sum(int(r["prompt_len"]) for r in recs) or 1
        hits = {t: sum(int(r.get(f"hit_{t}", 0)) for r in recs)
                for t in ("device", "host", "disk", "remote")}
        out.update({
            "tier_prefix_hit_rate": round(
                sum(hits.values()) / prompt_tokens, 4),
            "tier_device_hit_rate": round(
                hits["device"] / prompt_tokens, 4),
            "tier_host_hit_rate": round(
                hits["host"] / prompt_tokens, 4),
            "tier_disk_hit_rate": round(
                hits["disk"] / prompt_tokens, 4),
            "tier_remote_hit_rate": round(
                hits["remote"] / prompt_tokens, 4),
            "tier_demoted_pages": stats["demoted_pages"],
            "tier_promoted_pages": stats["promoted_pages"],
            "tier_spilled_pages": stats["spilled_pages"],
            "tier_io_errors": stats["io_errors"],
            "tier_cold_ttft_p99_ms": cold["ttft_p99_ms"],
            "tier_warm_ttft_p99_ms": warm["ttft_p99_ms"],
            "tier_promote_p50_ms": (
                round(tm.KV_TIER_PROMOTE_MS.percentile(50), 3)
                if tm.KV_TIER_PROMOTE_MS.count else None),
            "tier_warm_compile_on_path": warm["compile_on_path"],
        })
        out["tier_compile_on_path_total"] += warm["compile_on_path"]
    finally:
        if t_eng is not None:
            t_eng.state_manager.close()
        shutil.rmtree(tier_dir, ignore_errors=True)

    # -- leg 3: cross-replica page fetch vs recompute-prefill --------
    # fetch exists to dodge LONG prefix recomputes, so the measured
    # prefix is long (20 pages) — streaming 20 committed pages is a
    # host-side copy, recomputing them is a full-width prefill
    # dispatch.  Own model geometry: the trace-sized engines above
    # cannot seat a 20-page prompt.
    fetch_prefix_pages = 20
    fetch_need = (fetch_prefix_pages + 2) * page + 16
    fetch_fake = [{"prompt_len": fetch_need - page, "gen_len": 8}]
    fcfg, fparams, _, _ = _replay_model_parts(meta, fetch_fake)
    engines: Dict[str, Any] = {}

    def factory(label):
        eng = engines.get(label)
        if eng is None:
            eng = _build_engine(fcfg, fparams, page, fetch_need, 0, 8)
            engines[label] = eng
        return FastGenScheduler(eng)

    def _p(seed_, n):
        rng = np.random.default_rng(seed_)
        return rng.integers(0, vocab, n,
                            dtype=np.int64).astype(np.int32)

    warm_prefix = _p(1, fetch_prefix_pages * page)
    full = np.concatenate([warm_prefix, _p(2, page // 2)])
    sp = SamplingParams(max_new_tokens=8, temperature=0.0)

    def scenario(margin, warm):
        """One placement scenario; both arms see the SAME backlog
        shape (2 queued on r0, 1 on r1) so the measured request's
        TTFT differs only by fetch-vs-recompute, not queue depth."""
        for eng in engines.values():
            for uid in list(eng.state_manager._seqs):
                eng.flush(uid)
            eng.reset_prefix_cache()
        pool = ReplicaPool(factory, replicas=2,
                           page_fetch_margin=margin)
        if warm:
            pool.submit(1, warm_prefix, sp)
            pool.run_to_completion()
            pool.publish_hints()
        for uid, s in ((2, 7), (3, 8), (4, 9)):
            pool.submit(uid, _p(s, 3 * page), sp)
        pool.submit(100, full, sp)
        pool.run_to_completion()
        req = pool.request(100)
        return ((req.first_token_mono - req.submit_mono) * 1e3,
                req.replica)

    # the warmup must include an actual FETCH: the import side's
    # restore program is a compiled shape of its own, and eating that
    # XLA compile inside the measured fetch TTFT would swamp the
    # transfer-vs-recompute comparison
    scenario(0, True)
    scenario(-1, False)
    f0, fp0 = tm.POOL_PAGE_FETCHES.value, tm.POOL_PAGE_FETCH_PAGES.value
    # best-of-3 per arm: single-request TTFT on a shared CPU carries
    # ms-scale scheduler jitter that would drown a transfer-vs-prefill
    # delta measured once
    fetch_ttft, fetch_rep = min(
        scenario(0, True) for _ in range(3))
    fetches = tm.POOL_PAGE_FETCHES.value - f0
    recompute_ttft = min(
        scenario(-1, False)[0] for _ in range(3))
    out.update({
        "tier_fetch_prefix_tokens": len(warm_prefix),
        "tier_fetch_ttft_ms": round(fetch_ttft, 3),
        "tier_recompute_ttft_ms": round(recompute_ttft, 3),
        "tier_fetch_speedup_vs_recompute": (
            round(recompute_ttft / fetch_ttft, 3) if fetch_ttft
            else None),
        "tier_fetch_count": fetches,
        "tier_fetch_pages": tm.POOL_PAGE_FETCH_PAGES.value - fp0,
        "tier_fetch_replica": fetch_rep,
    })
    return out


# -- recorded-vs-replayed diff -----------------------------------------------
def recorded_percentiles(requests: List[Dict[str, Any]]
                         ) -> Dict[str, Optional[float]]:
    ttfts = [r.get("ttft_ms") for r in requests]
    waits = [r.get("queue_wait_ms") for r in requests]
    return {"ttft_p50_ms": percentile(ttfts, 50),
            "ttft_p99_ms": percentile(ttfts, 99),
            "queue_wait_p50_ms": percentile(waits, 50)}


def diff_replay(requests: List[Dict[str, Any]],
                prompts: List[np.ndarray], page_size: int,
                report: Dict[str, Any],
                tolerance: float = 4.0) -> Dict[str, Any]:
    """Structural-parity + SLO diff of one replay against its trace.
    Structure must match EXACTLY (count, prompt/gen lengths, share
    structure, arrival order); latency percentiles must agree within a
    multiplicative ``tolerance`` (host/noise dependent — a replay on
    the capture machine lands near 1x)."""
    problems: List[str] = []
    n = len(requests)
    if report["requests_submitted"] != n:
        problems.append(
            f"request count: {report['requests_submitted']} replayed "
            f"vs {n} recorded")
    for i, rec in enumerate(requests):
        if len(prompts[i]) != int(rec["prompt_len"]):
            problems.append(
                f"req {i}: prompt_len {len(prompts[i])} vs recorded "
                f"{rec['prompt_len']}")
        want = max(1, int(rec["gen_len"]))
        got = report["gen_lens"].get(i)
        if got != want:
            problems.append(
                f"req {i}: gen_len {got} vs recorded {want}")
    if (share_signature_prompts(prompts, page_size)
            != share_signature_recorded(requests)):
        problems.append("share structure: synthesized prompts do not "
                        "reproduce the recorded digest classes")
    arrival_order = sorted(
        range(n), key=lambda i: float(requests[i].get("arrival_s", 0.0)))
    if report["submit_order"] != arrival_order:
        problems.append("arrival order: replay submitted out of "
                        "recorded order")

    rec_pct = recorded_percentiles(requests)
    slo = {}
    for key in ("ttft_p50_ms", "ttft_p99_ms"):
        a, b = rec_pct.get(key), report.get(key)
        ratio = (round(b / a, 3) if a and b else None)
        slo[key] = {"recorded": a, "replayed": b, "ratio": ratio}
    within = all(
        v["ratio"] is None or 1.0 / tolerance <= v["ratio"] <= tolerance
        for v in slo.values())
    return {"structural_ok": not problems, "problems": problems,
            "slo": slo, "slo_within_tolerance": within,
            "tolerance": tolerance,
            "compile_on_path": report["compile_on_path"],
            "recorded_queue_wait_p50_ms": rec_pct["queue_wait_p50_ms"]}


def _reset_engine(engine) -> None:
    """Flush every tracked sequence and drop the prefix cache so the
    next replay pass starts from cold engine state."""
    for uid in list(engine.state_manager._seqs):
        engine.flush(uid)
    engine.reset_prefix_cache()


def run_replay(trace_path: str, limit: int = 0,
               include_errors: bool = False, speed: float = 0.0,
               model_size: str = "debug", seed: int = 0,
               warmup: bool = True,
               tolerance: float = 4.0,
               spec: bool = False,
               drafter: str = "ngram",
               tp: int = 1) -> Dict[str, Any]:
    """The one load → filter → build → synthesize → (shape-warmup) →
    measured-replay → diff sequence, shared by the CLI, the CI smoke,
    and bench.py's BENCH_REPLAY leg — so the three can't drift on the
    warmup convention or the vocab clamp.  With ``spec`` the same
    workload is replayed a second time with speculative decoding
    enabled and the report gains a ``spec`` block: accept rate, tok/s
    on/off, and the spec pass's own structural-parity diff (ISSUE 10 —
    speculation must change throughput and metrics, nothing else).
    ``drafter`` selects the spec pass's draft source (ISSUE 17):
    ``ngram`` replays on the same engine; ``model``/``auto`` rebuild
    the spec engine WITH the draft head (draft params and the parallel
    draft-KV array are engine-level state), and the spec block gains a
    per-drafter accept-rate split.  ``tp`` shards the replay engine
    over a ``tp``-way simulated mesh (ISSUE 18) — the replay must stay
    tokenwise/structurally identical to the unsharded run, so the same
    ``--check`` verdict applies; the CLI prelude sets
    ``--xla_force_host_platform_device_count`` before jax loads."""
    trace = load_trace(trace_path)
    requests = trace["requests"]
    if not include_errors:
        requests = [r for r in requests if r.get("outcome") == "ok"]
    if limit:
        requests = requests[:limit]
    if not requests:
        raise ValueError(f"{trace_path}: no replayable requests")
    meta = trace["meta"]
    page = int(meta.get("page_size", 16))
    base_serving = None
    if tp > 1:
        from deepspeed_tpu.inference.v2 import ServingOptimizationConfig
        base_serving = ServingOptimizationConfig(tp_degree=tp)
    engine = build_replay_engine(meta, requests, model_size=model_size,
                                 serving=base_serving)
    vocab = min(int(meta.get("vocab_size", 0))
                or engine.model.cfg.vocab_size,
                engine.model.cfg.vocab_size)
    prompts = synthesize_prompts(requests, page, vocab, seed=seed)
    if warmup:
        # untimed shape warmup (the bench convention): the measured
        # replay then shows REAL on-path recompiles, not cold-start
        replay(engine, requests, prompts, speed=0.0)
        _reset_engine(engine)
    report = replay(engine, requests, prompts, speed=speed)
    verdict = diff_replay(requests, prompts, page, report,
                          tolerance=tolerance)
    out = {"trace": trace_path, "meta": meta,
           "requests": len(requests),
           "recorded_compiles": len(trace["compiles"]),
           "tp": int(max(tp, 1)),
           "replay": report, "diff": verdict}
    if spec:
        from deepspeed_tpu.inference.v2 import ServingOptimizationConfig
        spec_serving = ServingOptimizationConfig(speculative=True,
                                                 spec_drafter=drafter,
                                                 tp_degree=tp)
        if drafter == "ngram":
            # same engine: the n-gram drafter is host-side state only
            spec_engine = engine
        else:
            # model/auto need the draft head — draft params and the
            # parallel draft-KV array are ENGINE-level state, so the
            # spec pass gets its own engine built with the config
            spec_engine = build_replay_engine(
                meta, requests, model_size=model_size,
                serving=spec_serving)
        if warmup:
            _reset_engine(spec_engine)
            replay(spec_engine, requests, prompts, speed=0.0,
                   serving=spec_serving)
        _reset_engine(spec_engine)
        spec_report = replay(spec_engine, requests, prompts, speed=speed,
                             serving=spec_serving)
        spec_diff = diff_replay(requests, prompts, page, spec_report,
                                tolerance=tolerance)
        drafted = spec_report["spec_drafted"]
        off_tok_s = report["decode_tok_s"]

        def _rate(acc, dr):
            return round(acc / dr, 4) if dr else None

        d_model = spec_report["spec_draft_drafted"]
        a_model = spec_report["spec_draft_accepted"]
        d_ngram = drafted - d_model
        a_ngram = spec_report["spec_accepted"] - a_model
        out["spec"] = {
            "replay": spec_report, "diff": spec_diff,
            "drafter": drafter,
            "accept_rate": _rate(spec_report["spec_accepted"], drafted),
            "drafted": drafted,
            "accepted": spec_report["spec_accepted"],
            "per_drafter": {
                "ngram": {"drafted": d_ngram, "accepted": a_ngram,
                          "accept_rate": _rate(a_ngram, d_ngram)},
                "model": {"drafted": d_model, "accepted": a_model,
                          "accept_rate": _rate(a_model, d_model)},
            },
            "tok_s_off": off_tok_s,
            "tok_s_on": spec_report["decode_tok_s"],
            "tok_s_ratio": (round(spec_report["decode_tok_s"]
                                  / off_tok_s, 3)
                            if off_tok_s else None),
        }
    return out


# -- CLI ---------------------------------------------------------------------
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", required=True, help="workload JSONL path")
    ap.add_argument("--speed", type=float, default=0.0,
                    help="arrival pacing: 0 = full speed (default), "
                    "1 = recorded offsets, 2 = twice as fast, ...")
    ap.add_argument("--limit", type=int, default=0,
                    help="replay only the first N requests (0 = all)")
    ap.add_argument("--model-size", default="debug",
                    help="llama preset for the replay engine")
    ap.add_argument("--seed", type=int, default=0,
                    help="prompt-synthesis seed")
    ap.add_argument("--tolerance", type=float, default=4.0,
                    help="SLO percentile agreement factor")
    ap.add_argument("--include-errors", action="store_true",
                    help="also replay requests whose recorded outcome "
                    "was a structured error (default: ok only)")
    ap.add_argument("--spec", action="store_true",
                    help="replay a second pass with speculative "
                    "decoding enabled and report accept rate + tok/s "
                    "delta (ISSUE 10)")
    ap.add_argument("--drafter", default="ngram",
                    choices=("ngram", "model", "auto"),
                    help="draft source for the --spec pass (ISSUE 17): "
                    "model/auto rebuild the spec engine with the "
                    "in-program draft head and the report splits "
                    "accept rate per drafter")
    ap.add_argument("--tp", type=int, default=1,
                    help="shard the replay engine over an N-way "
                    "simulated tensor-parallel mesh (ISSUE 18); the "
                    "prelude forces N host devices before jax loads, "
                    "and --check additionally requires zero on-path "
                    "compiles and zero structured errors")
    ap.add_argument("--disagg", action="store_true",
                    help="replay through the two-pool disaggregated "
                    "prefill/decode scheduler (ISSUE 13): committed-"
                    "page KV streaming handoff, keyed sampling on "
                    "both pools; --check additionally requires zero "
                    "lost requests")
    ap.add_argument("--tier", action="store_true",
                    help="replay twice on one device-starved engine "
                    "backed by the host/disk prefix tier (ISSUE 16): "
                    "wave 1 demotes every parked page, wave 2 must "
                    "warm back through promotion; --check additionally "
                    "requires demotions, disk spills, promotions, "
                    "warm==cold tokens, and clean tier accounting")
    ap.add_argument("--tier-device-pages", type=int, default=4,
                    help="requested device pool size for --tier "
                    "(clamped up to the smallest schedulable pool: "
                    "one worst-case sequence + one page)")
    ap.add_argument("--tier-host-pages", type=int, default=8,
                    help="host DRAM ring capacity for --tier (kept "
                    "tiny so the smoke also exercises disk spill)")
    ap.add_argument("--tier-disk-pages", type=int, default=256,
                    help="disk tier capacity for --tier (0 disables "
                    "the disk tier and its spill check)")
    ap.add_argument("--journeys", action="store_true",
                    help="with --disagg: enable telemetry for the "
                    "measured run and verify request journeys (ISSUE "
                    "19) — every completed request must reconstruct a "
                    "gap-free segment chain summing to its measured "
                    "e2e latency, with zero orphaned handoff "
                    "fragments; --check fails on any finding")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip the untimed shape-warmup pass (the "
                    "measured run then eats the XLA compiles)")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero unless structural parity holds "
                    "(CI smoke mode)")
    ap.add_argument("--json", default="",
                    help="also write the full report to this path")
    args = ap.parse_args(argv)
    if args.tp > 1 and (args.tier or args.disagg):
        ap.error("--tp shards the base/--spec replay only; the tier "
                 "and disagg legs build their own engines")
    if args.journeys and not args.disagg:
        ap.error("--journeys rides the --disagg leg (the journey "
                 "smoke verifies the handoff segments)")

    try:
        if args.tier:
            out = run_tier_smoke(
                args.trace, limit=args.limit,
                include_errors=args.include_errors,
                device_pages=args.tier_device_pages,
                host_pages=args.tier_host_pages,
                disk_pages=args.tier_disk_pages,
                model_size=args.model_size, seed=args.seed,
                tolerance=args.tolerance)
        elif args.disagg:
            out = run_replay_disagg(
                args.trace, limit=args.limit,
                include_errors=args.include_errors,
                speed=args.speed, model_size=args.model_size,
                seed=args.seed, warmup=not args.no_warmup,
                tolerance=args.tolerance, journeys=args.journeys)
        else:
            out = run_replay(args.trace, limit=args.limit,
                             include_errors=args.include_errors,
                             speed=args.speed,
                             model_size=args.model_size,
                             seed=args.seed, warmup=not args.no_warmup,
                             tolerance=args.tolerance, spec=args.spec,
                             drafter=args.drafter, tp=args.tp)
    except ValueError as e:
        print(f"replay_trace: {e}", file=sys.stderr)
        return 1
    verdict = out["diff"]
    print(json.dumps(out, indent=1, default=str))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1, default=str)
    problems = list(verdict["problems"]) if not verdict["structural_ok"] \
        else []
    if args.disagg and out["replay"].get("lost"):
        problems.append(
            f"[disagg] {out['replay']['lost']} request(s) lost "
            "(neither completed nor structurally errored)")
    if args.journeys:
        jrep = out["replay"].get("journeys") or {}
        problems += [f"[journey] {p}" for p in jrep.get("problems", ())]
        if not jrep.get("completed_journeys"):
            problems.append("[journey] no journeys flushed during the "
                            "measured replay")
    if args.tp > 1 and not (args.tier or args.disagg):
        # the sharded leg is a STRONGER contract than base structural
        # parity: the one-program step must come entirely out of the
        # warmed shape set (tp in the compile-cache digest — a mesh
        # change is a MISS, never a wrong executable), and sharding may
        # not surface as per-request structured errors
        if out["replay"].get("compile_on_path"):
            problems.append(
                f"[tp] {out['replay']['compile_on_path']} on-path "
                "compile(s) during the sharded measured replay")
        if out["replay"].get("errors"):
            problems.append(
                f"[tp] {len(out['replay']['errors'])} structured "
                "error(s) during the sharded replay")
    if args.spec and not out["spec"]["diff"]["structural_ok"]:
        # the spec pass must reproduce the same structure — speculation
        # may only change throughput/metrics
        problems += [f"[spec] {p}"
                     for p in out["spec"]["diff"]["problems"]]
    if args.check and problems:
        print("replay_trace: STRUCTURAL PARITY FAILED", file=sys.stderr)
        for p in problems:
            print(f"replay_trace:   {p}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
