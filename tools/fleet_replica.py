#!/usr/bin/env python
"""One serving replica as a process (ISSUE 11): the unit the fleet
federation scrapes.

Builds a CPU-debug engine, starts the telemetry endpoint on an
EPHEMERAL port (the ``DS_METRICS_PORT=0`` satellite — N replicas on a
host never collide), enables the time-series sampler, and drives a
deterministic synthetic workload in rounds.  The parent (a federation
test, ``tools/fleetctl.py --smoke``, or bench.py's ``BENCH_FLEET``
leg) reads the handshake line::

    FLEET_REPLICA ready label=<label> port=<port> pid=<pid>

then scrapes ``http://127.0.0.1:<port>/snapshot?raw=1`` like any other
replica.  Arm ``DS_CHAOS="serving.preempt:at=<N>"`` in the child's
environment to kill it mid-replay through the ISSUE 8 chaos site — the
injected preemption exits the process (status 17) exactly like a
preempted spot VM, server and all.

The workload is either synthetic (random prompts) or — with
``--trace <ledger.jsonl>`` — a CAPTURED workload trace replayed
through the ISSUE 9 machinery (``tools/replay_trace.py``): anonymized
prompts synthesized from the recorded page digests, recorded sampling
params, ``max_new_tokens = gen_len``, an engine sized to the trace.
The checked-in ``tools/traces/sample_200.jsonl`` is what the fleet
kill demo replays.

Progress lines (``FLEET_REPLICA round=<n> done``, ``... done``,
``... preempted``) are the parent's pacing signals; ``--step-sleep-s``
paces the step loop so the token rate is steady enough for burn-rate
windows to read.  After the workload the replica lingers
(``--linger-s``) so a controller can scrape final state.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

EXIT_PREEMPTED = 17


def build_engine(page_size: int, num_pages: int, max_seqs: int):
    import jax
    import jax.numpy as jnp
    from flax.core import meta
    from deepspeed_tpu.inference.v2 import (
        InferenceEngineV2, KVCacheConfig, RaggedInferenceEngineConfig,
        RaggedInferenceModel, StateManagerConfig)
    from deepspeed_tpu.models.llama import LlamaForCausalLM

    # fp32 like the test engines: random-init bf16 argmax ties make
    # greedy decode path-dependent across compiled shapes
    model_def = LlamaForCausalLM("debug", max_seq_len=256,
                                 dtype=jnp.float32)
    params = meta.unbox(model_def.init_params(jax.random.key(0)))
    cfg = model_def.cfg
    kv_cfg = KVCacheConfig(num_layers=cfg.num_layers,
                           kv_heads=cfg.kv_heads,
                           head_dim=cfg.dims_per_head,
                           page_size=page_size, num_pages=num_pages,
                           dtype=jnp.float32)
    model = RaggedInferenceModel(cfg, params, kv_config=kv_cfg)
    econf = RaggedInferenceEngineConfig(
        state_manager=StateManagerConfig(
            max_tracked_sequences=max_seqs,
            max_ragged_sequence_count=max_seqs,
            max_ragged_batch_size=256))
    return InferenceEngineV2(model, econf)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--label", default="r0")
    ap.add_argument("--port", type=int, default=0,
                    help="metrics port (0 = ephemeral, the default)")
    ap.add_argument("--trace", default="",
                    help="replay this captured workload-trace JSONL "
                    "(anonymized prompt synthesis, recorded sampling "
                    "params) instead of the synthetic workload")
    ap.add_argument("--trace-limit", type=int, default=8,
                    help="replay only the first N trace requests per "
                    "round")
    ap.add_argument("--requests", type=int, default=4,
                    help="concurrent requests per round (synthetic "
                    "workload)")
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--prompt-len", type=int, default=17)
    ap.add_argument("--max-new", type=int, default=12)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--step-sleep-s", type=float, default=0.0,
                    help="pace the step loop (steady token rate for "
                    "burn-rate windows)")
    ap.add_argument("--ts-interval-s", type=float, default=0.1,
                    help="time-series sampler cadence")
    ap.add_argument("--linger-s", type=float, default=30.0,
                    help="keep serving /snapshot after the workload")
    args = ap.parse_args(argv)

    import numpy as np
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference.v2 import FastGenScheduler, SamplingParams
    from deepspeed_tpu.runtime.fault_injection import \
        InjectedPreemptionFault

    telemetry.enable()
    telemetry.get_timeseries().configure(interval_s=args.ts_interval_s,
                                         retention_s=300.0)
    if args.trace:
        # replay a CAPTURED workload: the ISSUE 9 synthesis (prompts
        # from recorded page digests, engine sized to the trace)
        from tools.replay_trace import (build_replay_engine, load_trace,
                                        synthesize_prompts)
        trace = load_trace(args.trace)
        requests = [r for r in trace["requests"]
                    if r.get("outcome") == "ok"][:args.trace_limit]
        meta = trace["meta"]
        engine = build_replay_engine(meta, requests,
                                     max_seqs=len(requests))
        vocab = min(int(meta.get("vocab_size", 0))
                    or engine.model.cfg.vocab_size,
                    engine.model.cfg.vocab_size)
        prompts = synthesize_prompts(
            requests, int(meta.get("page_size", 16)), vocab,
            seed=args.seed)
        workload = [(prompts[i].tolist(), SamplingParams(
            temperature=float(r.get("temperature", 0.0)),
            top_k=int(r.get("top_k", 0)),
            top_p=float(r.get("top_p", 1.0)),
            max_new_tokens=max(1, int(r["gen_len"]))))
            for i, r in enumerate(requests)]
    else:
        engine = build_engine(args.page_size, args.num_pages,
                              max_seqs=args.requests)
        rng = np.random.default_rng(args.seed)
        vocab = engine.model.cfg.vocab_size
        sp = SamplingParams(max_new_tokens=args.max_new,
                            temperature=0.0)
        workload = [(rng.integers(0, vocab, args.prompt_len).tolist(),
                     sp) for _ in range(args.requests)]
    srv = telemetry.start_http_server(args.port)
    port = srv.server_address[1]
    print(f"FLEET_REPLICA ready label={args.label} port={port} "
          f"pid={os.getpid()}", flush=True)

    try:
        for rnd in range(args.rounds):
            sched = FastGenScheduler(engine)
            for i, (prompt, params) in enumerate(workload):
                sched.submit(rnd * len(workload) + i, prompt, params)
            while sched.has_work:
                sched.step()
                if args.step_sleep_s:
                    time.sleep(args.step_sleep_s)
            print(f"FLEET_REPLICA round={rnd} done", flush=True)
    except InjectedPreemptionFault:
        # the serving.preempt chaos site fired: die like a preempted
        # spot VM — abruptly, endpoint and all (os._exit skips atexit;
        # the federation must observe a replica that just vanishes)
        print("FLEET_REPLICA preempted", flush=True)
        sys.stdout.flush()
        os._exit(EXIT_PREEMPTED)
    print("FLEET_REPLICA done", flush=True)
    deadline = time.monotonic() + args.linger_s
    while time.monotonic() < deadline:
        time.sleep(0.2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
