#!/usr/bin/env python
"""Mine a workload trace for the request-shape facts that choose a
serving config (ISSUE 9; the direct input ROADMAP item 5 needs).

From a JSONL ledger captured by ``telemetry/workload_trace.py``:

- the request-length distribution (prompt / total tokens, percentiles),
  outcome mix, and an arrival-overlap concurrency estimate;
- the **(S, Q, P, fresh[, kind, ...]) occupancy distribution** — how
  often each compiled program actually ran (the ``keys`` summary
  records) — plus every XLA compile that executed ON the request path
  (the ``compile`` records: exactly the keys the precompiled lattice
  missed);
- a **coverage report** of the current default power-of-two lattice
  (``inference.v2.engine.lattice_keys`` — the same enumeration
  ``precompile()`` compiles, so this report can't drift from the live
  path) against the observed keys;
- a **journeys report** (ISSUE 19): per-segment p50/p99 of the
  flattened ``journey_<bucket>_ms`` TTFT-decomposition scalars, plus
  dominant-segment attribution for the slowest decile (legacy traces
  note-and-degrade);
- a **memory report** (ISSUE 20): the pages-per-sequence distribution
  and the hot/cold prefix-page split ``tools/plan_capacity.py`` sizes
  device pools and tier rings from (same mining implementation);
- a **recommended bucket lattice**: quantile-fitted Q/P boundaries
  (bucket tops placed on the observed length distribution instead of
  fixed powers, bounded per-bucket overshoot) plus a recommended
  precompile key set that covers every observed key — by construction
  its coverage report shows zero uncovered on-path compile keys.

Usage::

    python tools/analyze_trace.py --trace trace.jsonl
        [--max-concurrency 512] [--batch-size 768] [--ratio 1.3]
        [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Sequence

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

try:
    from . import replay_trace
except ImportError:                      # run as a script: tools/ on path
    import replay_trace
try:
    from . import plan_capacity as _plan_capacity
except ImportError:
    import plan_capacity as _plan_capacity


# the quantile-fitted bucket boundaries now live IN the package
# (``inference.v2.lattice``) so engine build can consume them via
# ``lattice="auto:<path>"`` without importing tools/.  Re-exported
# LAZILY (PEP 562) for existing callers/tests: an eager import would
# pull jax + the serving stack into this CLI's import time.
def __getattr__(name):
    if name == "fit_buckets":
        from deepspeed_tpu.inference.v2.lattice import fit_buckets
        return fit_buckets
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


#: one percentile implementation across the observatory tools
_pct = replay_trace.percentile


def recommend_spec_drafter(ngram_rate, model_rate,
                           margin: float = 0.15):
    """Recommend ``spec_drafter`` from per-drafter mined accept rates
    (None = that drafter never drafted in the trace).  The host n-gram
    drafter is free, the model drafter pays a draft-trunk forward per
    step — so prefer "ngram" unless the model drafter's accept rate
    beats it by ``margin``.  A low-accept n-gram workload with an
    UNTRIED model drafter recommends "auto": let the per-request state
    machine probe the draft trunk in production.  Both drafters mined
    below the pay-off floor recommends "off" (run with
    speculative=false).  Returns None when the trace has no
    speculation at all."""
    floor = 0.25
    if ngram_rate is None and model_rate is None:
        return None
    if model_rate is None:
        return "ngram" if ngram_rate >= floor else "auto"
    if ngram_rate is None:
        return "model" if model_rate >= floor else "off"
    if max(ngram_rate, model_rate) < floor:
        return "off"
    return ("model" if model_rate >= ngram_rate + margin else "ngram")


def recommend_spec_max_draft(accept_rate: float, cap: int = 8) -> int:
    """Recommend ``spec_max_draft`` from an observed per-draft accept
    rate ``p``: expected committed tokens per program with k drafts is
    the truncated geometric sum ``E(k) = (1 - p^(k+1)) / (1 - p)``,
    which saturates fast — pick the smallest k within 95% of the
    ``cap``-draft asymptote, so low accept rates recommend short (or
    zero) drafts and high rates recommend long ones without ever
    paying verify width that can't pay for itself."""
    p = min(max(float(accept_rate), 0.0), 0.999)
    if p <= 0.0:
        return 0

    def expected(k: int) -> float:
        return (1.0 - p ** (k + 1)) / (1.0 - p)

    target = 0.95 * expected(cap)
    for k in range(1, cap + 1):
        if expected(k) >= target:
            return k
    return cap


def _concurrency_estimate(requests: List[Dict[str, Any]]) -> int:
    """Max overlap of [arrival, completion] intervals, completion
    approximated from the recorded latency facts (TTFT + (n-1) * mean
    ITL); requests without stamps count as instantaneous."""
    events = []
    for r in requests:
        t0 = float(r.get("arrival_s", 0.0))
        dur = 0.0
        if r.get("ttft_ms") is not None:
            dur += float(r["ttft_ms"]) / 1e3
        if r.get("itl_ms") is not None and int(r.get("gen_len", 0)) > 1:
            dur += float(r["itl_ms"]) * (int(r["gen_len"]) - 1) / 1e3
        events.append((t0, 1))
        events.append((t0 + dur, -1))
    peak = cur = 0
    for _, d in sorted(events):
        cur += d
        peak = max(peak, cur)
    return peak


def observed_keys(trace: Dict[str, Any]) -> Dict[tuple, int]:
    """Occupancy union: step-key summaries plus on-path compiles (a
    compiled key was dispatched at least once even if the process died
    before its ``keys`` summary flushed)."""
    occ = {tuple(k): int(n) for k, n in trace["key_counts"].items()}
    for k in trace["compiles"]:
        occ.setdefault(tuple(k), 1)
    return occ


def analyze(trace: Dict[str, Any], max_concurrency: int = 0,
            batch_size: int = 768, ratio: float = 1.3,
            max_buckets: int = 12) -> Dict[str, Any]:
    requests = trace["requests"]
    meta = trace["meta"]
    page = int(meta.get("page_size", 16) or 16)

    prompt_lens = [int(r["prompt_len"]) for r in requests]
    total_lens = [int(r["prompt_len"]) + int(r["gen_len"])
                  for r in requests]
    outcomes: Dict[str, int] = {}
    for r in requests:
        outcomes[r.get("outcome", "?")] = \
            outcomes.get(r.get("outcome", "?"), 0) + 1
    concurrency = _concurrency_estimate(requests)

    occ = observed_keys(trace)
    compile_keys = [tuple(k) for k in trace["compiles"]]

    # -- current-lattice coverage (the ONE shared enumeration) --------
    from deepspeed_tpu.inference.v2.engine import lattice_keys
    mc = max_concurrency or max(concurrency, 1)
    # spec keys in the traffic imply speculation was on: widen the
    # current lattice with the observed spec Q bucket so enabled
    # speculation isn't misreported as uncovered
    spec_q = max((int(k[1]) for k in occ
                  if len(k) > 4 and k[4] in ("spec", "draft_spec")),
                 default=0)
    # draft_spec/draft_fill keys imply a draft trunk was live: widen
    # the current lattice with the draft twins (ISSUE 17)
    draft_seen = any(len(k) > 4 and k[4] in ("draft_spec", "draft_fill")
                     for k in occ)
    current = set(lattice_keys(
        max_prompt=max(prompt_lens), max_new_tokens=max(
            max(int(r["gen_len"]) for r in requests), 1),
        max_concurrency=mc, page_size=page,
        max_ragged_batch_size=batch_size, has_fresh=True,
        sampling=True, spec_max_draft=max(spec_q - 1, 0),
        draft=draft_seen))
    uncovered = sorted(k for k in occ if k not in current)

    # -- recommended lattice ------------------------------------------
    from deepspeed_tpu.inference.v2.lattice import fit_buckets
    q_buckets = fit_buckets(prompt_lens, ratio=ratio,
                            max_buckets=max_buckets)
    p_buckets = fit_buckets([-(-t // page) for t in total_lens],
                            ratio=ratio, max_buckets=max_buckets)
    s_buckets = sorted({int(k[0]) for k in occ}) or [mc]
    # the recommended precompile set: every key traffic actually formed
    # — which the fitted boundaries above would re-generate once
    # build_batch learns non-power lattices (ROADMAP item 5).  The
    # coverage field below checks it against the ON-PATH COMPILE keys
    # specifically (the acceptance bar); today's recommendation covers
    # them because compiles ⊆ occupancy, but the check is against the
    # emitted key set, so a future recommendation that trims keys
    # (e.g. dropping a rare-key tail) surfaces any regression here
    recommended_keys = sorted(occ)
    rec_uncovered = sorted(set(compile_keys) - set(recommended_keys))

    # -- speculation mining (ISSUE 10): accept rates recorded per
    # request recommend the verify width for this workload ------------
    drafted = sum(int(r.get("spec_drafted", 0)) for r in requests)
    accepted = sum(int(r.get("spec_accepted", 0)) for r in requests)
    accept_rate = (accepted / drafted) if drafted else None
    # per-drafter split (ISSUE 17): graceful on legacy traces, whose
    # request records predate the spec_<drafter>_drafted/_accepted
    # fields — the splits then read all-zero and the drafter
    # recommendation falls back to the aggregate note below
    per_drafter: Dict[str, Any] = {}
    for name in ("ngram", "model"):
        dn = sum(int(r.get(f"spec_{name}_drafted", 0))
                 for r in requests)
        an = sum(int(r.get(f"spec_{name}_accepted", 0))
                 for r in requests)
        per_drafter[name] = {
            "drafted": dn, "accepted": an,
            "accept_rate": (round(an / dn, 4) if dn else None)}
    legacy = bool(requests) and not any(
        "spec_drafter" in r for r in requests)
    speculation = {
        "drafted": drafted,
        "accepted": accepted,
        "accept_rate": (round(accept_rate, 4)
                        if accept_rate is not None else None),
        "per_drafter": per_drafter,
        "recommended_spec_max_draft": (
            recommend_spec_max_draft(accept_rate)
            if accept_rate is not None else None),
        "recommended_spec_drafter": recommend_spec_drafter(
            per_drafter["ngram"]["accept_rate"],
            per_drafter["model"]["accept_rate"]),
        "note": (("trace predates per-drafter ledger fields — "
                  "aggregate accept rate only; recapture to mine a "
                  "spec_drafter recommendation") if legacy and drafted
                 else None if drafted else
                 "no speculation in this trace — capture with "
                 "serving_optimization.speculative=true (or replay "
                 "with tools/replay_trace.py --spec) to mine accept "
                 "rates"),
    }

    # -- tier mining (ISSUE 16): the per-request hit_device/host/disk/
    # remote token attribution the scheduler writes at finish makes
    # tier sizing minable from a replayed trace the same way lattice
    # keys are: a big host-tier token share says grow the host ring, a
    # big disk share says promotions are eating disk reads, a big
    # remote share says affinity routing is losing placements ---------
    hit_fields = ("device", "host", "disk", "remote")
    hits = {t: sum(int(r.get(f"hit_{t}", 0)) for r in requests)
            for t in hit_fields}
    prompt_total = sum(prompt_lens) or 1
    tiers = {
        "hit_tokens": hits,
        "hit_rate": {t: round(hits[t] / prompt_total, 4)
                     for t in hit_fields},
        "prefix_hit_rate": round(sum(hits.values()) / prompt_total, 4),
        "requests_with_tier_hits": sum(
            1 for r in requests
            if any(int(r.get(f"hit_{t}", 0)) for t in hit_fields[1:])),
        "note": (None if any(hits.values()) else
                 "no tier-hit attribution in this trace — captured "
                 "before the tiered-KV ledger fields existed, or "
                 "prefix caching / kv_tier_* were off"),
    }

    # -- journey mining (ISSUE 19): the flattened journey_<bucket>_ms
    # TTFT-decomposition scalars the scheduler flushes at drain make
    # per-segment latency minable from the same ledger — where did the
    # slowest requests actually spend their time? -----------------------------
    jfields = ("queue", "placement", "prefill", "handoff", "promote",
               "decode", "migrate")
    jreqs = [r for r in requests if r.get("journey_queue_ms") is not None]
    per_bucket = {}
    for b in jfields:
        vals = [float(r.get(f"journey_{b}_ms", 0.0)) for r in jreqs]
        per_bucket[b] = {"p50": _pct(vals, 50), "p99": _pct(vals, 99)}
    dominant = None
    if jreqs:
        # dominant-segment attribution for the slowest decile (by
        # summed journey time — the e2e latency by construction)
        totals = sorted(
            (sum(float(r.get(f"journey_{b}_ms", 0.0)) for b in jfields),
             i) for i, r in enumerate(jreqs))
        n = max(1, len(totals) // 10)
        slow = [jreqs[i] for _, i in totals[-n:]]
        by_b = {b: sum(float(r.get(f"journey_{b}_ms", 0.0))
                       for r in slow) for b in jfields}
        total = sum(by_b.values())
        if total > 0:
            seg = max(by_b, key=by_b.get)
            dominant = {"bucket": seg,
                        "share": round(by_b[seg] / total, 4),
                        "slow_requests": len(slow)}
    # -- memory mining (ISSUE 20): the same per-sequence page facts
    # tools/plan_capacity.py plans capacity from, surfaced in the one
    # mining report — how many whole KV pages a sequence of this
    # workload charges, and how its prefix pages split hot (reused —
    # host-ring material) vs cold (once-seen — disk is fine).  Offline
    # by construction: pool-specific capacity and the live-ledger
    # cross-check are plan_capacity's --kv-pages / --validate legs.
    mined = _plan_capacity.mine_memory(requests, page,
                                       concurrency=concurrency)
    mem_plan = _plan_capacity.plan(mined, kv_pages=0)
    memory = {
        "pages_per_seq": mined["pages_per_seq"],
        "total_pages": mined["total_pages"],
        "predicted_seqs_per_1k_pages": mem_plan["seqs_per_1k_pages"],
        "tier_split": mem_plan["tier_split"],
        "note": (mined["note"] or
                 "pool-specific capacity + live-ledger validation: "
                 "tools/plan_capacity.py --kv-pages N --validate"),
    }

    journeys = {
        "requests_with_journeys": len(jreqs),
        "per_bucket_ms": per_bucket if jreqs else None,
        "slowest_decile_dominant": dominant,
        "note": (None if jreqs else
                 "no journey decomposition in this trace — captured "
                 "before the journey_<bucket>_ms ledger fields "
                 "existed, or telemetry was off at capture"),
    }

    return {
        "meta": {k: v for k, v in meta.items() if k != "kind"},
        "requests": {
            "count": len(requests),
            "outcomes": outcomes,
            "prompt_len": {"p50": _pct(prompt_lens, 50),
                           "p90": _pct(prompt_lens, 90),
                           "max": max(prompt_lens)},
            "total_len": {"p50": _pct(total_lens, 50),
                          "p90": _pct(total_lens, 90),
                          "max": max(total_lens)},
            "concurrency_estimate": concurrency,
            "ttft_p50_ms": _pct([r["ttft_ms"] for r in requests
                                 if r.get("ttft_ms") is not None], 50),
            "queue_wait_p50_ms": _pct(
                [r["queue_wait_ms"] for r in requests
                 if r.get("queue_wait_ms") is not None], 50),
        },
        "occupancy": {
            "keys": [[list(k), n]
                     for k, n in sorted(occ.items(),
                                        key=lambda kv: -kv[1])],
            "distinct_keys": len(occ),
            "dispatches": sum(occ.values()),
            "compile_on_path_keys": [list(k) for k in compile_keys],
        },
        "coverage": {
            "current_lattice_size": len(current),
            "observed_keys": len(occ),
            "uncovered_by_current": [list(k) for k in uncovered],
        },
        "speculation": speculation,
        "tiers": tiers,
        "journeys": journeys,
        "memory": memory,
        "recommended_lattice": {
            "page_size": page,
            "s_buckets": s_buckets,
            "q_buckets": q_buckets,
            "p_buckets": p_buckets,
            "keys": [list(k) for k in recommended_keys],
            "uncovered_on_path_compile_keys": [list(k)
                                               for k in rec_uncovered],
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", required=True, help="workload JSONL path")
    ap.add_argument("--max-concurrency", type=int, default=0,
                    help="current-lattice S range (default: the "
                    "trace's concurrency estimate)")
    ap.add_argument("--batch-size", type=int, default=768,
                    help="max_ragged_batch_size of the serving config")
    ap.add_argument("--ratio", type=float, default=1.3,
                    help="max per-bucket overshoot of the fitted "
                    "boundaries")
    ap.add_argument("--max-buckets", type=int, default=12)
    ap.add_argument("--json", default="",
                    help="also write the report to this path")
    ap.add_argument("--emit-lattice", default="", metavar="PATH",
                    help="write a versioned lattice artifact (fitted "
                    "bucket tops + precompile key set + config digest) "
                    "that engine build consumes via "
                    "serving_optimization.lattice=\"auto:PATH\" "
                    "(ISSUE 14); a digest mismatch at load refuses "
                    "with a structured error, never a silent cold "
                    "lattice")
    args = ap.parse_args(argv)

    trace = replay_trace.load_trace(args.trace)
    report = analyze(trace, max_concurrency=args.max_concurrency,
                     batch_size=args.batch_size, ratio=args.ratio,
                     max_buckets=args.max_buckets)
    if args.emit_lattice:
        from deepspeed_tpu.inference.v2 import lattice as dslattice
        artifact = dslattice.mine_lattice(
            trace, ratio=args.ratio, max_buckets=args.max_buckets,
            max_ragged_batch_size=args.batch_size, source=args.trace)
        dslattice.write_artifact(artifact, args.emit_lattice)
        report["emitted_lattice"] = {
            "path": args.emit_lattice,
            "config_digest": artifact["config_digest"],
            "keys": len(artifact["keys"]),
            "s_buckets": artifact["s_buckets"],
            "q_buckets": artifact["q_buckets"],
            "p_buckets": artifact["p_buckets"],
        }
    print(json.dumps(report, indent=1, default=str))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, default=str)
    return 0


if __name__ == "__main__":
    sys.exit(main())
