#!/usr/bin/env python
"""Cold-start smoke + bench driver (ISSUE 14).

Proves the recompile-proof cold-start story end to end, across REAL
process boundaries:

- **prime** (child process A): mine a lattice artifact from the
  checked-in workload trace, build an engine with
  ``lattice="auto:<artifact>"`` + a persistent compile cache dir,
  precompile the mined lattice (true XLA compiles, written to disk),
  run the trace once as the tokenwise reference, then snapshot a
  partially-served run — the bundle carries the compiled-key manifest.
  Also measures the **warm** control: restoring the bundle into a
  second engine over the same (already-compiled) model in-process.
- **resume** (child process B): a COLD process builds the same engine
  against the warm cache dir, ``restore()``s the bundle (the manifest
  precompile is all disk loads), finishes the restored requests, then
  replays the full trace — asserting tokenwise parity with the
  reference, ``ds_fastgen_compile_on_path_total == 0`` over the
  replay, and ZERO true compiles (cache loads only).
- optionally **resume without a cache** (child process C): the same
  cold restore paying true compiles — the baseline the cache is
  measured against (bench mode only; the CI smoke skips it).

CLI::

    python tools/coldstart_smoke.py [--check] [--full] [--limit 32]
        [--trace tools/traces/sample_200.jsonl] [--json out.json]

``--check`` exits non-zero unless parity holds and the warm-cache
resume is recompile-free (the ``tools/ci.sh`` smoke mode); ``--full``
adds the no-cache cold leg (the BENCH_COLDSTART mode, via
:func:`run_coldstart_bench`).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

DEFAULT_TRACE = os.path.join(REPO_ROOT, "tools", "traces",
                             "sample_200.jsonl")


def _load_requests(trace_path: str, limit: int):
    from tools import replay_trace
    trace = replay_trace.load_trace(trace_path)
    requests = [r for r in trace["requests"] if r.get("outcome") == "ok"]
    if limit:
        requests = requests[:limit]
    if not requests:
        raise ValueError(f"{trace_path}: no replayable requests")
    return trace, requests


def _build_engine(trace, requests, artifact: str, cache_dir: str):
    from deepspeed_tpu.inference.v2 import ServingOptimizationConfig
    from tools import replay_trace
    serving = ServingOptimizationConfig(
        lattice=f"auto:{artifact}" if artifact else "",
        compile_cache_dir=cache_dir or "")
    return replay_trace.build_replay_engine(trace["meta"], requests,
                                            serving=serving)


def _prompts(trace, requests, engine):
    from tools import replay_trace
    page = int(trace["meta"].get("page_size", 16))
    vocab = min(int(trace["meta"].get("vocab_size", 0))
                or engine.model.cfg.vocab_size,
                engine.model.cfg.vocab_size)
    return replay_trace.synthesize_prompts(requests, page, vocab), page


def _submit_all(sched, requests, prompts) -> None:
    """The ONE requests -> SamplingParams -> submit mapping every
    phase shares (prime reference, partial run, resume replay) — the
    parity gates compare their outputs, so the mapping must not
    fork."""
    from deepspeed_tpu.inference.v2 import SamplingParams
    for i, r in enumerate(requests):
        sched.submit(i, prompts[i], SamplingParams(
            temperature=float(r.get("temperature", 0.0)),
            top_k=int(r.get("top_k", 0)),
            top_p=float(r.get("top_p", 1.0)),
            max_new_tokens=max(1, int(r["gen_len"]))))


def _run_all(engine, requests, prompts) -> Dict[int, List[int]]:
    """One full deterministic pass (speed=0) collecting every token."""
    from deepspeed_tpu.inference.v2 import FastGenScheduler
    sched = FastGenScheduler(engine)
    _submit_all(sched, requests, prompts)
    out = sched.run_to_completion()
    return {int(u): [int(t) for t in toks] for u, toks in out.items()}


def _phase_prime(args) -> Dict[str, Any]:
    import jax  # noqa: F401 — backend init before timers
    from deepspeed_tpu.inference.v2 import (FastGenScheduler,
                                            SamplingParams)
    from deepspeed_tpu.inference.v2 import lattice as dslattice
    from deepspeed_tpu.telemetry import metrics as tm
    from tools.replay_trace import _reset_engine

    trace, requests = _load_requests(args.trace, args.limit)
    artifact = dslattice.mine_lattice(trace, source=args.trace)
    dslattice.write_artifact(artifact, args.artifact)

    engine = _build_engine(trace, requests, args.artifact, args.cache_dir)
    prompts, page = _prompts(trace, requests, engine)

    # the mined lattice, compiled cold (true XLA compiles -> disk)
    h0, m0 = (tm.FASTGEN_COMPILE_CACHE_HIT.value,
              tm.FASTGEN_COMPILE_CACHE_MISS.value)
    t0 = time.perf_counter()
    keys = engine.precompile(
        max_prompt=max(int(r["prompt_len"]) for r in requests),
        sampling=True)
    precompile_wall = time.perf_counter() - t0

    # tokenwise reference: the uninterrupted run
    ref_sched = FastGenScheduler(engine)
    _submit_all(ref_sched, requests, prompts)
    ref_tokens: Dict[int, List[int]] = {i: [] for i in range(len(requests))}
    for uid, toks in ref_sched.run_to_completion().items():
        ref_tokens[int(uid)] = [int(t) for t in toks]
    compile_on_path_ref = tm.FASTGEN_COMPILE_ON_PATH.value

    # partially-served run -> snapshot (manifest rides the bundle)
    _reset_engine(engine)
    part = FastGenScheduler(engine)
    _submit_all(part, requests, prompts)
    for _ in range(args.presteps):
        part.step()
    part.snapshot(args.bundle)
    # requests that COMPLETED before/at the snapshot drain are not in
    # the bundle; their reference tokens are the resume leg's parity
    # source for the missing uids
    bundled = set()
    from deepspeed_tpu.inference.v2.snapshot import read_bundle
    meta, _ = read_bundle(args.bundle)
    for group in meta["requests"].values():
        for d in group:
            bundled.add(int(d["uid"]))
    pre_done = {i: ref_tokens[i] for i in range(len(requests))
                if i not in bundled}

    # warm control: restore into a fresh engine over the SAME
    # (already-compiled) model — the in-process stand-in for a warm
    # process's restore-to-first-token
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    import dataclasses as _dc
    warm_cfg = _dc.replace(engine._config)
    warm_engine = InferenceEngineV2(engine.model, warm_cfg)
    first_tok = []
    t0 = time.perf_counter()
    warm_sched = FastGenScheduler(warm_engine).restore(args.bundle)
    restore_warm_ms = (time.perf_counter() - t0) * 1e3
    guard = 0
    while not first_tok and warm_sched.has_work and guard < 64:
        warm_sched.step(on_token=lambda u, t: first_tok.append(
            time.perf_counter()))
        guard += 1
    warm_first_token_ms = ((first_tok[0] - t0) * 1e3 if first_tok
                           else None)

    h1, m1 = (tm.FASTGEN_COMPILE_CACHE_HIT.value,
              tm.FASTGEN_COMPILE_CACHE_MISS.value)
    return {
        "requests": len(requests),
        "page_size": page,
        "lattice_keys_auto": len(keys),
        "precompile_wall_cold_s": round(precompile_wall, 3),
        "cache_hits": h1 - h0,
        "cache_misses": m1 - m0,
        "compile_on_path_ref": compile_on_path_ref,
        "manifest_keys": len(meta["compiled"]["keys"]),
        "restore_warm_ms": round(restore_warm_ms, 2),
        "restore_warm_first_token_ms": (
            round(warm_first_token_ms, 2)
            if warm_first_token_ms is not None else None),
        "ref_tokens": {str(u): t for u, t in ref_tokens.items()},
        "pre_done": {str(u): t for u, t in pre_done.items()},
    }


def _phase_resume(args) -> Dict[str, Any]:
    from deepspeed_tpu.inference.v2 import FastGenScheduler
    from deepspeed_tpu.telemetry import metrics as tm
    from tools.replay_trace import _reset_engine

    trace, requests = _load_requests(args.trace, args.limit)
    with open(args.ref) as f:
        prime = json.load(f)
    ref_tokens = {int(u): t for u, t in prime["ref_tokens"].items()}
    pre_done = {int(u): t for u, t in prime["pre_done"].items()}

    engine = _build_engine(trace, requests, args.artifact, args.cache_dir)
    prompts, _ = _prompts(trace, requests, engine)

    # restore-to-first-token: the bundle's compiled-key manifest
    # precompiles inside restore() — disk loads against a warm cache,
    # true compiles without one
    h0, m0 = (tm.FASTGEN_COMPILE_CACHE_HIT.value,
              tm.FASTGEN_COMPILE_CACHE_MISS.value)
    first_tok: List[float] = []
    delivered: Dict[int, List[int]] = {}

    def tap(u: int, t: int) -> None:
        if not first_tok:
            first_tok.append(time.perf_counter())
        delivered.setdefault(int(u), []).append(int(t))

    t0 = time.perf_counter()
    sched = FastGenScheduler(engine).restore(args.bundle)
    restore_ms = (time.perf_counter() - t0) * 1e3
    # the restore window's cache facts (the manifest precompile runs
    # INSIDE restore) — read before the separate full-lattice
    # precompile below, whose loads must not inflate them
    restore_hits = tm.FASTGEN_COMPILE_CACHE_HIT.value - h0
    restore_misses = tm.FASTGEN_COMPILE_CACHE_MISS.value - m0
    base = {int(r.uid): [int(t) for t in r.generated]
            for r in (list(sched._pending)
                      + list(sched._running.values())
                      + list(sched._preempted.values()))}
    stalls = 0
    while sched.has_work:
        out = sched.step(on_token=tap)
        stalls = (stalls + 1 if sched.last_step_scheduled == 0
                  and not out else 0)
        if stalls > 64:
            raise RuntimeError("restored run stalled")
    first_token_ms = ((first_tok[0] - t0) * 1e3 if first_tok else None)
    totals = {u: base[u] + delivered.get(u, []) for u in base}
    resume_parity = (
        all(totals[u] == ref_tokens.get(u) for u in base)
        and set(ref_tokens) - set(base) == set(pre_done))

    # the full-lattice precompile is all loads on a warm cache (the
    # second-process half of the tentpole claim)
    t0 = time.perf_counter()
    engine.precompile(
        max_prompt=max(int(r["prompt_len"]) for r in requests),
        sampling=True)
    precompile_wall = time.perf_counter() - t0

    # replay the full trace on the restored engine: the acceptance
    # window — zero on-path compiles, zero true compiles (loads only)
    _reset_engine(engine)
    c0 = tm.FASTGEN_COMPILE_ON_PATH.value
    m2 = tm.FASTGEN_COMPILE_CACHE_MISS.value
    replay_out = _run_all(engine, requests, prompts)
    replay_parity = all(
        replay_out.get(i, []) == ref_tokens[i]
        for i in range(len(requests)))
    from deepspeed_tpu.inference.v2 import compile_cache as cc
    return {
        "restore_ms": round(restore_ms, 2),
        "restore_to_first_token_ms": (round(first_token_ms, 2)
                                      if first_token_ms is not None
                                      else None),
        "precompile_wall_s": round(precompile_wall, 3),
        "restore_cache_hits": restore_hits,
        "restore_cache_misses": restore_misses,
        "cache_counters_available": cc.counters_available(),
        "resume_parity": bool(resume_parity),
        "replay_parity": bool(replay_parity),
        "replay_compile_on_path": tm.FASTGEN_COMPILE_ON_PATH.value - c0,
        "replay_cache_misses": tm.FASTGEN_COMPILE_CACHE_MISS.value - m2,
    }


def _spawn(phase: str, args, cache_dir: str, json_out: str,
           ref: Optional[str] = None) -> Dict[str, Any]:
    cmd = [sys.executable, os.path.abspath(__file__),
           "--phase", phase, "--trace", args.trace,
           "--limit", str(args.limit), "--artifact", args.artifact,
           "--bundle", args.bundle, "--cache-dir", cache_dir,
           "--presteps", str(args.presteps), "--json", json_out]
    if ref:
        cmd += ["--ref", ref]
    env = dict(os.environ)
    env.pop("DS_COMPILE_CACHE", None)   # the flag is the only control
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=1200)
    if proc.returncode != 0:
        raise RuntimeError(
            f"coldstart phase {phase} failed (rc={proc.returncode}):\n"
            f"{proc.stdout[-2000:]}\n{proc.stderr[-4000:]}")
    with open(json_out) as f:
        return json.load(f)


def run_coldstart(trace: str = DEFAULT_TRACE, limit: int = 32,
                  full: bool = False, presteps: int = 3,
                  workdir: Optional[str] = None) -> Dict[str, Any]:
    """Drive prime + resume (+ optional no-cache cold resume) across
    real process boundaries; returns the combined report.  A
    self-created workdir (``workdir=None``) is removed afterwards —
    the compile-cache tree holds one entry per compiled program, and
    CI/bench hosts run this every pass."""
    import shutil
    created = workdir is None
    tmp = workdir or tempfile.mkdtemp(prefix="ds_coldstart_")
    try:
        return _run_coldstart_impl(tmp, trace, limit, full, presteps)
    finally:
        if created:
            shutil.rmtree(tmp, ignore_errors=True)


def _run_coldstart_impl(tmp: str, trace: str, limit: int, full: bool,
                        presteps: int) -> Dict[str, Any]:
    ns = argparse.Namespace(
        trace=trace, limit=limit, presteps=presteps,
        artifact=os.path.join(tmp, "lattice.json"),
        bundle=os.path.join(tmp, "serving.snap"))
    cache = os.path.join(tmp, "compile_cache")
    prime = _spawn("prime", ns, cache, os.path.join(tmp, "a.json"))
    warm_cache = _spawn("resume", ns, cache, os.path.join(tmp, "b.json"),
                        ref=os.path.join(tmp, "a.json"))
    report = {
        "coldstart_requests": prime["requests"],
        "coldstart_lattice_keys_auto": prime["lattice_keys_auto"],
        "coldstart_manifest_keys": prime["manifest_keys"],
        "coldstart_precompile_wall_cold_s":
            prime["precompile_wall_cold_s"],
        "coldstart_precompile_wall_warmcache_s":
            warm_cache["precompile_wall_s"],
        "coldstart_cache_misses_prime": prime["cache_misses"],
        "coldstart_restore_ttft_warm_ms":
            prime["restore_warm_first_token_ms"],
        "coldstart_restore_ttft_warmcache_ms":
            warm_cache["restore_to_first_token_ms"],
        "coldstart_restore_warmcache_cache_hits":
            warm_cache["restore_cache_hits"],
        "coldstart_restore_warmcache_true_compiles":
            warm_cache["restore_cache_misses"],
        "coldstart_replay_compile_on_path":
            warm_cache["replay_compile_on_path"],
        "coldstart_replay_true_compiles":
            warm_cache["replay_cache_misses"],
        "coldstart_resume_parity": warm_cache["resume_parity"],
        "coldstart_replay_parity": warm_cache["replay_parity"],
        "coldstart_cache_counters_available": warm_cache.get(
            "cache_counters_available", True),
    }
    if full:
        nocache = _spawn("resume", ns, "", os.path.join(tmp, "c.json"),
                         ref=os.path.join(tmp, "a.json"))
        report["coldstart_restore_ttft_nocache_ms"] = \
            nocache["restore_to_first_token_ms"]
        report["coldstart_precompile_wall_nocache_s"] = \
            nocache["precompile_wall_s"]
        report["coldstart_nocache_parity"] = nocache["resume_parity"]
    return report


def coldstart_gates(report: Dict[str, Any]) -> List[str]:
    """Hard gate findings (empty = green).  Timing ratios are soft —
    CPU-debug walls are noisy — but structural facts are not.  The
    counter-based checks are skipped when the compile-cache monitoring
    listener could not install (counter degradation is survivable by
    design — caching still works, only the observability is gone)."""
    problems = []
    if not report.get("coldstart_resume_parity"):
        problems.append("restored run is not tokenwise identical to "
                        "the uninterrupted reference")
    if not report.get("coldstart_replay_parity"):
        problems.append("cold-process replay is not tokenwise "
                        "identical to the reference")
    if report.get("coldstart_replay_compile_on_path", 1) != 0:
        problems.append(
            f"cold process + warm cache replay executed "
            f"{report.get('coldstart_replay_compile_on_path')} XLA "
            "compiles on the request path (want 0)")
    if not report.get("coldstart_cache_counters_available", True):
        return problems     # counters degraded: loads/compiles unknown
    if report.get("coldstart_replay_true_compiles", 1) != 0:
        problems.append(
            f"cold process + warm cache replay paid "
            f"{report.get('coldstart_replay_true_compiles')} TRUE "
            "compiles (want 0: cache loads only)")
    if report.get("coldstart_restore_warmcache_true_compiles", 1) != 0:
        problems.append(
            f"warm-cache restore paid "
            f"{report.get('coldstart_restore_warmcache_true_compiles')}"
            " true compiles (want 0: manifest precompile should be "
            "loads)")
    if not report.get("coldstart_restore_warmcache_cache_hits"):
        problems.append("warm-cache restore loaded nothing from the "
                        "persistent cache")
    return problems


def run_coldstart_bench() -> Dict[str, Any]:
    """The BENCH_COLDSTART=1 leg: full three-way comparison + the
    25%-of-warm restore-to-first-token gate (soft: emitted as a
    finding key, hard-gated by tools/check_bench.py in-round)."""
    report = run_coldstart(full=True)
    warm = report.get("coldstart_restore_ttft_warm_ms")
    cached = report.get("coldstart_restore_ttft_warmcache_ms")
    if warm and cached:
        report["coldstart_ttft_warmcache_over_warm"] = round(
            cached / warm, 3)
    report["coldstart_gates_failed"] = len(coldstart_gates(report))
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--phase", default="",
                    help="(internal) child phase: prime|resume")
    ap.add_argument("--trace", default=DEFAULT_TRACE)
    ap.add_argument("--limit", type=int, default=32)
    ap.add_argument("--presteps", type=int, default=3,
                    help="scheduler steps before the mid-flight "
                    "snapshot in the prime phase")
    ap.add_argument("--artifact", default="")
    ap.add_argument("--bundle", default="")
    ap.add_argument("--cache-dir", default="")
    ap.add_argument("--ref", default="",
                    help="(internal) prime-phase JSON for parity")
    ap.add_argument("--json", default="")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero unless every hard gate holds "
                    "(CI smoke mode)")
    ap.add_argument("--full", action="store_true",
                    help="also run the no-cache cold leg (bench mode)")
    args = ap.parse_args(argv)

    if args.phase:
        out = (_phase_prime(args) if args.phase == "prime"
               else _phase_resume(args))
        with open(args.json or "/dev/stdout", "w") as f:
            json.dump(out, f, indent=1)
        return 0

    report = run_coldstart(trace=args.trace, limit=args.limit,
                           full=args.full, presteps=args.presteps)
    print(json.dumps(report, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    problems = coldstart_gates(report)
    if args.check and problems:
        print("coldstart_smoke: GATES FAILED", file=sys.stderr)
        for p in problems:
            print(f"coldstart_smoke:   {p}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
