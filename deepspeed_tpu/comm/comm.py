"""Communication facade over XLA collectives.

TPU-native analogue of ``deepspeed/comm/comm.py`` (the torch.distributed-
compatible facade) + ``comm/torch.py`` (``TorchBackend``).  Two layers:

* **Traced collectives** — free functions mirroring the reference op surface
  (all_reduce, all_gather, reduce_scatter, all_to_all, send/recv-as-permute,
  broadcast, barrier).  They are meant to be called *inside* ``shard_map``/
  ``jit`` over a :class:`~deepspeed_tpu.parallel.topology.MeshTopology` mesh
  and lower to XLA collectives on ICI/DCN (psum, all_gather,
  psum_scatter, all_to_all, ppermute).  "Process groups" become mesh axis
  names.

* **Host-side control plane** — :func:`init_distributed` performs multi-host
  rendezvous via ``jax.distributed.initialize`` (the reference reads
  RANK/WORLD_SIZE/MASTER_ADDR from the launcher env,
  ``comm/comm.py:604``; we honor the same variables), plus
  rank/world-size queries and a host barrier.

Every traced op is wrapped by :func:`timed_op` which feeds the comms
logger (reference ``comm.py:101-141``).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.jax_compat import axis_size as _axis_size
from ..utils.logging import logger

AxisName = Union[str, Sequence[str]]

_comms_logger = None  # lazily constructed CommsLogger


def configure_comms_logger(enabled: bool = True, verbose: bool = False, debug: bool = False):
    global _comms_logger
    from ..utils.comms_logging import CommsLogger
    _comms_logger = CommsLogger(enabled=enabled, verbose=verbose, debug=debug)
    return _comms_logger


def get_comms_logger():
    return _comms_logger


def timed_op(fn):
    """Record op name + message size for traced collectives.

    Timing individual device ops is meaningless under XLA (everything is
    fused/async); what we can faithfully log at trace time is op, shape and
    volume — actual latencies come from the profiler.  Mirrors the spirit of
    reference ``timed_op`` (comm.py:101).
    """

    @functools.wraps(fn)
    def wrapper(tensor, *args, **kwargs):
        if _comms_logger is not None and _comms_logger.enabled:
            _comms_logger.append_traced(fn.__name__, tensor)
        return fn(tensor, *args, **kwargs)

    return wrapper


# ---------------------------------------------------------------------------
# Traced collectives (call inside shard_map / with named axes in scope)
# ---------------------------------------------------------------------------

@timed_op
def all_reduce(tensor: jax.Array, axis_name: AxisName, op: str = "sum") -> jax.Array:
    """SUM/MAX/MIN/AVG all-reduce over a mesh axis (reference comm.py:483)."""
    if op in ("sum", "SUM"):
        return lax.psum(tensor, axis_name)
    if op in ("avg", "AVG", "mean"):
        return lax.pmean(tensor, axis_name)
    if op in ("max", "MAX"):
        return lax.pmax(tensor, axis_name)
    if op in ("min", "MIN"):
        return lax.pmin(tensor, axis_name)
    raise ValueError(f"unsupported reduce op: {op}")


@timed_op
def all_gather(tensor: jax.Array, axis_name: AxisName, axis: int = 0,
               tiled: bool = True) -> jax.Array:
    """Gather shards along ``axis`` (reference all_gather_into_tensor, comm.py:297)."""
    return lax.all_gather(tensor, axis_name, axis=axis, tiled=tiled)


@timed_op
def reduce_scatter(tensor: jax.Array, axis_name: AxisName, axis: int = 0,
                   tiled: bool = True) -> jax.Array:
    """Reduce-then-scatter along ``axis`` (reference reduce_scatter_fn, comm.py:246)."""
    return lax.psum_scatter(tensor, axis_name, scatter_dimension=axis, tiled=tiled)


@timed_op
def all_to_all(tensor: jax.Array, axis_name: AxisName, split_axis: int,
               concat_axis: int, tiled: bool = True) -> jax.Array:
    """All-to-all (reference all_to_all_single, comm.py:331). The Ulysses primitive."""
    return lax.all_to_all(tensor, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


@timed_op
def permute(tensor: jax.Array, axis_name: str, perm: Sequence[tuple]) -> jax.Array:
    """Point-to-point as collective-permute — the TPU replacement for the
    reference's pipeline send/recv (``runtime/pipe/p2p.py``).  ``perm`` is a
    list of (src, dst) pairs along ``axis_name``."""
    return lax.ppermute(tensor, axis_name, perm=list(perm))


def send_recv_next(tensor: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """Shift +1 along a ring: stage i -> stage i+1 (pipe activations)."""
    return lax.ppermute(tensor, axis_name,
                        perm=[(i, (i + 1) % axis_size) for i in range(axis_size)])


def send_recv_prev(tensor: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """Shift -1 along a ring: stage i -> stage i-1 (pipe gradients)."""
    return lax.ppermute(tensor, axis_name,
                        perm=[(i, (i - 1) % axis_size) for i in range(axis_size)])


@timed_op
def broadcast(tensor: jax.Array, axis_name: AxisName, src: int = 0) -> jax.Array:
    """Broadcast from ``src`` rank of the axis (reference comm.py:222)."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
    return lax.psum(masked, axis_name)


def axis_index(axis_name: AxisName) -> jax.Array:
    return lax.axis_index(axis_name)


def axis_size(axis_name: str) -> int:
    return _axis_size(axis_name)


# ---------------------------------------------------------------------------
# Host-side control plane
# ---------------------------------------------------------------------------

_initialized = False


def init_distributed(dist_backend: str = "xla",
                     timeout: Optional[float] = None,
                     rank: int = -1,
                     world_size: int = -1,
                     coordinator_address: Optional[str] = None,
                     auto_mpi_discovery: bool = True) -> None:
    """Multi-host rendezvous (reference init_distributed, comm.py:604).

    Honors the same env contract the reference launcher establishes
    (RANK / WORLD_SIZE / MASTER_ADDR / MASTER_PORT, launcher/launch.py) and
    maps it onto ``jax.distributed.initialize``.  Single-process usage is a
    no-op: JAX needs no rendezvous for one host.
    """
    global _initialized
    if _initialized:
        return
    env_world = int(os.environ.get("WORLD_SIZE", os.environ.get("DS_TPU_NUM_PROCESSES", "1")))
    world_size = world_size if world_size > 0 else env_world
    if world_size <= 1:
        _initialized = True
        return
    rank = rank if rank >= 0 else int(os.environ.get("RANK", "0"))
    if coordinator_address is None:
        addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
        port = os.environ.get("MASTER_PORT", "29500")
        coordinator_address = f"{addr}:{port}"
    logger.info("init_distributed: coordinator=%s rank=%d world=%d",
                coordinator_address, rank, world_size)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=world_size,
                               process_id=rank)
    _initialized = True


def is_initialized() -> bool:
    return _initialized


def get_rank() -> int:
    """Host-process rank.  The reference's rank==GPU==process identity
    splits on TPU (one process drives many chips): host-side concerns
    (logging, file writes, rendezvous) key on the PROCESS, device-level
    parallelism on the DEVICE — use get_device_count()/get_device_rank()
    for the latter.  rank/world pairs are always consistent."""
    return jax.process_index()


def get_world_size() -> int:
    """Number of host processes (consistent with get_rank)."""
    return jax.process_count()


def get_device_rank() -> int:
    """Global index of this process's first addressable device."""
    local = jax.local_devices()
    return local[0].id if local else 0


def get_device_count() -> int:
    """Device world size (the unit of SPMD parallelism on TPU)."""
    return jax.device_count()


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", "0"))


def barrier() -> None:
    """Host-level barrier: round-trip a tiny all-reduce through all devices."""
    if jax.process_count() == 1:
        return
    x = jnp.ones((), dtype=jnp.int32)
    jax.block_until_ready(
        jax.pmap(lambda v: lax.psum(v, "i"), axis_name="i")(
            jnp.ones((jax.local_device_count(),), jnp.int32)))
    del x


def monitored_barrier(timeout: float = 300.0) -> float:
    """Barrier with a wall-time watchdog (reference monitored_barrier,
    comm.py:375 — its gloo backend names the missing rank; XLA's
    collectives either complete or the runtime itself raises on a lost
    host, so the useful signal here is the measured wait).  A watchdog
    thread logs every ``timeout`` seconds while the barrier is blocked,
    so a genuinely hung host is at least visible in this rank's log.
    Returns the barrier wall time in seconds."""
    import threading
    import time as _time

    t0 = _time.time()
    done = threading.Event()
    interval = max(float(timeout), 1.0)  # non-positive would busy-spin

    def watchdog():
        while not done.wait(interval):
            logger.warning(
                "monitored_barrier: still blocked after %.1fs (timeout "
                "%.1fs) — a host is hung, straggling, or the fabric is "
                "congested", _time.time() - t0, timeout)

    w = threading.Thread(target=watchdog, daemon=True)
    w.start()
    try:
        barrier()
    finally:
        done.set()
    dt = _time.time() - t0
    if dt > timeout:
        # the watchdog already warned while blocked; one closing info line
        logger.info("monitored_barrier: barrier completed after %.1fs", dt)
    return dt


def record_bucket_plan(stats: dict) -> None:
    """Feed the CollectiveScheduler's static bucket plan into the comms
    logger (no-op when the logger is not configured).  The plan is exact
    — bucket boundaries are static — so the summary's gradient-wire
    volume needs no tracing hooks."""
    if _comms_logger is not None and _comms_logger.enabled:
        _comms_logger.record_bucket_plan(stats)


def log_summary(show_straggler: bool = False) -> str:
    """Print + return the comms-volume summary (reference comm.py
    log_summary; straggler analysis is meaningless under XLA's fused
    schedules — the profiler owns latency attribution)."""
    del show_straggler
    if _comms_logger is None:
        logger.warning("comms logger not configured; nothing to summarize")
        return ""
    text = _comms_logger.log_summary()
    logger.info("%s", text)
    return text


# ---------------------------------------------------------------------------
# Rooted collectives + reference-compat aliases.  "Process groups" are mesh
# axis names (or tuples of them); SPMD requires uniform shapes on every
# rank, so rooted ops return the payload on the root and a same-shaped
# dummy elsewhere (the reference returns None / leaves inputs untouched).
# ---------------------------------------------------------------------------

@timed_op
def reduce(tensor: jax.Array, axis_name: AxisName, dst: int = 0,
           op: str = "sum") -> jax.Array:
    """Rooted reduce (reference comm.py reduce): rank ``dst`` gets the
    reduction; every other rank keeps its own input."""
    red = all_reduce.__wrapped__(tensor, axis_name, op)
    return jnp.where(lax.axis_index(axis_name) == dst, red, tensor)


@timed_op
def gather(tensor: jax.Array, axis_name: AxisName, dst: int = 0,
           axis: int = 0) -> jax.Array:
    """Rooted gather (reference comm.py gather): rank ``dst`` gets the
    stacked shards (new leading dim at ``axis``), others zeros."""
    g = lax.all_gather(tensor, axis_name, axis=axis, tiled=False)
    return jnp.where(lax.axis_index(axis_name) == dst, g, jnp.zeros_like(g))


@timed_op
def scatter(tensor: jax.Array, axis_name: AxisName, src: int = 0,
            axis: int = 0) -> jax.Array:
    """Rooted scatter (reference comm.py scatter): every rank receives
    its ``axis``-slice of ``src``'s tensor."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
    full = lax.psum(masked, axis_name)
    n = _axis_size(axis_name)
    if full.shape[axis] % n != 0:
        raise ValueError(
            f"scatter: dim {axis} ({full.shape[axis]}) not divisible by "
            f"axis {axis_name!r} size {n} (torch.distributed rejects "
            "mismatched scatter sizes; so do we)")
    return lax.dynamic_slice_in_dim(full, idx * (full.shape[axis] // n),
                                    full.shape[axis] // n, axis)


def inference_all_reduce(tensor: jax.Array, axis_name: AxisName) -> jax.Array:
    """TP partial-sum reduction in inference kernels (reference
    inference_all_reduce) — same psum; XLA already skips grad machinery."""
    return all_reduce(tensor, axis_name)


def all_gather_into_tensor(tensor: jax.Array, axis_name: AxisName,
                           axis: int = 0) -> jax.Array:
    """reference all_gather_into_tensor / _all_gather_base."""
    return all_gather(tensor, axis_name, axis=axis)


all_gather_base = all_gather_into_tensor


def reduce_scatter_tensor(tensor: jax.Array, axis_name: AxisName,
                          axis: int = 0) -> jax.Array:
    """reference reduce_scatter_tensor / _reduce_scatter_base."""
    return reduce_scatter(tensor, axis_name, axis=axis)


reduce_scatter_base = reduce_scatter_tensor


def all_to_all_single(tensor: jax.Array, axis_name: AxisName,
                      split_axis: int = 0, concat_axis: int = 0) -> jax.Array:
    """reference all_to_all_single."""
    return all_to_all(tensor, axis_name, split_axis, concat_axis)


def all_reduce_coalesced(tensors, axis_name: AxisName, op: str = "sum"):
    """reference all_reduce_coalesced: one call over a list.  No manual
    coalescing manager needed — XLA's combiner passes merge adjacent
    collectives into one fused op."""
    return [all_reduce(t, axis_name, op) for t in tensors]


def all_gather_coalesced(tensors, axis_name: AxisName, axis: int = 0):
    """reference all_gather_coalesced."""
    return [all_gather(t, axis_name, axis=axis) for t in tensors]


def reduce_scatter_coalesced(tensors, axis_name: AxisName, axis: int = 0):
    """reference reduce_scatter_coalesced."""
    return [reduce_scatter(t, axis_name, axis=axis) for t in tensors]


# -- group shims ------------------------------------------------------------

def new_group(axis_names: Sequence[str]):
    """Reference ``new_group(ranks)`` -> mesh-axis tuple.  Under SPMD a
    communicator is not a rank list but a set of mesh axes; every traced
    collective here takes that tuple directly as ``axis_name``."""
    if isinstance(axis_names, str):
        return (axis_names,)
    names = tuple(axis_names)
    bad = [a for a in names if not isinstance(a, str)]
    if bad:
        raise ValueError(
            f"new_group expects mesh-AXIS NAMES (strings), got {names!r}. "
            "Reference-style rank lists (e.g. new_group([0, 1])) do not "
            "translate to SPMD: a communicator here is a set of "
            "jax.sharding.Mesh axes — pass e.g. new_group(['data']) or "
            "new_group(['data', 'fsdp']) matching your MeshTopology.")
    return names


def get_world_group():
    """All axes of the ambient mesh (None outside a mesh context —
    collectives then need an explicit axis)."""
    from ..parallel.topology import ambient_mesh
    m = ambient_mesh()
    return tuple(m.axis_names) if m is not None else None


def destroy_process_group() -> None:
    """Tear down the multi-host rendezvous (reference
    destroy_process_group -> torch.distributed.destroy_process_group)."""
    global _initialized
    if _initialized:
        try:
            jax.distributed.shutdown()
        except Exception as e:  # already down / single-host
            logger.warning("jax.distributed.shutdown: %s", e)
        _initialized = False
