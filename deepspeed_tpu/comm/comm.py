"""Communication facade over XLA collectives.

TPU-native analogue of ``deepspeed/comm/comm.py`` (the torch.distributed-
compatible facade) + ``comm/torch.py`` (``TorchBackend``).  Two layers:

* **Traced collectives** — free functions mirroring the reference op surface
  (all_reduce, all_gather, reduce_scatter, all_to_all, send/recv-as-permute,
  broadcast, barrier).  They are meant to be called *inside* ``shard_map``/
  ``jit`` over a :class:`~deepspeed_tpu.parallel.topology.MeshTopology` mesh
  and lower to XLA collectives on ICI/DCN (psum, all_gather,
  psum_scatter, all_to_all, ppermute).  "Process groups" become mesh axis
  names.

* **Host-side control plane** — :func:`init_distributed` performs multi-host
  rendezvous via ``jax.distributed.initialize`` (the reference reads
  RANK/WORLD_SIZE/MASTER_ADDR from the launcher env,
  ``comm/comm.py:604``; we honor the same variables), plus
  rank/world-size queries and a host barrier.

Every traced op is wrapped by :func:`timed_op` which feeds the comms
logger (reference ``comm.py:101-141``).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.logging import logger

AxisName = Union[str, Sequence[str]]

_comms_logger = None  # lazily constructed CommsLogger


def configure_comms_logger(enabled: bool = True, verbose: bool = False, debug: bool = False):
    global _comms_logger
    from ..utils.comms_logging import CommsLogger
    _comms_logger = CommsLogger(enabled=enabled, verbose=verbose, debug=debug)
    return _comms_logger


def get_comms_logger():
    return _comms_logger


def timed_op(fn):
    """Record op name + message size for traced collectives.

    Timing individual device ops is meaningless under XLA (everything is
    fused/async); what we can faithfully log at trace time is op, shape and
    volume — actual latencies come from the profiler.  Mirrors the spirit of
    reference ``timed_op`` (comm.py:101).
    """

    @functools.wraps(fn)
    def wrapper(tensor, *args, **kwargs):
        if _comms_logger is not None and _comms_logger.enabled:
            _comms_logger.append_traced(fn.__name__, tensor)
        return fn(tensor, *args, **kwargs)

    return wrapper


# ---------------------------------------------------------------------------
# Traced collectives (call inside shard_map / with named axes in scope)
# ---------------------------------------------------------------------------

@timed_op
def all_reduce(tensor: jax.Array, axis_name: AxisName, op: str = "sum") -> jax.Array:
    """SUM/MAX/MIN/AVG all-reduce over a mesh axis (reference comm.py:483)."""
    if op in ("sum", "SUM"):
        return lax.psum(tensor, axis_name)
    if op in ("avg", "AVG", "mean"):
        return lax.pmean(tensor, axis_name)
    if op in ("max", "MAX"):
        return lax.pmax(tensor, axis_name)
    if op in ("min", "MIN"):
        return lax.pmin(tensor, axis_name)
    raise ValueError(f"unsupported reduce op: {op}")


@timed_op
def all_gather(tensor: jax.Array, axis_name: AxisName, axis: int = 0,
               tiled: bool = True) -> jax.Array:
    """Gather shards along ``axis`` (reference all_gather_into_tensor, comm.py:297)."""
    return lax.all_gather(tensor, axis_name, axis=axis, tiled=tiled)


@timed_op
def reduce_scatter(tensor: jax.Array, axis_name: AxisName, axis: int = 0,
                   tiled: bool = True) -> jax.Array:
    """Reduce-then-scatter along ``axis`` (reference reduce_scatter_fn, comm.py:246)."""
    return lax.psum_scatter(tensor, axis_name, scatter_dimension=axis, tiled=tiled)


@timed_op
def all_to_all(tensor: jax.Array, axis_name: AxisName, split_axis: int,
               concat_axis: int, tiled: bool = True) -> jax.Array:
    """All-to-all (reference all_to_all_single, comm.py:331). The Ulysses primitive."""
    return lax.all_to_all(tensor, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


@timed_op
def permute(tensor: jax.Array, axis_name: str, perm: Sequence[tuple]) -> jax.Array:
    """Point-to-point as collective-permute — the TPU replacement for the
    reference's pipeline send/recv (``runtime/pipe/p2p.py``).  ``perm`` is a
    list of (src, dst) pairs along ``axis_name``."""
    return lax.ppermute(tensor, axis_name, perm=list(perm))


def send_recv_next(tensor: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """Shift +1 along a ring: stage i -> stage i+1 (pipe activations)."""
    return lax.ppermute(tensor, axis_name,
                        perm=[(i, (i + 1) % axis_size) for i in range(axis_size)])


def send_recv_prev(tensor: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """Shift -1 along a ring: stage i -> stage i-1 (pipe gradients)."""
    return lax.ppermute(tensor, axis_name,
                        perm=[(i, (i - 1) % axis_size) for i in range(axis_size)])


@timed_op
def broadcast(tensor: jax.Array, axis_name: AxisName, src: int = 0) -> jax.Array:
    """Broadcast from ``src`` rank of the axis (reference comm.py:222)."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
    return lax.psum(masked, axis_name)


def axis_index(axis_name: AxisName) -> jax.Array:
    return lax.axis_index(axis_name)


def axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name)


# ---------------------------------------------------------------------------
# Host-side control plane
# ---------------------------------------------------------------------------

_initialized = False


def init_distributed(dist_backend: str = "xla",
                     timeout: Optional[float] = None,
                     rank: int = -1,
                     world_size: int = -1,
                     coordinator_address: Optional[str] = None,
                     auto_mpi_discovery: bool = True) -> None:
    """Multi-host rendezvous (reference init_distributed, comm.py:604).

    Honors the same env contract the reference launcher establishes
    (RANK / WORLD_SIZE / MASTER_ADDR / MASTER_PORT, launcher/launch.py) and
    maps it onto ``jax.distributed.initialize``.  Single-process usage is a
    no-op: JAX needs no rendezvous for one host.
    """
    global _initialized
    if _initialized:
        return
    env_world = int(os.environ.get("WORLD_SIZE", os.environ.get("DS_TPU_NUM_PROCESSES", "1")))
    world_size = world_size if world_size > 0 else env_world
    if world_size <= 1:
        _initialized = True
        return
    rank = rank if rank >= 0 else int(os.environ.get("RANK", "0"))
    if coordinator_address is None:
        addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
        port = os.environ.get("MASTER_PORT", "29500")
        coordinator_address = f"{addr}:{port}"
    logger.info("init_distributed: coordinator=%s rank=%d world=%d",
                coordinator_address, rank, world_size)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=world_size,
                               process_id=rank)
    _initialized = True


def is_initialized() -> bool:
    return _initialized


def get_rank() -> int:
    """Host-process rank.  The reference's rank==GPU==process identity
    splits on TPU (one process drives many chips): host-side concerns
    (logging, file writes, rendezvous) key on the PROCESS, device-level
    parallelism on the DEVICE — use get_device_count()/get_device_rank()
    for the latter.  rank/world pairs are always consistent."""
    return jax.process_index()


def get_world_size() -> int:
    """Number of host processes (consistent with get_rank)."""
    return jax.process_count()


def get_device_rank() -> int:
    """Global index of this process's first addressable device."""
    local = jax.local_devices()
    return local[0].id if local else 0


def get_device_count() -> int:
    """Device world size (the unit of SPMD parallelism on TPU)."""
    return jax.device_count()


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", "0"))


def barrier() -> None:
    """Host-level barrier: round-trip a tiny all-reduce through all devices."""
    if jax.process_count() == 1:
        return
    x = jnp.ones((), dtype=jnp.int32)
    jax.block_until_ready(
        jax.pmap(lambda v: lax.psum(v, "i"), axis_name="i")(
            jnp.ones((jax.local_device_count(),), jnp.int32)))
    del x
