from .module import LayerSpec, PipelineModule, TiedLayerSpec
from .schedule import (BackwardPass, DataParallelSchedule, ForwardPass,
                       InferenceSchedule, LoadMicroBatch, OptimizerStep,
                       PipeInstruction, PipeSchedule, RecvActivation,
                       RecvGrad, ReduceGrads, ReduceTiedGrads,
                       SendActivation, SendGrad, TrainSchedule)
from .engine import (PipelineEngine, PipelinedCausalLM, PipelinedModule,
                     gpipe_spmd, stack_stages)

__all__ = [
    "LayerSpec", "TiedLayerSpec", "PipelineModule",
    "PipeSchedule", "TrainSchedule", "InferenceSchedule",
    "DataParallelSchedule", "PipeInstruction", "OptimizerStep",
    "ReduceGrads", "ReduceTiedGrads", "LoadMicroBatch", "ForwardPass",
    "BackwardPass", "SendActivation", "RecvActivation", "SendGrad",
    "RecvGrad", "PipelineEngine", "PipelinedCausalLM", "PipelinedModule",
    "gpipe_spmd", "stack_stages",
]
