"""PipelineEngine — pipeline-parallel training (reference
``runtime/pipe/engine.py:56`` ``PipelineEngine``).

TPU-native redesign.  The reference executes a 1F1B instruction stream
(schedule.py) with host-dispatched p2p sends/recvs per micro-batch.  Under
XLA the entire pipelined step is ONE compiled program:

  reference                               here
  ---------                               ----
  per-instruction host dispatch           ``lax.scan`` over pipeline ticks
  p2p.send/recv (NCCL) + tensor-meta      ``lax.ppermute`` over the 'pipe'
  handshake (engine.py:939)               mesh axis (static shapes: no
                                          handshake needed)
  explicit BackwardPass instructions +    JAX AD through the scan+ppermute
  grad buffer management                  (transpose of ppermute is the
                                          reverse-direction ppermute — the
                                          backward pipeline comes out of
                                          the chain rule)
  PipelineModule layer partitioning       stage-stacked params: the layer
  onto ranks (module.py:387)              dim [L,...] reshaped to
                                          [S, L/S, ...], S sharded on
                                          'pipe' via shard_map
  activation-checkpointed stages          ``jax.checkpoint`` on the stage
  (module.py:340 exec_range_func)         body (saves only stage I/O)

Memory/throughput model: GPipe-style schedule with M micro-batches and S
stages runs T = M + S - 1 ticks (bubble fraction (S-1)/T); rematerialized
stage bodies keep live activations at O(T) stage-inputs per device, the
same bound the reference's 1F1B + activation checkpointing achieves.
Tensor/sequence/ZeRO axes stay in GSPMD "auto" mode inside the loop, so
one program composes PP with TP/SP/DP/ZeRO shardings.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax.core import meta
from jax.sharding import PartitionSpec as P

from ...models import transformer as tfm
from ...utils.logging import log_dist
from ..engine import DeepSpeedEngine
from .module import PipelineModule

PIPE_AXIS = "pipe"


# ---------------------------------------------------------------------------
# the SPMD pipeline loop
# ---------------------------------------------------------------------------

def gpipe_spmd(mesh,
               num_stages: int,
               stage_fn: Callable,
               stage_params: Any,
               x: jax.Array,
               consts: Any = (),
               remat: bool = True) -> jax.Array:
    """Differentiable pipelined map over the 'pipe' mesh axis.

    ``stage_params`` leaves carry a leading stage dim (global size S,
    sharded over 'pipe').  ``x``: [M, ...mb shape...] micro-batched input,
    replicated over 'pipe' (sharded over data axes in auto mode).
    ``stage_fn(local_stage_params, activation, consts, mb_id) ->
    activation`` must be shape-preserving; ``mb_id`` is the micro-batch
    index this stage is processing at the current tick (for indexing
    per-micro-batch consts such as attention masks).  Returns last-stage
    outputs [M, ...], replicated over 'pipe'.
    """
    S = num_stages
    if S == 1:
        sp = jax.tree.map(lambda a: a[0], stage_params)
        body = jax.checkpoint(stage_fn) if remat else stage_fn
        M = x.shape[0]
        return jax.lax.map(
            lambda im: body(sp, im[1], consts, im[0]),
            (jnp.arange(M), x))

    param_specs = jax.tree.map(lambda _: P(PIPE_AXIS), stage_params)
    perm = [(i, (i + 1) % S) for i in range(S)]
    # x crosses the region boundary in fp32: the shard_map transpose psums
    # the cotangent of a replicated input over 'pipe', and XLA-CPU's
    # all-reduce promotion pass miscompiles sub-fp32 all-reduces.  Inside
    # the region compute proceeds in the original (bf16) dtype.
    x_dtype = x.dtype
    x_in = x.astype(jnp.float32) if jnp.issubdtype(x_dtype, jnp.floating) else x

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(param_specs, P(), jax.tree.map(lambda _: P(), consts)),
        out_specs=P(PIPE_AXIS),
        axis_names=frozenset({PIPE_AXIS}),
        check_vma=False)
    def region(sp, x, consts):
        sp = jax.tree.map(lambda a: a[0], sp)  # [1, ...] -> local stage slice
        x = x.astype(x_dtype)
        consts = jax.tree.map(jax.lax.stop_gradient, consts)
        stage = jax.lax.axis_index(PIPE_AXIS)
        M = x.shape[0]
        T = M + S - 1
        body = jax.checkpoint(stage_fn) if remat else stage_fn

        def tick(carry, t):
            act, outputs = carry
            # stage 0 consumes micro-batch t; later stages consume the
            # activation ppermuted in at the previous tick.  At tick t,
            # stage s is working on micro-batch t - s.
            x_t = jax.lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, x_t, act)
            mb_id = jnp.clip(t - stage, 0, M - 1)
            out = body(sp, inp, consts, mb_id)
            # last stage finishes micro-batch t-(S-1) at tick t.
            out_idx = jnp.clip(t - (S - 1), 0, M - 1)
            upd = jax.lax.dynamic_update_index_in_dim(outputs, out, out_idx, 0)
            outputs = jnp.where(t >= S - 1, upd, outputs)
            nxt = jax.lax.ppermute(out, PIPE_AXIS, perm)
            return (nxt, outputs), None

        init = (jnp.zeros_like(x[0]), jnp.zeros_like(x))
        (_, outputs), _ = jax.lax.scan(tick, init, jnp.arange(T))
        # Stack per-stage output buffers over 'pipe': the caller slices the
        # last stage's (the only meaningful one).  Cheaper than a masked
        # psum — the slice lowers to a broadcast from the last stage, and
        # its transpose routes the loss cotangent back to it alone.
        return outputs[None]

    return region(stage_params, x_in, consts)[-1]


# ---------------------------------------------------------------------------
# stage-stacking of parameters
# ---------------------------------------------------------------------------

def stack_stages(boxed_params: Any, num_stages: int, layers_name: str = "layers"):
    """Reshape every boxed leaf's '<layers_name>' dim [L,...] -> [S, L/S,...]
    and prepend a 'stages' logical axis (mapped to the 'pipe' mesh axis by
    the partitioner).  Non-layer leaves pass through unchanged."""

    def fix(leaf):
        if not isinstance(leaf, meta.Partitioned):
            return leaf
        names = tuple(leaf.names)
        if layers_name not in names:
            return leaf
        dim = names.index(layers_name)
        if dim != 0:
            raise ValueError(f"'{layers_name}' dim must lead, got names={names}")
        L = leaf.value.shape[0]
        if L % num_stages != 0:
            raise ValueError(
                f"num_layers {L} not divisible by {num_stages} pipeline stages")
        new = leaf.value.reshape((num_stages, L // num_stages)
                                 + leaf.value.shape[1:])
        return meta.Partitioned(new, names=("stages",) + names)

    return jax.tree.map(fix, boxed_params,
                        is_leaf=lambda x: isinstance(x, meta.Partitioned))


# ---------------------------------------------------------------------------
# pipelined transformer LM
# ---------------------------------------------------------------------------

class PipelinedCausalLM:
    """Engine-protocol adapter running a transformer-family CausalLM
    (models/transformer.py) under pipeline parallelism.

    Layout: embedding / final norm / lm head are replicated over 'pipe'
    (their compute is tiny or amortized across the whole batch and their
    grads arrive via the shard_map transpose psum); the L transformer
    layers are split into S contiguous stages of L/S layers each.
    """

    def __init__(self, model, num_stages: int):
        self.inner = model
        self.cfg: tfm.TransformerConfig = model.cfg
        if not self.cfg.scan_layers:
            raise ValueError("pipeline requires scan_layers=True (stacked params)")
        self.num_stages = num_stages
        self.mesh = None  # set by PipelineEngine once topology exists
        if getattr(model, "is_moe", False) or hasattr(model, "moe_cfg"):
            raise NotImplementedError(
                "MoE models under PipelineEngine are not yet supported "
                "(the pipeline carry does not thread the gating aux loss); "
                "use expert parallelism without 'pipe', or a dense model")

    def init_params(self, rng):
        return stack_stages(self.inner.init_params(rng), self.num_stages)

    # -- loss ------------------------------------------------------------
    def loss(self, params, batch, rng=None):
        """batch leaves are micro-batched: {'input_ids': [M, mb, s], ...}."""
        assert self.mesh is not None, "PipelineEngine must set .mesh"
        cfg = self.cfg
        ids = batch["input_ids"]
        M, b, s = ids.shape

        positions = batch.get("positions")
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s), (M, b, s))
        else:
            positions = positions.reshape(M, b, s)

        # -- pre-pipeline (replicated over 'pipe') ------------------------
        x = params["embed"]["tokens"].astype(cfg.dtype)[ids]  # [M,b,s,e]
        if cfg.pos_emb == "learned":
            x = x + params["embed"]["positions"].astype(cfg.dtype)[positions]

        # per-micro-batch mask [M,b,s,s] — each stage indexes its current
        # micro-batch's slice via the mb_id the pipeline loop provides.
        if cfg.causal:
            mask = positions[:, :, :, None] >= positions[:, :, None, :]
        else:
            mask = jnp.ones((M, b, s, s), bool)
        attn_mask = batch.get("attention_mask")
        if attn_mask is not None:
            mask = mask & attn_mask.reshape(M, b, s)[:, :, None, :].astype(bool)
        sin, cos = tfm.rope_table(cfg, positions) if cfg.pos_emb == "rope" \
            else (jnp.zeros((M, b, s, 1)), jnp.zeros((M, b, s, 1)))

        def stage_fn(stage_layers, act, consts, mb_id):
            sin, cos, mask = jax.tree.map(
                lambda c: jax.lax.dynamic_index_in_dim(c, mb_id, 0,
                                                       keepdims=False),
                consts)

            def layer(carry, lp):
                y, _ = tfm._layer_body(cfg, lp, carry, sin, cos, mask)
                return y, None
            out, _ = jax.lax.scan(layer, act, stage_layers)
            return out

        outputs = gpipe_spmd(self.mesh, self.num_stages, stage_fn,
                             params["layers"], x,
                             consts=(sin, cos, mask),
                             remat=cfg.remat)   # [M,b,s,e]

        # -- post-pipeline (replicated over 'pipe') -----------------------
        h = tfm._norm_apply(cfg, params["final_norm"],
                            outputs.reshape(M * b, s, -1))
        if cfg.tie_embeddings:
            logits = jnp.einsum("bse,ve->bsv", h,
                                params["embed"]["tokens"].astype(cfg.dtype))
        else:
            logits = jnp.einsum("bse,ev->bsv", h,
                                params["lm_head"].astype(cfg.dtype))
        logits = logits.astype(jnp.float32)

        attn_flat = attn_mask.reshape(M * b, s) if attn_mask is not None else None
        if "labels" in batch:
            labels = batch["labels"].reshape(M * b, s)
            return tfm.cross_entropy_loss(logits, labels, attn_flat)
        labels = ids.reshape(M * b, s)[:, 1:]
        return tfm.cross_entropy_loss(
            logits[:, :-1], labels,
            attn_flat[:, 1:] if attn_flat is not None else None)

    def eval_loss(self, params, batch, rng=None):
        """Non-micro-batched batch: add a leading M=1 dim."""
        batch = {k: v[None] if hasattr(v, "ndim") else v
                 for k, v in batch.items()}
        return self.loss(params, batch, rng)


# ---------------------------------------------------------------------------
# generic homogeneous PipelineModule path
# ---------------------------------------------------------------------------

class PipelinedModule:
    """Engine adapter for a :class:`PipelineModule` whose layers all share
    one param structure (the stackable case; heterogeneous stage support
    goes through :class:`PipelinedCausalLM`-style model adapters instead).

    Batch dict: {'x': [M, mb, ...], 'y': [M, mb, ...]} with
    ``module.loss_fn(out, y) -> scalar``.
    """

    def __init__(self, module: PipelineModule, num_stages: int):
        if module.loss_fn is None:
            raise ValueError("PipelineModule needs loss_fn for training")
        self.module = module
        self.num_stages = num_stages
        self.mesh = None
        L = len(module)
        if L % num_stages != 0:
            raise ValueError(
                f"{L} layers not divisible by {num_stages} stages")
        # homogeneity check
        shapes = [jax.eval_shape(l.init_params, jax.random.key(0))
                  for l in module._built]
        treedefs = {str(jax.tree.structure(sh)) for sh in shapes}
        leaf_shapes = {tuple((l.shape, str(l.dtype))
                             for l in jax.tree.leaves(sh)) for sh in shapes}
        if len(treedefs) > 1 or len(leaf_shapes) > 1:
            raise ValueError(
                "pipeline stage stacking requires homogeneous layer specs; "
                "wrap heterogeneous edges (embed/head) outside the pipeline "
                "body (see PipelinedCausalLM)")
        self._layer0 = module._built[0]

    def init_params(self, rng):
        per_layer = self.module.init_layer_params(rng, range(len(self.module)))
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)
        L = len(self.module)
        S = self.num_stages
        return jax.tree.map(
            lambda a: meta.Partitioned(
                a.reshape((S, L // S) + a.shape[1:]),
                names=("stages", "layers") + (None,) * (a.ndim - 1)),
            stacked)

    def loss(self, params, batch, rng=None):
        assert self.mesh is not None
        x, y = batch["x"], batch["y"]
        M = x.shape[0]
        apply_layer = self._layer0.__call__

        def stage_fn(stage_layers, act, consts, mb_id):
            def layer(carry, lp):
                return apply_layer(lp, carry), None
            out, _ = jax.lax.scan(layer, act, stage_layers)
            return out

        outputs = gpipe_spmd(self.mesh, self.num_stages, stage_fn,
                             params, x)
        flat_out = outputs.reshape((-1,) + outputs.shape[2:])
        flat_y = y.reshape((-1,) + y.shape[2:])
        return self.module.loss_fn(flat_out, flat_y)

    def eval_loss(self, params, batch, rng=None):
        batch = {k: v[None] for k, v in batch.items()}
        return self.loss(params, batch, rng)


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class PipelineEngine(DeepSpeedEngine):
    """Training engine with pipeline parallelism (reference
    runtime/pipe/engine.py:56).

    ``train_batch`` consumes gradient_accumulation_steps micro-batches and
    runs them through the pipelined step as one XLA program.  The number of
    stages comes from config ``pipeline.stages`` / mesh 'pipe' axis.
    """

    def __init__(self, model: Any = None, config: Any = None, **kw):
        from ..config import load_config
        cfg = load_config(config)
        stages = cfg.tpu.mesh.get("pipe", cfg.pipeline.stages or 1)
        if isinstance(model, PipelineModule):
            adapter: Any = PipelinedModule(model, stages)
        elif hasattr(model, "cfg") and isinstance(model.cfg, tfm.TransformerConfig):
            adapter = PipelinedCausalLM(model, stages)
        else:
            raise ValueError(
                "PipelineEngine needs a PipelineModule or a transformer-family "
                f"model with .cfg; got {type(model)}")
        self._pipe_adapter = adapter
        self.num_stages = stages
        # pipeline consumes all micro-batches inside one loss evaluation
        self._fused_microbatches = True
        super().__init__(model=adapter, config=cfg, **kw)
        if self.topology.pp_world_size != stages:
            raise ValueError(
                f"mesh 'pipe' axis ({self.topology.pp_world_size}) != "
                f"pipeline stages ({stages})")
        log_dist(f"PipelineEngine: {stages} stages x "
                 f"{self.gradient_accumulation_steps()} micro-batches "
                 f"(bubble {(stages - 1)}/{self.gradient_accumulation_steps() + stages - 1})",
                 ranks=[0])

    def _build_train_step(self):
        self._pipe_adapter.mesh = self.topology.mesh
        return super()._build_train_step()

    def _build_eval_step(self):
        self._pipe_adapter.mesh = self.topology.mesh
        return super()._build_eval_step()

    @property
    def micro_batches(self) -> int:
        return self.gradient_accumulation_steps()

    def schedule(self, stage_id: Optional[int] = None):
        """The 1F1B instruction stream this step corresponds to (for
        introspection/tests; the XLA executor fuses it)."""
        from .schedule import TrainSchedule
        return TrainSchedule(self.micro_batches, self.num_stages,
                             stage_id if stage_id is not None else 0)
