"""PipelineEngine — pipeline-parallel training (reference
``runtime/pipe/engine.py:56`` ``PipelineEngine``).

TPU-native redesign.  The reference executes a 1F1B instruction stream
(schedule.py) with host-dispatched p2p sends/recvs per micro-batch.  Under
XLA the entire pipelined step is ONE compiled program:

  reference                               here
  ---------                               ----
  per-instruction host dispatch           ``lax.scan`` over pipeline ticks
  p2p.send/recv (NCCL) + tensor-meta      ``lax.ppermute`` over the 'pipe'
  handshake (engine.py:939)               mesh axis (static shapes: no
                                          handshake needed)
  explicit BackwardPass instructions +    JAX AD through the scan+ppermute
  grad buffer management                  (transpose of ppermute is the
                                          reverse-direction ppermute — the
                                          backward pipeline comes out of
                                          the chain rule)
  PipelineModule layer partitioning       stage-stacked params: the layer
  onto ranks (module.py:387)              dim [L,...] reshaped to
                                          [S, L/S, ...], S sharded on
                                          'pipe' via shard_map
  activation-checkpointed stages          ``jax.checkpoint`` on the stage
  (module.py:340 exec_range_func)         body (saves only stage I/O)

Memory/throughput model: both schedules run T = M + S - 1 ticks (bubble
fraction (S-1)/T).  The default "1f1b" schedule fuses embedding into
stage 0 and loss into the last stage, so neither the [M, b, s, e]
embedding/output buffers nor any full-batch logits ever materialize —
the role the reference's 1F1B ``TrainSchedule`` (schedule.py:189) plays
for activation memory.  MEASURED (compiled temp buffers, llama-debug,
pipe=2 x data=4): 2.2x below the "gpipe" stack-outputs schedule at M=8
and 3.1x at M=16 (tests/test_pipeline.py
test_1f1b_schedule_uses_less_memory_than_gpipe keeps the ordering
honest).  Tensor/sequence/ZeRO axes stay in GSPMD "auto" mode inside
the loop, so one program composes PP with TP/SP/DP/ZeRO shardings.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax.core import meta
from jax.sharding import PartitionSpec as P

from ...models import transformer as tfm
from ...utils.jax_compat import shard_map as _compat_shard_map
from ...utils.logging import log_dist
from ..engine import DeepSpeedEngine
from .module import PipelineModule

PIPE_AXIS = "pipe"


# ---------------------------------------------------------------------------
# the SPMD pipeline loop
# ---------------------------------------------------------------------------

def gpipe_spmd(mesh,
               num_stages: int,
               stage_fn: Callable,
               stage_params: Any,
               x: jax.Array,
               consts: Any = (),
               remat: bool = True,
               first_fn: Optional[Callable] = None,
               last_fn: Optional[Callable] = None,
               edge_params: Any = None,
               stage_aux: bool = False,
               consts_batched: Any = None) -> Any:
    """Differentiable pipelined map over the 'pipe' mesh axis.

    ``stage_params`` leaves carry a leading stage dim (global size S,
    sharded over 'pipe').  ``x``: [M, ...mb shape...] micro-batched input,
    replicated over 'pipe' (sharded over data axes in auto mode).
    ``stage_fn(local_stage_params, activation, consts, mb_id) ->
    activation`` must be shape-preserving; ``mb_id`` is the micro-batch
    index this stage is processing at the current tick (for indexing
    per-micro-batch consts such as attention masks).

    Two output modes:

    * **stack** (``last_fn=None``): returns last-stage outputs [M, ...],
      replicated over 'pipe' — the GPipe formulation; the full [M, ...]
      buffer threads through the scan carry.
    * **reduce** (``last_fn`` given): ``last_fn(out, consts, mb_id)``
      runs at the LAST stage as each micro-batch completes and its pytree
      result is SUMMED over micro-batches — the memory-bounded schedule
      (reference ``TrainSchedule`` 1F1B, runtime/pipe/schedule.py:189,
      exists to bound in-flight activations to O(stages); here the same
      bound comes from never materializing the [M, ...] output buffer or
      any full-batch logits — the carry holds one boundary activation
      plus scalar accumulators, and remat re-derives the rest).

    ``first_fn(edge_params, inp_mb, consts, mb_id)`` optionally maps the
    raw stage-0 input (e.g. token ids) to the activation shape, so the
    [M, ...] pipeline input can stay narrow (ids, not embeddings).

    ``edge_params`` carries the DIFFERENTIABLE leaves first_fn/last_fn
    need (embedding table, final norm, lm head).  Everything the region
    touches must enter through arguments — shard_map closure capture of
    sharded arrays clashes with the Manual-mode mesh — and ``consts`` is
    stop-gradiented, so differentiable edge weights get their own slot.

    ``stage_aux``: stage_fn returns ``(activation, aux_scalar)`` and the
    call returns ``(result, aux_total)`` — the MoE gating load-balance
    loss threaded through the pipeline carry (differentiable; only
    active ticks contribute, and the per-stage accumulators are summed
    over 'pipe').  aux_total sums over micro-batches; divide by M for
    the per-forward mean the dense path reports.
    """
    S = num_stages
    if S == 1:
        sp = jax.tree.map(lambda a: a[0], stage_params)
        body = jax.checkpoint(stage_fn) if remat else stage_fn
        M = x.shape[0]

        def one(im):
            mb_id, inp = im
            act = first_fn(edge_params, inp, consts, mb_id) if first_fn else inp
            out = body(sp, act, consts, mb_id)
            aux = jnp.zeros((), jnp.float32)
            if stage_aux:
                out, aux = out
            res = last_fn(edge_params, out, consts, mb_id) if last_fn else out
            return res, aux
        res, auxs = jax.lax.map(one, (jnp.arange(M), x))
        if last_fn:
            res = jax.tree.map(lambda a: a.sum(0), res)
        return (res, auxs.sum()) if stage_aux else res

    param_specs = jax.tree.map(lambda _: P(PIPE_AXIS), stage_params)
    perm = [(i, (i + 1) % S) for i in range(S)]

    # Batch-parallel axes go MANUAL alongside 'pipe' (fully-manual
    # region): differentiating a PARTIAL-auto region hits hard
    # partitioner bugs on this JAX version (scalar-residual _SpecError,
    # unsupported PartitionId — see utils/jax_compat.py notes), while a
    # fully-manual region differentiates fine.  Leaves of x/consts whose
    # dim 1 is the global micro-batch width shard over these axes; the
    # activation's dim 0 is that batch dim by the first_fn/stage_fn
    # contract.  Tensor/seq axes (if any) stay auto — grad through that
    # combination remains unsupported on this JAX version.
    batch_axes = tuple(a for a in ("data", "expert", "fsdp", "hpz")
                       if mesh.shape.get(a, 1) > 1)
    x0 = jax.tree.leaves(x)[0]
    b_global = x0.shape[1] if x0.ndim >= 2 else None
    n_bshards = int(np.prod([mesh.shape[a] for a in batch_axes])) \
        if batch_axes else 1
    batch_manual = bool(batch_axes) and b_global is not None \
        and b_global % n_bshards == 0
    if not batch_manual:
        batch_axes, n_bshards = (), 1

    def _batched(leaf) -> bool:
        return (batch_manual and np.ndim(leaf) >= 2
                and np.shape(leaf)[1] == b_global)

    # Which consts leaves carry the batch at dim 1?  Callers that know
    # (PipelineEngine.loss) pass ``consts_batched`` explicitly — the
    # dim-1-width heuristic mis-shards any replicated const whose second
    # dim coincidentally equals the micro-batch width (e.g. an [s, s]
    # table with s == b).
    if consts_batched is None:
        consts_flags = jax.tree.map(_batched, consts)
    else:
        consts_flags = jax.tree.map(
            lambda _a, f: bool(f) and batch_manual, consts, consts_batched)

    def _local_sds(a, f):
        """ShapeDtypeStruct with the batch dim localized to one shard."""
        shape = tuple(a.shape)
        if f:
            shape = (shape[0], shape[1] // n_bshards) + shape[2:]
        return jax.ShapeDtypeStruct(shape, a.dtype)

    # shape inference OUTSIDE the Manual-mode region (eval_shape inside
    # shard_map trips on mixed Manual/Auto mesh contexts), on LOCAL
    # (per-batch-shard) micro-batch shapes
    x0_sds = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(
            _local_sds(a, _batched(a)).shape[1:], a.dtype), x)
    consts_sds = jax.tree.map(_local_sds, consts, consts_flags)
    if first_fn is None:
        act_sds = jax.tree.leaves(x0_sds)[0]
    else:
        act_sds = jax.eval_shape(first_fn, edge_params, x0_sds,
                                 consts_sds, 0)
        act_sds = jax.ShapeDtypeStruct(act_sds.shape, act_sds.dtype)
    acc_sds = (jax.eval_shape(last_fn, edge_params, act_sds, consts_sds, 0)
               if last_fn is not None else None)
    # On XLA-CPU, x and edge_params cross the region boundary in fp32:
    # the shard_map transpose psums the cotangent of a replicated input
    # over 'pipe', and XLA-CPU's all-reduce promotion pass miscompiles
    # sub-fp32 all-reduces.  On TPU the widening is skipped — an fp32
    # copy of the embedding/lm-head per stage would be real HBM.
    widen = jax.default_backend() == "cpu"

    def _to_f32(t):
        if not widen:
            return t
        return jax.tree.map(
            lambda a: a.astype(jnp.float32)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, t)

    x_dtypes = jax.tree.map(lambda a: a.dtype, x)
    x_in = _to_f32(x)
    edge_dtypes = jax.tree.map(lambda a: a.dtype, edge_params)
    edge_in = _to_f32(edge_params)
    # Partial-auto shard_map on this JAX version appends the auto axes
    # to every input's dim-0 names, so a RANK-0 leaf trips the spec
    # check (_SpecError on float32[]).  Lift scalars to rank 1 at the
    # boundary and unlift inside.
    consts_ndims = jax.tree.map(jnp.ndim, consts)
    consts_in = jax.tree.map(
        lambda a, n: jnp.asarray(a)[None] if n == 0 else a,
        consts, consts_ndims)

    def _data_spec(flag: bool) -> P:
        return P(None, batch_axes) if flag else P()

    if last_fn is None and batch_manual:
        # stack mode: [1, M, b_local, ...] output keeps its batch shard
        out_specs = (P(PIPE_AXIS, None, batch_axes), P(PIPE_AXIS))
    else:
        # reduce-mode accumulators are psum'd over every manual axis
        out_specs = P(PIPE_AXIS)

    @functools.partial(
        _compat_shard_map, mesh=mesh,
        in_specs=(param_specs, jax.tree.map(lambda _: P(), edge_params),
                  jax.tree.map(lambda a: _data_spec(_batched(a)), x),
                  jax.tree.map(lambda _a, f: _data_spec(f),
                               consts_in, consts_flags)),
        out_specs=out_specs,
        # only axes that actually have devices go auto: pipe-(x batch)
        # meshes stay FULLY manual, which this JAX version can
        # differentiate (partial-auto grad hits known partitioner bugs)
        auto=frozenset(a for a in mesh.axis_names
                       if a != PIPE_AXIS and a not in batch_axes
                       and mesh.shape[a] > 1),
        check_vma=False)
    def region(sp, edge, x, consts):
        sp = jax.tree.map(lambda a: a[0], sp)  # [1, ...] -> local stage slice
        consts = jax.tree.map(lambda a, n: a[0] if n == 0 else a,
                              consts, consts_ndims)
        x = jax.tree.map(lambda a, d: a.astype(d), x, x_dtypes)
        edge = jax.tree.map(lambda a, d: a.astype(d), edge, edge_dtypes)
        consts = jax.tree.map(jax.lax.stop_gradient, consts)
        stage = jax.lax.axis_index(PIPE_AXIS)
        M = jax.tree.leaves(x)[0].shape[0]
        T = M + S - 1
        body = jax.checkpoint(stage_fn) if remat else stage_fn

        act0 = jnp.zeros(act_sds.shape, act_sds.dtype)

        def tick_common(act, t):
            # stage 0 consumes micro-batch t; later stages consume the
            # activation ppermuted in at the previous tick.  At tick t,
            # stage s is working on micro-batch t - s.
            mb0 = jnp.clip(t, 0, M - 1)
            x_t = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, mb0, 0,
                                                       keepdims=False), x)
            if first_fn is None:
                inp = jnp.where(stage == 0, x_t, act)
            else:
                # only stage 0 pays the embedding gather (predicate is
                # uniform across the non-pipe mesh axes, like last_fn)
                inp = jax.lax.cond(
                    stage == 0,
                    lambda: first_fn(edge, x_t, consts, mb0).astype(
                        act.dtype),
                    lambda: act)
            mb_id = jnp.clip(t - stage, 0, M - 1)
            out = body(sp, inp, consts, mb_id)
            aux = jnp.zeros((), jnp.float32)
            if stage_aux:
                out, aux = out
            # this stage did real work at tick t iff its micro-batch
            # index is in range (fill/drain ticks recompute clipped mbs)
            active = jnp.logical_and(t >= stage, t - stage < M)
            return out, jnp.where(active, aux, 0.0)

        if last_fn is None:
            def tick(carry, t):
                act, outputs, aux_acc = carry
                out, aux = tick_common(act, t)
                # last stage finishes micro-batch t-(S-1) at tick t.
                out_idx = jnp.clip(t - (S - 1), 0, M - 1)
                upd = jax.lax.dynamic_update_index_in_dim(
                    outputs, out, out_idx, 0)
                outputs = jnp.where(t >= S - 1, upd, outputs)
                nxt = jax.lax.ppermute(out, PIPE_AXIS, perm)
                return (nxt, outputs, aux_acc + aux), None

            init = (act0, jnp.zeros((M,) + act0.shape, act0.dtype),
                    jnp.zeros((), jnp.float32))
            (_, outputs, aux_acc), _ = jax.lax.scan(tick, init,
                                                    jnp.arange(T))
            # Stack per-stage output buffers over 'pipe': the caller
            # slices the last stage's (the only meaningful one).
            aux_tot = jax.lax.psum(aux_acc, PIPE_AXIS)  # sum stages
            if batch_axes:  # per-shard group-local aux -> batch mean
                aux_tot = jax.lax.pmean(aux_tot, batch_axes)
            return outputs[None], aux_tot[None]

        # reduce mode: accumulate last_fn contributions, no [M] buffer
        acc0 = jax.tree.map(lambda l: jnp.zeros(l.shape, l.dtype), acc_sds)

        def tick(carry, t):
            act, acc, aux_acc = carry
            out, aux = tick_common(act, t)
            out_mb = jnp.clip(t - (S - 1), 0, M - 1)
            valid = jnp.logical_and(t >= S - 1, stage == S - 1)
            # lax.cond: non-last stages (and fill ticks) skip the
            # norm+head+CE entirely instead of computing and masking it —
            # the predicate is uniform across the non-pipe mesh axes, so
            # auto-mode collectives inside the branch stay consistent
            contrib = jax.lax.cond(
                valid,
                lambda: last_fn(edge, out, consts, out_mb),
                lambda: jax.tree.map(
                    lambda l: jnp.zeros(l.shape, l.dtype), acc_sds))
            acc = jax.tree.map(lambda a, c: a + c, acc, contrib)
            nxt = jax.lax.ppermute(out, PIPE_AXIS, perm)
            return (nxt, acc, aux_acc + aux), None

        (_, acc, aux_acc), _ = jax.lax.scan(
            tick, (act0, acc0, jnp.zeros((), jnp.float32)), jnp.arange(T))
        # only the last stage accumulated; psum broadcasts it to all —
        # and with manual batch axes, the per-shard (loss sum, count)
        # accumulators sum into the GLOBAL totals (exact: the caller's
        # loss_sum / count is then the global token-weighted mean)
        acc = jax.tree.map(
            lambda a: jax.lax.psum(a, (PIPE_AXIS,) + batch_axes), acc)
        aux_tot = jax.lax.psum(aux_acc, PIPE_AXIS)
        if batch_axes:
            aux_tot = jax.lax.pmean(aux_tot, batch_axes)
        return jax.tree.map(lambda a: a[None], acc), aux_tot[None]

    res, aux = region(stage_params, edge_in, x_in, consts_in)
    if last_fn is None:
        out = res[-1]
    else:
        out = jax.tree.map(lambda a: a[0], res)
    return (out, aux[0]) if stage_aux else out


# ---------------------------------------------------------------------------
# stage-stacking of parameters
# ---------------------------------------------------------------------------

def stack_stages(boxed_params: Any, num_stages: int, layers_name: str = "layers"):
    """Reshape every boxed leaf's '<layers_name>' dim [L,...] -> [S, L/S,...]
    and prepend a 'stages' logical axis (mapped to the 'pipe' mesh axis by
    the partitioner).  Non-layer leaves pass through unchanged."""

    def fix(leaf):
        if not isinstance(leaf, meta.Partitioned):
            return leaf
        names = tuple(leaf.names)
        if layers_name not in names:
            return leaf
        dim = names.index(layers_name)
        if dim != 0:
            raise ValueError(f"'{layers_name}' dim must lead, got names={names}")
        L = leaf.value.shape[0]
        if L % num_stages != 0:
            raise ValueError(
                f"num_layers {L} not divisible by {num_stages} pipeline stages")
        new = leaf.value.reshape((num_stages, L // num_stages)
                                 + leaf.value.shape[1:])
        return meta.Partitioned(new, names=("stages",) + names)

    return jax.tree.map(fix, boxed_params,
                        is_leaf=lambda x: isinstance(x, meta.Partitioned))


# ---------------------------------------------------------------------------
# pipelined transformer LM
# ---------------------------------------------------------------------------

class PipelinedCausalLM:
    """Engine-protocol adapter running a transformer-family CausalLM
    (models/transformer.py) under pipeline parallelism.

    Layout: embedding / final norm / lm head are replicated over 'pipe'
    (their compute is tiny or amortized across the whole batch and their
    grads arrive via the shard_map transpose psum); the L transformer
    layers are split into S contiguous stages of L/S layers each.
    """

    def __init__(self, model, num_stages: int, schedule: str = "1f1b"):
        self.inner = model
        self.cfg: tfm.TransformerConfig = model.cfg
        if not self.cfg.scan_layers:
            raise ValueError("pipeline requires scan_layers=True (stacked params)")
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        self.num_stages = num_stages
        self.schedule = schedule
        self.mesh = None  # set by PipelineEngine once topology exists
        # MoE: the gating aux loss threads through the pipeline carry
        # (gpipe_spmd stage_aux); gate noise is disabled under the
        # pipeline (rng cannot enter the Manual-mode region as a
        # closure), matching the deterministic top-k default
        self.moe_cfg = getattr(model, "moe_cfg", None)

    def init_params(self, rng):
        return stack_stages(self.inner.init_params(rng), self.num_stages)

    # -- loss ------------------------------------------------------------
    def loss(self, params, batch, rng=None, is_training=True):
        """batch leaves are micro-batched: {'input_ids': [M, mb, s], ...}."""
        assert self.mesh is not None, "PipelineEngine must set .mesh"
        cfg = self.cfg
        ids = batch["input_ids"]
        M, b, s = ids.shape

        positions = batch.get("positions")
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s), (M, b, s))
        else:
            positions = positions.reshape(M, b, s)

        # per-micro-batch mask [M,b,s,s] — each stage indexes its current
        # micro-batch's slice via the mb_id the pipeline loop provides.
        if cfg.causal:
            mask = positions[:, :, :, None] >= positions[:, :, None, :]
        else:
            mask = jnp.ones((M, b, s, s), bool)
        attn_mask = batch.get("attention_mask")
        if attn_mask is not None:
            mask = mask & attn_mask.reshape(M, b, s)[:, :, None, :].astype(bool)
        sin, cos = tfm.rope_table(cfg, positions) if cfg.pos_emb == "rope" \
            else (jnp.zeros((M, b, s, 1)), jnp.zeros((M, b, s, 1)))

        # ALiBi: [M, b, H, s] per-micro-batch additive bias (key-position
        # linear; see models/transformer.forward); None otherwise
        abias_all = None
        if cfg.pos_emb == "alibi":
            slopes = jnp.asarray(tfm.alibi_slopes(cfg.num_heads))
            abias_all = (slopes[None, None, :, None]
                         * positions[:, :, None, :].astype(jnp.float32))

        labels_all = batch.get("labels")
        if labels_all is not None:
            labels_all = labels_all.reshape(M, b, s)

        moe_cfg = self.moe_cfg
        if moe_cfg is not None:
            from ...moe.layer import moe_forward
            training = is_training  # eval regime: eval_capacity_factor
            if (getattr(moe_cfg, "noisy_gate_policy", None)
                    and not getattr(self, "_gate_noise_warned", False)):
                self._gate_noise_warned = True
                log_dist(
                    "PipelineEngine: noisy_gate_policy="
                    f"{moe_cfg.noisy_gate_policy!r} is DISABLED under the "
                    "pipeline (rng cannot enter the Manual-mode region); "
                    "gating is deterministic top-k here",
                    level=__import__("logging").WARNING)

            def mlp_fn(c, p, h):
                return moe_forward(moe_cfg, p, h, is_training=training)
        else:
            mlp_fn = None

        def stage_fn(stage_layers, act, consts, mb_id):
            sin, cos, mask = jax.tree.map(
                lambda c: jax.lax.dynamic_index_in_dim(c, mb_id, 0,
                                                       keepdims=False),
                consts[:3])
            ab = (jax.lax.dynamic_index_in_dim(consts[3], mb_id, 0,
                                               keepdims=False)
                  if cfg.pos_emb == "alibi" else None)

            def layer(carry, lp):
                h, aux_acc = carry
                y, aux = tfm._layer_body(cfg, lp, h, sin, cos, mask,
                                         mlp_fn=mlp_fn, attn_bias=ab)
                return (y, aux_acc + aux), None
            (out, aux), _ = jax.lax.scan(
                layer, (act, jnp.zeros((), jnp.float32)), stage_layers)
            return (out, aux) if moe_cfg is not None else out

        def head_and_ce(edge, h_mb, consts, mb_id):
            """Final norm + lm head + CE for ONE micro-batch ->
            (weighted loss sum, valid-token count)."""
            h = tfm._norm_apply(cfg, edge["final_norm"], h_mb)
            if cfg.tie_embeddings:
                logits = jnp.einsum(
                    "bse,ve->bsv", h,
                    edge["embed"]["tokens"].astype(cfg.dtype))
            else:
                logits = jnp.einsum(
                    "bse,ev->bsv", h, edge["lm_head"].astype(cfg.dtype))
            logits = logits.astype(jnp.float32)
            _, _, _, _, c_ids, c_labels, c_am, _ = consts
            am = (jax.lax.dynamic_index_in_dim(c_am, mb_id, 0,
                                               keepdims=False)
                  if c_am is not None else None)
            def _valid_count(lab, m):
                # mirror cross_entropy_loss: labels < 0 are ignored, and
                # the attention mask gates validity
                v = lab >= 0
                if m is not None:
                    v = v & m.astype(bool)
                return v.sum().astype(jnp.float32)

            if c_labels is not None:
                lab = jax.lax.dynamic_index_in_dim(c_labels, mb_id, 0,
                                                   keepdims=False)
                ce = tfm.cross_entropy_loss(logits, lab, am)
                count = _valid_count(lab, am)
            else:
                lab = jax.lax.dynamic_index_in_dim(c_ids, mb_id, 0,
                                                   keepdims=False)[:, 1:]
                am1 = am[:, 1:] if am is not None else None
                ce = tfm.cross_entropy_loss(logits[:, :-1], lab, am1)
                count = _valid_count(lab, am1)
            return ce * count, count

        # micro-batch entry: embed token ids at stage 0 (keeps the [M,...]
        # pipeline input at id width — the [M,b,s,e] embedding buffer of
        # the stack schedule never exists)
        def embed_mb(edge, ids_mb, consts, mb_id):
            x = edge["embed"]["tokens"].astype(cfg.dtype)[ids_mb]
            if cfg.pos_emb == "learned":
                pos_mb = jax.lax.dynamic_index_in_dim(
                    consts[7], mb_id, 0, keepdims=False)
                x = x + edge["embed"]["positions"].astype(cfg.dtype)[pos_mb]
            if cfg.embed_layernorm:  # BLOOM word_embeddings_layernorm
                x = tfm._norm_apply(cfg, edge["embed"]["norm"], x)
            return x

        if self.schedule == "1f1b":
            edge = {"embed": params["embed"],
                    "final_norm": params["final_norm"]}
            if not cfg.tie_embeddings:
                edge["lm_head"] = params["lm_head"]
            am_c = (attn_mask.reshape(M, b, s)
                    if attn_mask is not None else None)
            abias_c = (abias_all if abias_all is not None
                       else jnp.zeros((M, 1), jnp.float32))  # never indexed
            res = gpipe_spmd(
                self.mesh, self.num_stages, stage_fn, params["layers"], ids,
                consts=(sin, cos, mask, abias_c, ids, labels_all, am_c,
                        positions),
                consts_batched=(True, True, True, abias_all is not None,
                                True,
                                None if labels_all is None else True,
                                None if am_c is None else True, True),
                remat=cfg.remat,
                first_fn=embed_mb, last_fn=head_and_ce, edge_params=edge,
                stage_aux=moe_cfg is not None)
            if moe_cfg is not None:
                (loss_sum, count), aux = res
                # aux summed over micro-batches -> per-forward mean, the
                # dense path's convention (mixtral loss = ce + aux)
                return loss_sum / jnp.maximum(count, 1.0) + aux / M
            loss_sum, count = res
            return loss_sum / jnp.maximum(count, 1.0)

        # gpipe: stack all outputs, one full-batch head/CE
        x = params["embed"]["tokens"].astype(cfg.dtype)[ids]
        if cfg.pos_emb == "learned":
            x = x + params["embed"]["positions"].astype(cfg.dtype)[positions]
        if cfg.embed_layernorm:
            x = tfm._norm_apply(cfg, params["embed"]["norm"], x)
        outputs = gpipe_spmd(self.mesh, self.num_stages, stage_fn,
                             params["layers"], x,
                             consts=(sin, cos, mask,
                                     abias_all if abias_all is not None
                                     else jnp.zeros((M, 1), jnp.float32)),
                             consts_batched=(True, True, True,
                                             abias_all is not None),
                             remat=cfg.remat,
                             stage_aux=moe_cfg is not None)   # [M,b,s,e]
        aux_mean = jnp.zeros((), jnp.float32)
        if moe_cfg is not None:
            outputs, aux_tot = outputs
            aux_mean = aux_tot / M
        h = tfm._norm_apply(cfg, params["final_norm"],
                            outputs.reshape(M * b, s, -1))
        if cfg.tie_embeddings:
            logits = jnp.einsum("bse,ve->bsv", h,
                                params["embed"]["tokens"].astype(cfg.dtype))
        else:
            logits = jnp.einsum("bse,ev->bsv", h,
                                params["lm_head"].astype(cfg.dtype))
        logits = logits.astype(jnp.float32)

        attn_flat = attn_mask.reshape(M * b, s) if attn_mask is not None else None
        if "labels" in batch:
            labels = batch["labels"].reshape(M * b, s)
            return tfm.cross_entropy_loss(logits, labels,
                                          attn_flat) + aux_mean
        labels = ids.reshape(M * b, s)[:, 1:]
        return tfm.cross_entropy_loss(
            logits[:, :-1], labels,
            attn_flat[:, 1:] if attn_flat is not None else None) + aux_mean

    def eval_loss(self, params, batch, rng=None):
        """Non-micro-batched batch: add a leading M=1 dim; MoE gating
        runs in the eval regime (eval_capacity_factor, no noise)."""
        batch = {k: v[None] if hasattr(v, "ndim") else v
                 for k, v in batch.items()}
        return self.loss(params, batch, rng, is_training=False)


# ---------------------------------------------------------------------------
# generic homogeneous PipelineModule path
# ---------------------------------------------------------------------------

class PipelinedModule:
    """Engine adapter for a :class:`PipelineModule` whose layers all share
    one param structure (the stackable case; heterogeneous stage support
    goes through :class:`PipelinedCausalLM`-style model adapters instead).

    Batch dict: {'x': [M, mb, ...], 'y': [M, mb, ...]} with
    ``module.loss_fn(out, y) -> scalar``.
    """

    def __init__(self, module: PipelineModule, num_stages: int,
                 schedule: str = "1f1b"):
        if module.loss_fn is None:
            raise ValueError("PipelineModule needs loss_fn for training")
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        self.module = module
        self.num_stages = num_stages
        self.schedule = schedule
        self.mesh = None
        L = len(module)
        if L % num_stages != 0:
            raise ValueError(
                f"{L} layers not divisible by {num_stages} stages")
        # homogeneity check
        shapes = [jax.eval_shape(l.init_params, jax.random.key(0))
                  for l in module._built]
        treedefs = {str(jax.tree.structure(sh)) for sh in shapes}
        leaf_shapes = {tuple((l.shape, str(l.dtype))
                             for l in jax.tree.leaves(sh)) for sh in shapes}
        if len(treedefs) > 1 or len(leaf_shapes) > 1:
            raise ValueError(
                "pipeline stage stacking requires homogeneous layer specs; "
                "wrap heterogeneous edges (embed/head) outside the pipeline "
                "body (see PipelinedCausalLM)")
        self._layer0 = module._built[0]

    def init_params(self, rng):
        per_layer = self.module.init_layer_params(rng, range(len(self.module)))
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)
        L = len(self.module)
        S = self.num_stages
        return jax.tree.map(
            lambda a: meta.Partitioned(
                a.reshape((S, L // S) + a.shape[1:]),
                names=("stages", "layers") + (None,) * (a.ndim - 1)),
            stacked)

    def loss(self, params, batch, rng=None):
        assert self.mesh is not None
        x, y = batch["x"], batch["y"]
        M = x.shape[0]
        apply_layer = self._layer0.__call__

        def stage_fn(stage_layers, act, consts, mb_id):
            def layer(carry, lp):
                return apply_layer(lp, carry), None
            out, _ = jax.lax.scan(layer, act, stage_layers)
            return out

        if self.schedule == "1f1b":
            loss_fn = self.module.loss_fn

            def last_fn(edge, out, consts, mb_id):
                y_mb = jax.lax.dynamic_index_in_dim(consts[0], mb_id, 0,
                                                    keepdims=False)
                return loss_fn(out, y_mb)

            total = gpipe_spmd(self.mesh, self.num_stages, stage_fn,
                               params, x, consts=(y,), last_fn=last_fn,
                               consts_batched=(True,))
            # Micro-batch average, matching the reference pipeline
            # engine (its total_loss accumulates per-micro-batch losses
            # and divides by micro_batches).  CONTRACT: loss_fn must
            # return a per-micro-batch MEAN for this to equal the flat
            # batch mean; a sum-style or unevenly-masked loss_fn gets
            # the reference's mean-of-means semantics, not the flat
            # mean — use schedule="gpipe" for exact flat-batch loss.
            return total / M

        outputs = gpipe_spmd(self.mesh, self.num_stages, stage_fn,
                             params, x)
        flat_out = outputs.reshape((-1,) + outputs.shape[2:])
        flat_y = y.reshape((-1,) + y.shape[2:])
        return self.module.loss_fn(flat_out, flat_y)

    def eval_loss(self, params, batch, rng=None):
        batch = {k: v[None] for k, v in batch.items()}
        return self.loss(params, batch, rng)


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class PipelineEngine(DeepSpeedEngine):
    """Training engine with pipeline parallelism (reference
    runtime/pipe/engine.py:56).

    ``train_batch`` consumes gradient_accumulation_steps micro-batches and
    runs them through the pipelined step as one XLA program.  The number of
    stages comes from config ``pipeline.stages`` / mesh 'pipe' axis.
    """

    def __init__(self, model: Any = None, config: Any = None, **kw):
        from ..config import load_config
        cfg = load_config(config)
        stages = cfg.tpu.mesh.get("pipe", cfg.pipeline.stages or 1)
        if isinstance(model, PipelineModule):
            adapter: Any = PipelinedModule(model, stages,
                                           schedule=cfg.pipeline.schedule)
        elif hasattr(model, "cfg") and isinstance(model.cfg, tfm.TransformerConfig):
            adapter = PipelinedCausalLM(model, stages,
                                         schedule=cfg.pipeline.schedule)
        else:
            raise ValueError(
                "PipelineEngine needs a PipelineModule or a transformer-family "
                f"model with .cfg; got {type(model)}")
        self._pipe_adapter = adapter
        self.num_stages = stages
        # pipeline consumes all micro-batches inside one loss evaluation
        self._fused_microbatches = True
        super().__init__(model=adapter, config=cfg, **kw)
        if self.topology.pp_world_size != stages:
            raise ValueError(
                f"mesh 'pipe' axis ({self.topology.pp_world_size}) != "
                f"pipeline stages ({stages})")
        log_dist(f"PipelineEngine: {stages} stages x "
                 f"{self.gradient_accumulation_steps()} micro-batches "
                 f"(bubble {(stages - 1)}/{self.gradient_accumulation_steps() + stages - 1})",
                 ranks=[0])

    def _build_train_step(self):
        self._pipe_adapter.mesh = self.topology.mesh
        return super()._build_train_step()

    def _build_eval_step(self):
        self._pipe_adapter.mesh = self.topology.mesh
        return super()._build_eval_step()

    @property
    def micro_batches(self) -> int:
        return self.gradient_accumulation_steps()

    def schedule(self, stage_id: Optional[int] = None):
        """The 1F1B instruction stream this step corresponds to (for
        introspection/tests; the XLA executor fuses it)."""
        from .schedule import TrainSchedule
        return TrainSchedule(self.micro_batches, self.num_stages,
                             stage_id if stage_id is not None else 0)
