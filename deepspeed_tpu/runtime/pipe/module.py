"""Pipeline module specs (reference ``runtime/pipe/module.py``:
``LayerSpec`` :30, ``TiedLayerSpec`` :77, ``PipelineModule`` :86).

A ``PipelineModule`` declares the model as an ordered list of layer specs;
the pipeline engine partitions them into stages over the 'pipe' mesh axis
(partitioning methods mirror the reference ``_partition_layers`` :387:
uniform / parameters / type:regex).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ...utils.logging import logger


class LayerSpec:
    """Delayed layer construction (reference LayerSpec, module.py:30).

    ``typename`` is a callable returning a layer object exposing
    ``init_params(rng) -> params`` and ``__call__(params, x) -> x``
    (pure/functional; no nn.Module needed on TPU).
    """

    def __init__(self, typename: Callable, *args, **kwargs):
        self.typename = typename
        self.module_args = args
        self.module_kwargs = kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        name = getattr(self.typename, "__name__", str(self.typename))
        return f"LayerSpec({name})"


class TiedLayerSpec(LayerSpec):
    """Weight-tied layer (reference TiedLayerSpec, module.py:77): layers
    sharing ``key`` reuse one parameter set; the engine reduces tied grads
    across stages (reference allreduce_tied_weight_gradients, module.py:440)."""

    def __init__(self, key: str, typename: Callable, *args,
                 forward_fn: Optional[Callable] = None, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn


class PipelineModule:
    """Layer-list model for pipeline parallelism (reference
    PipelineModule, module.py:86)."""

    def __init__(self,
                 layers: Sequence[LayerSpec],
                 num_stages: Optional[int] = None,
                 loss_fn: Optional[Callable] = None,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0,
                 seed_layers: bool = False,
                 base_seed: int = 1234):
        self.layer_specs = list(layers)
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self._built = [spec.build() for spec in self.layer_specs]

    def __len__(self):
        return len(self.layer_specs)

    # -- partitioning (reference _partition_layers, module.py:387) --------
    def partition_layers(self, num_stages: int) -> List[List[int]]:
        n = len(self._built)
        method = self.partition_method.lower()
        if method == "uniform":
            bounds = _partition_uniform(n, num_stages)
        elif method == "parameters":
            weights = [self._layer_param_count(l) for l in self._built]
            bounds = _partition_balanced(weights, num_stages)
        elif method.startswith("type:"):
            pattern = method.split(":", 1)[1]
            weights = [1 if re.search(pattern, type(l).__name__, re.IGNORECASE) else 0
                       for l in self._built]
            bounds = _partition_balanced(weights, num_stages)
        else:
            raise ValueError(f"unknown partition_method {self.partition_method}")
        parts = [list(range(bounds[i], bounds[i + 1])) for i in range(num_stages)]
        logger.info("pipeline partition (%s): %s", method,
                    [len(p) for p in parts])
        return parts

    def _layer_param_count(self, layer) -> int:
        init = getattr(layer, "init_params", None)
        if init is None:
            return 0
        abstract = jax.eval_shape(init, jax.random.key(0))
        return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(abstract))

    def init_layer_params(self, rng, indices: Sequence[int]):
        params = []
        for i in indices:
            layer = self._built[i]
            seed_rng = jax.random.fold_in(rng, self.base_seed + i) \
                if self.seed_layers else jax.random.fold_in(rng, i)
            init = getattr(layer, "init_params", None)
            params.append(init(seed_rng) if init is not None else {})
        return params

    def forward_stage(self, layer_params, indices: Sequence[int], x):
        for p, i in zip(layer_params, indices):
            layer = self._built[i]
            x = layer(p, x)
        return x


def _partition_uniform(num_items: int, num_parts: int) -> List[int]:
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    extra = num_items % num_parts
    for i in range(1, num_parts + 1):
        parts[i] = parts[i - 1] + chunk + (1 if i <= extra else 0)
    return parts


def _partition_balanced(weights: List[int], num_parts: int) -> List[int]:
    """Greedy prefix-sum balancing (reference ds_utils.partition_balanced)."""
    prefix = np.concatenate([[0], np.cumsum(weights)])
    total = prefix[-1]
    bounds = [0]
    for p in range(1, num_parts):
        target = total * p / num_parts
        idx = int(np.searchsorted(prefix, target))
        idx = max(bounds[-1] + 1, min(idx, len(weights) - (num_parts - p)))
        bounds.append(idx)
    bounds.append(len(weights))
    return bounds
