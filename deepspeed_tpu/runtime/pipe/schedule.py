"""Pipeline schedules (reference ``runtime/pipe/schedule.py``).

The reference defines an instruction ISA (schedule.py:327-…: ``OptimizerStep,
ReduceGrads, ReduceTiedGrads, LoadMicroBatch, ForwardPass, BackwardPass,
SendActivation, RecvActivation, SendGrad, RecvGrad``) and per-rank
generators: ``TrainSchedule`` (1F1B, :189), ``InferenceSchedule`` (:135),
``DataParallelSchedule``.  ``PipelineEngine._exec_schedule`` walks the
instruction stream.

On TPU the *executor* is different: the whole pipeline is one XLA program
(`engine.py` here lowers the microbatch loop to ``lax.scan`` +
``ppermute``), so the per-instruction host dispatch of the reference
disappears.  The ISA is still the right description level for

  * schedule correctness reasoning/tests (1F1B invariants),
  * the host-driven executor fallback (debugging, heterogeneous stages),
  * tooling parity (anything that introspects schedules).

Semantics match the reference: ``micro_batches`` buffers flow through
``stages`` pipeline stages; a schedule yields, per "clock step", the list
of instructions one ``stage_id`` executes.
"""

from __future__ import annotations

from typing import Iterator, List


class PipeInstruction:
    """Base instruction (reference schedule.py:327)."""

    def __init__(self, **kwargs):
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{type(self).__name__}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    """Apply the optimizer update (reference schedule.py:338)."""


class ReduceGrads(PipeInstruction):
    """Data-parallel gradient reduction (reference schedule.py:346)."""


class ReduceTiedGrads(PipeInstruction):
    """All-reduce tied-weight grads across their tie group (reference
    schedule.py:353; module.py:440 allreduce_tied_weight_gradients)."""


class BufferOpInstruction(PipeInstruction):
    """Instruction operating on a pipeline buffer slot (schedule.py:363)."""

    def __init__(self, buffer_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    """Load micro-batch ``micro_batch_id`` into ``buffer_id``."""

    def __init__(self, buffer_id: int, micro_batch_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, micro_batch_id=micro_batch_id,
                         **kwargs)


class ForwardPass(BufferOpInstruction):
    """Run the stage forward on buffer ``buffer_id``."""

    def __init__(self, buffer_id: int, micro_batch_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, micro_batch_id=micro_batch_id,
                         **kwargs)


class BackwardPass(BufferOpInstruction):
    """Run the stage backward on buffer ``buffer_id``."""

    def __init__(self, buffer_id: int, micro_batch_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, micro_batch_id=micro_batch_id,
                         **kwargs)


class SendActivation(BufferOpInstruction):
    """p2p activation send to stage+1 (collective-permute on TPU)."""


class RecvActivation(BufferOpInstruction):
    """p2p activation recv from stage-1."""


class SendGrad(BufferOpInstruction):
    """p2p activation-grad send to stage-1."""


class RecvGrad(BufferOpInstruction):
    """p2p activation-grad recv from stage+1."""


class PipeSchedule:
    """Per-stage instruction-stream generator (reference schedule.py:22).

    Subclasses implement ``steps()`` yielding ``List[PipeInstruction]`` per
    clock step for this ``stage_id``.
    """

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        if not (0 <= stage_id < stages):
            raise ValueError(f"stage_id {stage_id} out of range for {stages}")
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    # -- topology helpers (reference schedule.py:66-101) -------------------
    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, micro_batch_id: int) -> bool:
        return 0 <= micro_batch_id < self.micro_batches

    def num_pipe_buffers(self) -> int:
        """Buffer slots this stage needs (reference schedule.py:102)."""
        return self.micro_batches

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def __iter__(self):
        return self.steps()


class InferenceSchedule(PipeSchedule):
    """Forward-only pipelining (reference schedule.py:135): at clock step t,
    stage s forwards micro-batch t - s (when valid)."""

    def num_pipe_buffers(self) -> int:
        return 2

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            buf = micro_batch_id % self.num_pipe_buffers() \
                if micro_batch_id >= 0 else 0
            if self._valid_micro_batch(micro_batch_id):
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buf, micro_batch_id))
                else:
                    cmds.append(RecvActivation(buf))
                cmds.append(ForwardPass(buf, micro_batch_id))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B (reference schedule.py:189).

    Phases per stage s (S stages, M micro-batches):
      warmup   : min(M, S - s) forwards
      steady   : alternate 1 backward / 1 forward
      cooldown : remaining backwards
      tail     : ReduceTiedGrads, ReduceGrads, OptimizerStep

    In-flight forwards never exceed S - s, which bounds activation
    memory — the property the XLA executor preserves via rematerialized
    stage bodies.
    """

    def num_pipe_buffers(self) -> int:
        # reference schedule.py:247: enough buffers for in-flight microbatches
        return max(2, min(self.micro_batches, self.stages - self.stage_id))

    def steps(self):
        M, S, s = self.micro_batches, self.stages, self.stage_id
        warmup = min(M, S - s)
        fwd_id = 0   # next micro-batch to forward
        bwd_id = 0   # next micro-batch to backward

        # warmup forwards
        for _ in range(warmup):
            yield self._forward_cmds(fwd_id)
            fwd_id += 1
        # steady state: 1B1F
        while fwd_id < M:
            yield self._backward_cmds(bwd_id)
            bwd_id += 1
            yield self._forward_cmds(fwd_id)
            fwd_id += 1
        # cooldown backwards
        while bwd_id < M:
            yield self._backward_cmds(bwd_id)
            bwd_id += 1
        # gradient reduction + step (reference schedule.py:222-244 tail)
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]

    def _forward_cmds(self, micro_batch_id: int) -> List[PipeInstruction]:
        buf = self._buffer_idx(micro_batch_id)
        cmds: List[PipeInstruction] = []
        if self.is_first_stage:
            cmds.append(LoadMicroBatch(buf, micro_batch_id))
        else:
            cmds.append(RecvActivation(buf))
        cmds.append(ForwardPass(buf, micro_batch_id))
        if not self.is_last_stage:
            cmds.append(SendActivation(buf))
        return cmds

    def _backward_cmds(self, micro_batch_id: int) -> List[PipeInstruction]:
        buf = self._buffer_idx(micro_batch_id)
        cmds: List[PipeInstruction] = []
        if not self.is_last_stage:
            cmds.append(RecvGrad(buf))
        cmds.append(BackwardPass(buf, micro_batch_id))
        if not self.is_first_stage:
            cmds.append(SendGrad(buf))
        return cmds


class DataParallelSchedule(PipeSchedule):
    """Degenerate no-pipeline schedule (reference schedule.py:305): forward+
    backward every micro-batch, then reduce + step."""

    def num_pipe_buffers(self) -> int:
        return 1

    def steps(self):
        for mb in range(self.micro_batches):
            yield [LoadMicroBatch(0, mb), ForwardPass(0, mb),
                   BackwardPass(0, mb)]
        yield [ReduceGrads(), OptimizerStep()]
