"""Sparse (indexed-slices) gradients for embedding tables.

Reference: ``deepspeed/runtime/sparse_tensor.py:13`` (``SparseTensor``,
a COO rows+values compression of embedding grads) and the sparse
allreduce path ``deepspeed/runtime/engine.py:2535-2608``
(``sparse_allreduce_no_retain``: all_gather indices+values across the DP
group instead of allreducing the dense ``[V, E]`` gradient).

TPU-native formulation.  Dynamic ``nonzero()`` row extraction is a
non-starter under XLA (shapes must be static), but the batch's token ids
ARE the touched rows — statically shaped ``[B*S]``.  So:

* :class:`SparseTensor` — (indices, values, dense_shape) pytree with the
  reference's ``to_dense`` / ``add`` / ``sparse_size`` surface, built
  from a batch cotangent rather than ``nonzero()``.
* :func:`embedding_lookup` — a ``custom_vjp`` table lookup whose
  backward replicates the SMALL ``[B*S, E]`` output cotangent across the
  data axes (an all-gather of ``B*S*E`` elements) and segment-sums into
  the dense grad locally.  The dense ``[V, E]`` gradient is thus born
  replicated: XLA inserts **no vocab-sized psum** — the wire cost is the
  reference's sparse allreduce, the arithmetic is one segment_sum.

With vocab 32k, E=4096, B*S=4096/rank the gradient allreduce drops from
``V*E = 128M`` to ``B*S*E = 16M`` elements per rank pair, same 8x-class
saving the reference's sparse path targets.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@jax.tree_util.register_pytree_node_class
class SparseTensor:
    """Indexed-slices gradient (reference ``SparseTensor``)."""

    def __init__(self, indices: jax.Array, values: jax.Array,
                 dense_shape: Tuple[int, ...]):
        self.indices = indices          # [n] int32 row ids (may repeat)
        self.values = values            # [n, E]
        self.dense_shape = tuple(int(d) for d in dense_shape)

    # -- pytree ------------------------------------------------------------
    def tree_flatten(self):
        return (self.indices, self.values), self.dense_shape

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    # -- reference API -------------------------------------------------------
    @classmethod
    def from_dense(cls, dense: jax.Array, indices: jax.Array) -> "SparseTensor":
        """Compress ``dense`` knowing ``indices`` are the touched rows
        (the static-shape stand-in for the reference's ``nonzero()``)."""
        return cls(indices, dense[indices], dense.shape)

    def to_dense(self) -> jax.Array:
        """Scatter-add values back to the dense shape (duplicate indices
        accumulate, matching ``scatter_add_`` in the reference)."""
        return jax.ops.segment_sum(self.values, self.indices,
                                   num_segments=self.dense_shape[0])

    def add(self, other: "SparseTensor") -> "SparseTensor":
        assert self.dense_shape == other.dense_shape
        return SparseTensor(
            jnp.concatenate([self.indices, other.indices]),
            jnp.concatenate([self.values, other.values]),
            self.dense_shape)

    def sparse_size(self) -> Tuple[int, int]:
        index_size = self.indices.shape[0]
        value_size = self.values.shape[0] * self.values.shape[1]
        dense_size = self.dense_shape[0] * self.dense_shape[1]
        return index_size + value_size, dense_size

    def __repr__(self):
        return (f"SparseTensor(n={self.indices.shape[0]}, "
                f"dense_shape={self.dense_shape})")


def sparse_allreduce(st: SparseTensor, axis_name: str) -> SparseTensor:
    """All-gather (indices, values) along a mesh axis (shard_map context)
    — the wire-level operation of reference ``sparse_allreduce``
    (engine.py:2550).  Static shapes make the reference's size-exchange /
    padding dance unnecessary."""
    from jax import lax
    idx = lax.all_gather(st.indices, axis_name, axis=0, tiled=True)
    vals = lax.all_gather(st.values, axis_name, axis=0, tiled=True)
    return SparseTensor(idx, vals, st.dense_shape)


def embedding_lookup(table: jax.Array, ids: jax.Array,
                     replicate_cotangent: bool = True) -> jax.Array:
    """``table[ids]`` whose backward is the sparse-gradient path."""
    return _embedding_lookup(table, ids, table.shape[0],
                             jnp.dtype(table.dtype).name,
                             replicate_cotangent)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _embedding_lookup(table, ids, vocab, dtype_name, replicate_cotangent):
    return table[ids]


def _embed_fwd(table, ids, vocab, dtype_name, replicate_cotangent):
    return table[ids], ids


def _embed_bwd(vocab, dtype_name, replicate_cotangent, ids, ct):
    emb = ct.shape[-1]
    ct2 = ct.reshape(-1, emb)
    ids2 = ids.reshape(-1)
    if replicate_cotangent:
        # Replicate the [B*S, E] cotangent + ids instead of the [V, E]
        # grad: XLA all-gathers B*S*E elements over the batch axes and the
        # dense grad below is then born replicated — no vocab-sized psum.
        # No-op outside a mesh context (single device).
        try:
            ct2 = jax.lax.with_sharding_constraint(ct2, P())
            ids2 = jax.lax.with_sharding_constraint(ids2, P())
        except (ValueError, RuntimeError):
            pass
    dense = jax.ops.segment_sum(ct2.astype(jnp.float32), ids2,
                                num_segments=vocab)
    return dense.astype(dtype_name), None


_embedding_lookup.defvjp(_embed_fwd, _embed_bwd)
