"""Hessian top-eigenvalue probe (power iteration).

TPU-native analogue of ``deepspeed/runtime/eigenvalue.py:12``
(``Eigenvalue``): estimates the loss curvature used to modulate
compression/quantization aggressiveness per layer.  The reference does
grad-of-grad with torch autograd; under JAX the Hessian-vector product is
a first-class transform — ``jax.jvp(jax.grad(loss), params, v)`` — and the
whole power iteration jit-compiles into one program.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.logging import logger


def _normalize(tree):
    norm = jnp.sqrt(sum(jnp.sum(jnp.square(l))
                        for l in jax.tree.leaves(tree)))
    return jax.tree.map(lambda l: l / (norm + 1e-12), tree), norm


class Eigenvalue:
    """Power-iteration estimator of the largest Hessian eigenvalue."""

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution

    def compute_eigenvalue(self, loss_fn: Callable, params: Any,
                           batch: Any, rng: Optional[jax.Array] = None,
                           seed: int = 0) -> float:
        """Top eigenvalue of d2(loss)/d(params)2 at ``params``."""
        grad_fn = jax.grad(
            lambda p: loss_fn(p, batch, rng) if rng is not None
            else loss_fn(p, batch))

        def hvp(v):
            return jax.jvp(grad_fn, (params,), (v,))[1]

        def body(carry, _):
            v, prev_ev = carry
            hv = hvp(v)
            ev = sum(jnp.sum(a * b) for a, b in
                     zip(jax.tree.leaves(v), jax.tree.leaves(hv)))
            v_new, norm = _normalize(hv)
            # guard against zero curvature directions
            v_new = jax.tree.map(
                lambda a, b: jnp.where(norm > self.stability, a, b),
                v_new, v)
            return (v_new, ev), ev

        key = jax.random.key(seed)
        leaves, treedef = jax.tree.flatten(params)
        keys = jax.random.split(key, len(leaves))
        v0 = jax.tree.unflatten(treedef, [
            jax.random.normal(k, l.shape, l.dtype)  # tangent dtype must
            for k, l in zip(keys, leaves)])         # match the primal's
        v0, _ = _normalize(v0)

        @jax.jit
        def run(v0):
            (v, ev), evs = jax.lax.scan(body, (v0, jnp.zeros(())),
                                        None, length=self.max_iter)
            return ev, evs

        ev, evs = run(v0)
        ev = float(ev)
        if self.verbose:
            logger.info("eigenvalue estimate: %.4e (iters=%d)",
                        ev, self.max_iter)
        return ev

    def compute_eigenvalue_per_block(self, loss_fn: Callable, params: Dict,
                                     batch: Any,
                                     rng: Optional[jax.Array] = None
                                     ) -> Dict[str, float]:
        """Per-top-level-block eigenvalues (reference per-layer loop):
        power-iterate with perturbations restricted to one block."""
        out: Dict[str, float] = {}
        for name in params:
            def masked_loss(sub, _name=name):
                merged = dict(params)
                merged[_name] = sub
                return loss_fn(merged, batch, rng) if rng is not None \
                    else loss_fn(merged, batch)
            ev = Eigenvalue(max_iter=self.max_iter, tol=self.tol,
                            stability=self.stability).compute_eigenvalue(
                lambda p, b, r=None: masked_loss(p), params[name], batch)
            out[name] = ev
        return out
