"""Typed config system.

TPU-native analogue of ``deepspeed/runtime/config.py`` (``DeepSpeedConfig``,
:706) + the pydantic ``DeepSpeedConfigModel`` pattern
(``runtime/config_utils.py``).  Accepts a DeepSpeed-style JSON/dict config —
the same top-level keys users already write (train_batch_size, optimizer,
scheduler, bf16/fp16, zero_optimization, pipeline, ...) — and resolves it
into typed sub-configs.  TPU-specific knobs live under the ``"tpu"`` key.

Batch arithmetic invariant (reference config.py sanity checks):
    train_batch_size == micro_batch_per_device * gradient_accumulation_steps
                        * batch-parallel world size
Any one of the three may be omitted and is inferred.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Union

from pydantic import BaseModel, ConfigDict, Field, model_validator

from ..utils.logging import logger

AUTO = "auto"


class DeepSpeedConfigModel(BaseModel):
    """Base model: tolerant of unknown keys (accept+warn, so any reference
    config parses), supports deprecated aliases via populate_by_name."""
    model_config = ConfigDict(extra="allow", populate_by_name=True)

    @model_validator(mode="after")
    def _warn_extra(self):
        extra = getattr(self, "model_extra", None) or {}
        for k in extra:
            logger.debug("config: unrecognized key '%s' accepted and ignored", k)
        return self


class OptimizerParams(DeepSpeedConfigModel):
    lr: float = 1e-3
    betas: List[float] = Field(default_factory=lambda: [0.9, 0.999])
    eps: float = 1e-8
    weight_decay: float = 0.0
    momentum: float = 0.0  # sgd
    bias_correction: bool = True
    adam_w_mode: bool = True  # FusedAdam default: decoupled decay


class OptimizerConfig(DeepSpeedConfigModel):
    type: str = "adamw"  # adam|adamw|fusedadam|lamb|lion|adagrad|sgd|onebitadam|...
    params: OptimizerParams = Field(default_factory=OptimizerParams)


class SchedulerConfig(DeepSpeedConfigModel):
    type: str = "WarmupLR"
    params: Dict[str, Any] = Field(default_factory=dict)


class FP16Config(DeepSpeedConfigModel):
    enabled: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0
    auto_cast: bool = False


class BF16Config(DeepSpeedConfigModel):
    enabled: bool = True
    # Keep a fp32 master copy + fp32 grad accumulation (reference
    # bf16_optimizer.py behavior). Disable to train pure-bf16.
    master_weights: bool = True
    accumulate_grads_in_fp32: bool = True


class OffloadDeviceEnum:
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class OffloadConfig(DeepSpeedConfigModel):
    device: str = "none"  # none|cpu|nvme
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0  # ZeRO-Offload++ partial offload (engine.py:766)
    aio_threads: int = 4  # NVMe swapper I/O thread pool size


class AioConfig(DeepSpeedConfigModel):
    """Top-level ``aio`` block (reference op_builder/async_io defaults):
    tunes the NVMe swapper's native I/O pool (ops/aio).  single_submit /
    overlap_events are accepted for config compatibility — the thread
    pool always submits asynchronously and overlaps by construction."""
    block_size: int = 1 << 20
    queue_depth: int = 128
    thread_count: int = 4
    single_submit: bool = False
    overlap_events: bool = True
    use_direct_io: bool = False  # O_DIRECT when alignment permits


class ZeroConfig(DeepSpeedConfigModel):
    """``zero_optimization`` section (reference runtime/zero/config.py).

    On TPU, stages map to GSPMD shardings over the 'fsdp' mesh axis:
      stage 0: params/grads/opt-state replicated (pure DP)
      stage 1: optimizer state + fp32 master sharded
      stage 2: + gradients reduce-scattered into shards
      stage 3: + parameters sharded (gathered per-layer by XLA)
    """
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = int(5e8)
    allgather_partitions: bool = True
    allgather_bucket_size: int = int(5e8)
    overlap_comm: bool = True
    offload_param: OffloadConfig = Field(default_factory=OffloadConfig)
    offload_optimizer: OffloadConfig = Field(default_factory=OffloadConfig)
    sub_group_size: int = int(1e9)
    stage3_max_live_parameters: int = int(1e9)
    stage3_max_reuse_distance: int = int(1e9)
    stage3_prefetch_bucket_size: int = int(5e7)
    stage3_param_persistence_threshold: int = int(1e5)
    # total bytes of params kept persistent model-wide (reference default
    # sys.maxsize = unbounded)
    stage3_model_persistence_threshold: int = int(2 ** 63 - 1)
    stage3_gather_16bit_weights_on_model_save: bool = False
    zero_hpz_partition_size: int = 1  # ZeRO++ secondary partition
    zero_quantized_weights: bool = False  # ZeRO++ qwZ
    zero_quantized_gradients: bool = False  # ZeRO++ qgZ
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    round_robin_gradients: bool = False
    memory_efficient_linear: bool = True


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native: jax.checkpoint policy name
    # (full | nothing | dots | dots_with_no_batch_dims | offload_dots)
    policy: str = "full"


class PipelineConfig(DeepSpeedConfigModel):
    stages: int = 1
    partition_method: str = "parameters"  # uniform|parameters|type:regex
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    micro_batches: Optional[int] = None  # default: gradient_accumulation_steps
    # "1f1b": loss fused into the last stage, no [M, ...] output buffer
    # (memory bounded like reference TrainSchedule); "gpipe": stack all
    # micro-batch outputs (needed when callers want logits back)
    schedule: str = "1f1b"


class TensorParallelConfig(DeepSpeedConfigModel):
    enabled: bool = False
    tp_size: int = 1


class SequenceParallelConfig(DeepSpeedConfigModel):
    enabled: bool = False
    sp_size: int = 1
    mode: str = "ulysses"  # ulysses | ring


class MoEConfig(DeepSpeedConfigModel):
    enabled: bool = False
    num_experts: int = 1
    ep_size: int = 1
    top_k: int = 2
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None  # None|Jitter|RSample
    drop_tokens: bool = True
    use_residual: bool = False
    # HabanaAI capacity-bins trick (moe/capacity_bins.py) — static-shape
    # capacity bucketing; on XLA this avoids recompilation: round the
    # capacity up to one of num_capacity_bins precompiled bucket sizes.
    num_capacity_bins: int = 0
    capacity_bins_exp_base: float = 2.0


class MonitorConfigItem(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"
    team: str = ""
    group: str = ""
    project: str = "deepspeed_tpu"


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    debug: bool = False
    prof_all: bool = True
    prof_ops: List[str] = Field(default_factory=list)


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class TelemetryConfig(DeepSpeedConfigModel):
    """``telemetry`` section — the process-wide observability spine
    (``deepspeed_tpu/telemetry``): metrics registry + span tracer + SLO
    histograms.  ``enabled: null`` (default) inherits the process state
    (``DS_TELEMETRY`` env / ``telemetry.enable()``); an explicit bool
    wins.  ``metrics_port`` starts the Prometheus endpoint
    (0 = off, same as ``DS_METRICS_PORT``); ``trace_buffer`` resizes the
    span ring buffer (0 = keep the current capacity).

    Watchdog / flight-recorder knobs (ISSUE 5, same keep-current
    convention): ``watchdog`` gates the health watchdog on top of the
    process telemetry flag (null = keep, default on);
    ``watchdog_threshold`` is the EWMA step-time anomaly ratio (0 =
    keep, default 3.0); ``watchdog_warmup`` the EWMA samples before
    verdicts fire (-1 = keep, default 8); ``postmortem_dir`` where
    crash/anomaly artifacts land ("" = keep, default
    ``DS_POSTMORTEM_DIR``); ``flight_recorder_events`` resizes the
    structured event ring (0 = keep, default 1024).

    Workload observatory (ISSUE 9): ``workload_trace_path`` opens the
    content-free per-request JSONL ledger ("" = keep, same as
    ``DS_WORKLOAD_TRACE``); ``workload_trace_max_mb`` bounds one
    rotation generation (0 = keep, default 32).

    Fleet observatory (ISSUE 11): ``metrics_port`` of -1 binds an
    EPHEMERAL port (``DS_METRICS_PORT=0`` semantics — the bound port
    lands in the ``ds_telemetry_port`` gauge); ``timeseries_interval_s``
    / ``timeseries_retention_s`` start the bounded time-series sampler
    (0 = keep/off, same as ``DS_TIMESERIES``); ``fleet_targets`` is a
    comma-separated ``[label=]host:port`` replica list for the
    ``/fleet`` federation ("" = keep, same as ``DS_FLEET_TARGETS``);
    ``slo_objectives`` is a list of burn-rate objective dicts (see
    ``telemetry/slo.py``; empty = keep)."""
    enabled: Optional[bool] = None
    metrics_port: int = 0
    trace_buffer: int = 0
    watchdog: Optional[bool] = None
    watchdog_threshold: float = 0.0
    watchdog_warmup: int = -1
    postmortem_dir: str = ""
    flight_recorder_events: int = 0
    workload_trace_path: str = ""
    workload_trace_max_mb: int = 0
    timeseries_interval_s: float = 0.0
    timeseries_retention_s: float = 0.0
    fleet_targets: str = ""
    slo_objectives: List[Dict[str, Any]] = Field(default_factory=list)

    def apply(self) -> None:
        """Push this block into the process-wide telemetry state (shared
        by the runtime engine and the inference-v2 engine)."""
        from ..telemetry import apply_settings
        apply_settings(self.enabled, self.metrics_port, self.trace_buffer,
                       watchdog=self.watchdog,
                       watchdog_threshold=self.watchdog_threshold,
                       watchdog_warmup=self.watchdog_warmup,
                       postmortem_dir=self.postmortem_dir,
                       flight_recorder_events=self.flight_recorder_events,
                       workload_trace_path=self.workload_trace_path,
                       workload_trace_max_mb=self.workload_trace_max_mb,
                       timeseries_interval_s=self.timeseries_interval_s,
                       timeseries_retention_s=self.timeseries_retention_s,
                       fleet_targets=self.fleet_targets,
                       slo_objectives=self.slo_objectives)


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"  # Ignore|Warn|Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = Field(default_factory=dict)
    async_save: bool = True  # orbax async checkpointing
    #: transient-I/O (OSError) retries per checkpoint operation, with
    #: exponential backoff starting at save_backoff_s (ISSUE 7)
    save_retries: int = 3
    save_backoff_s: float = 0.05


class FaultInjectionConfig(DeepSpeedConfigModel):
    """``fault_injection`` section — the deterministic chaos registry
    (``runtime/fault_injection.py``).  ``sites`` maps injection-site
    names to specs (``{"probability": .., "at_calls": [..],
    "max_fires": .., "value": ..}``); unknown site names raise at
    apply time.  ``enabled: false`` (default) leaves the process
    registry alone — in particular it does NOT disarm a ``DS_CHAOS``
    env arming, so one engine's default config can't silence a chaos
    run."""
    enabled: bool = False
    seed: int = 0
    sites: Dict[str, Dict[str, Any]] = Field(default_factory=dict)

    def apply(self) -> None:
        from .fault_injection import apply_fault_injection
        apply_fault_injection(self.enabled, self.seed, self.sites)


class FaultToleranceConfig(DeepSpeedConfigModel):
    """``fault_tolerance`` section — training self-healing (ISSUE 7).

    With ``self_healing`` on, ``train_batch`` turns watchdog verdicts
    into recovery actions: a non-finite loss/grad-norm on an APPLIED
    step (fp16 overflow skips stay routine) rolls the engine back to
    the last good checkpoint — or to an in-memory host snapshot when no
    checkpoint exists yet — and skips the offending batch window;
    transient faults (:class:`~.fault_injection.TransientFault`) raised
    at dispatch are retried with the same budget.  ``max_retries``
    bounds CONSECUTIVE rollbacks/retries (the budget resets on every
    healthy step); each consecutive recovery sleeps
    ``backoff_s * 2**(n-1)``.  ``snapshot_interval > 0`` refreshes the
    in-memory rollback snapshot every N applied steps (0 = snapshot
    only once, lazily, at the first self-healed batch)."""
    self_healing: bool = False
    max_retries: int = 3
    backoff_s: float = 0.05
    snapshot_interval: int = 0


class ElasticityConfig(DeepSpeedConfigModel):
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    version: float = 0.2


class AutotuningConfig(DeepSpeedConfigModel):
    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = False
    metric: str = "throughput"
    start_profile_step: int = 3
    end_profile_step: int = 5
    tuner_type: str = "gridsearch"
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    max_train_batch_size: Optional[int] = None
    mp_size: int = 1


class CompressionConfig(DeepSpeedConfigModel):
    weight_quantization: Dict[str, Any] = Field(default_factory=dict)
    activation_quantization: Dict[str, Any] = Field(default_factory=dict)
    sparse_pruning: Dict[str, Any] = Field(default_factory=dict)
    row_pruning: Dict[str, Any] = Field(default_factory=dict)
    head_pruning: Dict[str, Any] = Field(default_factory=dict)
    channel_pruning: Dict[str, Any] = Field(default_factory=dict)
    layer_reduction: Dict[str, Any] = Field(default_factory=dict)


class DataEfficiencyConfig(DeepSpeedConfigModel):
    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = Field(default_factory=dict)
    data_routing: Dict[str, Any] = Field(default_factory=dict)


class HybridEngineConfig(DeepSpeedConfigModel):
    """``hybrid_engine`` section (reference runtime/hybrid_engine.py config:
    enable RLHF train+generate mode)."""
    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True  # accepted; XLA manages placement
    tp_gather_partition_size: int = 8  # accepted; GSPMD handles gathers


class CommOptimizationConfig(DeepSpeedConfigModel):
    """``comm_optimization`` section — the CollectiveScheduler's knobs
    (runtime/comm/collective_scheduler.py).

    Generalizes the reference's manual gradient-collective machinery
    (allreduce buckets, engine.py allreduce_bucket / ZeRO++ qgZ
    compressed reduction) into one subsystem: gradients are bucketized
    by byte size, each bucket optionally rides an int8 block-scaled wire
    with persistent error-feedback residuals, and bucket reduction is
    scheduled per micro-batch so collectives overlap the next
    micro-batch's backward instead of forming one monolithic end-of-step
    reduction."""
    enabled: bool = False
    # bytes per bucket on the wire (reference engine allreduce_bucket_size
    # default 5e8); small tensors coalesce up to this, huge tensors chunk
    allreduce_bucket_size: int = int(5e8)
    # int8 block-scaled wire for bucketed gradient collectives
    quantize: bool = True
    # wire dtype for quantized buckets (int8 is the only wire today;
    # fp8 variants plug in here)
    quantize_dtype: str = "int8"
    # reduce bucket i of micro-batch k while micro-batch k+1 accumulates
    # (per-micro-batch reduction inside the scan); off = one reduction
    # at the gradient-accumulation boundary
    overlap: bool = True
    # persistent per-shard error-feedback residuals (1-bit Adam style):
    # quantization error is re-injected next reduction; costs one
    # grad-sized fp32 buffer per batch shard, carried in TrainState
    error_feedback: bool = True
    # quantization group size (elements per int8 scale block)
    quantization_block: int = 512

    @model_validator(mode="after")
    def _check_wire_dtype(self):
        if self.quantize_dtype != "int8":
            raise ValueError(
                f"comm_optimization.quantize_dtype={self.quantize_dtype!r} "
                "is not implemented — int8 is the only wire today (fp8 "
                "variants plug in here); remove the key or set 'int8'")
        return self


class ServingOptimizationConfig(DeepSpeedConfigModel):
    """``serving_optimization`` section — the fused serving step's knobs
    (inference/v2: engine + FastGenScheduler).

    One SplitFuse scheduler step lowers into ONE compiled device program
    (mixed prefill chunks + decode rows in a unified ragged layout) that
    also samples on device, so only int32 tokens cross device->host; the
    scheduler double-buffers steps via a device-side token gather.
    ``prefix_caching`` adds the automatic prefix cache over the paged KV
    pool: full prompt pages are ref-count-shared across sequences and
    retained after flush (LRU-evicted under pool pressure), so a
    warm-prefix admission only prefills the uncached suffix.  Each flag
    is an escape hatch back to the seed behavior (per-Q-bucket programs,
    host-side sampling over [n, V] logits, synchronous stepping, full
    re-prefill); ``enabled: false`` flips all four."""
    enabled: bool = True
    fused_step: bool = True
    on_device_sampling: bool = True
    async_scheduling: bool = True
    prefix_caching: bool = True
    # -- graceful degradation (ISSUE 7); 0 = off, preserving the
    # unbounded seed behavior ------------------------------------------
    #: bounded admission queue: a submit past this many pending
    #: requests is SHED with a structured error (0 = unbounded)
    max_queue_depth: int = 0
    #: SLO-driven load shedding: with telemetry on, shed new submits
    #: while the observed queue-wait p90 exceeds this (0 = off)
    shed_queue_wait_ms: float = 0.0
    #: default per-request TTL in seconds; expired requests drain with
    #: a structured error instead of hanging (0 = no deadline)
    default_ttl_s: float = 0.0
    #: on a would-be scheduler deadlock, shed the most demanding
    #: request with a structured "oom" error instead of raising
    shed_unservable: bool = False
    # -- preemption tolerance (ISSUE 8) --------------------------------
    #: grace budget in seconds for the SIGTERM drain->snapshot path;
    #: past it live requests terminate with a structured "migrated"
    #: error instead of vanishing
    snapshot_grace_s: float = 5.0
    #: bundle path the SIGTERM handler writes (with
    #: DS_DRAIN_ON_SIGTERM=1); empty = explicit snapshot() calls only
    snapshot_path: str = ""
    # -- speculative decoding (ISSUE 10), default off ------------------
    #: model-free speculative decoding: n-gram/prompt-lookup drafts
    #: verified Q-at-a-time inside the fused step; accepted drafts
    #: commit as a block at drain.  Enabling changes only throughput
    #: and the ds_fastgen_spec_* metrics
    speculative: bool = False
    #: drafted tokens per decode row per program
    spec_max_draft: int = 3
    #: shortest trailing n-gram the prompt-lookup drafter matches on
    spec_ngram_min: int = 2
    # -- model-drafted speculation (ISSUE 17) --------------------------
    #: drafter: "ngram" (prompt lookup, seed), "model" (same-family
    #: draft trunk, device-resident draft loop in the fused step), or
    #: "auto" (per-request EWMA accept rate switches ngram->model->off)
    spec_drafter: str = "ngram"
    #: draft trunk depth — first N target layers, weights shared; 0 =
    #: self-draft (every layer shared; pure dispatch amortization)
    spec_draft_layers: int = 0
    # -- disaggregated prefill/decode serving (ISSUE 13) ---------------
    #: scheduler role: "both" | "prefill" | "decode" — prefill-only
    #: engines run prompt chunks + the first token and park requests
    #: as handoff-ready; decode-only engines admit handoff imports
    #: only (plain submits rejected with code="misrouted")
    role: str = "both"
    #: schedule-invariant sampling: per-(uid, position) derived RNG so
    #: sampled output survives handoff/migration tokenwise identical
    keyed_sampling: bool = False
    # -- recompile-proof cold starts (ISSUE 14) ------------------------
    #: persistent XLA compile cache directory ("" = off;
    #: DS_COMPILE_CACHE env overrides) — restored/spawned replicas load
    #: executables from disk instead of re-compiling the lattice
    compile_cache_dir: str = ""
    #: bucket lattice: "" = power-of-two default; "auto:<path>" loads a
    #: mined lattice artifact (analyze_trace --emit-lattice) or mines a
    #: raw workload trace at engine build
    lattice: str = ""
    # -- tiered KV at fleet scale (ISSUE 16) ---------------------------
    #: KV page storage: "none" (fp pages) or "int8" (block-scaled
    #: codes + per-head_dim-block fp32 scales) — ~2x resident
    #: sequences per chip; engine-build-time
    kv_quantization: str = "none"
    #: host DRAM prefix tier size in pages (0 = tier off): evicted
    #: parked pages demote here instead of being freed, keyed by their
    #: chained prefix digests, and promote back on a prefix match
    kv_tier_host_pages: int = 0
    #: disk prefix tier below the host ring (pages; 0 = off)
    kv_tier_disk_pages: int = 0
    #: directory for disk-tier page files ("" = per-process temp dir)
    kv_tier_dir: str = ""
    # -- sharded fused serving (ISSUE 18) ------------------------------
    #: tensor-parallel degree for the fused serving program (1 =
    #: single-device); weights shard along a ``tp`` mesh axis and KV
    #: pages partition along KV heads — engine-build-time, part of the
    #: compile-cache digest
    tp_degree: int = 1
    #: cross-shard logits collective encoding: "none" (fp all-gather,
    #: tokenwise identical to tp=1) or "int8" (block-scaled codes +
    #: per-row-per-shard fp32 scales — ~4x fewer interconnect bytes)
    tp_collective_quantization: str = "none"

    def to_v2_dict(self) -> Dict[str, Any]:
        """The ``serving_optimization`` dict the inference-v2 config
        consumes (``RaggedInferenceEngineConfig.from_dict``)."""
        return {"enabled": self.enabled, "fused_step": self.fused_step,
                "on_device_sampling": self.on_device_sampling,
                "async_scheduling": self.async_scheduling,
                "prefix_caching": self.prefix_caching,
                "max_queue_depth": self.max_queue_depth,
                "shed_queue_wait_ms": self.shed_queue_wait_ms,
                "default_ttl_s": self.default_ttl_s,
                "shed_unservable": self.shed_unservable,
                "snapshot_grace_s": self.snapshot_grace_s,
                "snapshot_path": self.snapshot_path,
                "speculative": self.speculative,
                "spec_max_draft": self.spec_max_draft,
                "spec_ngram_min": self.spec_ngram_min,
                "spec_drafter": self.spec_drafter,
                "spec_draft_layers": self.spec_draft_layers,
                "role": self.role,
                "keyed_sampling": self.keyed_sampling,
                "compile_cache_dir": self.compile_cache_dir,
                "lattice": self.lattice,
                "kv_quantization": self.kv_quantization,
                "kv_tier_host_pages": self.kv_tier_host_pages,
                "kv_tier_disk_pages": self.kv_tier_disk_pages,
                "kv_tier_dir": self.kv_tier_dir,
                "tp_degree": self.tp_degree,
                "tp_collective_quantization":
                    self.tp_collective_quantization}


class TPUConfig(DeepSpeedConfigModel):
    """TPU-native extension knobs (no reference analogue)."""
    # Mesh axis sizes; -1 = absorb remaining devices.
    mesh: Dict[str, int] = Field(default_factory=dict)
    # scan over homogeneous transformer layers (compile time + remat unit)
    scan_layers: bool = True
    remat: bool = True
    remat_policy: str = "nothing_saveable"  # maps to jax.checkpoint policies
    # attention implementation: auto (flash when the mask allows it) |
    # flash (force) | einsum (dense reference path)
    attention_impl: str = "auto"
    donate_state: bool = True
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    # matmul precision: default|float32|tensorfloat32|highest
    matmul_precision: str = "default"


class DeepSpeedTPUConfig(DeepSpeedConfigModel):
    """Top-level config (reference DeepSpeedConfig, runtime/config.py:706)."""
    train_batch_size: Optional[int] = None
    train_micro_batch_size_per_gpu: Optional[int] = None
    gradient_accumulation_steps: Optional[int] = None
    steps_per_print: int = 10
    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    communication_data_type: Optional[str] = None
    seq_parallel_communication_data_type: str = "fp32"
    sparse_gradients: bool = False
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    dump_state: bool = False
    disable_allgather: bool = False

    optimizer: OptimizerConfig = Field(default_factory=OptimizerConfig)
    scheduler: Optional[SchedulerConfig] = None
    fp16: FP16Config = Field(default_factory=FP16Config)
    bf16: BF16Config = Field(default_factory=BF16Config)
    zero_optimization: ZeroConfig = Field(default_factory=ZeroConfig)
    comm_optimization: CommOptimizationConfig = Field(
        default_factory=CommOptimizationConfig)
    activation_checkpointing: ActivationCheckpointingConfig = Field(
        default_factory=ActivationCheckpointingConfig)
    aio: AioConfig = Field(default_factory=AioConfig)
    pipeline: PipelineConfig = Field(default_factory=PipelineConfig)
    tensor_parallel: TensorParallelConfig = Field(default_factory=TensorParallelConfig)
    sequence_parallel: SequenceParallelConfig = Field(default_factory=SequenceParallelConfig)
    moe: MoEConfig = Field(default_factory=MoEConfig)
    tensorboard: MonitorConfigItem = Field(default_factory=MonitorConfigItem)
    wandb: MonitorConfigItem = Field(default_factory=MonitorConfigItem)
    csv_monitor: MonitorConfigItem = Field(default_factory=MonitorConfigItem)
    comet: MonitorConfigItem = Field(default_factory=MonitorConfigItem)
    comms_logger: CommsLoggerConfig = Field(default_factory=CommsLoggerConfig)
    flops_profiler: FlopsProfilerConfig = Field(default_factory=FlopsProfilerConfig)
    telemetry: TelemetryConfig = Field(default_factory=TelemetryConfig)
    fault_injection: FaultInjectionConfig = Field(
        default_factory=FaultInjectionConfig)
    fault_tolerance: FaultToleranceConfig = Field(
        default_factory=FaultToleranceConfig)
    checkpoint: CheckpointConfig = Field(default_factory=CheckpointConfig)
    elasticity: ElasticityConfig = Field(default_factory=ElasticityConfig)
    autotuning: AutotuningConfig = Field(default_factory=AutotuningConfig)
    compression_training: CompressionConfig = Field(default_factory=CompressionConfig)
    data_efficiency: DataEfficiencyConfig = Field(default_factory=DataEfficiencyConfig)
    hybrid_engine: HybridEngineConfig = Field(default_factory=HybridEngineConfig)
    serving_optimization: ServingOptimizationConfig = Field(
        default_factory=ServingOptimizationConfig)
    tpu: TPUConfig = Field(default_factory=TPUConfig)

    # ------------------------------------------------------------------
    @model_validator(mode="after")
    def _normalize(self):
        if self.fp16.enabled and self.bf16.enabled:
            # bf16 is the TPU-natural default; explicit fp16 wins if the user
            # asked for it without touching bf16.
            object.__setattr__(self.bf16, "enabled", False)
        return self

    def resolve_batch_sizes(self, batch_parallel_world: int) -> None:
        """Enforce train_batch = micro * gas * dp (reference config sanity)."""
        tb, mb, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                       self.gradient_accumulation_steps)
        dp = batch_parallel_world
        if tb is not None and mb is not None and gas is not None:
            if tb != mb * gas * dp:
                raise ValueError(
                    f"train_batch_size {tb} != micro_batch {mb} * gas {gas} * dp {dp}")
        elif tb is not None and mb is not None:
            if tb % (mb * dp) != 0:
                raise ValueError(f"train_batch_size {tb} not divisible by micro*dp {mb * dp}")
            gas = tb // (mb * dp)
        elif tb is not None and gas is not None:
            if tb % (gas * dp) != 0:
                raise ValueError(f"train_batch_size {tb} not divisible by gas*dp {gas * dp}")
            mb = tb // (gas * dp)
        elif mb is not None:
            gas = gas or 1
            tb = mb * gas * dp
        elif tb is not None:
            gas = 1
            if tb % dp != 0:
                raise ValueError(f"train_batch_size {tb} not divisible by dp {dp}")
            mb = tb // dp
        else:
            mb = 1
            gas = gas or 1
            tb = mb * gas * dp
        self.train_batch_size = tb
        self.train_micro_batch_size_per_gpu = mb
        self.gradient_accumulation_steps = gas

    @property
    def precision_dtype(self) -> str:
        if self.fp16.enabled:
            return "float16"
        if self.bf16.enabled:
            return "bfloat16"
        return "float32"


def load_config(config: Union[str, dict, DeepSpeedTPUConfig, None]) -> DeepSpeedTPUConfig:
    if config is None:
        return DeepSpeedTPUConfig()
    if isinstance(config, DeepSpeedTPUConfig):
        return config
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    return DeepSpeedTPUConfig(**config)


# ---------------------------------------------------------------------------
# accepted-for-compatibility keys with no XLA-side behavior.  The engine
# calls warn_noop_keys at init: any of these the user EXPLICITLY set gets
# one loud log line naming the reason, so surface never silently exceeds
# substance (the round-4 verdict's partition_activations lesson).
# ---------------------------------------------------------------------------

_NOOP_KEYS = {
    ("zero_optimization", "overlap_comm"):
        "XLA's latency-hiding scheduler overlaps collectives automatically",
    ("zero_optimization", "contiguous_gradients"):
        "gradients live in XLA-managed buffers; no fragmentation to manage",
    ("zero_optimization", "reduce_bucket_size"):
        "the compiler fuses/schedules reductions; for an explicit "
        "bucketed gradient wire use comm_optimization.allreduce_bucket_size",
    ("zero_optimization", "allgather_bucket_size"):
        "the compiler fuses/schedules gathers; no manual bucketing",
    ("zero_optimization", "round_robin_gradients"):
        "grad layout is a sharding assignment, not a rank rotation",
    ("zero_optimization", "memory_efficient_linear"):
        "XLA rematerialization covers it; see tpu.remat_policy",
    ("zero_optimization", "mics_hierarchical_params_gather"):
        "the hpz mesh axis provides the hierarchical gather",
    ("activation_checkpointing", "contiguous_memory_optimization"):
        "XLA owns activation buffers",
    ("activation_checkpointing", "number_checkpoints"):
        "the scanned layer body is the checkpoint unit",
    ("activation_checkpointing", "synchronize_checkpoint_boundary"):
        "XLA dataflow ordering replaces manual syncs",
    ("activation_checkpointing", "profile"):
        "use utils.nvtx.trace / the flops profiler",
    ("aio", "single_submit"):
        "the native pool always submits asynchronously",
    ("aio", "overlap_events"):
        "completion overlap is inherent to the thread pool",
    ("checkpoint", "use_node_local_storage"):
        "Orbax paths are caller-controlled; point save_dir at local disk",
    ("checkpoint", "parallel_write"):
        "Orbax writes shards in parallel already",
}


def warn_noop_keys(config: "DeepSpeedTPUConfig") -> None:
    from ..utils.logging import logger
    for (section, key), reason in _NOOP_KEYS.items():
        sub = getattr(config, section, None)
        if sub is not None and key in getattr(sub, "model_fields_set", ()):
            logger.warning(
                "config %s.%s is accepted for compatibility but has no "
                "effect on TPU: %s", section, key, reason)
