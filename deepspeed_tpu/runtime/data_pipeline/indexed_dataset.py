"""Memory-mapped indexed dataset (Megatron ``.bin``/``.idx`` format).

TPU-native analogue of ``deepspeed/runtime/data_pipeline/data_sampling/
indexed_dataset.py`` (627 LoC, the Megatron mmap format): token documents
stored back-to-back in a flat binary file with an index of sizes/offsets,
read zero-copy via ``np.memmap``.  Format-compatible with files produced
by Megatron-LM / the reference (same magic, version, dtype codes), so
existing preprocessed corpora load unchanged.
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Sequence

import numpy as np

_INDEX_MAGIC = b"MMIDIDX\x00\x00"
_VERSION = 1

# dtype codes from the Megatron format (reference
# data_sampling/indexed_dataset.py:102 — 6/7/8 are the unsigned widths;
# uint16 corpora are the common vocab<=65536 case)
_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.uint16, 7: np.uint32, 8: np.uint64}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDataset:
    """Read-only mmap view: ``ds[i]`` -> np array of document *i*'s tokens."""

    def __init__(self, path_prefix: str):
        with open(index_file_path(path_prefix), "rb") as f:
            magic = f.read(9)
            if magic != _INDEX_MAGIC:
                raise ValueError(
                    f"{index_file_path(path_prefix)} is not an MMIDIDX file")
            version, = struct.unpack("<Q", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            code, = struct.unpack("<B", f.read(1))
            self.dtype = np.dtype(_DTYPES[code])
            self._len, = struct.unpack("<Q", f.read(8))
            doc_count, = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        idx_buf = np.memmap(index_file_path(path_prefix), mode="r",
                            order="C")
        self.sizes = np.frombuffer(idx_buf, dtype=np.int32,
                                   count=self._len, offset=offset)
        pointers_off = offset + self.sizes.nbytes
        self.pointers = np.frombuffer(idx_buf, dtype=np.int64,
                                      count=self._len, offset=pointers_off)
        doc_off = pointers_off + self.pointers.nbytes
        self.doc_idx = np.frombuffer(idx_buf, dtype=np.int64,
                                     count=doc_count, offset=doc_off)
        self._data = np.memmap(data_file_path(path_prefix), mode="r",
                               dtype=self.dtype, order="C")
        # Integrity check: the .bin must hold exactly the tokens the index
        # promises.  Catches indices written with a wrong dtype code (e.g.
        # by pre-r3 builds of this repo, whose codes 6/8 were swapped vs
        # Megatron — a uint16 corpus misread as uint64 fails here 4x over)
        # instead of silently decoding garbage.
        expected = int(self.pointers[-1]) // self.dtype.itemsize \
            + int(self.sizes[-1]) if self._len else 0
        if self._data.size != expected:
            raise ValueError(
                f"{data_file_path(path_prefix)}: {self._data.size} items of "
                f"{self.dtype} but index promises {expected} — dtype code "
                "mismatch (index written by an incompatible builder?)")

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        ptr = self.pointers[i] // self.dtype.itemsize
        return np.asarray(self._data[ptr:ptr + self.sizes[i]])

    def get(self, i: int, offset: int = 0,
            length: Optional[int] = None) -> np.ndarray:
        """Partial document read (reference ``get``)."""
        size = int(self.sizes[i])
        length = size - offset if length is None else length
        ptr = self.pointers[i] // self.dtype.itemsize + offset
        return np.asarray(self._data[ptr:ptr + length])

    @staticmethod
    def exists(path_prefix: str) -> bool:
        return (os.path.exists(index_file_path(path_prefix)) and
                os.path.exists(data_file_path(path_prefix)))


class MMapIndexedDatasetBuilder:
    """Streaming writer (reference ``MMapIndexedDatasetBuilder``)."""

    def __init__(self, out_prefix: str, dtype=np.int32):
        self.prefix = out_prefix
        self.dtype = np.dtype(dtype)
        self._data_f = open(data_file_path(out_prefix), "wb")
        self._sizes: List[int] = []
        self._doc_idx: List[int] = [0]

    def add_item(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens, dtype=self.dtype)
        self._data_f.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def merge_file(self, other_prefix: str) -> None:
        other = MMapIndexedDataset(other_prefix)
        base = len(self._sizes)
        for i in range(len(other)):
            self.add_item(other[i])
        for d in other.doc_idx[1:]:
            self._doc_idx.append(base + int(d))

    def finalize(self) -> None:
        self._data_f.close()
        sizes = np.asarray(self._sizes, np.int32)
        itemsize = self.dtype.itemsize
        pointers = np.zeros(len(sizes), np.int64)
        np.cumsum(sizes[:-1] * itemsize, out=pointers[1:])
        with open(index_file_path(self.prefix), "wb") as f:
            f.write(_INDEX_MAGIC)
            f.write(struct.pack("<Q", _VERSION))
            f.write(struct.pack("<B", _DTYPE_CODES[self.dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(self._doc_idx)))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(np.asarray(self._doc_idx, np.int64).tobytes(order="C"))
