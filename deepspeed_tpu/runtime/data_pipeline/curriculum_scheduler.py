"""Curriculum learning scheduler.

TPU-native analogue of ``deepspeed/runtime/data_pipeline/
curriculum_scheduler.py:11`` (``CurriculumScheduler``): maps global step →
current difficulty (e.g. sequence length), with the reference's schedule
types ``fixed_linear``, ``fixed_root``, ``fixed_discrete``, ``custom``.

Difficulty values are rounded to ``difficulty_step`` multiples so sequence-
length curricula keep TPU-friendly (static, padded) shapes — the same
reason the reference rounds to multiples of 8 for fp16 tensor cores.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

from ...utils.logging import logger

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    """``config`` mirrors the reference's ``curriculum_learning`` block::

        {"curriculum_type": "seqlen", "enabled": true,
         "min_difficulty": 8, "max_difficulty": 1024,
         "schedule_type": "fixed_linear",
         "schedule_config": {"total_curriculum_step": 10000,
                             "difficulty_step": 8}}
    """

    def __init__(self, config: Dict[str, Any]):
        self.state: Dict[str, Any] = {}
        self.min_difficulty = int(config.get("min_difficulty", 1))
        self.max_difficulty = int(config.get("max_difficulty", 1))
        self.schedule_type = config.get("schedule_type", FIXED_LINEAR)
        self.schedule_config = dict(config.get("schedule_config", {}))
        self.current_difficulty = self.min_difficulty
        self._custom_fn: Optional[Callable[[int], int]] = None

        if self.schedule_type in (FIXED_LINEAR, FIXED_ROOT):
            if "total_curriculum_step" not in self.schedule_config:
                raise ValueError(
                    f"{self.schedule_type} schedule requires "
                    f"'total_curriculum_step'")
            self.schedule_config.setdefault("difficulty_step", 1)
            if self.schedule_type == FIXED_ROOT:
                self.schedule_config.setdefault("root_degree", 2)
        elif self.schedule_type == FIXED_DISCRETE:
            need = ("difficulty", "max_step")
            if not all(k in self.schedule_config for k in need):
                raise ValueError(
                    "fixed_discrete schedule requires 'difficulty' and "
                    "'max_step' lists")
            if len(self.schedule_config["max_step"]) != \
                    len(self.schedule_config["difficulty"]) - 1:
                raise ValueError("len(max_step) must be "
                                 "len(difficulty) - 1")
        elif self.schedule_type == CUSTOM:
            pass  # set_custom_get_difficulty must be called
        else:
            raise ValueError(f"unknown schedule_type {self.schedule_type!r}")

    def set_custom_get_difficulty(self, fn: Callable[[int], int]) -> None:
        self._custom_fn = fn

    # ---------------------------------------------------------- schedules
    def _rounded(self, raw: float) -> int:
        step = int(self.schedule_config.get("difficulty_step", 1))
        d = int(raw // step) * step
        return max(self.min_difficulty, min(self.max_difficulty, d))

    def get_difficulty(self, global_steps: int) -> int:
        sc = self.schedule_config
        if self.schedule_type == FIXED_LINEAR:
            frac = min(1.0, global_steps / sc["total_curriculum_step"])
            raw = self.min_difficulty + \
                (self.max_difficulty - self.min_difficulty) * frac
            return self._rounded(raw)
        if self.schedule_type == FIXED_ROOT:
            frac = min(1.0, global_steps / sc["total_curriculum_step"])
            frac = frac ** (1.0 / sc["root_degree"])
            raw = self.min_difficulty + \
                (self.max_difficulty - self.min_difficulty) * frac
            return self._rounded(raw)
        if self.schedule_type == FIXED_DISCRETE:
            for difficulty, bound in zip(sc["difficulty"], sc["max_step"]):
                if global_steps < bound:
                    return int(difficulty)
            return int(sc["difficulty"][-1])
        if self._custom_fn is None:
            raise RuntimeError("custom schedule requires "
                               "set_custom_get_difficulty()")
        return int(self._custom_fn(global_steps))

    def update_difficulty(self, global_steps: int) -> int:
        self.current_difficulty = self.get_difficulty(global_steps)
        return self.current_difficulty

    # ------------------------------------------------------- persistence
    def state_dict(self) -> Dict[str, Any]:
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.current_difficulty = sd["current_difficulty"]
