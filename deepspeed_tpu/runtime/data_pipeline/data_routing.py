"""Random-LTD: random layerwise token dropping.

TPU-native analogue of ``deepspeed/runtime/data_pipeline/data_routing/``
(+ ``csrc/random_ltd/`` 724 LoC of CUDA gather/scatter): middle transformer
layers process a random *subset* of tokens, first/last layers see all —
the dropped tokens ride the residual stream unchanged.  The CUDA
gather/scatter kernels become ``jnp.take_along_axis`` / ``.at[].set``
(XLA lowers them to efficient dynamic-gather on TPU); the kept-token count
follows a per-step schedule so shapes stay static within a schedule stage.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.logging import logger


class RandomLTDScheduler:
    """Kept-sequence-length schedule (reference ``ltd_scheduler``):
    linear ramp from ``min_value`` tokens to the full ``max_value`` over
    ``schedule_config.total_layer_tokens``-style step budget."""

    def __init__(self, config: Dict[str, Any]):
        self.min_value = int(config.get("min_value", 128))
        self.max_value = int(config.get("max_value", 1024))
        sc = config.get("schedule_config", {})
        self.total_steps = int(sc.get("total_steps",
                                      config.get("total_steps", 10000)))
        self.step_size = int(sc.get("seq_per_step", 16))

    def get_value(self, global_step: int) -> int:
        frac = min(1.0, global_step / max(1, self.total_steps))
        raw = self.min_value + (self.max_value - self.min_value) * frac
        v = int(raw // self.step_size) * self.step_size
        return max(self.min_value, min(self.max_value, v))


def token_sort_indices(rng: jax.Array, batch: int, seq: int,
                       keep: int) -> Tuple[jax.Array, jax.Array]:
    """Random kept-token indices [B, keep] (sorted, preserving order) and
    the complement [B, seq-keep] (reference ``token_sort``/gather kernel)."""
    noise = jax.random.uniform(rng, (batch, seq))
    order = jnp.argsort(noise, axis=-1)
    kept = jnp.sort(order[:, :keep], axis=-1)
    dropped = jnp.sort(order[:, keep:], axis=-1)
    return kept, dropped


def gather_tokens(x: jax.Array, indices: jax.Array) -> jax.Array:
    """[B, S, H] gather -> [B, keep, H] (csrc/random_ltd gather kernel)."""
    return jnp.take_along_axis(x, indices[:, :, None], axis=1)


def scatter_tokens(full: jax.Array, sub: jax.Array,
                   indices: jax.Array) -> jax.Array:
    """Write processed kept tokens back into the full residual stream
    (csrc/random_ltd scatter kernel): dropped tokens keep their value."""
    b = jnp.arange(full.shape[0])[:, None]
    return full.at[b, indices].set(sub)


def apply_random_ltd(layer_fn: Callable[[jax.Array], jax.Array],
                     x: jax.Array, keep: int, rng: jax.Array) -> jax.Array:
    """Run ``layer_fn`` on a random ``keep``-token subset; dropped tokens
    bypass the layer via the residual stream (the Random-LTD forward)."""
    b, s = x.shape[0], x.shape[1]
    if keep >= s:
        return layer_fn(x)
    kept_idx, _ = token_sort_indices(rng, b, s, keep)
    sub = gather_tokens(x, kept_idx)
    sub = layer_fn(sub)
    return scatter_tokens(x, sub, kept_idx)


class ProgressiveLayerDrop:
    """PLD (reference ``runtime/progressive_layer_drop.py:10``): global
    keep-probability theta(t) decays from 1 toward ``theta`` with rate
    ``gamma``; layer i's keep prob interpolates toward theta with depth."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = float(theta)
        self.gamma = float(gamma)
        self.current_theta = 1.0

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = (1.0 - self.theta) * np.exp(
            -self.gamma * global_step) + self.theta
        return self.current_theta

    def layer_keep_prob(self, layer_idx: int, num_layers: int) -> float:
        """Deeper layers drop more (stochastic-depth linear rule)."""
        frac = (layer_idx + 1) / max(1, num_layers)
        return 1.0 - frac * (1.0 - self.current_theta)

    def state_dict(self):
        return {"current_theta": self.current_theta}

    def load_state_dict(self, sd):
        self.current_theta = float(sd["current_theta"])
