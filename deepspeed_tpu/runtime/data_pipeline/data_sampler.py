"""Curriculum-aware data sampler + offline difficulty analyzer.

TPU-native analogues of ``deepspeed/runtime/data_pipeline/data_sampling/``:

* ``DataAnalyzer`` (data_analyzer.py, 880 LoC): offline pass computing a
  difficulty metric per sample, persisting metric values and a
  difficulty-sorted sample index;
* ``DeepSpeedDataSampler`` (data_sampler.py:36): at each step, admit only
  samples whose difficulty ≤ the curriculum's current threshold, shuffle
  deterministically, shard across DP ranks.

Batches stay static-shape: the eligible pool only grows (curriculum
difficulty is monotone), and batch size is constant — XLA never sees the
curriculum at all, it is pure host-side index selection.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from ...utils.logging import logger


class DataAnalyzer:
    """Offline difficulty indexing (reference ``DataAnalyzer``)."""

    def __init__(self, dataset: Sequence[Any], metric_fns: Dict[str, Callable[[Any], float]],
                 save_path: str, num_workers: int = 1, worker_id: int = 0):
        self.dataset = dataset
        self.metric_fns = metric_fns
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id

    def _paths(self, metric: str):
        base = os.path.join(self.save_path, metric)
        return base + "_metric_values.npy", base + "_sample_to_metric.npy"

    def _worker_path(self, metric: str, worker: int) -> str:
        return os.path.join(self.save_path,
                            f"{metric}_metric_values.worker{worker}.npy")

    @staticmethod
    def _atomic_save(path: str, arr: np.ndarray) -> None:
        tmp = f"{path}.{os.getpid()}.tmp.npy"  # .npy suffix: np.save appends otherwise
        np.save(tmp, arr)
        os.replace(tmp, path)

    def run_map(self) -> None:
        """Compute metrics over this worker's shard into a per-worker file.

        Each worker owns its file exclusively (no shared read-modify-write
        — the reference's map/reduce split, data_analyzer.py run_map), so
        concurrent workers cannot lose updates."""
        os.makedirs(self.save_path, exist_ok=True)
        n = len(self.dataset)
        shard = range(self.worker_id, n, self.num_workers)
        for name, fn in self.metric_fns.items():
            values = np.full(n, np.nan, np.float64)
            for i in shard:
                values[i] = float(fn(self.dataset[i]))
            self._atomic_save(self._worker_path(name, self.worker_id), values)

    def run_reduce(self, strict: bool = True) -> bool:
        """Merge all workers' shard files into the final metric files.

        Idempotent and deterministic: whichever worker(s) see the full set
        of shard files write byte-identical output via atomic rename.
        Returns True if the merge completed."""
        done = True
        for name in self.metric_fns:
            paths = [self._worker_path(name, w) for w in range(self.num_workers)]
            missing = [p for p in paths if not os.path.exists(p)]
            if missing:
                if strict:
                    raise FileNotFoundError(
                        f"DataAnalyzer reduce: missing worker shards {missing}")
                done = False
                continue
            values = np.load(paths[0])
            for p in paths[1:]:
                shard_vals = np.load(p)
                mask = ~np.isnan(shard_vals)
                values[mask] = shard_vals[mask]
            vals_path, s2m_path = self._paths(name)
            self._atomic_save(vals_path, values)
            if not np.isnan(values).any():
                self._atomic_save(s2m_path, np.argsort(values, kind="stable"))
        return done

    def run_map_reduce(self) -> None:
        """Map this worker's shard, then merge if every shard is present
        (the last worker to finish completes the merge; single-process
        path computes everything)."""
        self.run_map()
        if self.run_reduce(strict=False):
            logger.info("DataAnalyzer: wrote metrics %s to %s",
                        sorted(self.metric_fns), self.save_path)

    @staticmethod
    def load(save_path: str, metric: str):
        base = os.path.join(save_path, metric)
        return (np.load(base + "_metric_values.npy"),
                np.load(base + "_sample_to_metric.npy"))


class DeepSpeedDataSampler:
    """Curriculum batch sampler (reference ``DeepSpeedDataSampler``).

    Yields per-step lists of *global sample indices* for this DP rank.
    """

    def __init__(self,
                 difficulties: np.ndarray,
                 curriculum_scheduler,
                 global_batch_size: int,
                 data_parallel_rank: int = 0,
                 data_parallel_size: int = 1,
                 drop_last: bool = True,
                 seed: int = 1234):
        if global_batch_size % data_parallel_size:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"dp size {data_parallel_size}")
        self.difficulties = np.asarray(difficulties, np.float64)
        self.sorted_idx = np.argsort(self.difficulties, kind="stable")
        self.sorted_vals = self.difficulties[self.sorted_idx]
        self.scheduler = curriculum_scheduler
        self.global_batch_size = global_batch_size
        self.dp_rank = data_parallel_rank
        self.dp_size = data_parallel_size
        self.micro = global_batch_size // data_parallel_size
        self.drop_last = drop_last
        self.seed = seed
        self.global_step = 0
        self._consumed = 0  # within the current eligible pool epoch

    def eligible_count(self) -> int:
        d = self.scheduler.update_difficulty(self.global_step)
        return int(np.searchsorted(self.sorted_vals, d, side="right"))

    def __iter__(self) -> Iterator[List[int]]:
        return self

    def __next__(self) -> List[int]:
        n_elig = self.eligible_count()
        if n_elig < self.global_batch_size:
            # too few easy samples yet: fall back to the easiest batch-size
            # pool (reference keeps training rather than starving; an empty
            # pool would crash rng.choice regardless of drop_last)
            n_elig = min(len(self.sorted_idx), self.global_batch_size)
        pool = self.sorted_idx[:n_elig]
        # deterministic shuffle that changes per step but is stable across
        # ranks (same seed -> same permutation; rank slices differ)
        rng = np.random.default_rng(self.seed + self.global_step)
        picks = rng.choice(pool.size, size=self.global_batch_size,
                           replace=pool.size < self.global_batch_size)
        batch = pool[picks]
        shard = batch[self.dp_rank * self.micro:(self.dp_rank + 1) * self.micro]
        self.global_step += 1
        return [int(i) for i in shard]

    # ------------------------------------------------------- persistence
    def state_dict(self) -> Dict[str, Any]:
        return {"global_step": self.global_step, "seed": self.seed,
                "scheduler": self.scheduler.state_dict()}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.global_step = int(sd["global_step"])
        self.seed = int(sd["seed"])
        self.scheduler.load_state_dict(sd["scheduler"])
