"""Data-efficiency pipeline (reference ``runtime/data_pipeline/``):
curriculum learning, difficulty-indexed sampling, Megatron mmap datasets,
Random-LTD token routing, progressive layer drop."""

from .curriculum_scheduler import CurriculumScheduler  # noqa: F401
from .data_routing import (  # noqa: F401
    ProgressiveLayerDrop,
    RandomLTDScheduler,
    apply_random_ltd,
    gather_tokens,
    scatter_tokens,
    token_sort_indices,
)
from .data_sampler import DataAnalyzer, DeepSpeedDataSampler  # noqa: F401
from .indexed_dataset import (  # noqa: F401
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
)
