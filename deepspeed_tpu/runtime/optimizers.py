"""Optimizer factory (reference ``runtime/engine.py:1330``
``_configure_basic_optimizer``: Adam/AdamW/FusedAdam/CPUAdam/Lamb/Lion/
OneBitAdam/OneBitLamb/ZeroOneAdam/Adagrad/SGD/Muon selection matrix).

TPU-native: every optimizer is an optax gradient transformation that runs
*inside* the jitted, sharded train step — "Fused" is the default on TPU
(XLA fuses the update chain into a handful of kernels over the sharded
flat buffers), so FusedAdam/Adam/CPUAdam map to the same adamw transform;
a Pallas multi-tensor fused path exists in ``ops/fused_optimizer.py`` for
the flat-shard fast path.  1-bit optimizers use the error-feedback
compressed-allreduce transform from ``runtime/comm/compressed.py``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Union

import optax

from ..utils.logging import logger

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM = "fusedadam"
CPU_ADAM = "cpuadam"  # offload path: states on host, update on host C++ Adam
LAMB_OPTIMIZER = "lamb"
FUSED_LAMB = "fusedlamb"
LION_OPTIMIZER = "lion"
ADAGRAD_OPTIMIZER = "adagrad"
SGD_OPTIMIZER = "sgd"
ONEBIT_ADAM = "onebitadam"
ONEBIT_LAMB = "onebitlamb"
ZERO_ONE_ADAM = "zerooneadam"
MUON = "muon"
ADAFACTOR = "adafactor"


def get_optimizer(name: str,
                  params_cfg: Any,
                  lr_schedule: Optional[Union[Callable, float]] = None
                  ) -> optax.GradientTransformation:
    """Build the optax transform for a DeepSpeed optimizer name."""
    name = name.lower().replace("_", "")
    lr = lr_schedule if lr_schedule is not None else params_cfg.lr
    betas = tuple(params_cfg.betas)
    eps = params_cfg.eps
    wd = params_cfg.weight_decay

    if name in (ADAM_OPTIMIZER, FUSED_ADAM, CPU_ADAM, "deepspeedcpuadam"):
        # DeepSpeed's FusedAdam defaults to adam_w_mode=True -> adamw
        # semantics; adam_w_mode=False selects coupled L2 (decay folded into
        # the grad before the moments — classic Adam+L2).
        if getattr(params_cfg, "adam_w_mode", True):
            return optax.adamw(lr, b1=betas[0], b2=betas[1], eps=eps,
                               weight_decay=wd)
        return optax.chain(
            optax.add_decayed_weights(wd) if wd else optax.identity(),
            optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps),
            optax.scale_by_learning_rate(lr))
    if name == ADAMW_OPTIMIZER:
        return optax.adamw(lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)
    if name in (LAMB_OPTIMIZER, FUSED_LAMB):
        return optax.lamb(lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)
    if name in (LION_OPTIMIZER, "fusedlion", "cpulion"):
        b1 = betas[0] if betas else 0.9
        b2 = betas[1] if len(betas) > 1 else 0.99
        return optax.lion(lr, b1=b1, b2=b2, weight_decay=wd)
    if name in (ADAGRAD_OPTIMIZER, "cpuadagrad"):
        return optax.adagrad(lr, eps=eps)
    if name == SGD_OPTIMIZER:
        return optax.sgd(lr, momentum=params_cfg.momentum or None)
    if name == ADAFACTOR:
        return optax.adafactor(lr)
    if name == MUON:
        try:
            return optax.contrib.muon(lr)
        except Exception:
            logger.warning("optax muon unavailable; falling back to adamw")
            return optax.adamw(lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)
    if name in (ONEBIT_ADAM, ONEBIT_LAMB, ZERO_ONE_ADAM):
        from .comm.compressed import onebit_optimizer
        return onebit_optimizer(name, lr, betas=betas, eps=eps, weight_decay=wd)
    raise ValueError(f"unsupported optimizer: {name!r}")
