"""Deterministic fault-injection registry (ISSUE 7 tentpole).

Chaos engineering for the training and serving hot paths: a process-wide
registry of **named injection sites** woven into the code the telemetry
spine already instruments.  Every site is seeded and call-counted, so a
chaos run is exactly reproducible: the same seed + site spec fires the
same faults at the same call ordinals, which is what lets the chaos
tests assert tokenwise parity between an injected and an uninjected run
for the requests a fault did NOT touch.

Sites (the registry refuses unknown names so a typo'd spec is loud):

=========================  ==================================================
``train.nan_grad``         poison the next train batch with NaNs — the real
                           NaN propagates through the real fused step, so
                           recovery must genuinely roll back corrupted state
``train.slow_step``        stall a train step by ``value`` ms (EWMA anomaly
                           detector food)
``comm.collective_failure``  raise :class:`InjectedCollectiveFault` (a
                           :class:`TransientFault`) at train-step dispatch,
                           before any state mutation — retry-safe
``ckpt.io_error``          raise :class:`InjectedCheckpointFault` (an
                           ``OSError``) inside checkpoint save / the atomic
                           ``latest`` write
``kv.alloc_oom``           raise ``KVAllocationError`` from the KV-page
                           allocation path
``fastgen.poison_request``  raise :class:`PoisonedRequestFault` inside ONE
                           request's admission path (isolation food)
``serving.preempt``        raise :class:`InjectedPreemptionFault` — a
                           deterministic SIGTERM-equivalent — between
                           scheduler steps, so the drain→snapshot→restore
                           preemption path is chaos-testable without signals
=========================  ==================================================

Arming: the ``fault_injection`` config block on either engine config, or
the ``DS_CHAOS`` env var (read at import)::

    DS_CHAOS="fastgen.poison_request:p=0.1,max=3;ckpt.io_error:at=1|3"
    DS_CHAOS_SEED=7

Per-site spec keys: ``p``/``probability`` (per-call fire chance),
``at`` / ``at_calls`` (explicit 1-based call ordinals, deterministic),
``max`` / ``max_fires`` (fire budget, 0 = unlimited), ``value`` (site
payload, e.g. slow-step milliseconds).

Disabled-path contract: :meth:`FaultInjector.fire` reads ONE attribute
(``armed``) and returns — the same <5µs bound the tracer and watchdog
keep, verified by the same style of test.  Every fire increments
``ds_chaos_injected_total`` and leaves a ``chaos.fire`` flight-recorder
event, so a postmortem bundle of a chaos run names exactly which faults
were injected where.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Any, Dict, Mapping, Optional


# -- fault taxonomy ----------------------------------------------------------

class InjectedFault(RuntimeError):
    """Base of every exception the registry raises on purpose."""


class TransientFault(RuntimeError):
    """Marker for retry-safe failures: raised before any state mutation,
    so the self-healing engine may retry the same work after backoff.
    Real transient errors (a flaky collective transport) may subclass
    this too — the recovery path keys on the marker, not on injection."""


class InjectedCollectiveFault(TransientFault, InjectedFault):
    """A collective failed at dispatch; no device state was touched."""


class InjectedCheckpointFault(InjectedFault, OSError):
    """Checkpoint I/O failed (an ``OSError``, so the checkpoint retry
    loop treats it exactly like a real full-disk / dead-mount error)."""


class PoisonedRequestFault(InjectedFault):
    """One serving request's processing blew up (attributable: raised
    inside that request's admission block)."""


class InjectedPreemptionFault(InjectedFault):
    """A SIGTERM-equivalent preemption raised BETWEEN scheduler steps
    (no step state is mid-mutation).  The driving loop catches it and
    runs ``drain_and_snapshot`` exactly as the real signal handler
    would — deterministic, so a chaos test can interrupt at any chosen
    step ordinal and assert tokenwise parity after restore."""


#: every known injection site -> short description (docs + validation)
SITES: Dict[str, str] = {
    "train.nan_grad": "poison the next train batch with NaNs",
    "train.slow_step": "stall a train step by `value` ms",
    "comm.collective_failure":
        "raise a transient collective failure at train-step dispatch",
    "ckpt.io_error": "raise OSError inside checkpoint save/latest write",
    "kv.alloc_oom": "raise KVAllocationError from KV-page allocation",
    "kv.tier_io_error":
        "raise OSError inside KV tier demotion/spill/promotion I/O "
        "(degrades to a clean tier miss, never a corrupt hit)",
    "fastgen.poison_request":
        "raise inside one serving request's admission path",
    "serving.preempt":
        "raise a SIGTERM-equivalent preemption between scheduler steps",
}


class FaultSpec:
    """One site's firing rule (immutable after configure)."""
    __slots__ = ("probability", "at_calls", "max_fires", "value")

    def __init__(self, probability: float = 0.0,
                 at_calls: Optional[frozenset] = None,
                 max_fires: int = 0, value: float = 0.0):
        self.probability = float(probability)
        self.at_calls = at_calls or frozenset()
        self.max_fires = int(max_fires)
        self.value = float(value)


_SPEC_KEYS = {
    "p": "probability", "prob": "probability", "probability": "probability",
    "at": "at_calls", "at_calls": "at_calls",
    "max": "max_fires", "max_fires": "max_fires",
    "value": "value",
}


def _normalize_spec(site: str, raw: Mapping[str, Any]) -> FaultSpec:
    if site not in SITES:
        raise ValueError(
            f"unknown fault-injection site {site!r}; known sites: "
            f"{sorted(SITES)}")
    kw: Dict[str, Any] = {}
    for k, v in raw.items():
        dest = _SPEC_KEYS.get(k)
        if dest is None:
            raise ValueError(
                f"fault-injection site {site!r}: unknown spec key {k!r} "
                f"(use p/at/max/value)")
        if dest == "at_calls":
            if isinstance(v, str):
                v = [int(x) for x in v.split("|") if x]
            kw[dest] = frozenset(int(x) for x in v)
        else:
            kw[dest] = float(v)
    return FaultSpec(**kw)


class FaultInjector:
    """Process-wide injector.  ``armed`` is the one-attribute fast gate:
    with no sites configured every ``fire()`` is a read + return."""

    def __init__(self):
        self.armed = False
        # RLock (dslint telemetry-rlock): fire() can run inside frames
        # the postmortem SIGTERM handler interrupts and re-enters
        self._lock = threading.RLock()
        self._seed = 0
        self._specs: Dict[str, FaultSpec] = {}
        self._rngs: Dict[str, random.Random] = {}
        self._calls: Dict[str, int] = {}
        self._fires: Dict[str, int] = {}

    # -- arming --------------------------------------------------------------
    def configure(self, sites: Mapping[str, Mapping[str, Any]],
                  seed: int = 0) -> None:
        """Arm the registry with per-site specs.  Deterministic: each
        site gets its own ``random.Random`` seeded from ``(seed, site)``,
        and call ordinals restart at 0, so two identically-configured
        processes inject identical fault sequences."""
        specs = {s: _normalize_spec(s, raw or {})
                 for s, raw in sites.items()}
        with self._lock:
            self._seed = int(seed)
            self._specs = specs
            self._rngs = {s: random.Random(f"{seed}:{s}") for s in specs}
            self._calls = {s: 0 for s in specs}
            self._fires = {s: 0 for s in specs}
            self.armed = bool(specs)

    def disarm(self) -> None:
        """Drop every spec; ``fire()`` returns to the one-read path."""
        with self._lock:
            self._specs = {}
            self._rngs = {}
            self._calls = {}
            self._fires = {}
            self.armed = False

    def has_site(self, site: str) -> bool:
        """Whether ``site`` is armed (lets a call site skip expensive
        applicability checks — and avoid mis-counting an inapplicable
        fire — without probing the RNG)."""
        return self.armed and site in self._specs

    # -- the hot-path gate ---------------------------------------------------
    # dslint: disabled-path
    def fire(self, site: str) -> bool:
        """Should the fault at ``site`` fire on this call?  Disabled
        path: one attribute read."""
        if not self.armed:
            return False
        return self._fire_slow(site)

    def _fire_slow(self, site: str) -> bool:
        with self._lock:
            spec = self._specs.get(site)
            if spec is None:
                return False
            self._calls[site] += 1
            call = self._calls[site]
            if spec.max_fires and self._fires[site] >= spec.max_fires:
                return False
            hit = call in spec.at_calls or (
                spec.probability > 0.0
                and self._rngs[site].random() < spec.probability)
            if not hit:
                return False
            self._fires[site] += 1
            fired = self._fires[site]
        from ..telemetry import metrics as tm
        tm.CHAOS_INJECTED.inc()
        from ..telemetry.flight_recorder import get_flight_recorder
        get_flight_recorder().record("chaos.fire", site=site, call=call,
                                     fired=fired)
        return True

    def maybe_raise(self, site: str, exc_type=InjectedFault,
                    message: str = "") -> None:
        """Raise ``exc_type`` when ``site`` fires (no-op otherwise)."""
        if self.armed and self.fire(site):
            raise exc_type(message or f"injected fault at {site}")

    def site_value(self, site: str, default: float = 0.0) -> float:
        """The site's ``value`` payload (e.g. slow-step ms)."""
        with self._lock:
            spec = self._specs.get(site)
            return spec.value if spec is not None and spec.value \
                else default

    # -- observability -------------------------------------------------------
    def stats(self) -> Dict[str, Dict[str, int]]:
        """Per-site call/fire counts (the chaos tests assert every
        configured site actually fired)."""
        with self._lock:
            return {s: {"calls": self._calls[s], "fires": self._fires[s]}
                    for s in self._specs}


def parse_chaos_env(spec: str) -> Dict[str, Dict[str, str]]:
    """``DS_CHAOS`` grammar: ``site:k=v,k=v;site2:k=v`` (``at`` ordinals
    are ``|``-separated).  A bare ``site`` with no keys means
    ``p=1.0`` — fire on every call."""
    sites: Dict[str, Dict[str, str]] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        site, _, args = part.partition(":")
        site = site.strip()
        kv: Dict[str, str] = {}
        for item in args.split(","):
            item = item.strip()
            if not item:
                continue
            k, _, v = item.partition("=")
            kv[k.strip()] = v.strip()
        if not kv:
            kv = {"p": "1.0"}
        sites[site] = kv
    return sites


#: process-wide singleton
_INJECTOR = FaultInjector()


def get_fault_injector() -> FaultInjector:
    return _INJECTOR


def apply_fault_injection(enabled: bool, seed: int,
                          sites: Mapping[str, Mapping[str, Any]]) -> None:
    """Single implementation behind both engine configs'
    ``FaultInjectionConfig.apply()`` (the telemetry ``apply_settings``
    pattern).  ``enabled=False`` leaves the process registry alone so a
    default-config engine build cannot disarm a ``DS_CHAOS`` arming."""
    if not enabled:
        return
    _INJECTOR.configure(sites, seed=seed)


def _arm_from_env() -> None:
    spec = os.environ.get("DS_CHAOS", "")
    if not spec:
        return
    seed = int(os.environ.get("DS_CHAOS_SEED", "0") or 0)
    _INJECTOR.configure(parse_chaos_env(spec), seed=seed)


_arm_from_env()
