"""DeepSpeedEngine — the training engine.

TPU-native redesign of ``deepspeed/runtime/engine.py`` (DeepSpeedEngine,
:184) + ``runtime/bf16_optimizer.py`` + ``runtime/fp16/`` loss scaling +
ZeRO optimizer wrapping (``_configure_zero_optimizer`` :1540).

Architecture: instead of wrapping a torch module and intercepting autograd,
the engine owns a **functional train step** — ``(state, batch, rng) ->
(state, metrics)`` — jitted once over a sharded
:class:`~deepspeed_tpu.parallel.topology.MeshTopology`.  Everything the
reference does imperatively is a region of that traced program:

  reference engine.forward/backward/step     one ``lax.scan`` over
  + grad-acc hooks + allreduce_gradients     micro-batches accumulating
  (engine.py:1846,1985,2185; stage3 hooks)   fp32 grads, then one update

  ZeRO-1/2/3 partitioning                    shardings from
  (stage_1_and_2.py, stage3.py)              runtime/zero/partitioner.py

  BF16_Optimizer fp32 master weights         state.params kept fp32,
  (bf16_optimizer.py:29)                     cast to bf16 for compute

  fp16 dynamic loss scaling                  traced overflow check +
  (fp16/loss_scaler.py)                      lax.cond skip/rescale

  CUDA streams / overlap_comm                XLA latency-hiding scheduler

The imperative ``forward()/backward()/step()`` triple is still provided for
API parity (micro-batches are buffered and the fused step runs at the
gradient-accumulation boundary inside ``step()``).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import math
import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm as dist
from ..parallel.topology import (BATCH_AXES, MeshTopology, TopologyConfig)
from ..telemetry import get_tracer, trace_span
from ..telemetry import metrics as tm
from ..telemetry.flight_recorder import get_flight_recorder
from ..telemetry.state import state as telemetry_state
from ..telemetry.watchdog import get_watchdog
from ..utils.logging import log_dist, logger
from ..utils.timer import (BACKWARD_GLOBAL_TIMER, FORWARD_GLOBAL_TIMER,
                           STEP_GLOBAL_TIMER, TRAIN_BATCH_TIMER,
                           SynchronizedWallClockTimer, ThroughputTimer)
from .config import DeepSpeedTPUConfig, load_config
from .fault_injection import (InjectedCollectiveFault, TransientFault,
                              get_fault_injector)
from .lr_schedules import LRScheduler, get_lr_schedule
from .optimizers import get_optimizer
from .zero.partitioner import ZeroPartitioner, unbox

DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


class TrainState(struct.PyTreeNode):
    """Sharded training state (the engine's entire mutable device state)."""
    step: jax.Array                 # int32 global step
    params: Any                     # fp32 master (or compute-dtype if no master)
    opt_state: Any
    loss_scale: jax.Array           # float32; 1.0 when not fp16
    good_steps: jax.Array           # int32 consecutive non-overflow steps
    skipped_steps: jax.Array        # int32 total skipped (overflow) steps
    hysteresis: jax.Array           # int32 remaining tolerated overflows
    # CollectiveScheduler error-feedback residuals: [world, E] fp32 per
    # batch shard (() when the quantized wire or error feedback is off).
    # Checkpointed with the state; universal-checkpoint load ignores it
    # (atoms cover params/opt only) and plain load falls back to zeros
    # when restoring a checkpoint written without it.
    comm_residuals: Any = ()


@dataclasses.dataclass
class EngineMetrics:
    loss: float = 0.0
    grad_norm: float = 0.0
    lr: float = 0.0
    skipped: bool = False


def _topology_from_config(config: DeepSpeedTPUConfig,
                          devices=None) -> MeshTopology:
    mesh_cfg = dict(config.tpu.mesh)
    tcfg = TopologyConfig(
        pipe=mesh_cfg.get("pipe", config.pipeline.stages or 1),
        data=mesh_cfg.get("data", -1),
        expert=mesh_cfg.get("expert", config.moe.ep_size if config.moe.enabled else 1),
        fsdp=mesh_cfg.get("fsdp", 1),
        seq=mesh_cfg.get("seq", config.sequence_parallel.sp_size
                         if config.sequence_parallel.enabled else 1),
        tensor=mesh_cfg.get("tensor", config.tensor_parallel.tp_size
                            if config.tensor_parallel.enabled else 1),
    )
    zcfg = config.zero_optimization
    hpz = max(1, int(mesh_cfg.get("hpz", zcfg.zero_hpz_partition_size)))
    tcfg = dataclasses.replace(tcfg, hpz=hpz)
    n = len(devices) if devices is not None else jax.device_count()
    # ZeRO wants the fsdp axis to absorb data-parallel devices. If the user
    # didn't lay out the mesh explicitly, put all free devices on 'fsdp' for
    # stage>=1 (equivalent DP semantics, enables sharding), else on 'data'.
    if "data" not in mesh_cfg and "fsdp" not in mesh_cfg:
        fixed = tcfg.pipe * tcfg.expert * tcfg.hpz * tcfg.seq * tcfg.tensor
        if fixed == 0 or n % fixed != 0:
            raise ValueError(
                f"mesh axes pipe={tcfg.pipe} expert={tcfg.expert} "
                f"hpz={tcfg.hpz} seq={tcfg.seq} tensor={tcfg.tensor} "
                f"(product {fixed}) do not divide device count {n}")
        free = n // fixed
        if zcfg.mics_shard_size > 0 and zcfg.stage >= 3:
            # MiCS (reference zero/mics.py:64): shard params only WITHIN
            # groups of mics_shard_size, replicate across groups — the
            # cross-group axis is plain data parallelism
            mics = zcfg.mics_shard_size
            if free % mics != 0:
                raise ValueError(
                    f"mics_shard_size {mics} does not divide the {free} "
                    f"free devices")
            tcfg = dataclasses.replace(tcfg, data=free // mics, fsdp=mics)
        elif zcfg.stage >= 1:
            tcfg = dataclasses.replace(tcfg, data=1, fsdp=free)
        else:
            tcfg = dataclasses.replace(tcfg, data=free, fsdp=1)
    return MeshTopology(tcfg, devices=devices)


class DeepSpeedEngine:
    """Training engine (reference runtime/engine.py:184).

    Parameters
    ----------
    model : object with ``init_params(rng) -> params`` and
        ``loss(params, batch, rng) -> scalar`` (see models/base.py), OR None
        if ``loss_fn`` + ``params`` are given directly.
    config : DeepSpeed-style dict / json path / DeepSpeedTPUConfig.
    """

    def __init__(self,
                 model: Any = None,
                 config: Any = None,
                 loss_fn: Optional[Callable] = None,
                 params: Any = None,
                 topology: Optional[MeshTopology] = None,
                 rng: Optional[jax.Array] = None,
                 training_data: Any = None,
                 collate_fn: Any = None,
                 lr_scheduler: Any = None,
                 dont_change_device: bool = False):
        self.config = load_config(config)
        from .config import warn_noop_keys
        warn_noop_keys(self.config)
        self.module = model
        self._apply_model_overrides()
        dist.init_distributed()
        self.topology = topology or _topology_from_config(self.config)
        self.config.resolve_batch_sizes(self.topology.batch_shard_size)

        zcfg = self.config.zero_optimization
        self.zero_stage = zcfg.stage
        self.partitioner = ZeroPartitioner(
            self.topology, zcfg.stage,
            persistence_threshold=zcfg.stage3_param_persistence_threshold)

        self.compute_dtype = DTYPES[self.config.precision_dtype] \
            if self.config.precision_dtype != "float16" else jnp.bfloat16
        # fp16 configs keep loss-scaling semantics but compute in bf16 (TPU
        # has no fast fp16); dynamic scaling still guards against inf/nan.
        self._fp16_enabled = self.config.fp16.enabled
        self.master_dtype = (jnp.float32 if (self.config.bf16.master_weights
                                             or self._fp16_enabled
                                             or self.config.precision_dtype == "float32")
                             else self.compute_dtype)

        # reference has no analogue; on TPU this selects the MXU pass
        # count (bfloat16 -> 1 pass, tensorfloat32/float32 -> 3/6).
        # Always applied — 'default' RESETS to None so one engine's
        # setting cannot leak into the next engine in the process.
        jax.config.update(
            "jax_default_matmul_precision",
            None if self.config.tpu.matmul_precision == "default"
            else self.config.tpu.matmul_precision)
        self._rng = rng if rng is not None else jax.random.key(0)
        self._loss_fn = loss_fn if loss_fn is not None else getattr(model, "loss", None)
        if self._loss_fn is None:
            raise ValueError("provide `model` with a .loss method or a `loss_fn`")

        # -- LR schedule & optimizer --------------------------------------
        opt_cfg = self.config.optimizer
        base_lr = opt_cfg.params.lr
        if self.config.scheduler is not None:
            self._schedule = get_lr_schedule(self.config.scheduler.type,
                                             self.config.scheduler.params, base_lr)
        elif callable(lr_scheduler):
            self._schedule = lr_scheduler
        else:
            self._schedule = lambda step: base_lr
        self.lr_scheduler = LRScheduler(self._schedule)
        self.optimizer = self._build_optimizer(opt_cfg)
        self.basic_optimizer = self.optimizer
        self.offload: Optional[Any] = None  # set in _maybe_enable_offload

        # -- state init ----------------------------------------------------
        if params is not None:
            # Keep the (possibly flax-Partitioned-boxed) abstract tree so
            # logical TP/EP axis names survive unboxing.
            self._abstract_params = jax.eval_shape(lambda p: p, params)
            init_params = params
        else:
            init_params = self._init_params()  # sets self._abstract_params
        self._maybe_enable_compression()
        self._maybe_enable_offload()
        self.comm_scheduler = self._build_comm_scheduler()
        if self.offload is not None:
            # masters come from the fp32 initializer output, BEFORE the
            # device copy is narrowed to compute dtype
            self.offload.init_masters(unbox(init_params))
        self.state = self._init_state(init_params)
        self.global_steps = 0
        self.micro_steps = 0
        self.global_samples = 0
        self._grad_acc_buffer: List[Any] = []

        # -- step compilation ---------------------------------------------
        self._train_step = self._build_train_step()
        self._eval_step = self._build_eval_step()

        # -- io/observability ---------------------------------------------
        self.config.telemetry.apply()
        self.config.fault_injection.apply()
        # self-healing state (ISSUE 7): the last checkpoint this engine
        # wrote, an in-memory host snapshot when no checkpoint exists
        # yet, and the consecutive-recovery counter the retry budget
        # bounds
        self._last_good_ckpt: Optional[Tuple[str, str]] = None
        self._state_snapshot: Optional[dict] = None
        self._rollback_streak = 0
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(),
            steps_per_output=self.config.steps_per_print)
        self.monitor = self._build_monitor()
        if self.config.comms_logger.enabled:
            dist.configure_comms_logger(verbose=self.config.comms_logger.verbose)
            if self.comm_scheduler is not None:
                dist.record_bucket_plan(
                    self.comm_scheduler.stats(
                        self.gradient_accumulation_steps()))
        self.training_dataloader = self.deepspeed_io(training_data, collate_fn=collate_fn) \
            if training_data is not None else None
        self.checkpoint_engine = self._build_checkpoint_engine()

        # flight recorder (ISSUE 5): the config is captured always (a
        # crash with telemetry off should still identify what ran); the
        # lifecycle event is enabled-gated inside record()
        self._monitor_write_warned = False
        recorder = get_flight_recorder()
        recorder.set_config("runtime", self.config)
        recorder.record(
            "engine.build", engine="train", zero_stage=self.zero_stage,
            micro_bs=self.train_micro_batch_size_per_gpu(),
            gas=self.gradient_accumulation_steps())

        log_dist(
            f"engine ready: zero_stage={self.zero_stage} "
            f"mesh={dict((a, self.topology.axis_size(a)) for a in self.topology.mesh.axis_names)} "
            f"micro_bs={self.train_micro_batch_size_per_gpu()} "
            f"gas={self.gradient_accumulation_steps()} "
            f"train_bs={self.train_batch_size()} dtype={self.compute_dtype.__name__}",
            ranks=[0])

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------
    def _apply_model_overrides(self) -> None:
        """Propagate explicitly-set ``tpu.*`` model knobs (scan_layers,
        remat, remat_policy, attention_impl) onto the model's
        TransformerConfig.  Only keys the user actually wrote in the
        engine config are applied, so model-constructor overrides win
        otherwise."""
        model = self.module
        if model is None or not hasattr(model, "cfg"):
            return
        from ..models.transformer import TransformerConfig
        if not isinstance(model.cfg, TransformerConfig):
            return
        tpu = self.config.tpu
        overrides = {k: getattr(tpu, k)
                     for k in ("scan_layers", "remat", "remat_policy",
                               "attention_impl")
                     if k in tpu.model_fields_set}
        # reference activation_checkpointing block
        # (runtime/activation_checkpointing/checkpointing.py:487)
        ac = self.config.activation_checkpointing
        if "policy" in ac.model_fields_set:
            overrides["remat_policy"] = {
                "full": "nothing_saveable",
                "nothing": "everything_saveable",
                "dots": "dots_saveable",
                "dots_with_no_batch_dims":
                    "dots_with_no_batch_dims_saveable",
                "offload_dots": "offload_dots",
            }.get(ac.policy, ac.policy)
            overrides["remat"] = True
        if ac.partition_activations:
            overrides["partition_activations"] = True
        if ac.cpu_checkpointing:
            # host-offload the saved names of the active policy; policies
            # that save nothing get the attn-out offload variant so the
            # option has its documented memory effect
            base = overrides.get("remat_policy", model.cfg.remat_policy)
            if base == "everything_saveable":
                raise ValueError(
                    "cpu_checkpointing requires recomputation boundaries, "
                    "but the active remat policy saves everything "
                    "(policy='nothing' / everything_saveable).  Drop one "
                    "of the two options.")
            overrides["remat_policy"] = {
                "save_attn_out": "offload_attn_out",
                "dots_with_no_batch_dims_saveable": "offload_dots",
                "dots_saveable": "offload_dots",
            }.get(base, "offload_attn_out")
            overrides["remat"] = True
        sp = self.config.sequence_parallel
        if sp.enabled and sp.mode != "ulysses":
            overrides["sp_mode"] = sp.mode
        if self.config.sparse_gradients:
            # reference top-level key: embedding grads take the sparse
            # (indexed-slices) backward, runtime/sparse_tensor.py
            overrides["sparse_gradients"] = True
        if overrides:
            model.cfg = dataclasses.replace(model.cfg, **overrides)

    def _build_optimizer(self, opt_cfg) -> optax.GradientTransformation:
        return get_optimizer(opt_cfg.type, opt_cfg.params,
                             lr_schedule=lambda count: self._traced_lr(count))

    def _maybe_enable_compression(self) -> None:
        """Scheduled compression (reference engine fwd hook engine.py:1862
        + compression/scheduler.py).  Functionally: weights are projected
        onto the compressed set (masks/quant grid) after each update."""
        self.compression = None
        comp_cfg = self.config.compression_training
        blocks = {k: getattr(comp_cfg, k) for k in (
            "weight_quantization", "activation_quantization",
            "sparse_pruning", "row_pruning", "head_pruning",
            "channel_pruning")}
        if not any(b.get("shared_parameters", {}).get("enabled", False)
                   for b in blocks.values() if isinstance(b, dict)):
            return
        from ..compression import init_compression
        unboxed_abstract = jax.eval_shape(unbox, self._abstract_params)
        self.compression = init_compression(blocks, unboxed_abstract)
        self._compression_min_offset = self.compression.min_param_offset()

    def _maybe_apply_compression(self) -> None:
        if self.compression is None or not self.compression.param_groups \
                or self.global_steps < self._compression_min_offset:
            return
        with self.topology.mesh:
            self.state = self.state.replace(
                params=self.compression.apply(self.state.params,
                                              self.global_steps))

    def _maybe_enable_offload(self) -> None:
        """ZeRO-Offload: mask offloaded leaves out of the device optimizer
        and hand them to the host C++ path (runtime/zero/offload.py)."""
        off = self.config.zero_optimization.offload_optimizer
        if off.device in (None, "none"):
            return
        from .zero.offload import HostOffloadOptimizer
        unboxed_abstract = jax.eval_shape(unbox, self._abstract_params)
        self.offload = HostOffloadOptimizer(unboxed_abstract, self.config)
        mask = self.offload.device_mask()
        inv_mask = jax.tree.map(lambda m: not m, mask)
        # masked() passes untouched leaves' updates through VERBATIM, so the
        # offloaded leaves' raw grads must be zeroed or apply_updates would
        # do SGD on them behind the host optimizer's back
        self.optimizer = optax.chain(
            optax.masked(self.optimizer, mask),
            optax.masked(optax.set_to_zero(), inv_mask))

    def _build_comm_scheduler(self):
        """Build the CollectiveScheduler (bucketed/quantized/overlapped
        gradient collectives) when the config asks for it and the mesh
        supports it; None means gradients reduce via the compiler's
        psum exactly as before (bit-identical path)."""
        cfg = self.config
        comm = cfg.comm_optimization
        # legacy ZeRO++ qgZ flag routes through the scheduler now
        legacy_qgz = (self.zero_stage >= 2
                      and cfg.zero_optimization.zero_quantized_gradients)
        if not (comm.enabled or legacy_qgz):
            return None
        if getattr(self, "_fused_microbatches", False):
            logger.warning(
                "comm_optimization: pipeline (fused micro-batch) engines "
                "reduce inside the pipelined program; scheduler disabled")
            return None
        mesh = self.topology.mesh
        sizes = {a: mesh.shape.get(a, 1) for a in mesh.axis_names}
        manual = tuple(a for a in ("data", "fsdp") if sizes.get(a, 1) > 1)
        if not manual:
            logger.warning(
                "comm_optimization: no data/fsdp axis larger than 1 — "
                "nothing to reduce; scheduler disabled")
            return None
        if any(sizes.get(a, 1) > 1 for a in ("expert", "hpz", "pipe")):
            logger.warning(
                "comm_optimization: expert/hpz/pipe meshes keep the "
                "compiler psum (their grad reduction is not a plain "
                "batch-axes sum); scheduler disabled")
            return None
        others = any(sizes.get(a, 1) > 1 for a in ("tensor", "seq"))
        if others and getattr(getattr(self.module, "cfg", None),
                              "scan_layers", False):
            # partial-auto regions (manual batch axes + auto tensor/seq)
            # miscompile a lax.scan over layers on this XLA version
            # (spmd partitioner manual-subgroup check); unrolled layers
            # work — the user picks which to keep
            logger.warning(
                "comm_optimization: tensor/seq meshes + tpu.scan_layers "
                "miscompile in partial-auto shard_map regions on this "
                "XLA version — set tpu.scan_layers=false to keep the "
                "scheduler; falling back to compiler psum")
            return None
        if others and not comm.enabled:
            # the legacy qgZ flag keeps its seed semantics: pure
            # batch-axes meshes only.  Opt into comm_optimization
            # explicitly for tensor/seq meshes.
            logger.warning(
                "zero_quantized_gradients: mesh has tensor/seq axes; "
                "enable comm_optimization explicitly for the quantized "
                "wire on such meshes — falling back to compiler psum")
            return None
        if legacy_qgz and not comm.enabled:
            # seed qgZ semantics: quantized per-micro-batch reduction,
            # NO persistent error feedback (the seed path kept no
            # residual state — silently adding a full-gradient fp32
            # buffer per rank could OOM a previously-fitting model).
            # Opt into comm_optimization explicitly for error feedback.
            comm = comm.model_copy(update={"quantize": True,
                                           "error_feedback": False,
                                           "overlap": True})
        acc_dtype = (jnp.float32 if cfg.bf16.accumulate_grads_in_fp32
                     else self.compute_dtype)
        from .comm.collective_scheduler import CollectiveScheduler
        abstract_grads = jax.eval_shape(unbox, self._abstract_params)
        gspecs = self.partitioner.tree_grad_specs(self._abstract_params)
        return CollectiveScheduler(self.topology, comm, abstract_grads,
                                   gspecs, acc_dtype=acc_dtype)

    def comm_stats(self) -> Optional[dict]:
        """Static per-step wire accounting from the CollectiveScheduler
        (None when gradients reduce via the compiler psum)."""
        if self.comm_scheduler is None:
            return None
        return self.comm_scheduler.stats(self.gradient_accumulation_steps())

    def _traced_lr(self, count):
        sched = self._schedule
        try:
            return sched(count)  # works when count is concrete OR sched is jnp-safe
        except Exception:
            from .lr_schedules import _traced_schedule
            return _traced_schedule(sched, count)

    def _init_params(self):
        init = getattr(self.module, "init_params", None)
        if init is None:
            raise ValueError("model must define init_params(rng)")
        rng = self._rng
        # Initialize directly into the sharded layout: jit the initializer
        # with sharded out_shardings so no single host/device ever holds the
        # full fp32 model (the reference needs zero.Init's __init__ patching
        # for this; on TPU it is just sharded compilation of the initializer).
        self._abstract_params = jax.eval_shape(init, rng)
        shardings = self.partitioner.master_shardings(self._abstract_params)
        init_fn = jax.jit(init, out_shardings=shardings)
        with self.topology.mesh:
            p = init_fn(rng)
        return p

    def _init_state(self, params) -> TrainState:
        params = unbox(params)
        if self.offload is not None:
            # fp32 master of offloaded leaves lives on the HOST; the device
            # keeps only the compute-dtype copy (the offload memory win)
            offloaded = set(self.offload.offload_idx)
            flat, treedef = jax.tree.flatten(params)
            flat = [x.astype(self.compute_dtype
                             if i in offloaded else self.master_dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x
                    for i, x in enumerate(flat)]
            params = jax.tree.unflatten(treedef, flat)
        else:
            params = jax.tree.map(
                lambda x: x.astype(self.master_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        # Specs computed from the boxed abstract tree (keeps logical axes);
        # its Partitioned nodes sit exactly where unboxed array leaves sit,
        # so the resulting sharding tree matches the unboxed param treedef.
        master_sh = self.partitioner.master_shardings(self._abstract_params)

        def make_state(p):
            opt_state = self.optimizer.init(p)
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=p,
                opt_state=opt_state,
                loss_scale=jnp.asarray(self._initial_loss_scale(), jnp.float32),
                good_steps=jnp.zeros((), jnp.int32),
                skipped_steps=jnp.zeros((), jnp.int32),
                hysteresis=jnp.asarray(self.config.fp16.hysteresis, jnp.int32),
                comm_residuals=(self.comm_scheduler.init_residuals()
                                if self.comm_scheduler is not None else ()))

        abstract = jax.eval_shape(make_state, params)
        state_sh = self._state_shardings(abstract, master_sh)
        with self.topology.mesh:
            state = jax.jit(make_state, out_shardings=state_sh)(params)
        self._state_shardings_cache = state_sh
        return state

    def _state_shardings(self, abstract_state, master_sh):
        """Shardings for the full TrainState: params & their optimizer
        moments follow the master sharding; non-param state replicated."""
        mesh = self.topology.mesh
        rep = NamedSharding(mesh, P())
        # Optimizer moments mirror the param tree inside optax state
        # namedtuples; tree_map_params pairs them with master shardings.
        # Offloaded leaves have MaskedNode (no device moments): their
        # sharding slot must be a matching empty container, not a leaf.
        if self.offload is not None:
            offloaded = set(self.offload.offload_idx)
            flat_sh, sh_treedef = jax.tree.flatten(master_sh)
            flat_sh = [optax.MaskedNode() if i in offloaded else s
                       for i, s in enumerate(flat_sh)]
            master_sh_for_opt = jax.tree.unflatten(sh_treedef, flat_sh)
        else:
            master_sh_for_opt = master_sh
        opt_sh = optax.tree_map_params(
            self.optimizer,
            lambda _leaf, sh: sh,
            abstract_state.opt_state,
            master_sh_for_opt,
            transform_non_params=lambda _leaf: rep)
        return TrainState(
            step=rep,
            params=master_sh,
            opt_state=opt_sh,
            loss_scale=rep, good_steps=rep, skipped_steps=rep, hysteresis=rep,
            comm_residuals=(self.comm_scheduler.residual_sharding()
                            if self.comm_scheduler is not None else ()))

    def _initial_loss_scale(self) -> float:
        if not self._fp16_enabled:
            return 1.0
        if self.config.fp16.loss_scale > 0:
            return float(self.config.fp16.loss_scale)
        return float(2 ** self.config.fp16.initial_scale_power)

    def _build_monitor(self):
        try:
            from ..monitor.monitor import MonitorMaster
            return MonitorMaster(self.config)
        except Exception as e:  # monitor optional — but say WHY it's off
            logger.warning(
                "monitor disabled (%s: %s) — training continues without "
                "monitor writers", type(e).__name__, e)
            return None

    def _monitor_write(self, fn, *args) -> None:
        """Run one monitor write batch.  A raising writer (full disk,
        dead tensorboard socket, wandb auth) must not kill the training
        step — but it must not vanish either: warn once with the
        exception class and count every dropped batch in
        ``ds_train_monitor_drop_total``."""
        try:
            fn(*args)
        except Exception as e:
            tm.TRAIN_MONITOR_DROP.inc()
            if not self._monitor_write_warned:
                self._monitor_write_warned = True
                logger.warning(
                    "monitor write failed (%s: %s) — dropped; further "
                    "drops are counted in ds_train_monitor_drop_total "
                    "without logging", type(e).__name__, e)

    def _build_checkpoint_engine(self):
        from ..checkpoint.engine import OrbaxCheckpointEngine
        ckpt = self.config.checkpoint
        return OrbaxCheckpointEngine(async_save=ckpt.async_save,
                                     save_retries=ckpt.save_retries,
                                     save_backoff_s=ckpt.save_backoff_s)

    # ------------------------------------------------------------------
    # the fused train step
    # ------------------------------------------------------------------
    def _build_train_step(self):
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        clip = cfg.gradient_clipping
        fp16 = self._fp16_enabled
        compute_dtype = self.compute_dtype
        loss_fn = self._loss_fn
        optimizer = self.optimizer
        partitioner = self.partitioner
        mesh = self.topology.mesh

        scale_window = cfg.fp16.loss_scale_window
        min_scale = cfg.fp16.min_loss_scale
        dynamic = fp16 and cfg.fp16.loss_scale == 0

        param_specs = partitioner.tree_param_specs(self._abstract_params)
        gspecs = partitioner.tree_grad_specs(self._abstract_params)
        # reference bf16_optimizer fp32 grad accumulation; disabling
        # halves the accumulator memory (pure-bf16 training)
        acc_dtype = (jnp.float32 if cfg.bf16.accumulate_grads_in_fp32
                     else compute_dtype)

        # ZeRO++ qwZ (zero_quantized_weights): compute weights snap to the
        # int8 blockwise grid before use, reproducing the numerics of the
        # reference's quantized weight all-gather (the wire-compressed
        # gather op itself is ops.quantized_all_gather_st for shard_map
        # paths; under GSPMD the gather is compiler-inserted, so the grid
        # projection is where qwZ's accuracy behavior lives).
        qw = (self.zero_stage >= 3
              and cfg.zero_optimization.zero_quantized_weights)
        if qw:
            from ..ops.quantization import quantize_dequantize_st

        def cast_for_compute(p):
            def one(x):
                if not jnp.issubdtype(x.dtype, jnp.floating):
                    return x
                if qw and x.ndim >= 2:
                    x = quantize_dequantize_st(x)
                return x.astype(compute_dtype)
            return jax.tree.map(one, p)

        def constrain(tree, specs):
            return jax.tree.map(
                lambda x, s: jax.lax.with_sharding_constraint(x, NamedSharding(mesh, s)),
                tree, specs)

        # Pipeline mode: the loss_fn consumes the whole [gas, micro, ...]
        # batch in one pipelined evaluation (no outer micro-batch scan).
        fused_mb = getattr(self, "_fused_microbatches", False)

        # Gradient-collective scheduler (runtime/comm/collective_scheduler):
        # bucketed int8 wire + error feedback + per-micro-batch overlap,
        # generalizing the old inline qgZ special case.  None => the
        # compiler-inserted psum reduces gradients exactly as before.
        sched = self.comm_scheduler

        def step_fn(state: TrainState, batch, rng):
            # ZeRO: compute params = cast(master) re-sharded to param layout.
            # stage>=1: this IS the post-step allgather of bf16 weights —
            # done in compute dtype so the wire carries 2-byte words.
            params_c = constrain(cast_for_compute(state.params), param_specs)

            def micro(carry, xs):
                mb, mb_rng = xs

                def scaled_loss(p):
                    l = loss_fn(p, mb, mb_rng)
                    return (l * state.loss_scale).astype(jnp.float32)
                loss, grads = jax.value_and_grad(scaled_loss)(params_c)
                grads = jax.tree.map(
                    lambda g: g.astype(acc_dtype), grads)
                # fp32 accumulation (reference bf16_optimizer immediate
                # hp-grad accumulation), born reduce-scattered for stage>=2
                grads = constrain(grads, gspecs)
                carry = jax.tree.map(jnp.add, carry, grads)
                return carry, loss / state.loss_scale

            def micro_sched(carry, xs):
                # backward in a batch-axes-manual region => unreduced
                # per-shard grads; the scheduler owns the reduction wire
                mb, mb_rng = xs
                loss, flat_local, direct = sched.backward(
                    loss_fn, params_c, mb, mb_rng, state.loss_scale)
                if sched.overlap:
                    # reduce THIS micro-batch's buckets now: their
                    # collectives overlap the remaining buckets' quantize
                    # work and the next micro-batch's backward
                    acc, resid = carry
                    flat_red, resid = sched.reduce(flat_local, resid,
                                                   state.loss_scale)
                    g = constrain(sched.combine(flat_red, direct), gspecs)
                    acc = jax.tree.map(jnp.add, acc, g)
                    return (acc, resid), loss / state.loss_scale
                # accumulate unreduced; one bucketed reduction at the
                # gradient-accumulation boundary
                acc_flat, acc_direct = carry
                acc_flat = acc_flat + flat_local
                acc_direct = jax.tree.map(jnp.add, acc_direct, direct)
                return (acc_flat, acc_direct), loss / state.loss_scale

            if sched is None:
                zero_carry = jax.tree.map(
                    lambda p: jnp.zeros(p.shape, acc_dtype), params_c)
                micro_fn = micro
            elif sched.overlap:
                zero_carry = (jax.tree.map(
                    lambda p: jnp.zeros(p.shape, acc_dtype), params_c),
                    state.comm_residuals)
                micro_fn = micro_sched
            else:
                zero_carry = (sched.zero_flat(), sched.zero_direct())
                micro_fn = micro_sched

            rngs = jax.random.split(rng, gas)
            if fused_mb:
                # loss is already a mean over every micro-batch token
                def scaled_loss(p):
                    l = loss_fn(p, batch, rngs[0])
                    return (l * state.loss_scale).astype(jnp.float32)
                loss, grads = jax.value_and_grad(scaled_loss)(params_c)
                grads = constrain(
                    jax.tree.map(lambda g: g.astype(acc_dtype), grads), gspecs)
                losses = (loss / state.loss_scale)[None]
            elif gas == 1:
                carry, losses = micro_fn(
                    zero_carry, (jax.tree.map(lambda x: x[0], batch), rngs[0]))
                losses = losses[None]
            else:
                carry, losses = jax.lax.scan(micro_fn, zero_carry,
                                             (batch, rngs))
            new_residuals = state.comm_residuals
            if fused_mb:
                pass  # grads already reduced by the fused evaluation
            elif sched is None:
                grads = carry
            elif sched.overlap:
                grads, new_residuals = carry
            else:
                acc_flat, acc_direct = carry
                flat_red, new_residuals = sched.reduce(
                    acc_flat, state.comm_residuals, state.loss_scale)
                grads = constrain(sched.combine(flat_red, acc_direct),
                                  gspecs)
            inv = 1.0 / ((1 if fused_mb else gas) * state.loss_scale)
            grads = jax.tree.map(lambda g: g * inv, grads)

            # global grad norm (over ALL shards; XLA handles cross-device sum)
            gnorm = optax.global_norm(grads)
            finite = jnp.isfinite(gnorm)
            if sched is not None:
                if fp16 and jax.tree.leaves(new_residuals):
                    # an overflow step quantizes inf gradients (absmax inf
                    # -> NaN payload): committing that error-feedback
                    # update would poison every later step's buckets, so
                    # keep the previous residuals on overflow
                    new_residuals = jax.tree.map(
                        lambda n, o: jnp.where(finite, n, o),
                        new_residuals, state.comm_residuals)
                state = state.replace(comm_residuals=new_residuals)
            if clip > 0:
                scale = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * scale, grads)

            def do_update(operand):
                grads, state = operand
                updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
                new_params = optax.apply_updates(state.params, updates)
                return state.replace(
                    step=state.step + 1, params=new_params, opt_state=new_opt,
                    good_steps=state.good_steps + 1)

            def skip_update(operand):
                _, state = operand
                return state.replace(step=state.step + 1, good_steps=jnp.zeros((), jnp.int32),
                                     skipped_steps=state.skipped_steps + 1)

            if fp16:
                new_state = jax.lax.cond(finite, do_update, skip_update, (grads, state))
                if dynamic:
                    # dynamic loss scale update (fp16/loss_scaler.py semantics,
                    # incl. hysteresis: tolerate hysteresis-1 overflows before
                    # lowering the scale)
                    ls = new_state.loss_scale
                    hy = new_state.hysteresis
                    halve = (~finite) & (hy <= 1)
                    hy = jnp.where(~finite & ~halve, hy - 1, hy)
                    ls = jnp.where(halve, jnp.maximum(ls / 2.0, min_scale), ls)
                    hy = jnp.where(halve, jnp.asarray(cfg.fp16.hysteresis, jnp.int32), hy)
                    grow = (new_state.good_steps % scale_window == 0) & (new_state.good_steps > 0)
                    ls = jnp.where(finite & grow, ls * 2.0, ls)
                    hy = jnp.where(finite & grow,
                                   jnp.asarray(cfg.fp16.hysteresis, jnp.int32), hy)
                    new_state = new_state.replace(loss_scale=ls, hysteresis=hy)
            else:
                new_state = do_update((grads, state))

            metrics = {
                "loss": jnp.mean(losses).astype(jnp.float32),
                "grad_norm": gnorm,
                "lr": jnp.asarray(self._traced_lr(state.step), jnp.float32),
                # lr at the APPLIED-update count: optax's schedule counter
                # only advances on non-skipped steps, and the host offload
                # optimizer must see the identical lr or offloaded leaves
                # drift off-schedule after any fp16 overflow
                "applied_lr": jnp.asarray(
                    self._traced_lr(state.step - state.skipped_steps),
                    jnp.float32),
                "overflow": (~finite).astype(jnp.int32),
            }
            if self.offload is not None:
                # ship reduced+clipped fp32 grads of offloaded leaves to the
                # host optimizer
                flat_grads = jax.tree.leaves(grads)
                off_grads = [flat_grads[i] for i in self.offload.offload_idx]
                return new_state, metrics, off_grads
            return new_state, metrics, ()

        state_sh = self._state_shardings_cache
        donate = (0,) if cfg.tpu.donate_state else ()
        # Batch shardings are rank-dependent per leaf, so the batch is
        # device_put with explicit shardings in train_batch and jit inherits
        # them (in_shardings left unspecified for that arg).
        model_cfg = getattr(self.module, "cfg", None)
        if str(getattr(model_cfg, "remat_policy", "")).startswith("offload_"):
            # XLA workaround: explicit out_shardings + a host-offload remat
            # policy makes jit annotate every result with a device
            # placement custom-call that the SPMD partitioner rejects
            # ("Side-effect HLO must have sharding", spmd_partitioner.cc).
            # Enforce the state layout with in-function constraints instead.
            def constrained_step(state, batch, rng):
                new_state, metrics, off = step_fn(state, batch, rng)
                new_state = jax.tree.map(
                    lambda x, s: (jax.lax.with_sharding_constraint(x, s)
                                  if isinstance(s, NamedSharding) else x),
                    new_state, state_sh)
                return new_state, metrics, off
            return jax.jit(constrained_step, donate_argnums=donate)
        return jax.jit(step_fn,
                       out_shardings=(state_sh, None, None),
                       donate_argnums=donate)

    def _batch_leaf_sharding(self, leaf, microbatched: bool) -> NamedSharding:
        """Rank-aware sharding for a batch leaf: batch dim over the batch
        axes, sequence dim (if any) over 'seq'."""
        mesh = self.topology.mesh
        ndim = np.ndim(leaf)
        lead = (None,) if microbatched else ()  # gas dim unsharded
        spec = lead + (BATCH_AXES,)
        if self.topology.sp_world_size > 1 and ndim >= len(spec) + 1:
            spec = spec + ("seq",)
        spec = spec[:ndim]
        return NamedSharding(mesh, P(*spec))

    def _place_batch(self, batch, microbatched: bool):
        shards = self.topology.batch_shard_size

        def place(x):
            batch_dim = 1 if microbatched else 0
            if np.ndim(x) > batch_dim and np.shape(x)[batch_dim] % shards != 0:
                raise ValueError(
                    f"batch dim {np.shape(x)[batch_dim]} not divisible by the "
                    f"{shards} batch shards (mesh data x expert x fsdp); pad "
                    f"the batch or adjust the mesh")
            return jax.device_put(x, self._batch_leaf_sharding(x, microbatched))
        return jax.tree.map(place, batch)

    def _build_eval_step(self):
        # models may provide a dedicated eval path (e.g. MoE
        # eval_capacity_factor / no gate noise)
        loss_fn = getattr(self.module, "eval_loss", None) or self._loss_fn
        compute_dtype = self.compute_dtype
        partitioner = self.partitioner
        mesh = self.topology.mesh
        param_specs = partitioner.tree_param_specs(self._abstract_params)

        def eval_fn(state: TrainState, batch, rng):
            params_c = jax.tree.map(
                lambda x, s: jax.lax.with_sharding_constraint(
                    x.astype(compute_dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
                    NamedSharding(mesh, s)),
                state.params, param_specs)
            return loss_fn(params_c, batch, rng)

        return jax.jit(eval_fn)

    # ------------------------------------------------------------------
    # public API (reference parity)
    # ------------------------------------------------------------------
    def train_batch_size(self) -> int:
        return self.config.train_batch_size

    def train_micro_batch_size_per_gpu(self) -> int:
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self.config.gradient_accumulation_steps

    def get_lr(self):
        return [float(self._schedule(self.global_steps))]

    def get_global_grad_norm(self) -> float:
        return getattr(self, "_last_grad_norm", 0.0)

    @property
    def loss_scale(self) -> float:
        return float(self.state.loss_scale)

    @property
    def skipped_steps(self) -> int:
        """Total steps skipped on fp16 overflow (reference engine attr)."""
        return int(self.state.skipped_steps)

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _shape_batch(self, batch) -> Any:
        """Reshape a global batch to [gas, global_micro, ...] device arrays."""
        gas = self.gradient_accumulation_steps()
        micro_global = self.train_micro_batch_size_per_gpu() * self.topology.batch_shard_size

        def shape_leaf(x):
            x = np.asarray(x) if not isinstance(x, jax.Array) else x
            if x.shape[0] == gas * micro_global:
                return x.reshape((gas, micro_global) + x.shape[1:])
            if x.ndim >= 2 and x.shape[0] == gas and x.shape[1] == micro_global:
                return x
            raise ValueError(
                f"batch leading dim {x.shape} incompatible with "
                f"gas={gas} x global_micro={micro_global}")
        return jax.tree.map(shape_leaf, batch)

    def train_batch(self, batch=None, data_iter: Optional[Iterable] = None) -> float:
        """Run one full training step: gas micro-batches + optimizer update
        (reference PipelineEngine.train_batch / engine fwd+bwd+step cycle).

        With ``fault_tolerance.self_healing`` on, watchdog verdicts
        become recovery actions: a non-finite applied step rolls back to
        the last good checkpoint/snapshot and skips the batch window;
        transient dispatch faults are retried — both bounded by
        ``max_retries`` consecutive recoveries with exponential
        backoff."""
        ft = self.config.fault_tolerance
        if not ft.self_healing:
            try:
                return self._train_batch_impl(batch, data_iter)
            except Exception as e:
                # crash forensics (ISSUE 5): leave a postmortem bundle
                # before the exception leaves the engine; never masks it
                get_flight_recorder().on_crash("train_batch", e)
                raise
        return self._train_batch_self_healing(batch, data_iter, ft)

    # -- self-healing wrapper (ISSUE 7) ---------------------------------
    def _train_batch_self_healing(self, batch, data_iter, ft) -> float:
        self._check_not_destroyed()
        if self._last_good_ckpt is None and self._state_snapshot is None:
            # a rollback target must exist BEFORE the first guarded step
            self._snapshot_state()
        # materialize the batch once: a transient-fault retry must replay
        # the SAME data, not consume fresh micro-batches from the iterator
        batch = self._resolve_batch(batch, data_iter)
        attempt = 0
        while True:
            try:
                loss = self._train_batch_impl(batch, None)
            except TransientFault as e:
                # dispatch-boundary failure: no state was mutated, so
                # the same batch is retried after backoff
                attempt += 1
                tm.TRAIN_RETRY.inc()
                get_flight_recorder().record(
                    "selfheal.retry", attempt=attempt,
                    error=f"{type(e).__name__}: {e}"[:200])
                if attempt > ft.max_retries:
                    get_flight_recorder().on_crash("train_batch", e)
                    raise
                logger.warning(
                    "self-healing: transient fault in train_batch (%s) "
                    "— retry %d/%d", e, attempt, ft.max_retries)
                time.sleep(ft.backoff_s * (2 ** (attempt - 1)))
                continue
            except Exception as e:
                get_flight_recorder().on_crash("train_batch", e)
                raise
            applied = getattr(self, "_last_step_applied", True)
            bad = applied and not (
                math.isfinite(loss)
                and math.isfinite(getattr(self, "_last_grad_norm", 0.0)))
            if not bad:
                self._rollback_streak = 0
                self._maybe_refresh_snapshot(ft)
                return loss
            # non-finite verdict on an APPLIED step: params may hold
            # NaN/inf — roll back and skip the offending batch window
            self._rollback_streak += 1
            tm.TRAIN_ROLLBACK.inc()
            bad_step = self.global_steps
            get_flight_recorder().record(
                "selfheal.rollback", streak=self._rollback_streak,
                at_step=bad_step, loss=repr(loss))
            time.sleep(ft.backoff_s * (2 ** (self._rollback_streak - 1)))
            # restore FIRST even when about to give up: the caller
            # catches the exception with the engine at last-good state,
            # not with NaN params
            source = self._restore_last_good()
            if self._rollback_streak > ft.max_retries:
                err = RuntimeError(
                    f"self-healing: {self._rollback_streak} consecutive "
                    f"non-finite steps exceed "
                    f"fault_tolerance.max_retries={ft.max_retries}")
                get_flight_recorder().on_crash("train_batch", err)
                raise err
            logger.warning(
                "self-healing: non-finite step at global step %d — "
                "rolled back to %s and skipped the batch window "
                "(rollback %d/%d)", bad_step, source,
                self._rollback_streak, ft.max_retries)
            return loss  # the non-finite loss is surfaced, not hidden

    def _snapshot_state(self) -> None:
        """Host copy of everything a rollback must restore (device state,
        RNG stream, host-side step counters, LR-scheduler state)."""
        self._state_snapshot = {
            "state": jax.device_get(self.state),
            "rng": np.asarray(jax.random.key_data(self._rng)),
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "micro_steps": self.micro_steps,
            "lr_scheduler": self.lr_scheduler.state_dict(),
        }

    def _maybe_refresh_snapshot(self, ft) -> None:
        if ft.snapshot_interval > 0 and \
                self.global_steps % ft.snapshot_interval == 0:
            self._snapshot_state()

    def _restore_last_good(self) -> str:
        """Roll device + host state back to the last good checkpoint
        (preferred: it survives the process too) or the in-memory
        snapshot.  Returns a description of the source used."""
        if self._last_good_ckpt is not None:
            save_dir, tag = self._last_good_ckpt
            try:
                self._load_checkpoint_impl(save_dir, tag, True, True,
                                           False)
                return f"checkpoint {tag}"
            except Exception as e:
                if self._state_snapshot is None:
                    raise
                logger.warning(
                    "self-healing: checkpoint rollback to %s failed "
                    "(%s) — falling back to the in-memory snapshot",
                    tag, e)
        snap = self._state_snapshot
        if snap is None:
            raise RuntimeError("self-healing: no rollback target")
        with self.topology.mesh:
            self.state = jax.device_put(snap["state"],
                                        self._state_shardings_cache)
        self._rng = jax.random.wrap_key_data(jnp.asarray(snap["rng"]))
        self.global_steps = snap["global_steps"]
        self.global_samples = snap["global_samples"]
        self.micro_steps = snap["micro_steps"]
        self.lr_scheduler.load_state_dict(snap["lr_scheduler"])
        return f"snapshot at step {snap['global_steps']}"

    def _resolve_batch(self, batch, data_iter):
        """Materialize one [gas, micro, ...] host batch from whichever
        source the caller provided (idempotent on an already-shaped
        batch)."""
        if batch is None:
            source = data_iter if data_iter is not None else self.training_dataloader
            if source is None:
                raise ValueError("no batch and no dataloader")
            it = source if hasattr(source, "__next__") else iter(source)
            micro = [next(it) for _ in range(self.gradient_accumulation_steps())]
            return jax.tree.map(lambda *xs: np.stack(xs), *micro)
        return self._shape_batch(batch)

    def _train_batch_impl(self, batch, data_iter) -> float:
        self._check_not_destroyed()
        batch = self._resolve_batch(batch, data_iter)

        # fault-injection sites (ISSUE 7), all BEFORE any timer/state
        # mutation so an injected failure aborts cleanly:
        # a collective failure raises retry-safe (nothing dispatched);
        # a NaN batch flows through the REAL fused step so recovery must
        # genuinely repair state
        fi = get_fault_injector()
        if fi.armed:
            fi.maybe_raise("comm.collective_failure",
                           InjectedCollectiveFault,
                           "injected collective failure at dispatch")
            if fi.has_site("train.nan_grad"):
                # only probe the site when the batch actually has a
                # float leaf to poison — an int-only (token-id) batch
                # must not count a fault as injected while injecting
                # nothing
                poisonable = any(
                    np.issubdtype(np.asarray(x).dtype, np.floating)
                    for x in jax.tree.leaves(batch))
                if not poisonable:
                    if not getattr(self, "_nan_site_warned", False):
                        self._nan_site_warned = True
                        logger.warning(
                            "fault injection: train.nan_grad is armed "
                            "but the batch has no floating-point leaf "
                            "to poison — site skipped (not counted)")
                elif fi.fire("train.nan_grad"):
                    batch = jax.tree.map(
                        lambda x: np.full_like(x, np.nan)
                        if np.issubdtype(np.asarray(x).dtype,
                                         np.floating)
                        else x, batch)

        if not getattr(self, "_train_mode", True) and \
                not getattr(self, "_eval_mode_warned", False):
            self._eval_mode_warned = True
            logger.warning(
                "train_batch called on an engine in eval() mode; the "
                "batch runs in the TRAIN regime (use eval_batch for "
                "eval-regime scoring)")
        self.timers(TRAIN_BATCH_TIMER).start()
        self.tput_timer.start()
        watchdog = get_watchdog()
        t_batch0 = None
        if telemetry_state.enabled:
            get_tracer().set_step(self.global_steps)
            t_batch0 = time.perf_counter()
        with trace_span("train.batch"), self.topology.mesh:
            with trace_span("train.place_batch"), \
                    watchdog.track("input_wait"):
                batch = self._place_batch(batch, microbatched=True)
            self._maybe_profile_flops(batch)
            # the fused step is ONE compiled program (fwd + bwd +
            # collective flush + optimizer); the float() sync below is
            # where the host blocks on it, so train.step covers dispatch
            # + device execution.  Per-phase device attribution comes
            # from the jax profiler (the span's TraceAnnotation lines
            # host spans up with the device timeline).  Goodput: the
            # first global step's wall time is compile+warmup (the jit
            # trace happens under it), later steps bill the step phase.
            with trace_span("train.step"), watchdog.track(
                    "compile" if self.global_steps == 0 else "step"):
                self.state, metrics, off_grads = self._train_step(
                    self.state, batch, self._next_rng())
                loss = float(metrics["loss"])
            # overflow skip exists only under fp16 loss scaling — the
            # device path updates unconditionally in bf16 mode, and the
            # host must mirror it exactly or the two halves desync
            if self.offload is not None and not (
                    self._fp16_enabled and int(metrics["overflow"])):
                with trace_span("train.offload_step"), \
                        watchdog.track("step"):
                    self._apply_offload_step(off_grads,
                                             float(metrics["applied_lr"]))
        from ..tools.tensor_logger import record_active
        # iteration stays the caller's (log_iteration/set_iteration)
        record_active("model_inputs", "batch", batch)
        record_active("fwd_act", "loss", np.asarray(loss))
        self._last_grad_norm = float(metrics["grad_norm"])
        self._last_step_applied = not (self._fp16_enabled
                                       and bool(metrics["overflow"]))
        if fi.armed and fi.fire("train.slow_step"):
            # inside the measured window, so the EWMA anomaly detector
            # sees the stall exactly like a real straggler step
            time.sleep(fi.site_value("train.slow_step", 100.0) / 1e3)
        if telemetry_state.enabled:
            # non-finite sentinel (ISSUE 5): loss and grad_norm are the
            # HOST-fetched floats above — no new device syncs.  A
            # HANDLED fp16 overflow skip is routine (overflow IS
            # ~isfinite(gnorm); the loss-scale machinery exists for it),
            # so it feeds only the skip counter — the non-finite verdict
            # is reserved for steps the engine actually applied.
            if not self._last_step_applied:
                watchdog.note_overflow_skip(self.global_steps)
            else:
                if not math.isfinite(loss):
                    watchdog.note_nonfinite("loss", self.global_steps,
                                            loss)
                if not math.isfinite(self._last_grad_norm):
                    watchdog.note_nonfinite("grad_norm",
                                            self.global_steps,
                                            self._last_grad_norm)
        self.global_steps += 1
        self._maybe_apply_compression()
        self.micro_steps += self.gradient_accumulation_steps()
        self.global_samples += self.train_batch_size()
        self.lr_scheduler.step()
        self.tput_timer.stop(report_speed=self.global_steps % self.config.steps_per_print == 0)
        self.timers(TRAIN_BATCH_TIMER).stop()
        if t_batch0 is not None:
            # EWMA step-time anomaly detector (ISSUE 5): warns once per
            # storm and dumps the span ring around the offending step
            watchdog.observe_step_time(
                "train", (time.perf_counter() - t_batch0) * 1e3,
                step=self.global_steps - 1)
        if self.monitor is not None:
            self._monitor_write(self.monitor.write_events, [
                ("Train/Samples/train_loss", loss, self.global_samples),
                ("Train/Samples/lr", float(metrics["lr"]), self.global_samples)])
            if self.global_steps % self.config.steps_per_print == 0:
                # full telemetry-registry snapshot rides the monitor fan-
                # out at the print cadence (one source of truth: the same
                # names the /metrics endpoint and bench.py read)
                self._monitor_write(self.monitor.write_registry_snapshot,
                                    self.global_samples)
        if self.config.wall_clock_breakdown and \
                self.global_steps % self.config.steps_per_print == 0:
            self.timers.log([TRAIN_BATCH_TIMER])
        return loss

    def _maybe_profile_flops(self, placed_batch) -> None:
        """Print the flops-profiler report at the configured step
        (reference engine.py:1858/:2193 profile_step integration)."""
        fp_cfg = self.config.flops_profiler
        if not fp_cfg.enabled or self.global_steps != fp_cfg.profile_step:
            return
        from ..profiling import FlopsProfiler
        prof = FlopsProfiler(params=self.state.params)
        # fixed key: lowering must not consume the training RNG stream, or
        # enabling the profiler changes every later step's randomness
        lowered = self._train_step.lower(self.state, placed_batch,
                                         jax.random.key(0))
        cost = lowered.compile().cost_analysis() or {}
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        prof._cost = {"flops": float(cost.get("flops", 0.0)),
                      "bytes_accessed": float(cost.get("bytes accessed", 0.0))}
        prof._duration = self.tput_timer.avg_step_time()
        prof.print_model_profile(
            profile_step=self.global_steps,
            module_depth=fp_cfg.module_depth,
            top_modules=fp_cfg.top_modules,
            detailed=fp_cfg.detailed,
            output_file=fp_cfg.output_file)

    def _apply_offload_step(self, off_grads, lr: float) -> None:
        """Host optimizer step over offloaded leaves + push updated weights
        back to the device (ZeRO-Offload hot path)."""
        host_grads = jax.device_get(list(off_grads))
        updated = self.offload.step(
            [np.asarray(g, np.float32) for g in host_grads], lr=lr)
        flat, treedef = jax.tree.flatten(self.state.params)
        if not hasattr(self, "_offload_leaf_shardings"):
            flat_sh = jax.tree.leaves(
                self.partitioner.master_shardings(self._abstract_params))
            self._offload_leaf_shardings = [
                flat_sh[i] if isinstance(flat_sh[i], NamedSharding)
                else NamedSharding(self.topology.mesh, flat_sh[i])
                for i in self.offload.offload_idx]
        arrays = [
            updated[k].reshape(flat[i].shape).astype(flat[i].dtype)
            for k, i in enumerate(self.offload.offload_idx)]
        placed = jax.device_put(arrays, self._offload_leaf_shardings)
        for k, i in enumerate(self.offload.offload_idx):
            flat[i] = placed[k]
        self.state = self.state.replace(
            params=jax.tree.unflatten(treedef, flat))

    # --- imperative-compat API ----------------------------------------
    def forward(self, batch) -> float:
        """Buffer a micro-batch; returns its loss under current params
        (extra fwd — for exact-parity UX only; prefer train_batch)."""
        self._check_not_destroyed()
        self._grad_acc_buffer.append(batch)
        with trace_span("train.forward"), self.topology.mesh:
            placed = self._place_batch(batch, microbatched=False)
            loss = self._eval_step(self.state, placed, self._next_rng())
            self._last_loss = float(loss)
        return self._last_loss

    def __call__(self, batch):
        return self.forward(batch)

    def backward(self, loss=None, **kwargs):
        """No-op marker (autodiff happens fused in step()); kept for parity."""
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        """True when step() will consume the buffer and update.  An
        explicit set_gradient_accumulation_boundary overrides the
        buffer-count rule (reference engine.py semantics)."""
        if getattr(self, "_ga_boundary", None) is not None:
            return self._ga_boundary
        return len(self._grad_acc_buffer) >= self.gradient_accumulation_steps()

    def step(self):
        """Consume buffered micro-batches at the GAS boundary and update.

        A forced boundary (set_gradient_accumulation_boundary(True)) can
        fire with a partial buffer; the update then accumulates over
        exactly the buffered micro-batches (reference semantics: apply
        whatever has accumulated), via a one-off step traced for that
        count."""
        if not self.is_gradient_accumulation_boundary():
            return
        if not self._grad_acc_buffer:
            return
        n = len(self._grad_acc_buffer)
        batch = jax.tree.map(lambda *xs: np.stack(xs), *self._grad_acc_buffer)
        self._grad_acc_buffer = []
        flat = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), batch)
        gas = self.gradient_accumulation_steps()
        if n == gas:
            self.train_batch(batch=flat)
            return
        saved_step, saved_tbs = self._train_step, self.config.train_batch_size
        cache = getattr(self, "_partial_step_cache", None)
        if cache is None:
            cache = self._partial_step_cache = {}
        try:
            object.__setattr__(self.config, "gradient_accumulation_steps", n)
            object.__setattr__(
                self.config, "train_batch_size",
                self.config.train_micro_batch_size_per_gpu
                * self.topology.batch_shard_size * n)
            if n not in cache:  # one trace+compile per distinct count
                cache[n] = self._build_train_step()
            self._train_step = cache[n]
            self.train_batch(batch=flat)
        finally:
            object.__setattr__(self.config, "gradient_accumulation_steps", gas)
            object.__setattr__(self.config, "train_batch_size", saved_tbs)
            self._train_step = saved_step

    def eval_batch(self, batch) -> float:
        self._check_not_destroyed()
        with trace_span("train.eval_batch"), self.topology.mesh:
            placed = self._place_batch(batch, microbatched=False)
            return float(self._eval_step(self.state, placed, self._next_rng()))

    def _invalidate_step_caches(self):
        """Anything that changes what a trace would bake in (lr
        schedule, batch geometry) must drop cached partial-count steps
        too."""
        if getattr(self, "_partial_step_cache", None):
            self._partial_step_cache.clear()

    def set_lr(self, lr: float):
        self._schedule = lambda step: lr
        self._train_step = self._build_train_step()
        self._invalidate_step_caches()

    # --- dataloader ----------------------------------------------------
    def deepspeed_io(self, dataset, batch_size=None, collate_fn=None, **kw):
        from .dataloader import DeepSpeedDataLoader
        return DeepSpeedDataLoader(
            dataset,
            batch_size=batch_size or (self.train_micro_batch_size_per_gpu()
                                      * self.topology.batch_shard_size),
            collate_fn=collate_fn)

    # --- checkpointing --------------------------------------------------
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[dict] = None, save_latest: bool = True):
        with get_watchdog().track("checkpoint"):
            return self._save_checkpoint_impl(save_dir, tag, client_state,
                                              save_latest)

    def _save_checkpoint_impl(self, save_dir, tag, client_state,
                              save_latest):
        self._check_not_destroyed()
        tag = tag or f"global_step{self.global_steps}"
        get_flight_recorder().record("checkpoint.save", dir=save_dir,
                                     tag=tag,
                                     global_step=self.global_steps)
        client_state = dict(client_state or {})
        client_state.update({
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "micro_steps": self.micro_steps,
            "lr_scheduler": self.lr_scheduler.state_dict(),
            # the engine RNG stream: a resume (or self-healing
            # rollback) replays the same randomness whichever rollback
            # source is used — checkpoint and snapshot must not diverge
            "rng_key_data": np.asarray(
                jax.random.key_data(self._rng)).tolist(),
            # topology fingerprint for universal-checkpoint reshaping:
            # pipeline params are stage-stacked [S, L/S, ...] on disk and
            # ds_to_universal must unstack them into topology-free atoms
            "pipe_stages": getattr(self, "num_stages", 1),
        })
        self.checkpoint_engine.save(save_dir, tag, self.state, client_state)
        if self.offload is not None:
            os.makedirs(os.path.join(save_dir, tag), exist_ok=True)
            self.offload.save_npz(os.path.join(
                save_dir, tag, f"offload_rank{jax.process_index()}.npz"))
        if save_latest:
            # write_latest LAST (atomic tmp+rename), and only after any
            # async serialization has fully drained — otherwise a crash
            # between dispatch and finalization leaves `latest` naming
            # an incomplete checkpoint.  The pointer update trades the
            # tail of the async overlap for durability; callers that
            # want the full overlap pass save_latest=False and commit
            # the pointer at their own barrier.
            self.checkpoint_engine.wait()
            self.checkpoint_engine.write_latest(save_dir, tag)
        # a completed save is the freshest rollback target for the
        # self-healing path (the async drain is awaited at load time)
        self._last_good_ckpt = (save_dir, tag)
        return True

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True,
                        load_module_only: bool = False):
        with get_watchdog().track("checkpoint"):
            return self._load_checkpoint_impl(
                load_dir, tag, load_optimizer_states,
                load_lr_scheduler_states, load_module_only)

    def _load_checkpoint_impl(self, load_dir, tag, load_optimizer_states,
                              load_lr_scheduler_states, load_module_only):
        self._check_not_destroyed()
        get_flight_recorder().record("checkpoint.load", dir=load_dir,
                                     tag=tag or "")
        if self.config.checkpoint.load_universal:
            # reference --universal-checkpoint load path: restore the
            # topology-free atoms regardless of the saving mesh.  Accepts
            # a universal dir directly, or the checkpoint dir whose
            # <tag>_universal sibling ds_to_universal wrote.
            from ..checkpoint.universal import (ATOMS_FILE,
                                                load_universal_into_engine)
            cand = None
            if os.path.exists(os.path.join(load_dir, ATOMS_FILE)):
                cand = load_dir
            else:
                t = tag or self.checkpoint_engine.read_latest(load_dir)
                if t is not None:
                    c = os.path.join(load_dir, f"{t}_universal")
                    if os.path.exists(os.path.join(c, ATOMS_FILE)):
                        cand = c
            if cand is None:
                raise FileNotFoundError(
                    f"checkpoint.load_universal: no universal atoms under "
                    f"{load_dir!r} — run ds_to_universal first")
            load_universal_into_engine(
                self, cand,
                load_optimizer_states=(load_optimizer_states
                                       and not load_module_only),
                load_lr_scheduler_states=load_lr_scheduler_states)
            return load_dir, {}
        tag = tag or self.checkpoint_engine.read_latest(load_dir)
        if tag is None:
            return None, {}
        try:
            state, client_state = self.checkpoint_engine.load(
                load_dir, tag, self.state, self._state_shardings_cache,
                module_only=load_module_only or not load_optimizer_states)
        except Exception as load_err:
            if self.comm_scheduler is None or not jax.tree.leaves(
                    self.state.comm_residuals):
                raise
            # If the checkpoint actually CONTAINS residuals, the failure
            # is something else — retrying without them would silently
            # discard saved state and mask the real cause.
            state_dir = os.path.join(load_dir, tag, "state")
            try:
                has_saved_residuals = any(
                    "comm_residuals" in name
                    for name in os.listdir(state_dir))
            except OSError:
                has_saved_residuals = False
            if has_saved_residuals:
                raise
            # checkpoint predates the CollectiveScheduler (no
            # comm_residuals leaf at all): restore everything else; the
            # residuals are re-zeroed below — feedback history is an
            # accuracy refinement, not load-bearing state
            logger.warning(
                "checkpoint load with comm_residuals template failed "
                "(%s); retrying without the residual leaf", load_err)
            template = self.state.replace(comm_residuals=())
            shardings = self._state_shardings_cache.replace(
                comm_residuals=())
            state, client_state = self.checkpoint_engine.load(
                load_dir, tag, template, shardings,
                module_only=load_module_only or not load_optimizer_states)
            state = state.replace(comm_residuals=())
        if self.comm_scheduler is not None and \
                jax.tree.leaves(self.state.comm_residuals) and \
                not jax.tree.leaves(state.comm_residuals):
            # checkpoint carried no error-feedback residuals (saved
            # pre-scheduler or with the wire disabled): start from zero
            logger.warning(
                "checkpoint %s has no comm_residuals — zero-initializing "
                "error feedback", tag)
            with self.topology.mesh:
                state = state.replace(comm_residuals=jax.device_put(
                    self.comm_scheduler.init_residuals(),
                    self.comm_scheduler.residual_sharding()))
        self.state = state
        if self.offload is not None:
            off_path = os.path.join(
                load_dir, tag, f"offload_rank{jax.process_index()}.npz")
            if load_optimizer_states and not load_module_only \
                    and os.path.exists(off_path):
                self.offload.load_npz(off_path)
            else:
                # no host-state file for this checkpoint (module-only load,
                # or saved without offload): masters MUST re-sync from the
                # restored device params, else the next step would push
                # init-era masters back over the loaded weights
                self.offload.init_masters(self.state.params)
        self.global_steps = client_state.get("global_steps", 0)
        self.global_samples = client_state.get("global_samples", 0)
        self.micro_steps = client_state.get("micro_steps", 0)
        if "rng_key_data" in client_state:
            self._rng = jax.random.wrap_key_data(jnp.asarray(np.array(
                client_state["rng_key_data"], dtype=np.uint32)))
        if load_lr_scheduler_states and "lr_scheduler" in client_state:
            self.lr_scheduler.load_state_dict(client_state["lr_scheduler"])
        return tag, client_state

    def get_fp32_state_dict(self):
        """Consolidated fp32 params on host (reference
        ``_zero3_consolidated_16bit_state_dict`` / zero_to_fp32)."""
        rep = NamedSharding(self.topology.mesh, P())
        gathered = jax.jit(lambda p: p, out_shardings=rep)(self.state.params)
        return jax.tree.map(np.asarray, gathered)

    def save_16bit_model(self, save_dir: str,
                         filename: str = "model_weights.npz"):
        """Export consolidated bf16 weights for inference handoff
        (reference ``save_16bit_model`` engine.py:3620)."""
        self._check_not_destroyed()
        from ..checkpoint.zero_to_fp32 import flatten_state_dict
        params = self.get_fp32_state_dict()
        flat = {k: v.astype(jnp.bfloat16)
                for k, v in flatten_state_dict(params).items()}
        os.makedirs(save_dir, exist_ok=True)
        path = os.path.join(save_dir, filename)
        if jax.process_index() == 0:
            # bf16 has no numpy dtype string npz understands natively;
            # store as uint16 view + sidecar dtype manifest
            np.savez(path, **{k: np.asarray(v).view(np.uint16)
                              for k, v in flat.items()})
            with open(path + ".dtypes.json", "w") as f:
                json.dump({k: "bfloat16" for k in flat}, f)
        logger.info("saved 16-bit model -> %s", path)
        return path

    # ------------------------------------------------------------------
    # Reference API compatibility surface (engine.py exposes ~100 config
    # accessors + small state queries that user scripts and the
    # autotuner read; each one maps onto our pydantic config or engine
    # state.  Torch-mechanics methods with no TPU meaning — graph
    # harvesting, amp — are deliberately absent: grads reduce inside the
    # jitted step, and explicit bucketing/quantization/overlap of that
    # reduction is the CollectiveScheduler's job (comm_optimization
    # config block), not an imperative method family.)
    # ------------------------------------------------------------------

    def train(self, mode: bool = True):
        """Reference nn.Module.train passthrough.  Regime here is bound
        to the PATH, not a module flag: train_batch always runs the
        train regime, forward/eval_batch always the eval regime (MoE
        eval capacity, no dropout) — so this only records intent and
        train_batch warns when called under eval()."""
        self._train_mode = bool(mode)
        return self

    def eval(self):
        return self.train(False)

    def zero_grad(self):
        """No-op: gradients are values inside the jitted step, not
        buffers (nothing accumulates outside train_batch)."""

    def destroy(self):
        """Drop compiled steps + device state (reference destroy)."""
        get_flight_recorder().record("engine.destroy", engine="train",
                                     global_steps=self.global_steps)
        self._train_step = None
        self._eval_step = None
        self._invalidate_step_caches()
        self.state = None
        self._destroyed = True

    def _check_not_destroyed(self):
        if getattr(self, "_destroyed", False):
            raise RuntimeError(
                "engine destroyed: this DeepSpeedEngine was torn down by "
                "destroy(); build a new engine with deepspeed_tpu.initialize")

    def compile(self, *a, **k):
        """Everything is already jitted by construction (SURVEY: compile
        support n/a); kept for torch.compile-style call sites."""
        return self

    def is_compiled(self) -> bool:
        return True

    def was_step_applied(self) -> bool:
        """False when the last train_batch was skipped by the fp16
        overflow guard (reference was_step_applied)."""
        return getattr(self, "_last_step_applied", True)

    def get_batch_info(self):
        return (self.train_batch_size(),
                self.train_micro_batch_size_per_gpu(),
                self.gradient_accumulation_steps())

    def set_train_batch_size(self, train_batch_size: int):
        """Elastic rescale (reference set_train_batch_size): must stay
        consistent with micro * gas * shards."""
        micro = self.config.train_micro_batch_size_per_gpu
        shards = self.topology.batch_shard_size
        if train_batch_size % (micro * shards) != 0:
            raise ValueError(
                f"train_batch_size {train_batch_size} != micro {micro} * "
                f"gas * batch shards {shards}")
        object.__setattr__(self.config, "train_batch_size", train_batch_size)
        object.__setattr__(self.config, "gradient_accumulation_steps",
                           train_batch_size // (micro * shards))
        self._train_step = self._build_train_step()  # gas is traced in
        self._invalidate_step_caches()
        self.tput_timer.batch_size = train_batch_size

    def set_train_micro_batch_size(self, micro_batch_size: int):
        object.__setattr__(self.config, "train_micro_batch_size_per_gpu",
                           micro_batch_size)
        object.__setattr__(
            self.config, "train_batch_size",
            micro_batch_size * self.config.gradient_accumulation_steps
            * self.topology.batch_shard_size)
        self._train_step = self._build_train_step()  # new shapes
        self._invalidate_step_caches()
        self.tput_timer.batch_size = self.config.train_batch_size

    def set_gradient_accumulation_boundary(self, is_boundary: bool):
        """Force (True) / defer (False) the optimizer update on the
        legacy forward/backward/step path: overrides
        is_gradient_accumulation_boundary until cleared with None.
        train_batch is unaffected (its micro-batches run inside one
        fused program)."""
        self._ga_boundary = None if is_boundary is None else bool(is_boundary)

    def dump_state(self):
        self._check_not_destroyed()
        logger.info(
            "engine state: step=%s lr=%.3e loss_scale=%s skipped=%s "
            "zero_stage=%s mesh=%s", int(self.state.step), self.get_lr()[0],
            self.loss_scale, self.skipped_steps, self.zero_stage,
            dict(self.topology.mesh.shape))

    def memory_breakdown(self):
        """Per-device memory stats (reference memory_breakdown prints
        torch.cuda stats; TPU exposes them via device.memory_stats)."""
        out = []
        for d in jax.local_devices():
            try:
                out.append({"device": str(d), **(d.memory_stats() or {})})
            except Exception:
                out.append({"device": str(d)})
        return out

    # -- config accessors (reference names) -----------------------------
    def zero_optimization(self) -> bool:
        return self.zero_stage > 0

    def zero_optimization_stage(self) -> int:
        return self.zero_stage

    def zero_optimization_partition_gradients(self) -> bool:
        return self.zero_stage >= 2

    def zero_optimization_partition_weights(self) -> bool:
        return self.zero_stage >= 3

    def zero_allgather_bucket_size(self) -> int:
        return self.config.zero_optimization.allgather_bucket_size

    def zero_allgather_partitions(self) -> bool:
        return self.config.zero_optimization.allgather_partitions

    def zero_reduce_bucket_size(self) -> int:
        return self.config.zero_optimization.reduce_bucket_size

    def zero_reduce_scatter(self) -> bool:
        return self.config.zero_optimization.reduce_scatter

    def zero_contiguous_gradients(self) -> bool:
        return self.config.zero_optimization.contiguous_gradients

    def zero_overlap_comm(self) -> bool:
        return self.config.zero_optimization.overlap_comm

    def zero_sub_group_size(self) -> int:
        return self.config.zero_optimization.sub_group_size

    def zero_max_live_parameters(self) -> int:
        return self.config.zero_optimization.stage3_max_live_parameters

    def zero_max_reuse_distance(self) -> int:
        return self.config.zero_optimization.stage3_max_reuse_distance

    def zero_prefetch_bucket_size(self) -> int:
        return self.config.zero_optimization.stage3_prefetch_bucket_size

    def zero_param_persistence_threshold(self) -> int:
        return self.config.zero_optimization.stage3_param_persistence_threshold

    def zero_model_persistence_threshold(self) -> int:
        return self.config.zero_optimization.stage3_model_persistence_threshold

    def zero_gather_16bit_weights_on_model_save(self) -> bool:
        return (self.config.zero_optimization
                .stage3_gather_16bit_weights_on_model_save)

    def zero_hpz_partition_size(self) -> int:
        return self.config.zero_optimization.zero_hpz_partition_size

    def zero_quantized_weights(self) -> bool:
        return self.config.zero_optimization.zero_quantized_weights

    def zero_quantized_gradients(self) -> bool:
        return self.config.zero_optimization.zero_quantized_gradients

    def mics_shard_size(self) -> int:
        return self.config.zero_optimization.mics_shard_size

    def zero_cpu_offload(self) -> bool:
        return self.config.zero_optimization.offload_optimizer.device \
            in ("cpu", "nvme")

    def zero_offload_param(self):
        return self.config.zero_optimization.offload_param

    def zero_offload_optimizer(self):
        return self.config.zero_optimization.offload_optimizer

    def zero_has_nvme_offload(self) -> bool:
        return ("nvme" in (self.config.zero_optimization
                           .offload_optimizer.device,
                           self.config.zero_optimization.offload_param.device))

    def zero_round_robin_gradients(self) -> bool:
        return self.config.zero_optimization.round_robin_gradients

    def fp16_enabled(self) -> bool:
        return self.config.fp16.enabled

    def bfloat16_enabled(self) -> bool:
        return self.config.bf16.enabled

    def fp16_auto_cast(self) -> bool:
        return self.config.fp16.auto_cast

    def fp16_master_weights_and_gradients(self) -> bool:
        """Reference meaning: masters/grads kept in fp16 to halve
        optimizer memory.  Always False here — under fp16 configs the
        TPU engine keeps fp32 masters (bf16 is the compute dtype; there
        is no fp16 master mode to save memory with)."""
        return False

    def dynamic_loss_scale(self) -> bool:
        return self.config.fp16.loss_scale == 0

    def initial_dynamic_scale(self) -> float:
        return 2.0 ** self.config.fp16.initial_scale_power

    def dynamic_loss_scale_args(self):
        c = self.config.fp16
        return {"init_scale": 2.0 ** c.initial_scale_power,
                "scale_window": c.loss_scale_window,
                "delayed_shift": c.hysteresis,
                "min_scale": c.min_loss_scale}

    def gradient_clipping(self) -> float:
        return self.config.gradient_clipping

    def gradient_predivide_factor(self) -> float:
        return self.config.gradient_predivide_factor

    def postscale_gradients(self) -> bool:
        return not self.config.prescale_gradients

    def communication_data_type(self) -> str:
        return self.config.communication_data_type or "bfloat16"

    def sparse_gradients_enabled(self) -> bool:
        return self.config.sparse_gradients

    def steps_per_print(self) -> int:
        return self.config.steps_per_print

    def wall_clock_breakdown(self) -> bool:
        return self.config.wall_clock_breakdown

    def optimizer_name(self) -> str:
        return self.config.optimizer.type

    def optimizer_params(self):
        return self.config.optimizer.params

    def scheduler_name(self):
        return self.config.scheduler.type if self.config.scheduler else None

    def scheduler_params(self):
        return self.config.scheduler.params if self.config.scheduler else None

    def elasticity_enabled(self) -> bool:
        return self.config.elasticity.enabled

    def autotuning_enabled(self) -> bool:
        return self.config.autotuning.enabled

    def flops_profiler_enabled(self) -> bool:
        return self.config.flops_profiler.enabled

    def flops_profiler_profile_step(self) -> int:
        return self.config.flops_profiler.profile_step

    def aio_config(self):
        """Top-level ``aio`` section (reference config layout; parses
        into the pydantic extra fields)."""
        return getattr(self.config, "aio", None)

    def data_efficiency_enabled(self) -> bool:
        return self.config.data_efficiency.enabled

    def data_efficiency_config(self):
        return self.config.data_efficiency

    def data_sampling_enabled(self) -> bool:
        return bool(self.config.data_efficiency.data_sampling.get(
            "enabled", False))

    def data_sampling_config(self):
        return self.config.data_efficiency.data_sampling

    def curriculum_learning_enabled(self) -> bool:
        return bool(self.config.data_efficiency.data_sampling.get(
            "curriculum_learning", {}).get("enabled", False))

    def curriculum_learning_config(self):
        return self.config.data_efficiency.data_sampling.get(
            "curriculum_learning", {})

    def random_ltd_enabled(self) -> bool:
        return bool(self.config.data_efficiency.data_routing.get(
            "random_ltd", {}).get("enabled", False))

    def random_ltd_config(self):
        return self.config.data_efficiency.data_routing.get("random_ltd", {})

    def module_state_dict(self):
        """Reference module_state_dict -> consolidated host params."""
        return self.get_fp32_state_dict()

    def save_fp16_model(self, save_dir: str,
                        filename: str = "model_weights.npz"):
        """Deprecated reference alias of save_16bit_model."""
        return self.save_16bit_model(save_dir, filename)
