"""Runtime communication subsystems: compressed-communication optimizers
(1-bit Adam/LAMB numerics) and the CollectiveScheduler (bucketed,
quantized, overlap-scheduled gradient collectives)."""

from .collective_scheduler import Bucket, CollectiveScheduler

__all__ = ["Bucket", "CollectiveScheduler"]
