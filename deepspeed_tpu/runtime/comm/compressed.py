"""1-bit / compressed-communication optimizers.

Reference: ``deepspeed/runtime/fp16/onebit/{adam,lamb,zoadam}.py`` +
``runtime/comm/compressed.py:13`` (CompressedBackend error-feedback
compressed allreduce) + ``runtime/comm/nccl.py:16``.

Algorithm (1-bit Adam, Tang et al.): run vanilla Adam for ``freeze_step``
warmup steps; then freeze the variance term and communicate only the
sign of the momentum update with per-worker error feedback.

TPU-native shape: gradients are reduced by XLA collectives inside the
jitted step, so the *math* of compression + error feedback is expressed as
an optax transform over the (already sharded) gradient tree.  The REAL
wire compression lives in the engine's qgZ path: with
``zero_optimization.zero_quantized_gradients`` on a batch-axes-only mesh,
the whole backward runs in a shard_map region and the gradient reduction
is ``ops.quantization.quantized_grad_reduce_shard`` — int8 hierarchical
reduce-scatter over 'fsdp' + int8 allreduce over 'data'
(engine._build_train_step; HLO-verified in tests/test_zeropp.py
TestQgzWire).  This optimizer's sign-compression remains a numerics
transform (the momentum tree it compresses is already ZeRO-sharded, so
each rank touches only its shard).  State (momentum, frozen variance,
error buffer) shards with the ZeRO partitioner like any optimizer state.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax


class OneBitAdamState(NamedTuple):
    count: jax.Array
    mu: optax.Updates
    nu: optax.Updates
    error: optax.Updates  # error-feedback residual (compression phase)


def _sign_compress(tree):
    """1-bit compression: sign(x) * mean(|x|) per tensor (the reference's
    compressed allreduce payload), plus the residual for error feedback."""
    def comp(x):
        scale = jnp.mean(jnp.abs(x))
        q = jnp.sign(x) * scale
        return q, x - q
    pairs = jax.tree.map(comp, tree)
    comp_t = jax.tree.map(lambda p: p[0], pairs, is_leaf=lambda p: isinstance(p, tuple))
    err_t = jax.tree.map(lambda p: p[1], pairs, is_leaf=lambda p: isinstance(p, tuple))
    return comp_t, err_t


def onebit_adam(learning_rate,
                b1: float = 0.9,
                b2: float = 0.999,
                eps: float = 1e-8,
                weight_decay: float = 0.0,
                freeze_step: int = 100) -> optax.GradientTransformation:
    """1-bit Adam (reference fp16/onebit/adam.py:310-LoC `OnebitAdam`)."""

    def init_fn(params):
        zeros = jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params)
        return OneBitAdamState(count=jnp.zeros((), jnp.int32),
                               mu=zeros, nu=zeros,
                               error=jax.tree.map(jnp.zeros_like, zeros))

    def update_fn(grads, state: OneBitAdamState, params=None):
        count = state.count + 1
        mu = optax.tree_utils.tree_update_moment(grads, state.mu, b1, 1)
        in_warmup = count <= freeze_step

        # warmup: update variance normally; compression phase: freeze nu
        nu_new = optax.tree_utils.tree_update_moment_per_elem_norm(grads, state.nu, b2, 2)
        nu = jax.tree.map(lambda new, old: jnp.where(in_warmup, new, old),
                          nu_new, state.nu)

        # compression phase: 1-bit compress momentum w/ error feedback
        mu_comp, err = _sign_compress(jax.tree.map(jnp.add, mu, state.error))
        mu_eff = jax.tree.map(lambda m, c: jnp.where(in_warmup, m, c), mu, mu_comp)
        error = jax.tree.map(lambda e_old, e_new: jnp.where(in_warmup, e_old, e_new),
                             state.error, err)

        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** jnp.minimum(count, freeze_step).astype(jnp.float32)
        lr = learning_rate(count) if callable(learning_rate) else learning_rate

        def upd(m, v, p):
            step = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay > 0 and p is not None:
                step = step + weight_decay * p
            return -lr * step

        updates = jax.tree.map(upd, mu_eff, nu,
                               params if params is not None
                               else jax.tree.map(lambda x: None, mu_eff))
        return updates, OneBitAdamState(count=count, mu=mu_eff, nu=nu, error=error)

    return optax.GradientTransformation(init_fn, update_fn)


def onebit_optimizer(name: str, lr, betas: Tuple[float, float] = (0.9, 0.999),
                     eps: float = 1e-8, weight_decay: float = 0.0,
                     freeze_step: int = 100) -> optax.GradientTransformation:
    name = name.lower().replace("_", "")
    if name in ("onebitadam", "zerooneadam"):
        return onebit_adam(lr, b1=betas[0], b2=betas[1], eps=eps,
                           weight_decay=weight_decay, freeze_step=freeze_step)
    if name == "onebitlamb":
        # LAMB trust ratio on top of the compressed update
        inner = onebit_adam(1.0, b1=betas[0], b2=betas[1], eps=eps,
                            weight_decay=weight_decay, freeze_step=freeze_step)
        def init_fn(params):
            return inner.init(params)
        def update_fn(grads, state, params=None):
            updates, state = inner.update(grads, state, params)
            def trust(u, p):
                un = jnp.linalg.norm(u)
                pn = jnp.linalg.norm(p)
                ratio = jnp.where((un > 0) & (pn > 0), pn / un, 1.0)
                lr_v = lr(state.count) if callable(lr) else lr
                return u * ratio * lr_v
            return jax.tree.map(trust, updates, params), state
        return optax.GradientTransformation(init_fn, update_fn)
    raise ValueError(f"unknown 1-bit optimizer {name}")
