"""CollectiveScheduler — bucketed, quantized, overlap-scheduled gradient
collectives.

Generalizes the engine's special-case ZeRO++ qgZ wire into the subsystem
the reference builds imperatively out of ``allreduce_bucket`` flushing
(engine.py:2185), 1-bit/qgZ compressed reduction
(``runtime/comm/coalesced_collectives.py:31``) and ``overlap_comm``
stream juggling:

* **Bucketing** — the gradient pytree is flattened into one logical
  fp32 vector and cut into buckets of ``allreduce_bucket_size`` bytes
  (boundaries aligned to ``world * block`` elements so every bucket is
  whole quantization blocks per rank).  Small tensors coalesce into one
  collective; tensors larger than the bucket chunk across several.
* **Quantization** — each bucket rides an int8 block-scaled two-hop
  wire (:func:`~deepspeed_tpu.ops.quantization.quantized_allreduce_ef`:
  all_to_all reduce-scatter + all_gather, ~1.03 bytes/elem/hop vs 4),
  the EQuARX recipe (PAPERS.md arXiv 2506.17615).  Per-shard
  error-feedback residuals persist in the engine's ``TrainState`` so
  the quantization error of step *t* is re-injected at step *t+1*
  (1-bit Adam's worker error, Tang et al.).
* **Overlap** — with ``overlap`` on, bucket *i* of micro-batch *k* is
  reduced inside the micro-batch scan body, so its collective is live
  while the rest of micro-batch *k*'s buckets quantize and while
  micro-batch *k+1* begins accumulating (T3-style fine-grained overlap,
  arXiv 2401.16677, expressed as dataflow for XLA's latency-hiding
  scheduler instead of hardware triggers).  Off, gradients accumulate
  unreduced and one bucketed reduction runs at the gradient-
  accumulation boundary (fewer quantizations, one collective burst).

Mesh generality — and its limits on this XLA version:

* The loss+backward runs in a ``shard_map`` region **manual over only
  the batch-ish axes** (``data``/``fsdp``) with every other mesh axis
  (``tensor``/``seq``) left to GSPMD (``auto``), so tensor/sequence
  parallel models keep their compiler-inserted collectives.  Only
  ``psum``-family collectives lower inside partial-auto regions (the
  SPMD partitioner check-fails on all_to_all/all_gather there), so the
  quantized exchange lives in a SECOND, fully-manual region whose
  inputs are replicated over the non-batch axes: each tensor/seq rank
  runs the identical bucket exchange within its own (data, fsdp) plane
  — duplicate elementwise quantize work, but no extra bytes per link.
* Gradient leaves whose layout touches an auto axis (tensor-parallel
  shards) cannot enter the replicated flat vector without paying an
  all-gather over that axis; they take a **direct** exact ``psum`` over
  the batch axes inside the backward region instead.  The
  ``quantized_fraction`` stat makes this visible.
* ``expert``/``hpz``/``pipe`` meshes fall back to the compiler's psum
  (their gradient reduction is not a plain batch-axes sum).

Observability: the bucket plan is static, so per-bucket wire volume is
exact at build time — recorded through
:class:`~deepspeed_tpu.utils.comms_logging.CommsLogger` and exposed as
``engine.comm_stats()`` / the bench artifact's ``comm_bytes_per_step``
and ``comm_quantized_fraction``.  Per-bucket *time* comes from
:meth:`CollectiveScheduler.profile_buckets`, which runs each bucket's
collective in isolation (XLA fuses per-op timing away in the real step;
the profiler owns in-step attribution).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...ops.quantization import quantized_allreduce_ef
from ...utils.jax_compat import shard_map
from ...utils.logging import logger


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One contiguous slice of the flat gradient vector (elements)."""
    index: int
    start: int
    end: int
    quantized: bool

    @property
    def elems(self) -> int:
        return self.end - self.start

    def wire_bytes(self, block: int, itemsize: int = 4) -> int:
        """Bytes this bucket moves per device per reduction."""
        if self.quantized:
            # two int8 hops, each with fp32 scales every `block` elems
            return int(2 * self.elems * (1 + 4.0 / block))
        # exact allreduce in the accumulation dtype: ~2 hops x itemsize
        return 2 * itemsize * self.elems

    def fp32_bytes(self) -> int:
        """What an uncompressed fp32 allreduce would move (the baseline
        the wire-reduction claim is measured against)."""
        return 8 * self.elems


class CollectiveScheduler:
    """Plans and executes the gradient-collective schedule for one engine.

    Built once per engine from the abstract (shape-only) gradient tree;
    all bucket boundaries, leaf classification and wire volumes are
    static.  The traced entry points are :meth:`backward` (partial-auto
    region: loss+grad, unreduced flat buckets + direct-psum leaves) and
    :meth:`reduce` (fully-manual region: the bucketed int8 wire).
    """

    def __init__(self,
                 topology,
                 comm_cfg,
                 abstract_grads: Any,
                 grad_specs: Any,
                 acc_dtype=jnp.float32):
        self.topology = topology
        self.mesh = topology.mesh
        self.cfg = comm_cfg
        self.acc_dtype = acc_dtype
        self.block = int(comm_cfg.quantization_block)
        self.quantize = bool(comm_cfg.quantize)
        self.overlap = bool(comm_cfg.overlap)
        self.error_feedback = bool(comm_cfg.error_feedback) and self.quantize

        sizes = {a: self.mesh.shape.get(a, 1) for a in self.mesh.axis_names}
        self.manual_axes: Tuple[str, ...] = tuple(
            a for a in ("data", "fsdp") if sizes.get(a, 1) > 1)
        self.auto_axes = frozenset(
            a for a in self.mesh.axis_names
            if a not in self.manual_axes and sizes[a] > 1)
        self.world = int(np.prod([sizes[a] for a in self.manual_axes]))
        if self.world <= 1:
            raise ValueError("CollectiveScheduler needs data*fsdp > 1")

        leaves, self._treedef = jax.tree.flatten(abstract_grads)
        spec_leaves = jax.tree.leaves(
            grad_specs, is_leaf=lambda s: isinstance(s, P))
        assert len(leaves) == len(spec_leaves), \
            "grad spec tree does not align with the grad tree"

        def touches_auto(spec: P) -> bool:
            for entry in spec:
                axes = entry if isinstance(entry, tuple) else (
                    (entry,) if entry else ())
                if any(a in self.auto_axes for a in axes):
                    return True
            return False

        self._leaves = leaves
        self.bucketed_idx = [i for i, s in enumerate(spec_leaves)
                             if not touches_auto(s)]
        self.direct_idx = [i for i, s in enumerate(spec_leaves)
                           if touches_auto(s)]

        # -- flat layout + bucket boundaries --------------------------------
        self._offsets = {}
        off = 0
        for i in self.bucketed_idx:
            self._offsets[i] = off
            off += int(np.prod(leaves[i].shape))
        self.total_elems = off
        align = self.world * self.block
        self.padded_elems = -(-max(off, 0) // align) * align if off else 0
        per_bucket = max(
            align,
            (int(comm_cfg.allreduce_bucket_size)
             // jnp.dtype(acc_dtype).itemsize) // align * align)
        self.buckets: List[Bucket] = []
        start = 0
        while start < self.padded_elems:
            end = min(start + per_bucket, self.padded_elems)
            self.buckets.append(Bucket(len(self.buckets), start, end,
                                       quantized=self.quantize))
            start = end
        self.direct_elems = int(sum(np.prod(leaves[i].shape)
                                    for i in self.direct_idx))
        logger.info(
            "CollectiveScheduler: %d bucket(s) x <=%d elems over axes %s "
            "(world %d), %d/%d elems quantized, %d direct-psum leaves, "
            "overlap=%s error_feedback=%s",
            len(self.buckets), per_bucket, self.manual_axes, self.world,
            self.total_elems if self.quantize else 0,
            self.total_elems + self.direct_elems, len(self.direct_idx),
            self.overlap, self.error_feedback)

    # ------------------------------------------------------------------
    # residuals (persistent error feedback, carried in TrainState)
    # ------------------------------------------------------------------
    def init_residuals(self) -> Any:
        if not self.error_feedback or self.padded_elems == 0:
            return ()
        return jnp.zeros((self.world, self.padded_elems), self.acc_dtype)

    def residual_sharding(self):
        if not self.error_feedback or self.padded_elems == 0:
            return ()
        return NamedSharding(self.mesh, P(self.manual_axes, None))

    # ------------------------------------------------------------------
    # traced region 1: loss + backward, unreduced
    # ------------------------------------------------------------------
    def backward(self, loss_fn: Callable, params: Any, mb: Any, rng,
                 scale) -> Tuple[jax.Array, jax.Array, Tuple]:
        """Per-shard loss+grad in a shard_map region manual over the
        batch axes (other axes auto).  Returns ``(loss, flat_local,
        direct)`` where ``flat_local`` is the [world, E] unreduced
        bucketed flat gradient (sharded over the batch axes — each
        rank's row is its local contribution, pre-divided by world) and
        ``direct`` is the tuple of already-psum'd auto-axis leaves.
        """
        world = self.world
        manual = self.manual_axes

        def region(p, mb, rng, scale):
            # distinct randomness per batch shard: without the fold-in,
            # every shard would draw the IDENTICAL dropout mask for its
            # local slice (the GSPMD baseline draws one global mask)
            for a in manual:
                rng = jax.random.fold_in(rng, lax.axis_index(a))

            def scaled_loss(pp):
                return (loss_fn(pp, mb, rng) * scale).astype(jnp.float32)
            loss, g = jax.value_and_grad(scaled_loss)(p)
            loss = lax.pmean(loss, manual)
            g_leaves = jax.tree.leaves(g)
            flat = self._flatten_local(g_leaves)
            # only psum-family collectives lower in partial-auto regions;
            # the bucketed exchange runs in reduce()'s fully-manual region
            direct = tuple(
                lax.psum(g_leaves[i].astype(self.acc_dtype) / world, manual)
                for i in self.direct_idx)
            return loss, flat[None] / world, direct

        batch_specs = jax.tree.map(
            lambda x: P(manual) if np.ndim(x) else P(), mb)
        direct_specs = tuple(P() for _ in self.direct_idx)
        return shard_map(
            region, mesh=self.mesh,
            in_specs=(jax.tree.map(lambda _: P(), params),
                      batch_specs, P(), P()),
            out_specs=(P(), P(manual, None), direct_specs),
            check_vma=False,
            auto=self.auto_axes or None)(params, mb, rng, scale)

    def _flatten_local(self, g_leaves: Sequence[jax.Array]) -> jax.Array:
        parts = [g_leaves[i].ravel().astype(self.acc_dtype)
                 for i in self.bucketed_idx]
        if not parts:
            return jnp.zeros((0,), self.acc_dtype)
        if self.padded_elems > self.total_elems:
            # concatenated zeros, NOT jnp.pad: the pad HLO miscompiles in
            # partial-auto (manual-subgroup) regions on this XLA version
            # (hlo_sharding_util.cc IsManualSubgroup check failure)
            parts.append(jnp.zeros((self.padded_elems - self.total_elems,),
                                   self.acc_dtype))
        return jnp.concatenate(parts)

    # ------------------------------------------------------------------
    # traced region 2: the bucketed wire
    # ------------------------------------------------------------------
    def reduce(self, flat_acc: jax.Array, residual: Any, scale=None
               ) -> Tuple[jax.Array, Any]:
        """Reduce the [world, E] unreduced flat gradients over the batch
        axes, bucket by bucket, on the int8 (or exact fp32) wire.
        Returns ``(flat_reduced [E], new_residual)``; the reduced vector
        is replicated over every mesh axis.

        ``scale``: the fp16 loss scale the flat gradients are multiplied
        by.  Residuals are stored UNSCALED (divided by ``scale``) and
        re-injected multiplied by the CURRENT scale, so error feedback
        stays correctly weighted across dynamic loss-scale changes.

        Runs fully manual over ALL mesh axes: the flat vector is
        replicated over non-batch axes, so each tensor/seq rank performs
        the identical exchange within its own (data, fsdp) plane — same
        bytes per link, duplicated elementwise quantize work.
        """
        if self.padded_elems == 0:
            return jnp.zeros((0,), self.acc_dtype), residual
        ef = self.error_feedback

        def region(fl, res, sc):
            fl = fl[0]
            if ef:
                res = res[0]
            outs, errs = [], []
            for b in self.buckets:
                seg = lax.dynamic_slice_in_dim(fl, b.start, b.elems)
                if ef:
                    seg = seg + sc * lax.dynamic_slice_in_dim(
                        res, b.start, b.elems)
                if b.quantized:
                    red, err = quantized_allreduce_ef(
                        seg, self.manual_axes, self.world, self.block)
                else:
                    red, err = lax.psum(seg, self.manual_axes), None
                outs.append(red)
                if ef:
                    errs.append(err / sc if err is not None
                                else jnp.zeros_like(seg))
            full = jnp.concatenate(outs)
            new_res = jnp.concatenate(errs)[None] if ef else ()
            return full, new_res

        sc = jnp.asarray(1.0 if scale is None else scale, jnp.float32)
        in_res_spec = P(self.manual_axes, None) if ef else P()
        full, new_res = shard_map(
            region, mesh=self.mesh,
            in_specs=(P(self.manual_axes, None), in_res_spec, P()),
            out_specs=(P(), P(self.manual_axes, None) if ef else P()),
            check_vma=False)(flat_acc, residual if ef else (), sc)
        return full, new_res

    # ------------------------------------------------------------------
    # assembly
    # ------------------------------------------------------------------
    def combine(self, flat_reduced: jax.Array, direct: Tuple) -> Any:
        """Reassemble the full gradient tree from the reduced flat
        vector and the direct-psum leaves."""
        out: List[Optional[jax.Array]] = [None] * len(self._leaves)
        for i in self.bucketed_idx:
            n = int(np.prod(self._leaves[i].shape))
            seg = lax.dynamic_slice_in_dim(flat_reduced, self._offsets[i], n)
            out[i] = seg.reshape(self._leaves[i].shape)
        for k, i in enumerate(self.direct_idx):
            out[i] = direct[k].reshape(self._leaves[i].shape)
        return jax.tree.unflatten(self._treedef, out)

    def zero_flat(self) -> jax.Array:
        return jnp.zeros((self.world, self.padded_elems), self.acc_dtype)

    def zero_direct(self) -> Tuple:
        return tuple(jnp.zeros(self._leaves[i].shape, self.acc_dtype)
                     for i in self.direct_idx)

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def stats(self, gas: int = 1) -> dict:
        """Static per-step wire accounting (exact: the plan is static)."""
        bucket_rounds = gas if self.overlap else 1
        itemsize = jnp.dtype(self.acc_dtype).itemsize
        bucket_bytes = sum(b.wire_bytes(self.block, itemsize)
                           for b in self.buckets)
        bucket_fp32 = sum(b.fp32_bytes() for b in self.buckets)
        direct_bytes = 2 * itemsize * self.direct_elems * gas
        total = bucket_bytes * bucket_rounds + direct_bytes
        fp32_equiv = bucket_fp32 * bucket_rounds + 8 * self.direct_elems * gas
        quantized_elems = (self.total_elems if self.quantize else 0)
        return {
            "bucket_count": len(self.buckets),
            "bucket_rounds_per_step": bucket_rounds,
            "comm_bytes_per_step": int(total),
            "comm_fp32_equiv_bytes_per_step": int(fp32_equiv),
            "comm_quantized_fraction": round(
                quantized_elems
                / max(1, self.total_elems + self.direct_elems), 4),
            "reduce_axes": list(self.manual_axes),
            "reduce_world": self.world,
            "overlap": self.overlap,
            "error_feedback": self.error_feedback,
            "per_bucket": [
                {"index": b.index, "elems": b.elems,
                 "quantized": b.quantized,
                 "wire_bytes": b.wire_bytes(self.block, itemsize),
                 "fp32_bytes": b.fp32_bytes()}
                for b in self.buckets],
        }

    def profile_buckets(self, iters: int = 5) -> List[dict]:
        """Time each bucket's reduction collective in isolation
        (block_until_ready around a jitted single-bucket reduce).  The
        in-step latencies are hidden by XLA's scheduler — this measures
        the standalone cost so regressions in bucket sizing are visible.
        """
        import time

        results = []
        flat = self.zero_flat()
        res = self.init_residuals()
        for b in self.buckets:
            sub = CollectiveScheduler.__new__(CollectiveScheduler)
            sub.__dict__.update(self.__dict__)
            sub.buckets = [dataclasses.replace(b, index=0, start=0,
                                               end=b.elems)]
            sub.padded_elems = b.elems
            fn = jax.jit(lambda f, r: sub.reduce(f, r)[0])
            args = (flat[:, :b.elems],
                    res[:, :b.elems] if self.error_feedback else ())
            jax.block_until_ready(fn(*args))  # compile
            t0 = time.perf_counter()
            for _ in range(iters):
                jax.block_until_ready(fn(*args))
            dt = (time.perf_counter() - t0) / iters
            results.append({"index": b.index, "elems": b.elems,
                            "mean_ms": round(dt * 1e3, 3)})
        return results
