"""LR schedules (reference ``runtime/lr_schedules.py``: LRRangeTest,
OneCycle, WarmupLR, WarmupDecayLR, WarmupCosineLR).

TPU-native design: schedules are pure ``step -> lr`` functions (optax
convention) so they trace into the jitted train step; a thin stateful
wrapper provides the reference's ``step()``/``get_lr()`` object API.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict

import optax

Schedule = Callable[[int], float]

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"

VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_) -> Schedule:
    def schedule(step):
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = math.floor(interval) if not hasattr(interval, "astype") else interval // 1
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)
    return schedule


def one_cycle(cycle_min_lr: float = 1e-5, cycle_max_lr: float = 1e-3,
              cycle_first_step_size: int = 2000, cycle_second_step_size: int | None = None,
              decay_step_size: int = 0, decay_lr_rate: float = 0.0, **_) -> Schedule:
    second = cycle_second_step_size or cycle_first_step_size
    total = cycle_first_step_size + second

    def schedule(step):
        if step < cycle_first_step_size:
            frac = step / cycle_first_step_size
            return cycle_min_lr + (cycle_max_lr - cycle_min_lr) * frac
        if step < total:
            frac = (step - cycle_first_step_size) / second
            return cycle_max_lr - (cycle_max_lr - cycle_min_lr) * frac
        if decay_step_size > 0:
            decay_steps = (step - total) / decay_step_size
            return cycle_min_lr / (1.0 + decay_lr_rate * decay_steps)
        return cycle_min_lr
    return schedule


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 1e-3,
              warmup_num_steps: int = 1000, warmup_type: str = "log", **_) -> Schedule:
    warmup_num_steps = max(warmup_num_steps, 2)

    def schedule(step):
        if step >= warmup_num_steps:
            return warmup_max_lr
        if warmup_type == "log":
            frac = math.log(step + 1) / math.log(warmup_num_steps)
        else:
            frac = step / warmup_num_steps
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * min(frac, 1.0)
    return schedule


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 1e-3, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_) -> Schedule:
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        if step < warmup_num_steps:
            return base(step)
        frac = max(0.0, (total_num_steps - step) / max(total_num_steps - warmup_num_steps, 1))
        return warmup_max_lr * frac
    return schedule


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 1e-4,
                     warmup_max_lr: float = 1e-3, **_) -> Schedule:
    def schedule(step):
        if step < warmup_num_steps:
            frac = warmup_min_ratio + (1 - warmup_min_ratio) * (step / max(warmup_num_steps, 1))
            return warmup_max_lr * frac
        progress = min((step - warmup_num_steps) / max(total_num_steps - warmup_num_steps, 1), 1.0)
        cos = 0.5 * (1 + math.cos(math.pi * progress))
        return warmup_max_lr * (cos_min_ratio + (1 - cos_min_ratio) * cos)
    return schedule


_FACTORY = {
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
    WARMUP_COSINE_LR: warmup_cosine_lr,
}


def get_lr_schedule(sched_type: str, params: Dict[str, Any], base_lr: float) -> Schedule:
    if sched_type not in _FACTORY:
        raise ValueError(f"unknown scheduler {sched_type!r}; valid: {VALID_LR_SCHEDULES}")
    params = dict(params)
    params.setdefault("warmup_max_lr", base_lr)
    return _FACTORY[sched_type](**params)


def as_optax_schedule(schedule: Schedule) -> optax.Schedule:
    # Schedules are pure python-float functions of int step; optax calls them
    # with traced ints inside jit, so wrap branches with jnp where needed.
    import jax.numpy as jnp

    def sched(count):
        # Evaluate on concrete grid lazily: use piecewise via jnp ops when traced.
        try:
            return schedule(int(count))
        except TypeError:
            # traced: fall back to float32 computation via interpolation-free call
            return _traced_schedule(schedule, count)
    return sched


def _traced_schedule(schedule: Schedule, count):
    """Evaluate a python schedule under tracing by tabulating is impossible;
    instead re-express common schedules with jnp.  For arbitrary schedules we
    sample on host per step (engine passes concrete step when possible)."""
    import jax.numpy as jnp
    # Piecewise-linear approximation over a log-spaced grid up to 2**22 steps.
    import numpy as np
    grid = np.unique(np.concatenate([
        np.arange(0, 2048), np.geomspace(2048, 2 ** 22, 2048).astype(np.int64)]))
    vals = np.asarray([schedule(int(s)) for s in grid], dtype=np.float32)
    return jnp.interp(count.astype(jnp.float32), jnp.asarray(grid, jnp.float32),
                      jnp.asarray(vals))


class LRScheduler:
    """Stateful wrapper providing the reference object API
    (``step()``, ``get_last_lr()``, ``state_dict()``)."""

    def __init__(self, schedule: Schedule, last_step: int = 0):
        self.schedule = schedule
        self.last_batch_iteration = last_step

    def step(self, last_batch_iteration: int | None = None):
        if last_batch_iteration is not None:
            self.last_batch_iteration = last_batch_iteration
        else:
            self.last_batch_iteration += 1

    def get_last_lr(self):
        return [self.schedule(self.last_batch_iteration)]

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


def add_tuning_arguments(parser):
    """Argparse group for convergence-tuning flags (reference
    lr_schedules.py:61).  One flag per schedule parameter, derived from
    the schedule functions' signatures so the CLI stays in lockstep
    with the schedules themselves.  All flags default to None — only
    explicitly-passed values reach the schedule config, so
    get_lr_schedule's own defaulting (e.g. warmup_max_lr -> optimizer
    base lr) still applies."""
    import inspect

    def str2bool(v):
        if v.lower() in ("1", "true", "yes", "on"):
            return True
        if v.lower() in ("0", "false", "no", "off"):
            return False
        raise __import__("argparse").ArgumentTypeError(
            f"expected a boolean, got {v!r}")

    group = parser.add_argument_group(
        "Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help=f"LR schedule for training "
                            f"(one of {VALID_LR_SCHEDULES}).")
    seen = set()
    for fn in (lr_range_test, one_cycle, warmup_lr, warmup_decay_lr,
               warmup_cosine_lr):
        # eval_str: under ``from __future__ import annotations`` every
        # annotation is a string ("int | None"), which the type dispatch
        # below would silently funnel to the float fallback
        for name, p in inspect.signature(fn, eval_str=True).parameters.items():
            if name in seen or p.kind in (p.VAR_KEYWORD, p.VAR_POSITIONAL):
                continue
            seen.add(name)
            import inspect as _i
            import typing as _t
            ann = p.annotation
            if ann is _i.Parameter.empty and \
                    p.default is not _i.Parameter.empty \
                    and p.default is not None:
                ann = type(p.default)  # un-annotated: infer from default
            # Optional[int] / "int | None" annotations: the CLI type is
            # the non-None member, not a float fallback
            args = [a for a in _t.get_args(ann) if a is not type(None)]
            if len(args) == 1:
                ann = args[0]
            if ann is bool:
                argtype = str2bool
            elif ann in (int, float, str):
                argtype = ann
            else:
                argtype = float
            group.add_argument(f"--{name}", type=argtype, default=None,
                               help=f"{fn.__name__} parameter {name}.")
    return parser


def convert_lr_tuning_args(args):
    """Parsed tuning args -> the scheduler config dict ``initialize``
    consumes (reference get_lr_from_args flow).  Only explicitly-passed
    flags enter params; schedules requiring total_num_steps raise a
    clear error when the flag is missing."""
    import inspect

    sched = getattr(args, "lr_schedule", None)
    if not sched:
        return None
    if sched not in VALID_LR_SCHEDULES:
        raise ValueError(f"unknown lr_schedule {sched!r} "
                         f"(valid: {VALID_LR_SCHEDULES})")
    fn = _FACTORY[sched]
    params = {}
    for name, p in inspect.signature(fn).parameters.items():
        if getattr(args, name, None) is not None:
            params[name] = getattr(args, name)
        elif p.default is inspect.Parameter.empty and \
                p.kind not in (p.VAR_KEYWORD, p.VAR_POSITIONAL):
            raise ValueError(
                f"lr_schedule {sched} requires --{name}")
    return {"type": sched, "params": params}
