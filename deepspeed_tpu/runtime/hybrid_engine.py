"""Hybrid engine: one set of weights, training AND fast generation (RLHF).

TPU-native analogue of ``deepspeed/runtime/hybrid_engine.py:30``
``DeepSpeedHybridEngine``: during RLHF the same model alternates between
ZeRO-3 training (actor update) and batched inference (rollout generation).
The reference flips nn.Modules into kernel-injected inference containers
and gathers ZeRO-3 shards per layer (``_zero3_forward`` :357).

On TPU none of that machinery is needed — the training params already live
sharded on the mesh, and generation is just a *different jitted program
over the same arrays*:

* ``train_batch`` delegates to the wrapped DeepSpeedEngine (ZeRO shardings
  intact);
* ``generate`` casts the current master params to compute dtype (the same
  cast the train step applies) and drives the ragged v2 engine's paged-KV
  decode; XLA's sharding propagation plays the role of the per-layer
  allgather, fused into the compute;
* weights are never copied host-side and never materialize unsharded.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist, logger
from .engine import DeepSpeedEngine


class DeepSpeedHybridEngine(DeepSpeedEngine):
    """Training engine + in-place rollout generation."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if not hasattr(self.module, "cfg"):
            raise ValueError(
                "hybrid engine needs a transformer model exposing .cfg "
                "(TransformerConfig) for the inference path")
        self._inflight_engine = None
        self._inference_params_step = -1
        self._in_eval = False
        # rollout perf counters (reference hybrid_engine latency logging)
        self._generate_latency = 0.0
        self._generate_tokens = 0

    # ----------------------------------------------------------- modes
    def eval(self) -> None:
        self._in_eval = True

    def train(self, mode: bool = True) -> None:
        self._in_eval = not mode

    # ------------------------------------------------------- inference
    def _inference_engine(self):
        """(Re)build the ragged engine view when weights changed."""
        from ..inference.v2.config import RaggedInferenceEngineConfig
        from ..inference.v2.engine import InferenceEngineV2
        from ..inference.v2.model import RaggedInferenceModel

        if self._inflight_engine is not None and \
                self._inference_params_step == self.global_steps:
            return self._inflight_engine
        # same arrays, cast to compute dtype — the ZeRO "gather" is XLA
        # resharding inside the compiled step, not a copy here
        params = jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            self.state.params)
        cfg = self.module.cfg
        if self._inflight_engine is not None:
            # keep compiled step cache + KV pages; swap weights only
            self._inflight_engine.model.params = params
        else:
            model = RaggedInferenceModel(cfg, params,
                                         mesh=self.topology.mesh)
            # the user's serving_optimization block (escape hatch back
            # to the split serving path) flows through to the rollout
            # engine
            v2cfg = RaggedInferenceEngineConfig.from_dict({
                "serving_optimization":
                    self.config.serving_optimization.to_v2_dict()})
            self._inflight_engine = InferenceEngineV2(model, v2cfg)
        self._inference_params_step = self.global_steps
        return self._inflight_engine

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 64,
                 temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 do_sample: bool = True,
                 eos_token_id: Optional[int] = None) -> List[List[int]]:
        """Rollout generation from the CURRENT training weights
        (reference ``generate`` hybrid_engine.py:168)."""
        from ..inference.v2.sampling import SamplingParams
        from ..inference.v2.scheduler import generate as ragged_generate

        engine = self._inference_engine()
        t0 = time.perf_counter()
        outs = ragged_generate(
            engine, [list(map(int, p)) for p in prompts],
            SamplingParams(
                max_new_tokens=int(max_new_tokens),
                temperature=float(temperature) if do_sample else 0.0,
                top_k=int(top_k), top_p=float(top_p),
                stop_token=eos_token_id))
        dt = time.perf_counter() - t0
        n_tok = sum(len(o) for o in outs)
        self._generate_latency += dt
        self._generate_tokens += n_tok
        log_dist(f"hybrid generate: {n_tok} tokens in {dt:.2f}s "
                 f"({n_tok / max(dt, 1e-9):.1f} tok/s)", ranks=[0])
        return outs

    # ------------------------------------------------------ train hook
    def train_batch(self, *args, **kwargs):
        # any step invalidates the cached inference weight view
        loss = super().train_batch(*args, **kwargs)
        self._inference_params_step = -1
        return loss

    def generate_throughput(self) -> float:
        return self._generate_tokens / max(self._generate_latency, 1e-9)
