"""Data loading (reference ``runtime/dataloader.py``: DeepSpeedDataLoader,
RepeatingLoader).

Framework-agnostic: wraps any indexable dataset (numpy arrays, lists of
dicts, torch Dataset) into batched numpy pytrees ready for the engine's
sharded train step.  Curriculum-aware sampling plugs in via the
``data_sampler`` argument (see runtime/data_pipeline/).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference :17)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


def default_collate(samples: Sequence[Any]):
    """Stack a list of samples (dicts/tuples/arrays) into a batched pytree."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples])
                           for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:
    """Batched loader (reference DeepSpeedDataLoader, dataloader.py:41)."""

    def __init__(self,
                 dataset,
                 batch_size: int,
                 collate_fn: Optional[Callable] = None,
                 shuffle: bool = False,
                 seed: int = 0,
                 drop_last: bool = True,
                 data_sampler: Optional[Any] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.data_sampler = data_sampler
        self._epoch = 0

    def __len__(self):
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[Any]:
        n = len(self.dataset)
        if self.data_sampler is not None:
            order = list(self.data_sampler)
        elif self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            order = rng.permutation(n).tolist()
        else:
            order = list(range(n))
        self._epoch += 1
        for i in range(0, len(order) - (self.batch_size - 1 if self.drop_last else 0),
                       self.batch_size):
            idx = order[i:i + self.batch_size]
            if self.drop_last and len(idx) < self.batch_size:
                break
            yield self.collate_fn([self.dataset[j] for j in idx])
