"""ZeRO-Offload: optimizer states on host (CPU) or NVMe, step on host C++.

TPU-native analogue of the reference offload stack:

* stage-1/2 CPU grad/step path (``runtime/zero/stage_1_and_2.py:1185-1321``)
  and stage-3 offload via ``DeepSpeedCPUAdam`` — here the host step runs the
  SIMD C++ kernels from ``csrc/adam|adagrad|lion`` while the TPU computes;
* NVMe optimizer-state swapping (``runtime/swap_tensor/
  partitioned_optimizer_swapper.py`` over ``csrc/aio``) — here a
  prefetching swapper over :class:`~deepspeed_tpu.ops.aio.AsyncIOHandle`;
* ZeRO-Offload++ partial offload ratio (``zero_partial_offload``,
  engine.py:766 Twin-Flow): only a configured fraction of parameter
  elements is offloaded, the rest keeps the fast on-device optax path.

Design: the engine's jitted step applies the device optimizer only to
non-offloaded leaves (``optax.masked``) and returns the reduced, clipped
fp32 grads of offloaded leaves as an extra output.  The host then runs the
C++ optimizer over pinned fp32 masters and pushes updated weights back in
compute dtype.  Offloaded leaves never hold Adam moments (or fp32 masters)
in HBM — the reference's memory equation, reached through XLA sharding
instead of hooks.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ...utils.logging import log_dist, logger


class NVMeStateSwapper:
    """Optimizer-state tier on NVMe with async prefetch.

    One file per (leaf, slot) under ``swap_dir``; reads for leaf *i+1* are
    submitted before the host steps leaf *i* (the pipelined swapper
    pattern, reference ``pipelined_optimizer_swapper.py``).
    """

    def __init__(self, swap_dir: str, aio_threads: int = 4,
                 block_size: int = 1 << 20, queue_depth: int = 128,
                 use_direct: bool = False):
        from ...ops.aio import AsyncIOHandle
        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        self.handle = AsyncIOHandle(num_threads=aio_threads,
                                    block_size=block_size,
                                    queue_depth=queue_depth,
                                    use_direct=use_direct)
        self._pending_reads: Dict[str, Tuple[int, np.ndarray]] = {}
        self._on_disk: set = set()

    def _path(self, key: str) -> str:
        return os.path.join(self.swap_dir, f"{key}.bin")

    def prefetch(self, key: str, nbytes_elems: int) -> None:
        """Submit an async read of a state buffer (no-op if never written)."""
        if key in self._pending_reads or key not in self._on_disk:
            return
        buf = np.empty(nbytes_elems, np.float32)
        req = self.handle.pread(buf, self._path(key))
        self._pending_reads[key] = (req, buf)

    def fetch(self, key: str, n_elems: int) -> np.ndarray:
        """Blocking read (or completion of a prefetch); zeros if new."""
        if key in self._pending_reads:
            req, buf = self._pending_reads.pop(key)
            self.handle.wait(req)
            return buf
        if key not in self._on_disk:
            return np.zeros(n_elems, np.float32)
        buf = np.empty(n_elems, np.float32)
        self.handle.sync_pread(buf, self._path(key))
        return buf

    def writeback(self, key: str, buf: np.ndarray) -> None:
        """Async write; the swapper owns the buffer until flushed."""
        self.handle.pwrite(buf, self._path(key))
        self._on_disk.add(key)

    def flush(self) -> None:
        self.handle.wait_all()

    def close(self) -> None:
        self.handle.close()


class HostOffloadOptimizer:
    """Host-side optimizer over the offloaded subset of parameters."""

    #: optimizer types the host C++ kernels cover
    SUPPORTED = ("adam", "adamw", "fusedadam", "cpuadam", "deepspeedcpuadam",
                 "adagrad", "lion", "fusedlion", "cpulion")

    def __init__(self, abstract_params: Any, config: Any):
        zcfg = config.zero_optimization
        off = zcfg.offload_optimizer
        self.device = off.device  # "cpu" | "nvme"
        self.ratio = float(getattr(off, "ratio", 1.0))
        opt_cfg = config.optimizer
        self._select_leaves(abstract_params)
        self._build_host_optimizer(opt_cfg)
        self.swapper: Optional[NVMeStateSwapper] = None
        if self.device == "nvme":
            aio = config.aio
            # aio.thread_count is authoritative when the user set it;
            # else the offload block's aio_threads (max() of the two
            # defaults could never LOWER the pool)
            threads = (aio.thread_count
                       if "thread_count" in aio.model_fields_set
                       else int(getattr(off, "aio_threads", 4)))
            self.swapper = NVMeStateSwapper(
                os.path.join(off.nvme_path or "/tmp/ds_tpu_nvme",
                             f"rank{jax.process_index()}"),
                aio_threads=threads,
                block_size=aio.block_size, queue_depth=aio.queue_depth,
                use_direct=aio.use_direct_io)
        self.masters: List[np.ndarray] = []
        n_off = sum(int(np.prod(l.shape)) for l in self._leaves(self.offload_idx))
        n_all = sum(int(np.prod(l.shape)) for l in self._flat_abstract)
        log_dist(
            f"ZeRO-Offload: {len(self.offload_idx)}/{len(self._flat_abstract)} "
            f"leaves, {n_off}/{n_all} elements ({n_off / max(1, n_all):.0%}) "
            f"-> {self.device}", ranks=[0])

    # ------------------------------------------------------------ leaves
    def _select_leaves(self, abstract_params: Any) -> None:
        flat, treedef = jax.tree.flatten(abstract_params)
        self._flat_abstract = flat
        self._treedef = treedef
        float_idx = [i for i, l in enumerate(flat)
                     if np.issubdtype(l.dtype, np.floating)]
        total = sum(int(np.prod(flat[i].shape)) for i in float_idx)
        # Twin-Flow partial offload: offload the largest leaves first until
        # the element ratio is reached (big leaves amortize transfer best)
        by_size = sorted(float_idx,
                         key=lambda i: -int(np.prod(flat[i].shape)))
        chosen: List[int] = []
        acc = 0
        for i in by_size:
            if self.ratio >= 1.0 or acc < self.ratio * total:
                chosen.append(i)
                acc += int(np.prod(flat[i].shape))
        self.offload_idx = sorted(chosen)

    def _leaves(self, idx: List[int]) -> List[Any]:
        return [self._flat_abstract[i] for i in idx]

    def device_mask(self) -> Any:
        """Pytree of bools: True where the *device* optimizer applies."""
        flags = [i not in set(self.offload_idx)
                 for i in range(len(self._flat_abstract))]
        return jax.tree.unflatten(self._treedef, flags)

    # ----------------------------------------------------- host optimizer
    def _build_host_optimizer(self, opt_cfg) -> None:
        name = opt_cfg.type.lower().replace("_", "")
        p = opt_cfg.params
        if name not in self.SUPPORTED:
            raise ValueError(
                f"offload_optimizer does not support optimizer {opt_cfg.type!r}; "
                f"host kernels exist for {sorted(set(self.SUPPORTED))}")
        if name == "adagrad":
            from ...ops.adam import DeepSpeedCPUAdagrad
            self.host_opt = DeepSpeedCPUAdagrad(
                lr=p.lr, eps=p.eps, weight_decay=p.weight_decay)
        elif name in ("lion", "fusedlion", "cpulion"):
            from ...ops.adam import DeepSpeedCPULion
            self.host_opt = DeepSpeedCPULion(
                lr=p.lr, betas=tuple(p.betas)[:2], weight_decay=p.weight_decay)
        else:
            from ...ops.adam import DeepSpeedCPUAdam
            # adamw always decouples; adam/fusedadam/cpuadam follow
            # adam_w_mode (FusedAdam's default True) — same rule as the
            # device factory in runtime/optimizers.py
            self.host_opt = DeepSpeedCPUAdam(
                lr=p.lr, betas=tuple(p.betas)[:2], eps=p.eps,
                weight_decay=p.weight_decay,
                adamw_mode=name == "adamw" or p.adam_w_mode)
        self._slots = self.host_opt.SLOTS

    # ------------------------------------------------------------- state
    def init_masters(self, params: Any) -> None:
        """Pull fp32 masters of offloaded leaves to host memory."""
        flat = jax.tree.flatten(params)[0]
        self.masters = [
            np.ascontiguousarray(
                np.asarray(jax.device_get(flat[i]), np.float32).ravel())
            for i in self.offload_idx
        ]

    def step(self, host_grads: List[np.ndarray],
             lr: Optional[float] = None) -> List[np.ndarray]:
        """One host optimizer step over every offloaded leaf.

        ``host_grads`` aligns with ``offload_idx``.  Returns the updated
        fp32 masters (flat), caller reshapes/casts for the device.
        """
        assert len(host_grads) == len(self.offload_idx)
        if self.swapper is not None:
            return self._step_nvme(host_grads, lr)
        for k, grad in enumerate(host_grads):
            self.host_opt.step(k, self.masters[k],
                               np.asarray(grad, np.float32).ravel(), lr=lr)
        return self.masters

    def _step_nvme(self, host_grads: List[np.ndarray],
                   lr: Optional[float]) -> List[np.ndarray]:
        """Sequential leaf loop with one-ahead state prefetch."""
        n = len(self.offload_idx)
        state_of = self.host_opt._state  # managed externally per leaf
        if n:
            for slot in self._slots:
                self.swapper.prefetch(f"l0_{slot}", self.masters[0].size)
        for k in range(n):
            # fetch current leaf's slots (completes the prefetch)
            state_of[k] = {
                slot: self.swapper.fetch(f"l{k}_{slot}", self.masters[k].size)
                for slot in self._slots
            }
            if hasattr(self.host_opt, "_steps"):
                self.host_opt._steps.setdefault(k, 0)
            # overlap: submit next leaf's reads before computing
            if k + 1 < n:
                for slot in self._slots:
                    self.swapper.prefetch(f"l{k + 1}_{slot}",
                                          self.masters[k + 1].size)
            self.host_opt.step(k, self.masters[k],
                               np.asarray(host_grads[k], np.float32).ravel(),
                               lr=lr)
            for slot in self._slots:
                self.swapper.writeback(f"l{k}_{slot}", state_of[k][slot])
            del state_of[k]  # states live on NVMe, not RAM
        self.swapper.flush()
        return self.masters

    # ------------------------------------------------------ checkpointing
    def state_dict(self) -> Dict[str, Any]:
        if self.swapper is not None:
            # materialize NVMe states for the checkpoint
            states = {}
            for k in range(len(self.offload_idx)):
                states[k] = {
                    slot: self.swapper.fetch(f"l{k}_{slot}",
                                             self.masters[k].size)
                    for slot in self._slots
                }
            steps = dict(getattr(self.host_opt, "_steps", {}))
            return {"masters": [m.copy() for m in self.masters],
                    "state": states, "steps": steps}
        sd = {"masters": [m.copy() for m in self.masters]}
        sd.update(self.host_opt.state_dict())
        return sd

    def save_npz(self, path: str) -> None:
        """Persist masters + host optimizer states (one npz per rank,
        reference ``zero_pp_rank_*`` shard files)."""
        sd = self.state_dict()
        arrays: Dict[str, np.ndarray] = {}
        for k, m in enumerate(sd["masters"]):
            arrays[f"master_{k}"] = m
        for k, slots in sd.get("state", {}).items():
            for slot, buf in slots.items():
                arrays[f"state_{k}_{slot}"] = np.asarray(buf)
        steps = sd.get("steps", {})
        arrays["steps_keys"] = np.asarray(sorted(int(k) for k in steps),
                                          np.int64)
        arrays["steps_vals"] = np.asarray(
            [int(steps[k]) for k in sorted(steps, key=int)], np.int64)
        np.savez(path, **arrays)

    def load_npz(self, path: str) -> None:
        with np.load(path) as z:
            masters = []
            k = 0
            while f"master_{k}" in z:
                masters.append(np.asarray(z[f"master_{k}"], np.float32))
                k += 1
            state: Dict[int, Dict[str, np.ndarray]] = {}
            for name in z.files:
                if name.startswith("state_"):
                    _, idx, slot = name.split("_", 2)
                    state.setdefault(int(idx), {})[slot] = np.asarray(
                        z[name], np.float32)
            steps = {int(k_): int(v) for k_, v in
                     zip(z["steps_keys"], z["steps_vals"])}
        self.load_state_dict({"masters": masters, "state": state,
                              "steps": steps})

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.masters = [np.asarray(m, np.float32) for m in sd["masters"]]
        if self.swapper is not None:
            for k, slots in sd.get("state", {}).items():
                for slot, buf in slots.items():
                    self.swapper.writeback(f"l{int(k)}_{slot}",
                                           np.asarray(buf, np.float32))
            self.swapper.flush()
            if hasattr(self.host_opt, "_steps"):
                self.host_opt._steps = {int(k): int(v)
                                        for k, v in sd.get("steps", {}).items()}
        else:
            self.host_opt.load_state_dict({"steps": sd.get("steps", {}),
                                           "state": sd.get("state", {})})
