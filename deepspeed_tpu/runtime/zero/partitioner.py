"""ZeRO stages as GSPMD shardings — the TPU-native redesign of
``deepspeed/runtime/zero/`` (stage_1_and_2.py:96 ``DeepSpeedZeroOptimizer``,
stage3.py:76 ``DeepSpeedZeroOptimizer_Stage3``,
partition_parameters.py:808 ``zero.Init``).

The reference implements ZeRO imperatively: flatten param groups, slice
1/N per rank, install autograd hooks, hand-schedule all-gathers and
reduce-scatters on side streams.  Under XLA none of that machinery is
needed — the *policy* is expressed as shardings and the compiler inserts
and overlaps the collectives:

  stage 0  params/grads/opt replicated; grad psum over dp axes
  stage 1  optimizer state (incl. fp32 master) sharded over the 'fsdp'
           mesh axis.  XLA's sharded weight-update pass then turns the
           grad all-reduce into reduce-scatter + (post-update) all-gather
           automatically (cf. "Automatic Cross-Replica Sharding of Weight
           Update in Data-Parallel Training", arXiv:2004.13336 — the
           GSPMD-era formulation of ZeRO-1/2).
  stage 2  same sharded opt state + an explicit sharding constraint on
           gradients so they are born reduce-scattered (never a full
           replicated gradient buffer lives in HBM).
  stage 3  parameters themselves carry the 'fsdp' sharding; XLA
           all-gathers each layer's weights just-in-time and frees them
           after use — the compiler's liveness analysis replaces the
           reference's PartitionedParameterCoordinator trace/prefetch
           machinery (partitioned_param_coordinator.py:62).  Prefetch
           distance is the scheduler's latency-hiding, tunable via XLA
           flags rather than python hooks.

Param classification: leaves annotated with logical axes (flax
``nn.with_partitioning``) follow the sharding-rule table; bare leaves get
the generic "shard the largest divisible dim" rule the reference's flat
partitioner approximates with round-robin slicing (stage_1_and_2.py:643).
"""

from __future__ import annotations

import math
from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...parallel.topology import MeshTopology
from ...utils.logging import logger


def _axis_sizes_in_spec(spec: P, mesh: Mesh) -> dict:
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def _largest_divisible_dim(shape: Tuple[int, ...], divisor: int,
                           taken_dims: set) -> Optional[int]:
    best = None
    best_size = 0
    for i, s in enumerate(shape):
        if i in taken_dims:
            continue
        if s % divisor == 0 and s > best_size:
            best, best_size = i, s
    return best


def add_fsdp_axis(spec: P, shape: Tuple[int, ...], fsdp_size: int,
                  min_size: int = 2 ** 12,
                  blocked_dims: Optional[set] = None,
                  axes: Tuple[str, ...] = ("fsdp",),
                  axis_sizes: Optional[Tuple[int, ...]] = None) -> P:
    """Augment a (possibly tensor-parallel) spec with ZeRO sharding on the
    largest still-unsharded divisible dim.  Tiny params (< min_size elems,
    cf. stage3_param_persistence_threshold) stay replicated — gathering
    them is cheaper than the latency of a tiny collective.
    ``blocked_dims``: dims that must stay unsharded (e.g. the stacked
    'layers' dim that lax.scan slices per iteration).
    ``axes``: which mesh axes shard the dim — ("fsdp",) for plain ZeRO,
    ("fsdp", "hpz") for the hpZ primary partition, ("hpz",) for the hpZ
    secondary (compute) partition.  ``axis_sizes`` (parallel to ``axes``)
    enables degrading to a prefix of the axes when the full product does
    not divide any dim — never a silent full replication of a large leaf
    (cf. reference _partition_param_sec divisibility assert,
    partition_parameters.py:1653)."""
    if fsdp_size <= 1:
        return spec
    if int(np.prod(shape)) < min_size:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    taken = {i for i, e in enumerate(entries) if e is not None}
    if blocked_dims:
        taken |= blocked_dims
    dim = _largest_divisible_dim(shape, fsdp_size, taken)
    use_axes = axes
    if dim is None and axis_sizes is not None and len(axes) > 1:
        for cut in range(len(axes) - 1, 0, -1):
            sub_size = int(np.prod(axis_sizes[:cut]))
            if sub_size <= 1:
                continue
            dim = _largest_divisible_dim(shape, sub_size, taken)
            if dim is not None:
                use_axes = axes[:cut]
                logger.warning(
                    "zero partitioner: shape %s not divisible by the full "
                    "%s=%d partition; degrading to %s=%d (leaf stays "
                    "replicated over %s)", shape, axes, fsdp_size,
                    use_axes, sub_size, axes[cut:])
                break
    if dim is None:
        logger.warning(
            "zero partitioner: no dim of shape %s divisible by %d on axes "
            "%s — leaf stays REPLICATED (memory savings lost)",
            shape, fsdp_size, axes)
        return spec
    entries[dim] = use_axes if len(use_axes) > 1 else use_axes[0]
    return P(*entries)


def logical_to_mesh_spec(logical_axes: Tuple[Optional[str], ...], rules: dict) -> P:
    entries = []
    used = set()
    for name in logical_axes:
        axis = rules.get(name) if name is not None else None
        if axis is not None and axis in used:
            axis = None  # a mesh axis may shard only one dim
        if axis is not None:
            if isinstance(axis, tuple):
                used.update(axis)
            else:
                used.add(axis)
        entries.append(axis)
    return P(*entries)


def default_sharding_rules(topology: MeshTopology, zero_stage: int) -> dict:
    """Logical-axis -> mesh-axis table (the TPU analogue of Megatron's
    row/column classification in the reference's AutoTP,
    module_inject/auto_tp.py:191)."""
    tp = "tensor" if topology.tp_world_size > 1 else None
    rules = {
        "embed": None,          # embedding/model dim: kept unsharded for TP
        "vocab": tp,            # vocab-parallel embedding / lm head
        "mlp": tp,              # ffn hidden (column-parallel in, row-parallel out)
        "heads": tp,            # attention heads
        "kv": None,
        "qkv": tp,
        "expert": "expert" if topology.ep_world_size > 1 else None,
        "layers": None,         # scan-over-layers axis never sharded
        "stages": "pipe" if topology.pp_world_size > 1 else None,
        "norm": None,
    }
    return rules


class ZeroPartitioner:
    """Computes NamedShardings for params / gradients / optimizer state."""

    def __init__(self, topology: MeshTopology, stage: int,
                 persistence_threshold: int = 2 ** 12,
                 rules: Optional[dict] = None):
        if stage not in (0, 1, 2, 3):
            raise ValueError(f"invalid ZeRO stage {stage}")
        self.topology = topology
        self.stage = stage
        self.persistence_threshold = persistence_threshold
        self.rules = rules or default_sharding_rules(topology, stage)

    # -- per-leaf specs ---------------------------------------------------
    def _base_spec(self, leaf: Any) -> P:
        """TP/EP sharding from logical-axis metadata, if present.  Axis
        entries that do not divide the dim size are dropped (e.g. 8 KV heads
        under tp=16 stay replicated, as reference AutoTP keeps indivisible
        modules unsharded, auto_tp.py)."""
        names = getattr(leaf, "names", None)
        if not names:
            return P()
        spec = logical_to_mesh_spec(tuple(names), self.rules)
        shape = np.shape(getattr(leaf, "value", leaf))
        entries = []
        for i, entry in enumerate(spec):
            if entry is None:
                entries.append(None)
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            size = math.prod(self.topology.axis_size(a) for a in axes)
            entries.append(entry if i < len(shape) and shape[i] % size == 0 else None)
        return P(*entries)

    def _blocked_dims(self, leaf: Any) -> set:
        names = getattr(leaf, "names", None)
        if not names:
            return set()
        return {i for i, n in enumerate(names) if n == "layers"}

    def param_spec(self, leaf: Any) -> P:
        """Sharding of the model parameters used in fwd/bwd.

        ZeRO++ hpZ (reference ``zero_hpz_partition_size``,
        ``_partition_param_sec`` partition_parameters.py:1653): with an
        'hpz' mesh axis, compute params shard over ONLY the inner 'hpz'
        axis — the per-layer just-in-time gathers then ride ICI-adjacent
        devices, while the once-per-step master->compute reshard carries
        the cross-'fsdp' (DCN) traffic a single time."""
        spec = self._base_spec(leaf)
        shape = np.shape(getattr(leaf, "value", leaf))
        if self.stage >= 3:
            hpz = self.topology.hpz_world_size
            if hpz > 1:
                spec = add_fsdp_axis(spec, shape, hpz,
                                     self.persistence_threshold,
                                     blocked_dims=self._blocked_dims(leaf),
                                     axes=("hpz",))
            else:
                spec = add_fsdp_axis(spec, shape,
                                     self.topology.fsdp_world_size,
                                     self.persistence_threshold,
                                     blocked_dims=self._blocked_dims(leaf))
        return spec

    def master_spec(self, leaf: Any) -> P:
        """Sharding of fp32 master weights + optimizer moments: always the
        FULL zero partition (fsdp x hpz under ZeRO++)."""
        spec = self._base_spec(leaf)
        shape = np.shape(getattr(leaf, "value", leaf))
        if self.stage >= 1:
            hpz = self.topology.hpz_world_size
            total = self.topology.fsdp_world_size * hpz
            axes = ("fsdp", "hpz") if hpz > 1 else ("fsdp",)
            sizes = ((self.topology.fsdp_world_size, hpz) if hpz > 1
                     else (self.topology.fsdp_world_size,))
            spec = add_fsdp_axis(spec, shape, total,
                                 min_size=2,  # shard even small opt state
                                 blocked_dims=self._blocked_dims(leaf),
                                 axes=axes, axis_sizes=sizes)
        return spec

    def grad_spec(self, leaf: Any) -> P:
        """Sharding constraint applied to gradients inside the step.
        Stage >= 2: born reduce-scattered (matches master layout so the
        update is purely local)."""
        if self.stage >= 2:
            return self.master_spec(leaf)
        return self.param_spec(leaf)

    # -- tree-level -------------------------------------------------------
    def tree_param_specs(self, params: Any) -> Any:
        return jax.tree.map(self.param_spec, params,
                            is_leaf=_is_partitioned_leaf)

    def tree_master_specs(self, params: Any) -> Any:
        return jax.tree.map(self.master_spec, params,
                            is_leaf=_is_partitioned_leaf)

    def tree_grad_specs(self, params: Any) -> Any:
        return jax.tree.map(self.grad_spec, params,
                            is_leaf=_is_partitioned_leaf)

    def param_shardings(self, params: Any) -> Any:
        mesh = self.topology.mesh
        return jax.tree.map(lambda s: NamedSharding(mesh, s),
                            self.tree_param_specs(params))

    def master_shardings(self, params: Any) -> Any:
        mesh = self.topology.mesh
        return jax.tree.map(lambda s: NamedSharding(mesh, s),
                            self.tree_master_specs(params))

    def describe(self, params: Any) -> str:
        lines = [f"ZeRO stage {self.stage} over fsdp={self.topology.fsdp_world_size}"]
        flat, _ = jax.tree.flatten_with_path(self.tree_param_specs(params))
        for path, spec in flat[:50]:
            lines.append(f"  {jax.tree_util.keystr(path)}: {spec}")
        return "\n".join(lines)


def _is_partitioned_leaf(x: Any) -> bool:
    return hasattr(x, "names") and hasattr(x, "value")


def unbox(params: Any) -> Any:
    """Strip flax Partitioned boxes -> raw arrays."""
    return jax.tree.map(
        lambda x: x.value if _is_partitioned_leaf(x) else x, params,
        is_leaf=_is_partitioned_leaf)
