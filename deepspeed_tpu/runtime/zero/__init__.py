"""ZeRO public API (reference ``deepspeed/runtime/zero/__init__.py``:
``Init``, ``GatheredParameters`` + the partitioner internals).

Under GSPMD the heavy machinery the reference exposes here is absorbed
by sharding: parameters are BORN partitioned (the engine jits the
initializer with sharded out_shardings), and gathering is a resharding.
The two context managers stay as migration seams with those semantics.
"""

from __future__ import annotations

import contextlib

from .partitioner import ZeroPartitioner  # noqa: F401


@contextlib.contextmanager
def Init(*args, **kwargs):
    """Reference ``zero.Init`` (partition_parameters.py:880): construct
    the model with parameters already partitioned so the full model
    never materializes on one device.

    Compatibility no-op: under GSPMD every ``initialize()`` already
    jits parameter init with sharded out_shardings (engine
    ``_init_params``), so there is nothing to enter — models are never
    materialized unsharded in the first place."""
    del args, kwargs
    yield


class GatheredParameters:
    """Materialize full (host) copies of possibly-sharded params inside
    a context (reference ``zero.GatheredParameters``,
    partition_parameters.py:2283 — gather, optionally modify on one
    rank, re-partition on exit).

    Functional-params formulation: entering yields a NEW pytree of host
    ``numpy`` arrays assembled from all shards; mutate those and write
    them back yourself (params are immutable values here, so in-place
    re-partition on exit has nothing to write into).
    """

    def __init__(self, params, modifier_rank=None, **kwargs):
        del modifier_rank, kwargs
        self.params = params

    def __enter__(self):
        import jax
        import numpy as np
        from flax.core import meta

        def gather(x):
            if isinstance(x, meta.Partitioned):
                x = x.value
            if isinstance(x, jax.Array):
                if not x.is_fully_addressable:
                    # multi-process: shards live on other hosts;
                    # all-gather the global value across processes
                    from jax.experimental import multihost_utils
                    return np.asarray(
                        multihost_utils.process_allgather(x, tiled=True))
                return np.asarray(jax.device_get(x))
            return x
        return jax.tree.map(
            gather, self.params,
            is_leaf=lambda x: isinstance(x, meta.Partitioned))

    def __exit__(self, *exc):
        return False
