"""Elastic training (reference ``deepspeed/elasticity/``)."""

from .elasticity import (  # noqa: F401
    ElasticityConfig,
    ElasticityError,
    ElasticityConfigError,
    ElasticityIncompatibleWorldSize,
    candidate_batch_sizes,
    compute_elastic_config,
    elasticity_enabled,
    get_compatible_chips_v01,
    get_compatible_chips_v02,
    usable_chip_count,
    valid_chip_counts,
)
from .elastic_agent import AgentResult, ElasticAgent  # noqa: F401
