"""Elastic agent: supervise a launch, rescale + resume on membership change.

TPU-native analogue of ``deepspeed/elasticity/elastic_agent.py:32``
``DSElasticAgent``.  The reference wraps torch-elastic's rendezvous: on a
worker join/leave it restarts all ranks and training resumes from the last
checkpoint at the new world size.  On TPU the equivalent loop is
pod-reslice + auto-resume: the agent re-probes the host set between
restarts, verifies the new chip count is in the elastic config's valid set
(:func:`~deepspeed_tpu.elasticity.compute_elastic_config`), and relaunches;
the engine's ``load_checkpoint(latest)`` path restores state.
"""

from __future__ import annotations

import subprocess
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from .elasticity import usable_chip_count
from ..utils.logging import logger


@dataclass
class AgentResult:
    exit_code: int
    restarts: int
    world_sizes: List[int] = field(default_factory=list)


class ElasticAgent:
    """Restart-supervision loop around a launch callable.

    ``launch_fn(world_size) -> int`` runs one training generation and
    returns its exit code; ``probe_fn() -> int`` reports the currently
    available chip count (e.g. re-reading the hostfile or querying the TPU
    pod API).  Injection of both keeps the loop unit-testable without
    hardware — the same role the reference's pg_sim plays.
    """

    def __init__(self,
                 ds_config: Dict,
                 launch_fn: Callable[[int], int],
                 probe_fn: Callable[[], int],
                 max_restarts: int = 100,
                 restart_backoff_s: float = 5.0):
        self.ds_config = ds_config
        self.launch_fn = launch_fn
        self.probe_fn = probe_fn
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s

    def _usable_world(self, available: int) -> int:
        return usable_chip_count(self.ds_config, available)

    def run(self) -> AgentResult:
        restarts = 0
        history: List[int] = []
        while True:
            world = self._usable_world(self.probe_fn())
            history.append(world)
            logger.info("elastic agent: generation %d with %d chips",
                        restarts, world)
            code = self.launch_fn(world)
            if code == 0:
                return AgentResult(0, restarts, history)
            restarts += 1
            if restarts > self.max_restarts:
                logger.error("elastic agent: max restarts exceeded")
                return AgentResult(code, restarts - 1, history)
            logger.warning("elastic agent: generation failed (%d); "
                           "re-probing and restarting in %.1fs",
                           code, self.restart_backoff_s)
            time.sleep(self.restart_backoff_s)
