"""Elastic training: batch-size-invariant scale-up/down.

TPU-native analogue of ``deepspeed/elasticity/elasticity.py`` (algorithms
v0.1 ``_get_compatible_gpus_v01`` :83 and v0.2 :126, public API
``compute_elastic_config`` :233).  The contract: given a maximum acceptable
global batch and a menu of micro-batch sizes, pick one global batch size
that is simultaneously divisible by as many chip counts as possible, so the
job can be rescheduled onto any of those chip counts without changing the
effective batch (gradient accumulation absorbs the difference:
``batch = micro * gas * dp_world``).

On TPU "gpu count" reads as *chip count*; v0.2's node granularity reads as
*host granularity* (a pod reslices in whole hosts), and model-parallel size
is the product of the non-DP mesh axes (tp·pp·sp·ep).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.logging import logger


class ElasticityError(RuntimeError):
    """Generic elasticity failure."""


class ElasticityConfigError(ElasticityError):
    """Bad or missing elasticity config."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """Current world size is not in the valid set for this config."""


# Highly composite numbers: maximal divisor counts, so scaling a base
# micro-batch by one of these maximizes the number of chip counts that
# divide the resulting global batch. Enough entries for ~720K batch.
_HIGHLY_COMPOSITE = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260,
    1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360,
    50400, 55440, 83160, 110880, 166320, 221760, 277200, 332640, 498960,
    554400, 665280, 720720,
]


@dataclass
class ElasticityConfig:
    """Typed view of the ``"elasticity"`` config block."""
    max_acceptable_batch_size: int
    micro_batches: List[int]
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    version: float = 0.1
    model_parallel_size: int = 1
    num_gpus_per_node: int = 1

    @classmethod
    def from_dict(cls, d: Dict) -> "ElasticityConfig":
        if "max_train_batch_size" not in d and \
                "max_acceptable_batch_size" not in d:
            raise ElasticityConfigError(
                "elasticity config requires 'max_train_batch_size'")
        micro = d.get("micro_batch_sizes", d.get("micro_batches"))
        if not micro:
            raise ElasticityConfigError(
                "elasticity config requires 'micro_batch_sizes'")
        if not all(isinstance(m, int) and m > 0 for m in micro):
            raise ElasticityConfigError(
                f"micro_batch_sizes must be positive ints, got {micro}")
        cfg = cls(
            max_acceptable_batch_size=int(
                d.get("max_train_batch_size",
                      d.get("max_acceptable_batch_size"))),
            micro_batches=sorted(set(int(m) for m in micro)),
            min_gpus=int(d.get("min_gpus", 1)),
            max_gpus=int(d.get("max_gpus", 10000)),
            min_time=int(d.get("min_time", 0)),
            prefer_larger_batch=bool(d.get("prefer_larger_batch", True)),
            ignore_non_elastic_batch_info=bool(
                d.get("ignore_non_elastic_batch_info", False)),
            version=float(d.get("version", 0.1)),
            model_parallel_size=int(d.get("model_parallel_size", 1)),
            num_gpus_per_node=int(d.get("num_gpus_per_node", 1)),
        )
        if cfg.min_gpus < 1 or cfg.max_gpus < cfg.min_gpus:
            raise ElasticityConfigError(
                f"invalid chip range [{cfg.min_gpus}, {cfg.max_gpus}]")
        return cfg


def _scale_to_hcn(base: int, ceiling: int) -> int:
    """Largest ``base * hcn`` not exceeding ``ceiling`` (>= base)."""
    if base >= ceiling:
        return base
    budget = ceiling // base
    best = 1
    for h in _HIGHLY_COMPOSITE:
        if h > budget:
            break
        best = h
    return base * best


def candidate_batch_sizes(micro_batches: Sequence[int],
                          max_batch: int) -> List[int]:
    """Candidate global batches: each micro-batch (and their LCM) scaled by
    the largest highly-composite multiplier that stays under ``max_batch``."""
    bases = list(micro_batches)
    bases.append(math.lcm(*micro_batches))
    cands = {_scale_to_hcn(b, max_batch) for b in bases}
    # the LCM base can itself exceed the cap; keep the contract batch<=max
    # (micro batches themselves are validated <= max by the caller)
    capped = {c for c in cands if c <= max_batch}
    return sorted(capped or {max(m for m in micro_batches if m <= max_batch)})


def valid_chip_counts(batch_size: int, micro_batches: Sequence[int],
                      min_chips: int, max_chips: int) -> List[int]:
    """All chip counts g in [min,max] such that some micro-batch evenly
    tiles: batch = micro * gas * g for integer gas."""
    valid = set()
    for micro in micro_batches:
        if batch_size % micro:
            continue
        quotient = batch_size // micro  # = gas * chips
        # enumerate divisor pairs in O(sqrt)
        d = 1
        while d * d <= quotient:
            if quotient % d == 0:
                for g in (d, quotient // d):
                    if min_chips <= g <= max_chips:
                        valid.add(g)
            d += 1
    return sorted(valid)


def _best_candidate(cands: Sequence[int], micro_batches: Sequence[int],
                    min_chips: int, max_chips: int,
                    prefer_larger: bool) -> Tuple[int, List[int]]:
    best_batch = min(micro_batches)
    best_valid: List[int] = []
    for batch in cands:
        valid = valid_chip_counts(batch, micro_batches, min_chips, max_chips)
        better = len(valid) > len(best_valid)
        tie = len(valid) == len(best_valid)
        if better or (tie and ((prefer_larger and batch > best_batch) or
                               (not prefer_larger and batch < best_batch))):
            best_batch, best_valid = batch, valid
    return best_batch, best_valid


def get_compatible_chips_v01(micro_batches: Sequence[int], max_batch: int,
                             min_chips: int = 1,
                             max_chips: Optional[int] = None,
                             prefer_larger: bool = True
                             ) -> Tuple[int, List[int]]:
    """v0.1: pick the global batch with the most compatible chip counts."""
    if any(m > max_batch for m in micro_batches):
        raise ElasticityConfigError(
            f"every micro batch must be <= max batch {max_batch}")
    max_chips = max_chips or max_batch // min(micro_batches)
    cands = candidate_batch_sizes(micro_batches, max_batch)
    return _best_candidate(cands, micro_batches, min_chips, max_chips,
                           prefer_larger)


def get_compatible_chips_v02(micro_batches: Sequence[int], max_batch: int,
                             current_num_chips: int,
                             min_chips: int = 1,
                             max_chips: Optional[int] = None,
                             prefer_larger: bool = True,
                             chips_per_host: int = 1,
                             model_parallel_size: int = 1
                             ) -> Tuple[int, List[int], Optional[int]]:
    """v0.2: host-granular + model-parallel aware.

    Chips are allocated in whole hosts; each host contributes
    ``chips_per_host // model_parallel_size`` data-parallel ranks.  Solves
    v0.1 at host granularity, then maps back to DP world sizes.  If the
    *current* allocation (``current_num_chips > 0``) is not in the valid
    set, falls back to the largest batch reachable at the current DP size
    (so a degraded pod still trains); ``current_num_chips == 0`` means "no
    current allocation" and just returns the valid set.
    """
    if chips_per_host % model_parallel_size:
        raise ElasticityError(
            f"chips per host {chips_per_host} must be divisible by "
            f"model parallel size {model_parallel_size}")
    dp_per_host = chips_per_host // model_parallel_size
    min_chips = min_chips or 1
    max_chips = max_chips or max_batch // min(micro_batches) * chips_per_host
    # host bounds must stay inside [min_chips, max_chips]: round the lower
    # bound UP and reject a ceiling smaller than one host
    min_hosts = -(-min_chips // chips_per_host)
    max_hosts = max_chips // chips_per_host
    if max_hosts < 1:
        raise ElasticityConfigError(
            f"max_gpus {max_chips} is smaller than one host "
            f"({chips_per_host} chips)")

    host_batch, valid_hosts = get_compatible_chips_v01(
        micro_batches,
        max_batch // dp_per_host,
        min_hosts,
        max_hosts,
        prefer_larger=prefer_larger)
    final_batch = host_batch * dp_per_host
    valid_dp = [h * dp_per_host for h in valid_hosts]

    def pick_micro(batch: int, dp: int) -> Optional[int]:
        choice = None
        for micro in micro_batches:
            if dp and batch // dp % micro == 0:
                if choice is None or (prefer_larger and micro > choice):
                    choice = micro
        return choice

    current_dp = current_num_chips // model_parallel_size
    if current_num_chips == 0 or current_dp in valid_dp:
        micro = pick_micro(final_batch, current_dp) if current_dp else None
        return final_batch, valid_dp, micro

    # degraded path: keep current allocation, maximize batch under the cap
    cands = [micro * current_dp * (max_batch // (micro * current_dp))
             for micro in micro_batches if micro * current_dp <= max_batch]
    if not cands:
        raise ElasticityIncompatibleWorldSize(
            f"no batch fits {current_num_chips} chips under {max_batch}")
    batch = max(cands) if prefer_larger else min(cands)
    return batch, [current_dp], pick_micro(batch, current_dp)


def elasticity_enabled(ds_config: Dict) -> bool:
    return bool(ds_config.get("elasticity", {}).get("enabled", False))


def compute_elastic_config(ds_config: Dict, world_size: int = 0,
                           return_microbatch: bool = False):
    """Public API (reference ``elasticity.py:233``): resolve
    ``(final_batch_size, valid_chip_counts[, micro_batch])`` from a config
    containing an ``"elasticity"`` block.  Deterministic for a given config
    so the scheduler and the runtime agree."""
    block = ds_config.get("elasticity")
    if block is None:
        raise ElasticityConfigError("'elasticity' missing from config")
    if not block.get("enabled", False):
        raise ElasticityConfigError("elasticity is disabled in config")
    cfg = ElasticityConfig.from_dict(block)

    if cfg.model_parallel_size > 1 and cfg.version < 0.2:
        raise ElasticityConfigError(
            "model-parallel elasticity requires version 0.2")

    micro_batch: Optional[int] = None
    if cfg.version >= 0.2:
        final_batch, valid, micro_batch = get_compatible_chips_v02(
            cfg.micro_batches, cfg.max_acceptable_batch_size,
            current_num_chips=world_size,
            min_chips=cfg.min_gpus, max_chips=cfg.max_gpus,
            prefer_larger=cfg.prefer_larger_batch,
            chips_per_host=cfg.num_gpus_per_node,
            model_parallel_size=cfg.model_parallel_size)
    else:
        final_batch, valid = get_compatible_chips_v01(
            cfg.micro_batches, cfg.max_acceptable_batch_size,
            cfg.min_gpus, cfg.max_gpus,
            prefer_larger=cfg.prefer_larger_batch)

    if world_size > 0:
        dp = world_size // cfg.model_parallel_size
        if dp not in valid:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} (dp={dp}) not in valid set {valid}")
        if micro_batch is None:
            for micro in sorted(cfg.micro_batches,
                                reverse=cfg.prefer_larger_batch):
                if final_batch // dp % micro == 0:
                    micro_batch = micro
                    break

    logger.info("elastic config: batch=%d valid_chips=%s micro=%s",
                final_batch, valid, micro_batch)
    if return_microbatch or world_size > 0:
        return final_batch, valid, micro_batch
    return final_batch, valid


def usable_chip_count(ds_config: Dict, available_chips: int) -> int:
    """Largest valid *chip* count not exceeding ``available_chips``.

    Shared by the launcher's elastic host resolution and the elastic agent
    so both always agree.  ``compute_elastic_config`` returns valid sizes
    in DP-rank units; with model parallelism each DP rank spans ``mp``
    chips.
    """
    _, valid = compute_elastic_config(ds_config)
    mp = ElasticityConfig.from_dict(ds_config["elasticity"]).model_parallel_size
    usable = max((v * mp for v in valid if v * mp <= available_chips),
                 default=0)
    if usable == 0:
        raise ElasticityIncompatibleWorldSize(
            f"{available_chips} chips available but valid chip counts are "
            f"{[v * mp for v in valid]}")
    return usable
