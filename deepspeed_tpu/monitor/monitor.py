"""Monitoring backends (reference ``deepspeed/monitor/``: MonitorMaster
fanning out write_events to TensorBoard / WandB / CSV / Comet writers)."""

from __future__ import annotations

import csv
import os
from typing import Any, List, Tuple

import jax

from ..utils.logging import logger

Event = Tuple[str, Any, int]  # (tag, value, step)


class Monitor:
    def __init__(self, config):
        self.enabled = config.enabled

    def write_events(self, event_list: List[Event]) -> None:
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if self.enabled and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter
                log_dir = os.path.join(config.output_path or "./runs", config.job_name)
                self.summary_writer = SummaryWriter(log_dir=log_dir)
            except Exception as e:
                logger.warning("tensorboard unavailable: %s", e)
                self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if self.summary_writer is None:
            return
        for tag, value, step in event_list:
            self.summary_writer.add_scalar(tag, float(value), int(step))
        self.summary_writer.flush()


class CSVMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.output_path = config.output_path or "./csv_monitor"
        self.job_name = config.job_name
        # tag -> (file handle, csv.writer): one open append handle per
        # tag for the life of the monitor (an open+close per EVENT was
        # the dominant cost of a steps_per_print flush), flushed once
        # per write_events batch
        self._files = {}

    def _writer(self, tag: str):
        entry = self._files.get(tag)
        if entry is None:
            fname = os.path.join(self.output_path, self.job_name,
                                 tag.replace("/", "_") + ".csv")
            os.makedirs(os.path.dirname(fname), exist_ok=True)
            new = not os.path.exists(fname) or os.path.getsize(fname) == 0
            f = open(fname, "a", newline="")
            w = csv.writer(f)
            if new:
                w.writerow(["step", tag])
            entry = self._files[tag] = (f, w)
        return entry

    def write_events(self, event_list: List[Event]) -> None:
        if not self.enabled or jax.process_index() != 0:
            return
        touched = []
        for tag, value, step in event_list:
            f, w = self._writer(tag)
            w.writerow([int(step), float(value)])
            touched.append(f)
        for f in touched:
            f.flush()

    def close(self) -> None:
        for f, _ in self._files.values():
            f.close()
        self._files.clear()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self._wandb = None
        if self.enabled and jax.process_index() == 0:
            try:
                import wandb
                wandb.init(project=config.project or "deepspeed_tpu",
                           group=config.group or None, team=config.team or None)
                self._wandb = wandb
            except Exception as e:
                logger.warning("wandb unavailable: %s", e)
                self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if self._wandb is None:
            return
        for tag, value, step in event_list:
            self._wandb.log({tag: value}, step=int(step))


class CometMonitor(Monitor):
    """Comet experiment writer (reference monitor/comet.py CometMonitor);
    gated import — comet_ml is not in the image, so this degrades to
    disabled with a warning rather than failing."""

    def __init__(self, config):
        super().__init__(config)
        self._exp = None
        if self.enabled and jax.process_index() == 0:
            try:
                import comet_ml
                self._exp = comet_ml.Experiment(
                    project_name=config.project or "deepspeed_tpu",
                    workspace=config.team or None)
                if config.job_name:
                    self._exp.set_name(config.job_name)
            except Exception as e:
                logger.warning("comet_ml unavailable: %s", e)
                self.enabled = False

    @property
    def experiment(self):
        return self._exp

    def write_events(self, event_list: List[Event]) -> None:
        if self._exp is None:
            return
        for tag, value, step in event_list:
            self._exp.log_metric(tag, value, step=int(step))


class MonitorMaster(Monitor):
    """Fan-out master (reference monitor/monitor.py:30)."""

    def __init__(self, ds_config):
        self.monitors: List[Monitor] = []
        if ds_config.tensorboard.enabled:
            self.monitors.append(TensorBoardMonitor(ds_config.tensorboard))
        if ds_config.csv_monitor.enabled:
            self.monitors.append(CSVMonitor(ds_config.csv_monitor))
        if ds_config.wandb.enabled:
            self.monitors.append(WandbMonitor(ds_config.wandb))
        if ds_config.comet.enabled:
            self.monitors.append(CometMonitor(ds_config.comet))
        self.enabled = any(m.enabled for m in self.monitors)

    def write_events(self, event_list: List[Event]) -> None:
        for m in self.monitors:
            if m.enabled:
                m.write_events(event_list)

    def write_registry_snapshot(self, step: int) -> None:
        """Publish the telemetry registry's ``snapshot()`` through every
        enabled writer under ``Telemetry/<metric>`` tags — the SAME
        names (and values) the /metrics endpoint and bench.py read, so
        monitor artifacts stop being a fifth metrics namespace.  Called
        by the engine at the ``steps_per_print`` cadence.  Metrics that
        have never recorded anything (zero counters, never-observed
        histograms, unbound/unset gauges) are skipped — a training-only
        process does not fan out ~40 all-zero serving series per flush."""
        if not self.enabled:
            return
        from ..telemetry import Counter, Gauge, Histogram, get_registry
        events: List[Event] = []
        for name, m in sorted(get_registry().all_metrics().items()):
            if isinstance(m, Histogram):
                if m.count == 0:
                    continue
                events += [(f"Telemetry/{name}_p50", m.percentile(50), step),
                           (f"Telemetry/{name}_p90", m.percentile(90), step),
                           (f"Telemetry/{name}_p99", m.percentile(99), step),
                           (f"Telemetry/{name}_count", m.count, step),
                           (f"Telemetry/{name}_mean", m.mean, step)]
            elif isinstance(m, Counter):
                if m.value:
                    events.append((f"Telemetry/{name}", m.value, step))
            elif isinstance(m, Gauge):
                if m.touched:
                    events.append((f"Telemetry/{name}", m.value, step))
        self.write_events(events)
