"""Compression primitives: fake quantization + pruning masks.

TPU-native analogue of ``deepspeed/compression/basic_layer.py`` (121:
``LinearLayer_Compress`` et al.) and ``compression/utils.py``.  The
reference swaps ``nn.Linear`` for subclasses that quantize/prune inside
``forward``; in a functional world the same math is a *transform over the
param tree* applied at schedule boundaries — XLA folds the (de)quant into
the surrounding program, which is exactly what the reference's
``quantizer_kernel`` flag tried to buy.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


# ----------------------------------------------------------- quantization

def quantize_weight(w: jax.Array, bits: int, symmetric: bool = True,
                    per_channel: bool = True) -> jax.Array:
    """Fake (quant-dequant) weight quantization to ``bits``.

    per_channel: scales per output channel (last dim) — the reference's
    ``weight_quantize_in_forward`` group-wise path with one group/channel.
    """
    if bits >= 32:
        return w
    axis = tuple(range(w.ndim - 1)) if per_channel and w.ndim > 1 else None
    if symmetric:
        qmax = 2.0 ** (bits - 1) - 1
        scale = jnp.max(jnp.abs(w), axis=axis, keepdims=True) / qmax
        scale = jnp.where(scale == 0, 1.0, scale)
        q = jnp.clip(jnp.round(w / scale), -qmax - 1, qmax)
        return (q * scale).astype(w.dtype)
    qmax = 2.0 ** bits - 1
    lo = jnp.min(w, axis=axis, keepdims=True)
    hi = jnp.max(w, axis=axis, keepdims=True)
    scale = jnp.where(hi > lo, (hi - lo) / qmax, 1.0)
    q = jnp.clip(jnp.round((w - lo) / scale), 0, qmax)
    return (q * scale + lo).astype(w.dtype)


def quantize_activation(x: jax.Array, bits: int,
                        symmetric: bool = True) -> jax.Array:
    """Dynamic per-tensor activation fake-quant (``activation_quantization``
    with ``range_calibration: dynamic``)."""
    return quantize_weight(x, bits, symmetric=symmetric, per_channel=False)


# ---------------------------------------------------------------- pruning

def magnitude_mask(w: jax.Array, dense_ratio: float) -> jax.Array:
    """Unstructured magnitude mask keeping the top ``dense_ratio`` weights
    (``sparse_pruning`` method l1/topk)."""
    k = max(1, int(round(dense_ratio * w.size)))
    thresh = jnp.sort(jnp.abs(w).ravel())[-k]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def row_mask(w: jax.Array, dense_ratio: float) -> jax.Array:
    """Row (output-channel) mask by L1 norm (``row_pruning``): rows live on
    the LAST dim in the jax [in, out] layout."""
    norms = jnp.sum(jnp.abs(w), axis=tuple(range(w.ndim - 1)))
    k = max(1, int(round(dense_ratio * norms.size)))
    thresh = jnp.sort(norms)[-k]
    keep = (norms >= thresh).astype(w.dtype)
    return jnp.broadcast_to(keep, w.shape)


def head_mask(w: jax.Array, num_heads: int,
              dense_ratio: float) -> jax.Array:
    """Attention-head mask (``head_pruning``) for [.., heads*dim] weights:
    score heads by L1, keep the strongest fraction."""
    out = w.shape[-1]
    if out % num_heads:
        raise ValueError(f"out dim {out} not divisible by {num_heads} heads")
    hd = out // num_heads
    grouped = w.reshape((-1, num_heads, hd))
    norms = jnp.sum(jnp.abs(grouped), axis=(0, 2))
    k = max(1, int(round(dense_ratio * num_heads)))
    thresh = jnp.sort(norms)[-k]
    keep = (norms >= thresh).astype(w.dtype)  # [heads]
    return jnp.broadcast_to(
        jnp.repeat(keep, hd), w.shape[:-1] + (out,))


def channel_mask(w: jax.Array, dense_ratio: float) -> jax.Array:
    """Input-channel mask (``channel_pruning``): channels = dim -2."""
    if w.ndim < 2:
        return jnp.ones_like(w)
    reduce_axes = tuple(i for i in range(w.ndim) if i != w.ndim - 2)
    norms = jnp.sum(jnp.abs(w), axis=reduce_axes)
    k = max(1, int(round(dense_ratio * norms.size)))
    thresh = jnp.sort(norms)[-k]
    keep = (norms >= thresh).astype(w.dtype)
    return jnp.broadcast_to(keep[:, None], w.shape)


def apply_mask(w: jax.Array, mask: Optional[jax.Array]) -> jax.Array:
    return w if mask is None else w * mask


def compress_rows(w: jax.Array, mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Physically drop fully-masked output channels (``redundancy_clean``):
    returns (smaller array, kept-index vector)."""
    keep_vec = mask.reshape((-1, mask.shape[-1]))[0] > 0
    idx = jnp.where(keep_vec)[0]
    return jnp.take(w, idx, axis=-1), idx
