"""Compression (reference ``deepspeed/compression/``)."""

from .compress import (  # noqa: F401
    CompressionManager,
    CompressionScheduler,
    init_compression,
)
from .utils import (  # noqa: F401
    apply_mask,
    channel_mask,
    compress_rows,
    head_mask,
    magnitude_mask,
    quantize_activation,
    quantize_weight,
    row_mask,
)
