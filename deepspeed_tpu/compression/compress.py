"""Compression manager: scheduled layer-group compression.

TPU-native analogue of ``deepspeed/compression/compress.py``
(``init_compression`` / ``redundancy_clean``) + ``compression/scheduler.py``
(``CompressionScheduler`` drives per-group ``schedule_offset``).

Config shape mirrors the reference (``compression_training`` block)::

    {"weight_quantization": {
        "shared_parameters": {"enabled": true, "schedule_offset": 100},
        "different_groups": {
            "wq1": {"params": {"start_bits": 8, "target_bits": 4,
                               "quantization_period": 50},
                    "modules": ["attn", "mlp"]}}},
     "sparse_pruning": {...}, "row_pruning": {...},
     "head_pruning": {...}, "channel_pruning": {...}}

``modules`` entries are substring/regex patterns over param paths (the
reference matches nn.Module names).  The manager computes masks/quant
transforms once past each group's schedule offset and applies them to the
param tree at gradient-accumulation boundaries; masks are cached so
pruning decisions are sticky (reference behavior after mask creation).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist, logger
from . import utils as U

KINDS = ("weight_quantization", "activation_quantization", "sparse_pruning",
         "row_pruning", "head_pruning", "channel_pruning")


@dataclass
class CompressionGroup:
    kind: str
    name: str
    patterns: List[str]
    params: Dict[str, Any]
    schedule_offset: int
    matched: List[Tuple[int, str]] = field(default_factory=list)
    masks: Dict[int, jax.Array] = field(default_factory=dict)

    def matches(self, path: str) -> bool:
        return any(re.search(p, path) for p in self.patterns)

    def current_bits(self, global_step: int) -> int:
        """Progressive bit reduction (start_bits -> target_bits every
        quantization_period steps, reference quantize scheduler)."""
        start = int(self.params.get("start_bits", 8))
        target = int(self.params.get("target_bits", start))
        period = int(self.params.get("quantization_period", 1))
        if global_step < self.schedule_offset:
            return 32
        # halve toward target each period
        steps = (global_step - self.schedule_offset) // max(1, period)
        bits = start
        for _ in range(steps):
            if bits <= target:
                break
            bits = max(target, bits // 2 if bits > target * 2 else target)
        return bits


def _path_str(path) -> str:
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", p))))
    return ".".join(parts)


class CompressionScheduler:
    """Step-driven trigger (reference ``compression/scheduler.py``)."""

    def __init__(self, manager: "CompressionManager"):
        self.manager = manager

    def step(self, params: Any, global_step: int) -> Any:
        return self.manager.apply(params, global_step)


class CompressionManager:
    def __init__(self, config: Dict[str, Any], abstract_params: Any):
        self.groups: List[CompressionGroup] = []
        self._jit_cache: Dict[Tuple, Callable] = {}
        flat, self._treedef = jax.tree_util.tree_flatten_with_path(
            abstract_params)
        self._paths = [_path_str(p) for p, _ in flat]
        for kind in KINDS:
            block = config.get(kind) or {}
            if hasattr(block, "items") and not isinstance(block, dict):
                block = dict(block)
            shared = block.get("shared_parameters", {})
            if not shared.get("enabled", False):
                continue
            offset = int(shared.get("schedule_offset", 0))
            for name, group in block.get("different_groups", {}).items():
                cg = CompressionGroup(
                    kind=kind, name=name,
                    patterns=[str(m) for m in group.get("modules", [".*"])],
                    params=dict(group.get("params", {})),
                    schedule_offset=int(group.get(
                        "schedule_offset", offset)))
                cg.matched = [(i, p) for i, p in enumerate(self._paths)
                              if cg.matches(p)]
                if not cg.matched:
                    logger.warning("compression group %s/%s matched no "
                                   "parameters (patterns %s)", kind, name,
                                   cg.patterns)
                self.groups.append(cg)
        self.param_groups = [g for g in self.groups
                             if g.kind != "activation_quantization"]
        self.act_groups = [g for g in self.groups
                           if g.kind == "activation_quantization"]
        if self.act_groups:
            logger.warning(
                "activation_quantization is a FORWARD hook: the model must "
                "call CompressionManager.act_quant(x, step) on the "
                "activations it wants quantized — it does not alter params")
        if self.groups:
            log_dist(f"compression: {len(self.groups)} group(s) over "
                     f"{sum(len(g.matched) for g in self.groups)} param "
                     f"tensors", ranks=[0])

    def min_param_offset(self) -> int:
        return min((g.schedule_offset for g in self.param_groups), default=0)

    # ----------------------------------------------------- act-quant hook
    def act_quant(self, x: jax.Array, global_step: int) -> jax.Array:
        """Quantize one activation tensor per the first eligible
        activation_quantization group (model-forward hook)."""
        for g in self.act_groups:
            if global_step >= g.schedule_offset:
                return U.quantize_activation(
                    x, int(g.params.get("bits", 8)),
                    symmetric=g.params.get("symmetric", True))
        return x

    # ------------------------------------------------------------- apply
    def _ensure_masks(self, flat: List[Any], active) -> None:
        for g in active:
            if g.kind == "weight_quantization":
                continue
            ratio = float(g.params.get("dense_ratio", 0.5))
            for i, _ in g.matched:
                leaf = flat[i]
                if i in g.masks or not hasattr(leaf, "dtype") or \
                        not jnp.issubdtype(leaf.dtype, jnp.floating):
                    continue
                if g.kind == "sparse_pruning":
                    g.masks[i] = U.magnitude_mask(leaf, ratio)
                elif g.kind == "row_pruning":
                    g.masks[i] = U.row_mask(leaf, ratio)
                elif g.kind == "head_pruning":
                    g.masks[i] = U.head_mask(
                        leaf, int(g.params.get("num_heads", 1)), ratio)
                elif g.kind == "channel_pruning":
                    g.masks[i] = U.channel_mask(leaf, ratio)

    def apply(self, params: Any, global_step: int) -> Any:
        """Compressed view of ``params``: one jit-compiled projection per
        (group, bits) signature — the per-step hot path dispatches ONE
        compiled program, not per-leaf eager ops."""
        active = [g for g in self.param_groups
                  if global_step >= g.schedule_offset]
        if not active:
            return params
        flat, treedef = jax.tree.flatten(params)
        self._ensure_masks(flat, active)

        key = tuple((g.kind, g.name, g.current_bits(global_step))
                    for g in active)
        fn = self._jit_cache.get(key)
        if fn is None:
            # static plan: (leaf index, op, bits, mask slot)
            plan: List[Tuple[int, str, int, int]] = []
            n_masks = 0
            mask_order: List[Tuple[Any, int]] = []
            for g in active:
                bits = g.current_bits(global_step)
                symmetric = bool(g.params.get("symmetric", True))
                for i, _ in g.matched:
                    if not hasattr(flat[i], "dtype") or \
                            not jnp.issubdtype(flat[i].dtype, jnp.floating):
                        continue
                    if g.kind == "weight_quantization":
                        plan.append((i, "q" if symmetric else "qa", bits, -1))
                    elif i in g.masks:
                        plan.append((i, "m", 0, n_masks))
                        mask_order.append((g, i))
                        n_masks += 1

            def project(flat_in, masks):
                out = list(flat_in)
                for i, op, bits, mi in plan:
                    if op == "m":
                        out[i] = out[i] * masks[mi]
                    else:
                        out[i] = U.quantize_weight(out[i], bits,
                                                   symmetric=op == "q")
                return out

            fn = (jax.jit(project), mask_order)
            self._jit_cache[key] = fn
        jit_fn, mask_order = fn
        masks = [g.masks[i] for g, i in mask_order]
        flat = jit_fn(flat, masks)
        return jax.tree.unflatten(treedef, list(flat))

    # --------------------------------------------------------- clean-up
    def redundancy_clean(self, params: Any) -> Any:
        """Physically shrink row-pruned tensors (reference
        ``redundancy_clean``): fully-zero output channels are dropped."""
        flat, treedef = jax.tree.flatten(params)
        for g in self.groups:
            if g.kind != "row_pruning":
                continue
            for i, _ in g.matched:
                mask = g.masks.get(i)
                if mask is not None:
                    flat[i], _ = U.compress_rows(flat[i], mask)
        return jax.tree.unflatten(treedef, flat)


def init_compression(config: Dict[str, Any], abstract_params: Any
                     ) -> CompressionManager:
    """Reference ``init_compression`` entry point."""
    return CompressionManager(config, abstract_params)
