from .tensor_logger import TensorLogger, tap, diff_logs, record_active  # noqa: F401
