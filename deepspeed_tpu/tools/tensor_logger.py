"""Per-step named-tensor capture for cross-run diffing.

Reference: ``deepspeed/tools/tensor_logger/tensor_logger.py:16``
(``TensorLogger`` — nn.Module forward/backward hooks recording
activations / gradients / model inputs per iteration, saved to a pickle
for comparing two runs).

TPU-native formulation: there are no module hooks in a functional jitted
program, so capture points are explicit **taps**:

* :func:`tap` — ``x = tap("name", x)`` anywhere inside (or outside)
  jitted code.  Forward records the value under ``fwd_act``; the
  backward pass of the same tap records the cotangent under
  ``bwd_grad`` — the same two streams the reference's hooks capture.
  Host transfer happens via ``jax.debug.callback``, so the tap is a
  no-op in compiled code while no logger is active (the callback body
  checks the active-logger stack).
* :class:`TensorLogger` — iteration windowing (``start_iteration`` /
  ``end_iteration``), ``log_iteration`` context manager, ``save`` to
  ``.npz`` with flat ``it{N}/{stream}/{name}/{i}`` keys.
* :func:`diff_logs` — compare two saved runs, returning per-key max
  abs/rel differences (the cross-run debugging workflow the reference
  tool exists for).

Usage::

    tl = TensorLogger(start_iteration=1, end_iteration=2)
    for it, batch in enumerate(loader):
        with tl.log_iteration(it):
            loss = engine.train_batch(batch)   # fwd/bwd taps record
    tl.save("run_a.npz")
    ...
    print(diff_logs("run_a.npz", "run_b.npz"))
"""

from __future__ import annotations

import collections
import contextlib
import functools
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

# stack of active loggers — taps record into every active logger whose
# iteration window admits the current iteration
_ACTIVE: List["TensorLogger"] = []


def record_active(stream: str, name: str, value) -> None:
    """Record into every active logger whose window admits the current
    iteration — the hook point for engines and taps alike."""
    for tl in _ACTIVE:
        tl._maybe_record(stream, name, value)


_record = record_active  # internal alias used by the tap callbacks


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def tap(name: str, x: jax.Array) -> jax.Array:
    """Identity whose forward records ``fwd_act/name`` and whose backward
    records ``bwd_grad/name`` into the active :class:`TensorLogger`."""
    jax.debug.callback(lambda v: _record("fwd_act", name, np.asarray(v)), x)
    return x


def _tap_fwd(name, x):
    jax.debug.callback(lambda v: _record("fwd_act", name, np.asarray(v)), x)
    return x, None


def _tap_bwd(name, _res, ct):
    jax.debug.callback(lambda v: _record("bwd_grad", name, np.asarray(v)), ct)
    return (ct,)


tap.defvjp(_tap_fwd, _tap_bwd)


class TensorLogger:
    """Iteration-windowed tensor recorder (reference ``TensorLogger``).

    ``end_iteration=0`` disables recording (reference semantics);
    iteration numbers follow the caller's counter.
    """

    def __init__(self, start_iteration: int = 0, end_iteration: int = 0,
                 prefix: Optional[str] = None):
        self.start_iteration = start_iteration
        self.end_iteration = end_iteration
        self.prefix = prefix or "model"
        self.current_iteration = 0
        # data[iteration][stream][name] -> list of arrays (grad-accum
        # steps append; reference keeps lists for the same reason)
        self.data: Dict[int, Dict[str, Dict[str, List[np.ndarray]]]] = \
            collections.defaultdict(
                lambda: collections.defaultdict(
                    lambda: collections.defaultdict(list)))

    # -- iteration control -------------------------------------------------
    def set_iteration(self, iteration: int) -> None:
        self.current_iteration = iteration

    def get_num_recorded_iterations(self) -> int:
        return len(self.data)

    def _window_admits(self) -> bool:
        if self.end_iteration == 0:
            return False
        return (self.start_iteration <= self.current_iteration
                <= self.end_iteration)

    @contextlib.contextmanager
    def log_iteration(self, iteration: int):
        self.current_iteration = iteration
        _ACTIVE.append(self)
        try:
            yield self
        finally:
            _ACTIVE.remove(self)

    def __enter__(self):
        _ACTIVE.append(self)
        return self

    def __exit__(self, *exc):
        _ACTIVE.remove(self)
        return False

    # -- recording ---------------------------------------------------------
    def _maybe_record(self, stream: str, name: str, value: np.ndarray):
        if self._window_admits():
            self.record(stream, name, value)

    def record(self, stream: str, name: str, value) -> None:
        """Direct host-side record (engine uses this for model inputs and
        loss — the reference overloads ``model.forward`` for inputs)."""
        leaves, _ = jax.tree.flatten(value)
        for i, leaf in enumerate(leaves):
            key = name if len(leaves) == 1 else f"{name}.{i}"
            self.data[self.current_iteration][stream][key].append(
                np.asarray(leaf))

    def clear(self) -> None:
        self.data.clear()

    # -- persistence -------------------------------------------------------
    def save(self, filename: str, do_clear: bool = True) -> None:
        flat = {}
        for it, streams in self.data.items():
            for stream, names in streams.items():
                for name, tensors in names.items():
                    for i, t in enumerate(tensors):
                        flat[f"it{it}/{stream}/{self.prefix}.{name}/{i}"] = t
        np.savez_compressed(filename, **flat)
        if do_clear:
            self.clear()


def diff_logs(file_a: str, file_b: str, rtol: float = 1e-5,
              atol: float = 1e-6) -> List[Tuple[str, float, float]]:
    """Compare two saved runs.  Returns ``(key, max_abs, max_rel)`` for
    every key that differs beyond tolerance, plus entries with
    ``max_abs = inf`` for keys present in only one run."""
    a = np.load(file_a)
    b = np.load(file_b)
    out: List[Tuple[str, float, float]] = []
    keys_a, keys_b = set(a.files), set(b.files)
    for k in sorted(keys_a ^ keys_b):
        out.append((k, float("inf"), float("inf")))
    for k in sorted(keys_a & keys_b):
        ta, tb = a[k], b[k]
        if ta.shape != tb.shape:
            out.append((k, float("inf"), float("inf")))
            continue
        ta32 = ta.astype(np.float64)
        tb32 = tb.astype(np.float64)
        absd = np.abs(ta32 - tb32)
        max_abs = float(absd.max()) if absd.size else 0.0
        denom = np.maximum(np.abs(tb32), 1e-12)
        max_rel = float((absd / denom).max()) if absd.size else 0.0
        if max_abs > atol and max_rel > rtol:
            out.append((k, max_abs, max_rel))
    return out
