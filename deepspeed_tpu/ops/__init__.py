from .flash_attention import flash_attention, mha_reference  # noqa: F401
from .fused_optimizer import (fused_adamw, fused_adamw_flat,  # noqa: F401
                              fused_lamb, fused_lamb_flat, fused_lion,
                              fused_lion_flat)
from .normalization import layernorm, rmsnorm  # noqa: F401
from .quantization import (  # noqa: F401
    dequantize_blockwise,
    quantize_blockwise,
    quantize_dequantize,
    quantized_all_gather,
    quantized_psum_scatter,
)
