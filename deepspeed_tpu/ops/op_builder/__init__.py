"""Native host-op build system (reference ``op_builder/``)."""

from .builder import (  # noqa: F401
    ALL_OPS,
    AsyncIOBuilder,
    CPUAdagradBuilder,
    CPUAdamBuilder,
    CPULionBuilder,
    OpBuilder,
    OpBuilderError,
    create_op_builder,
    get_op_builder,
)
