"""JIT build system for native host ops.

TPU-native analogue of ``op_builder/builder.py`` (``OpBuilder`` :108,
``load``/``jit_load`` :491-574).  Differences by design:

* Device compute compiles through XLA/Pallas, so native ops here are *host*
  ops only (offload optimizers, async NVMe I/O) — there is no nvcc stage.
* No pybind11/torch extension machinery: sources compile with ``g++ -shared
  -fPIC`` into a cached ``.so`` keyed by a content hash, loaded via
  :mod:`ctypes` with explicit prototypes.

Builders are named classes resolved through the accelerator
(``op_builder_dir``/``get_op_builder`` seam, reference
``abstract_accelerator.py:271-281``).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import tempfile
from pathlib import Path
from typing import Dict, List, Optional, Type

from ...utils.logging import logger

_REPO_ROOT = Path(__file__).resolve().parents[3]
CSRC_DIR = _REPO_ROOT / "csrc"


def _cache_dir() -> Path:
    root = os.environ.get("DS_TPU_OPS_CACHE",
                          os.path.join(tempfile.gettempdir(), "ds_tpu_ops"))
    path = Path(root)
    path.mkdir(parents=True, exist_ok=True)
    return path


class OpBuilderError(RuntimeError):
    pass


class OpBuilder:
    """Compile-and-load for one named native op."""

    NAME: str = "base"
    # subclasses list .cpp sources relative to csrc/
    SOURCES: List[str] = []

    _loaded: Dict[str, ctypes.CDLL] = {}

    def absolute_sources(self) -> List[Path]:
        return [CSRC_DIR / s for s in self.SOURCES]

    def include_dirs(self) -> List[Path]:
        return [CSRC_DIR / "includes"]

    def cxx_args(self) -> List[str]:
        args = ["-O3", "-std=c++17", "-shared", "-fPIC", "-pthread"]
        if not os.environ.get("DS_TPU_DISABLE_NATIVE_SIMD"):
            args.append("-march=native")
        return args

    def is_compatible(self) -> bool:
        from shutil import which
        return which(self.compiler()) is not None and \
            all(p.is_file() for p in self.absolute_sources())

    def compiler(self) -> str:
        return os.environ.get("CXX", "g++")

    # ---------------------------------------------------------------- load
    _compiler_id_cache: Dict[str, str] = {}

    def _compiler_id(self) -> str:
        """Compiler version + host CPU: -march=native binaries are host-
        specific, so a shared cache dir must never serve a mismatched .so
        (SIGILL on an older CPU)."""
        cxx = self.compiler()
        cached = OpBuilder._compiler_id_cache.get(cxx)
        if cached is None:
            try:
                ver = subprocess.run([cxx, "--version"], capture_output=True,
                                     text=True).stdout.splitlines()[0]
            except Exception:
                ver = "unknown"
            cached = ver + "|" + platform.processor() + platform.machine()
            OpBuilder._compiler_id_cache[cxx] = cached
        return cached

    def _hash(self) -> str:
        h = hashlib.sha256()
        for src in self.absolute_sources():
            h.update(src.read_bytes())
        for inc_dir in self.include_dirs():
            for header in sorted(inc_dir.glob("*.h")):
                h.update(header.read_bytes())
        h.update(" ".join(self.cxx_args()).encode())
        h.update(self._compiler_id().encode())
        return h.hexdigest()[:16]

    def so_path(self) -> Path:
        return _cache_dir() / f"{self.NAME}_{self._hash()}.so"

    def build(self) -> Path:
        out = self.so_path()
        if out.is_file():
            return out
        cmd = [self.compiler(), *self.cxx_args()]
        for inc in self.include_dirs():
            cmd.append(f"-I{inc}")
        cmd += [str(s) for s in self.absolute_sources()]
        tmp_out = out.with_suffix(f".tmp{os.getpid()}.so")
        cmd += ["-o", str(tmp_out)]
        logger.info("building native op %s: %s", self.NAME, " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise OpBuilderError(
                f"native build of {self.NAME} failed:\n{proc.stderr}")
        os.replace(tmp_out, out)  # atomic under concurrent builders
        return out

    def load(self) -> ctypes.CDLL:
        if self.NAME in OpBuilder._loaded:
            return OpBuilder._loaded[self.NAME]
        if not self.is_compatible():
            raise OpBuilderError(
                f"op {self.NAME} is not buildable here (missing compiler "
                f"or sources)")
        lib = ctypes.CDLL(str(self.build()))
        self._annotate(lib)
        OpBuilder._loaded[self.NAME] = lib
        return lib

    def _annotate(self, lib: ctypes.CDLL) -> None:
        """Attach argtypes/restype prototypes. Subclasses override."""


_f32p = ctypes.POINTER(ctypes.c_float)


class CPUAdamBuilder(OpBuilder):
    """Reference ``op_builder/cpu_adam.py`` / ``csrc/adam/cpu_adam.cpp``."""
    NAME = "cpu_adam"
    SOURCES = ["adam/cpu_adam.cpp"]

    def _annotate(self, lib):
        lib.ds_cpu_adam_step.argtypes = [
            _f32p, _f32p, _f32p, _f32p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_int,
        ]
        lib.ds_cpu_adam_step.restype = None
        lib.ds_simd_width.restype = ctypes.c_int


class CPUAdagradBuilder(OpBuilder):
    NAME = "cpu_adagrad"
    SOURCES = ["adagrad/cpu_adagrad.cpp"]

    def _annotate(self, lib):
        lib.ds_cpu_adagrad_step.argtypes = [
            _f32p, _f32p, _f32p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ]
        lib.ds_cpu_adagrad_step.restype = None


class CPULionBuilder(OpBuilder):
    NAME = "cpu_lion"
    SOURCES = ["lion/cpu_lion.cpp"]

    def _annotate(self, lib):
        lib.ds_cpu_lion_step.argtypes = [
            _f32p, _f32p, _f32p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ]
        lib.ds_cpu_lion_step.restype = None


class AsyncIOBuilder(OpBuilder):
    """Reference ``op_builder/async_io.py`` / ``csrc/aio/``."""
    NAME = "async_io"
    SOURCES = ["aio/ds_aio.cpp"]

    def _annotate(self, lib):
        lib.ds_aio_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.ds_aio_create.restype = ctypes.c_void_p
        lib.ds_aio_create2.argtypes = [ctypes.c_int, ctypes.c_int,
                                       ctypes.c_int, ctypes.c_int]
        lib.ds_aio_create2.restype = ctypes.c_void_p
        lib.ds_aio_destroy.argtypes = [ctypes.c_void_p]
        lib.ds_aio_destroy.restype = None
        for fn in (lib.ds_aio_pread, lib.ds_aio_pwrite):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                           ctypes.c_int64, ctypes.c_int64]
            fn.restype = ctypes.c_int64
        lib.ds_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ds_aio_wait.restype = ctypes.c_int64
        lib.ds_aio_wait_all.argtypes = [ctypes.c_void_p]
        lib.ds_aio_wait_all.restype = ctypes.c_int64


class NotImplementedBuilder(OpBuilder):
    """Stub for ops that are intentionally absent on TPU (reference
    ``op_builder/hpu/no_impl.py`` — the registry stays honest about what
    is out of scope instead of failing with a missing-name KeyError)."""
    NAME = "no_impl"
    SOURCES: List[str] = []
    REASON = "not implemented on TPU"

    def is_compatible(self) -> bool:
        return False

    def build(self):  # pragma: no cover - trivial
        raise OpBuilderError(f"op {self.NAME!r}: {self.REASON}")

    def load(self):
        raise OpBuilderError(f"op {self.NAME!r}: {self.REASON}")


class EvoformerAttnBuilder(NotImplementedBuilder):
    """reference csrc/deepspeed4science/evoformer_attn (CUTLASS): out of
    scope (SURVEY §2.5); AlphaFold-style workloads should use the flash
    attention kernel over fused pair activations."""
    NAME = "evoformer_attn"
    REASON = ("DS4Science evoformer CUTLASS kernels are out of scope on "
              "TPU; use ops.flash_attention over pair activations")


class SparseAttnBuilder(NotImplementedBuilder):
    """reference csrc/sparse_attention (triton-era remnant)."""
    NAME = "sparse_attn"
    REASON = ("legacy triton sparse attention is not ported; "
              "sliding-window / ring attention cover the use cases")


class SpatialInferenceBuilder(NotImplementedBuilder):
    """reference csrc/spatial (diffusers bias-add helpers)."""
    NAME = "spatial_inference"
    REASON = "diffusers spatial kernels are not ported; XLA fuses bias-adds"


ALL_OPS: Dict[str, Type[OpBuilder]] = {
    cls.NAME: cls
    for cls in (CPUAdamBuilder, CPUAdagradBuilder, CPULionBuilder,
                AsyncIOBuilder, EvoformerAttnBuilder, SparseAttnBuilder,
                SpatialInferenceBuilder)
}


def get_op_builder(name: str) -> Type[OpBuilder]:
    try:
        return ALL_OPS[name]
    except KeyError:
        raise OpBuilderError(
            f"unknown op builder {name!r}; available: {sorted(ALL_OPS)}")


def create_op_builder(name: str) -> OpBuilder:
    return get_op_builder(name)()
