"""Flash attention — Pallas TPU kernel.

TPU-native replacement for the reference's fused attention kernels
(``csrc/transformer/`` softmax/attention CUDA kernels and the
``blocked_flash`` FastGen path, ``inference/v2/kernels/ragged_ops/``):
blockwise softmax with running max/denominator so the S x S score matrix
never materializes in HBM.

Layout: q, k, v are [B, H, S, D] (callers fold GQA groups into H).
Causal masking skips fully-masked k-blocks.  Backward is the standard
two-kernel flash backward (dkv sweep over q-blocks, dq sweep over
k-blocks) with the delta = rowsum(dO * O) precomputation.

On non-TPU backends (CI) the public entry point falls back to a jnp
reference implementation with identical semantics.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)


def _band_keep(q_idx_base, k_idx_base, block_q, block_k, causal, window):
    """Block-local keep mask for banded (causal / sliding-window)
    attention: q attends k iff q_pos >= k_pos (causal) and
    q_pos - k_pos < window (Mistral (t-window, t] semantics).  Shared by
    all three kernels so the band definition cannot diverge."""
    q_pos = q_idx_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = k_idx_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = jnp.ones((block_q, block_k), bool)
    if causal:
        keep &= q_pos >= k_pos
    if window is not None:
        keep &= (q_pos - k_pos) < window
    return keep


# ---------------------------------------------------------------------------
# reference (and CPU fallback)
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, causal: bool = True, sm_scale: Optional[float] = None,
                  window: Optional[int] = None):
    """[B,H,S,D] attention in fp32 softmax — semantics ground truth.
    ``window``: sliding-window size incl. self (HF Mistral semantics:
    position t attends to (t - window, t])."""
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(d)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    s_q, s_k = scores.shape[-2:]
    mask = jnp.ones((s_q, s_k), bool)
    if causal:
        mask &= jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
    if window is not None:
        q_pos = jnp.arange(s_q)[:, None] + (s_k - s_q)
        k_pos = jnp.arange(s_k)[None, :]
        mask &= (q_pos - k_pos) < window
    if causal or window is not None:
        scores = jnp.where(mask, scores, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_k, seq_k, window):
    q_idx = pl.program_id(2)
    block_q = q_ref.shape[0]
    d = q_ref.shape[1]
    q = q_ref[:]  # [block_q, d]

    num_k = pl.cdiv(seq_k, block_k)
    if causal:
        # highest k block that intersects this q block's diagonal
        num_k = jnp.minimum(num_k, (q_idx + 1) * block_q // block_k
                            + ((q_idx + 1) * block_q % block_k != 0))
    k_lo = jnp.int32(0)
    if window is not None:
        # first k block any row of this q block can see: row 0's window
        # start is q_idx*block_q - window + 1 (blocks below it are fully
        # masked and skipped — the flash win for long sliding-window seqs)
        k_lo = jnp.maximum(
            jnp.int32(0), (q_idx * block_q - window + 1) // block_k)

    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(ki, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[pl.ds(ki * block_k, block_k), :]  # [block_k, d]
        v = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if causal or window is not None:
            s = jnp.where(_band_keep(q_idx * block_q, ki * block_k, block_q,
                                     block_k, causal, window),
                          s, DEFAULT_MASK_VALUE)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m, l, acc = jax.lax.fori_loop(k_lo, num_k, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l).astype(o_ref.dtype)
    lse_ref[:] = (m + jnp.log(l))[:, 0]


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret, window):
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    grid = (b, h, pl.cdiv(s_q, block_q))

    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_k=block_k, seq_k=s_k, window=window)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, s_k, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, s_k, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_q), lambda bi, hi, qi: (bi, hi, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s_q), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, sm_scale, causal, block_q, seq_q,
                    window):
    k_idx = pl.program_id(2)
    block_k = k_ref.shape[0]
    d = k_ref.shape[1]
    k = k_ref[:]
    v = v_ref[:]

    num_q = pl.cdiv(seq_q, block_q)
    q0 = jnp.int32(0)
    if causal:
        q0 = (k_idx * block_k) // block_q  # first q block on/under diagonal
    if window is not None:
        # last q that sees this k block: k_pos_max + window - 1
        q_hi_pos = k_idx * block_k + block_k - 1 + window - 1
        num_q = jnp.minimum(num_q, q_hi_pos // block_q + 1)

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qi * block_q, block_q), :]
        do = do_ref[pl.ds(qi * block_q, block_q), :]
        lse = lse_ref[pl.ds(qi * block_q, block_q)]
        delta = delta_ref[pl.ds(qi * block_q, block_q)]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal or window is not None:
            s = jnp.where(_band_keep(qi * block_q, k_idx * block_k, block_q,
                                     block_k, causal, window),
                          s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse[:, None])  # [bq, bk]
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(q0, num_q, body, (dk0, dv0))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, sm_scale, causal, block_k, seq_k, window):
    q_idx = pl.program_id(2)
    block_q = q_ref.shape[0]
    d = q_ref.shape[1]
    q = q_ref[:]
    do = do_ref[:]
    lse = lse_ref[:]
    delta = delta_ref[:]

    num_k = pl.cdiv(seq_k, block_k)
    if causal:
        num_k = jnp.minimum(num_k, (q_idx + 1) * block_q // block_k
                            + ((q_idx + 1) * block_q % block_k != 0))
    k_lo = jnp.int32(0)
    if window is not None:
        k_lo = jnp.maximum(
            jnp.int32(0), (q_idx * block_q - window + 1) // block_k)

    dq0 = jnp.zeros((block_q, d), jnp.float32)

    def body(ki, dq):
        k = k_ref[pl.ds(ki * block_k, block_k), :]
        v = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal or window is not None:
            s = jnp.where(_band_keep(q_idx * block_q, ki * block_k, block_q,
                                     block_k, causal, window),
                          s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(k_lo, num_k, body, dq0)
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _flash_bwd(res, g, sm_scale, causal, block_q, block_k, interpret, window):
    q, k, v, out, lse = res
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, block_q=block_q, seq_q=s_q,
                                   window=window)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, pl.cdiv(s_k, block_k)),
        in_specs=[
            pl.BlockSpec((None, None, s_q, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, s_q, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, s_q), lambda bi, hi, ki: (bi, hi, 0)),
            pl.BlockSpec((None, None, s_q), lambda bi, hi, ki: (bi, hi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    dq_kernel = functools.partial(_bwd_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, block_k=block_k, seq_k=s_k,
                                  window=window)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, pl.cdiv(s_q, block_q)),
        in_specs=[
            pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, s_k, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, s_k, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_q), lambda bi, hi, qi: (bi, hi, qi)),
            pl.BlockSpec((None, None, block_q), lambda bi, hi, qi: (bi, hi, qi)),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_attention(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                     window):
    out, _ = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                        window)
    return out


def _flash_attention_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                         window):
    out, lse = _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                          window)
    return out, (q, k, v, out, lse)


def _flash_attention_bwd(sm_scale, causal, block_q, block_k, interpret, window,
                         res, g):
    return _flash_bwd(res, g, sm_scale, causal, block_q, block_k, interpret,
                      window)


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 512,
                    block_k: int = 512,
                    interpret: Optional[bool] = None,
                    window: Optional[int] = None) -> jax.Array:
    """Blockwise attention, [B,H,S,D].  GQA callers fold groups into H or
    repeat kv.  Falls back to the jnp reference off-TPU."""
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    if interpret is None:
        backend = jax.default_backend()
        if backend != "tpu":
            return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                                 window=window)
        interpret = False
    return _flash_attention(q, k, v, sm_scale, causal, block_q, block_k,
                            interpret, window)
