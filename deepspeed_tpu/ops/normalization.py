"""RMSNorm / LayerNorm Pallas kernels.

Reference: ``csrc/transformer/inference/csrc/{layer_norm.cu, rms_norm.cu}``
and inference-v2 ``kernels/core_ops/cuda_{layer,rms}_norm`` (incl. the
fused residual-add variants).  One VMEM pass per row block: fp32 moments,
optional fused residual add, cast back to input dtype.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _rmsnorm_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[:] = (x * jax.lax.rsqrt(var + eps)
                * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rmsnorm_res_kernel(x_ref, res_ref, w_ref, o_ref, res_o_ref, *, eps):
    s = x_ref[:].astype(jnp.float32) + res_ref[:].astype(jnp.float32)
    res_o_ref[:] = s.astype(res_o_ref.dtype)
    var = jnp.mean(s * s, axis=-1, keepdims=True)
    o_ref[:] = (s * jax.lax.rsqrt(var + eps)
                * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _layernorm_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    o_ref[:] = ((x - mean) * jax.lax.rsqrt(var + eps)
                * w_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _row_call(kernel, args, out_shapes, d, block_rows, interpret):
    lead = args[0].shape[0]
    block_rows = min(block_rows, lead)
    grid = (pl.cdiv(lead, block_rows),)
    specs = []
    for a in args:
        if a.ndim == 1:  # scale/bias
            specs.append(pl.BlockSpec((d,), lambda i: (0,)))
        else:
            specs.append(pl.BlockSpec((block_rows, d), lambda i: (i, 0)))
    out_specs = [pl.BlockSpec((block_rows, d), lambda i: (i, 0))
                 for _ in out_shapes]
    single = len(out_shapes) == 1
    return pl.pallas_call(
        kernel, grid=grid, in_specs=specs,
        out_specs=out_specs[0] if single else out_specs,
        out_shape=out_shapes[0] if single else out_shapes,
        interpret=interpret)(*args)


def rmsnorm(x: jax.Array, weight: jax.Array, eps: float = 1e-6,
            residual: Optional[jax.Array] = None,
            block_rows: int = 256, interpret: Optional[bool] = None):
    """x: [..., D].  With ``residual``, computes the FastGen fused
    (residual-add -> norm) and returns (normed, new_residual)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    if residual is None:
        out = _row_call(functools.partial(_rmsnorm_kernel, eps=eps),
                        [x2, weight], [jax.ShapeDtypeStruct(x2.shape, x.dtype)],
                        d, block_rows, interpret)
        return out.reshape(shape)
    r2 = residual.reshape(-1, d)
    out, res = _row_call(
        functools.partial(_rmsnorm_res_kernel, eps=eps),
        [x2, r2, weight],
        [jax.ShapeDtypeStruct(x2.shape, x.dtype),
         jax.ShapeDtypeStruct(x2.shape, x.dtype)],
        d, block_rows, interpret)
    return out.reshape(shape), res.reshape(shape)


def layernorm(x: jax.Array, weight: jax.Array, bias: jax.Array,
              eps: float = 1e-5, block_rows: int = 256,
              interpret: Optional[bool] = None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    out = _row_call(functools.partial(_layernorm_kernel, eps=eps),
                    [x2, weight, bias],
                    [jax.ShapeDtypeStruct(x2.shape, x.dtype)],
                    d, block_rows, interpret)
    return out.reshape(shape)
