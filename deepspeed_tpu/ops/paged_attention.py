"""Paged (blocked-KV) attention for ragged inference batches.

TPU-native replacement for the FastGen ragged kernel set
(``inference/v2/kernels/ragged_ops/``: ``blocked_flash`` paged
attention, ``linear_blocked_kv_rotary`` fused KV-write+RoPE,
``logits_gather``).  The CUDA path splits sequences into "atoms" sized
to thread blocks; on TPU the ragged batch is instead padded to a static
``[S, Q]`` grid (see ragged/batch.py) and the three kernels become:

* ``write_kv``        — scatter new K/V into cache pages (null page 0
                        absorbs padding writes, keeping shapes static).
* ``paged_attention`` — gather each slot's pages and run masked GQA
                        attention over ``[S, C]`` context; everything is
                        dense einsum -> MXU, raggedness lives in masks.
* ``gather_last``     — last-token hidden-state gather for logits.

``paged_decode_attention`` is the Pallas ragged kernel: a
``(slot, kv_head, page)`` grid whose BlockSpec index map reads the page
table via scalar prefetch, so each KV page is DMA'd HBM->VMEM exactly
once and the gathered ``[S, C, K, D]`` context never materializes in
HBM.  Q=1 is the classic decode step; Q>1 rows carry prefill chunks
with per-row causal limits, so ONE launch serves a fused mixed
prefill+decode ragged batch (Ragged Paged Attention, arxiv 2604.15464).
The jnp formulation is the semantics ground truth and the CPU/CI path;
``paged_attention`` auto-selects.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# renamed TPUCompilerParams -> CompilerParams across pallas releases
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)

#: largest Q * gqa_groups query block the ragged Pallas kernel accepts
#: before falling back to the jnp gather path (VMEM: the q block and the
#: [rows, page] score tile must fit alongside the fp32 accumulator)
MAX_KERNEL_Q_ROWS = 4096

#: supported serving_optimization.kv_quantization values
KV_QUANT_FORMATS = ("none", "int8")


@jax.tree_util.register_pytree_node_class
class KVPages:
    """Block-scaled int8 KV page store (ISSUE 16): the quantized twin of
    the plain ``[..., page, 2, K, D]`` cache array.

    ``payload`` holds the int8 codes at the fp layout's exact shape;
    ``scale`` is the per-(token, kv-head) fp32 sidecar — one scale per
    ``head_dim`` block (``payload.shape[:-1]``), the EQuARX block
    discipline the comm path already uses.  Per-token scales mean a
    decode append never rescales previously-written content: each
    written row carries its own amax, so pages are immutable after
    write exactly like the fp path (the prefix-sharing contract).

    Registered as a pytree so it rides every existing seam unchanged:
    ``lax.scan`` slices both leaves along the layer axis, ``jit``
    donation donates both, and the engine's opaque ``kv_cache.data``
    threading never looks inside.  ``__getitem__`` mirrors the
    per-layer indexing of the non-scan model path."""

    __slots__ = ("payload", "scale")

    def __init__(self, payload, scale):
        self.payload = payload
        self.scale = scale

    def tree_flatten(self):
        return (self.payload, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __getitem__(self, idx):
        return KVPages(self.payload[idx], self.scale[idx])

    @property
    def shape(self):
        return self.payload.shape

    @property
    def dtype(self):
        return self.payload.dtype

    def __repr__(self):
        return (f"KVPages(payload={self.payload.shape}, "
                f"scale={self.scale.shape})")


def quantize_kv_blocks(kv: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 block quantization over the trailing ``head_dim``
    axis: returns ``(codes int8 [..., D], scales f32 [...])`` with
    ``codes * scales ~= kv``.  Computed in fp32 (a bf16 divide would
    waste code points); an all-zero block gets scale 0 and codes 0."""
    kvf = kv.astype(jnp.float32)
    scale = jnp.max(jnp.abs(kvf), axis=-1) / 127.0            # [...]
    codes = jnp.round(kvf / jnp.maximum(scale, 1e-30)[..., None])
    return (jnp.clip(codes, -127, 127).astype(jnp.int8),
            scale.astype(jnp.float32))


def dequantize_kv_blocks(codes: jax.Array, scale: jax.Array,
                         dtype=jnp.float32) -> jax.Array:
    """Inverse of :func:`quantize_kv_blocks`."""
    return (codes.astype(jnp.float32) * scale[..., None]).astype(dtype)


def token_positions(start_pos: jax.Array, q_len_max: int) -> jax.Array:
    """pos[s, i] = start_pos[s] + i  (int32, [S, Q])."""
    return start_pos[:, None] + jnp.arange(q_len_max, dtype=jnp.int32)[None, :]


def write_kv(kv_layer: jax.Array, k_new: jax.Array, v_new: jax.Array,
             page_table: jax.Array, start_pos: jax.Array,
             q_lens: jax.Array) -> jax.Array:
    """Scatter new KV into the cache pages of one layer.

    kv_layer : [num_pages+1, page_size, 2, K, D] (or :class:`KVPages`)
    k_new/v_new : [S, Q, K, D]
    Returns the updated kv_layer (functional; donate at jit boundary).
    A quantized layer quantizes at append: codes and scales scatter at
    the same (page, slot), so a row is always self-consistent.
    """
    S, Q = k_new.shape[:2]
    quantized = isinstance(kv_layer, KVPages)
    page_size = (kv_layer.payload if quantized else kv_layer).shape[1]
    pos = token_positions(start_pos, Q)                     # [S, Q]
    valid = jnp.arange(Q, dtype=jnp.int32)[None, :] < q_lens[:, None]
    page_idx_in_seq = pos // page_size
    slot = pos % page_size
    pages = jnp.take_along_axis(page_table, page_idx_in_seq, axis=1)
    pages = jnp.where(valid, pages, 0)                      # null page
    pages_f = pages.reshape(-1)
    slot_f = slot.reshape(-1)
    kv_new = jnp.stack([k_new, v_new], axis=2)              # [S,Q,2,K,D]
    if quantized:
        codes, scales = quantize_kv_blocks(kv_new)
        return KVPages(
            kv_layer.payload.at[pages_f, slot_f].set(
                codes.reshape((S * Q,) + codes.shape[2:]), mode="drop"),
            kv_layer.scale.at[pages_f, slot_f].set(
                scales.reshape((S * Q,) + scales.shape[2:]), mode="drop"))
    kv_f = kv_new.reshape((S * Q,) + kv_new.shape[2:]).astype(kv_layer.dtype)
    return kv_layer.at[pages_f, slot_f].set(kv_f, mode="drop")


def paged_attention(q: jax.Array, kv_layer: jax.Array,
                    page_table: jax.Array, start_pos: jax.Array,
                    q_lens: jax.Array, *,
                    sm_scale: float | None = None,
                    use_kernel: Optional[bool] = None,
                    alibi_slopes: Optional[jax.Array] = None,
                    window: Optional[int] = None,
                    interpret: bool = False) -> jax.Array:
    """Masked GQA attention of [S, Q] new tokens over their paged context.

    q        : [S, Q, H, D]    (H = K * groups)
    kv_layer : [num_pages+1, page_size, 2, K, D] (new KV already written)
    Returns  : [S, Q, H, D]

    Ragged buckets route to the Pallas kernel (``use_kernel`` None =
    auto: on TPU, or anywhere with ``interpret=True``) — the kernel
    handles ANY Q with per-query causal limits, so a fused mixed
    prefill+decode step is one kernel launch, not a per-Q-bucket split
    (arxiv 2604.15464's single-kernel ragged serving).  Oversized query
    blocks (Q * groups > ``MAX_KERNEL_Q_ROWS``) and the CPU default fall
    back to the dense-gather jnp path.  ``interpret`` runs the kernel in
    Pallas interpret mode (CPU testing), independent of path selection.
    """
    S, Q, H, D = q.shape
    quantized = isinstance(kv_layer, KVPages)
    kv_arr = kv_layer.payload if quantized else kv_layer
    K_heads = kv_arr.shape[3]
    if use_kernel is None:
        use_kernel = ((interpret or jax.default_backend() == "tpu")
                      and Q * (H // K_heads) <= MAX_KERNEL_Q_ROWS)
    if use_kernel:
        return paged_decode_attention(
            q, kv_layer, page_table, start_pos,
            sm_scale=sm_scale, alibi_slopes=alibi_slopes,
            window=window, interpret=interpret)
    page_size = kv_arr.shape[1]
    K = kv_arr.shape[3]
    G = H // K
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)

    pages = kv_arr[page_table]                  # [S, P, page, 2, K, D]
    if quantized:
        # dequantize the gathered context only — the resident cache
        # stays int8; [S, P, page, 2, K] scales broadcast over D
        pages = dequantize_kv_blocks(
            pages, kv_layer.scale[page_table], dtype=q.dtype)
    P = pages.shape[1]
    C = P * page_size
    k = pages[..., 0, :, :].reshape(S, C, K, D)
    v = pages[..., 1, :, :].reshape(S, C, K, D)

    qg = q.reshape(S, Q, K, G, D)
    scores = jnp.einsum("sqkgd,sckd->skgqc", qg, k).astype(jnp.float32) * scale

    pos = token_positions(start_pos, Q)                     # [S, Q]
    ctx = jnp.arange(C, dtype=jnp.int32)
    if alibi_slopes is not None:
        # ALiBi: per-q-head bias linear in the absolute key position
        # (context row c IS position c — pages fill in order); head
        # h = k*G + g matches the grouped reshape above
        sl = jnp.asarray(alibi_slopes, jnp.float32).reshape(K, G)
        scores = scores + (sl[None, :, :, None, None]
                           * ctx[None, None, None, None, :])
    # context element c visible to query (s, i) iff c <= pos[s, i]; the
    # page gather places context position c at row c of the flattened
    # pages exactly (pages are filled in order).
    mask = ctx[None, None, :] <= pos[:, :, None]            # [S, Q, C]
    if window is not None:  # Mistral sliding window: (pos-window, pos]
        mask &= ctx[None, None, :] > pos[:, :, None] - window
    # null-page / unallocated-page rows beyond the sequence never pass
    # the causal check since pos < allocated capacity * page_size.
    scores = jnp.where(mask[:, None, None, :, :], scores, MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("skgqc,sckd->sqkgd", probs, v)
    return out.reshape(S, Q, H, D)


# ---------------------------------------------------------------------------
# Pallas ragged kernel (any Q: decode rows AND prefill-chunk rows)
# ---------------------------------------------------------------------------

def _decode_kernel(pt_ref, sp_ref, *refs, page_size, num_pages_per_seq,
                   sm_scale, has_alibi, has_scale, window, q_len, groups):
    """One (slot, kv_head, page) grid step of flash-style ragged attention.

    q_ref : [Q*G, D]       (this slot's queries for one kv head; row
                            r = q_idx * G + g, so per-row causal limit
                            ctx_len_r = start_pos + r // G + 1)
    k_ref/v_ref : [page_size, D]  (one cache page, DMA'd via the page
                            table — see the index maps in the caller)
    ks_ref/vs_ref : [page_size, 1]  per-token block scales — present
                            ONLY when ``has_scale`` (quantized int8
                            pages, ISSUE 16): the page dequantizes in
                            VMEM right after its one DMA, so HBM
                            traffic stays int8-sized
    slopes_ref : [1, G]    per-q-head ALiBi slopes — present ONLY when
                            ``has_alibi`` (the kernel is specialized
                            statically so non-ALiBi models pay nothing)
    Q = 1 is the decode specialization; Q > 1 rows are prefill chunks
    whose own new tokens are already in the cache (write_kv runs before
    attention), so the causal mask is exactly the jnp path's
    ``ctx <= pos``.  Rows beyond a slot's q_len compute garbage that the
    caller's logits gather / KV null page ignore.
    Scratch m/l/acc carry the running max / denominator / weighted sum
    across the page axis (the innermost, sequential grid dim).
    """
    rest = list(refs)
    slopes_ref = rest.pop(0) if has_alibi else None
    if has_scale:
        q_ref, k_ref, ks_ref, v_ref, vs_ref = rest[:5]
        o_ref, m_scr, l_scr, acc_scr = rest[5:]
    else:
        ks_ref = vs_ref = None
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = rest
    s = pl.program_id(0)
    p = pl.program_id(2)
    rows = q_len * groups

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # the LAST query row sees the longest context; earlier rows mask
    ctx_len_max = sp_ref[s] + q_len
    page_valid = p * page_size < ctx_len_max
    if window is not None:
        # pages wholly below the FIRST row's window start contribute
        # nothing: skip their DMA compute (the banded-decode analogue of
        # the flash kernel's k_lo bound)
        page_valid &= (p + 1) * page_size > sp_ref[s] + 1 - window

    @pl.when(page_valid)
    def _attend():
        q = q_ref[:]                                   # [Q*G, D]
        if has_scale:
            # block dequant in VMEM: codes [page, D] * scales [page, 1]
            k = (k_ref[:].astype(jnp.float32)
                 * ks_ref[:]).astype(q_ref.dtype)
        else:
            k = k_ref[:]                               # [page, D]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [Q*G, page]
        ctx = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        if has_alibi:  # additive bias linear in the absolute key position
            # row r = q_idx * G + g: split the row dim so the per-head
            # slope is a plain broadcast (Mosaic lowers reshapes and
            # rank-2 iota; it rejects 1-D iota and in-kernel gathers)
            page = scores.shape[1]
            bias = (slopes_ref[0, :][None, :, None]
                    * ctx.astype(jnp.float32).reshape(
                        q_len, groups, page))
            scores = scores + bias.reshape(rows, page)
        # per-row causal limit: row r is query index r // G
        ctx_len = (sp_ref[s] + 1 + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 0) // groups)
        keep = ctx < ctx_len
        if window is not None:
            keep &= ctx >= ctx_len - window
        scores = jnp.where(keep, scores, MASK_VALUE)
        m_prev = m_scr[:]                              # [Q*G, 1]
        l_prev = l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        pexp = jnp.exp(scores - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_prev * alpha + jnp.sum(pexp, axis=1, keepdims=True)
        if has_scale:
            vv = v_ref[:].astype(jnp.float32) * vs_ref[:]  # [page, D]
            acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
                pexp, vv, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
                pexp.astype(v_ref.dtype), v_ref[:],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(p == num_pages_per_seq - 1)
    def _finish():
        o_ref[:] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)
                    ).astype(o_ref.dtype)


def paged_decode_attention(q: jax.Array, kv_layer: jax.Array,
                           page_table: jax.Array, start_pos: jax.Array, *,
                           sm_scale: float | None = None,
                           alibi_slopes: Optional[jax.Array] = None,
                           window: Optional[int] = None,
                           interpret: bool = False) -> jax.Array:
    """Pallas ragged paged attention: [S, Q] queries over paged KV.

    TPU-native counterpart of the reference's blocked_flash atoms
    (``inference/v2/kernels/ragged_ops/atom_builder/`` splits sequences
    into KV blocks per thread block; here the page IS the block and the
    page table drives the BlockSpec index map through scalar prefetch).
    Q = 1 is the classic decode step; Q > 1 rows carry prefill chunks
    with per-row causal limits, so one launch serves a fused mixed
    prefill+decode ragged batch (the single-kernel serving formulation
    of Ragged Paged Attention, arxiv 2604.15464).

    q: [S, Q, H, D]; kv_layer: [num_pages+1, page_size, 2, K, D];
    page_table: [S, P]; start_pos: [S].  Returns [S, Q, H, D].
    """
    S, Q, H, D = q.shape
    has_scale = isinstance(kv_layer, KVPages)
    kv_arr = kv_layer.payload if has_scale else kv_layer
    page_size = kv_arr.shape[1]
    K = kv_arr.shape[3]
    G = H // K
    P_pages = page_table.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)

    # fold GQA per kv head: [S, K, Q*G, D], row r = q_idx * G + g
    qg = q.reshape(S, Q, K, G, D).transpose(0, 2, 1, 3, 4)
    qg = qg.reshape(S, K, Q * G, D)
    has_alibi = alibi_slopes is not None

    grid = (S, K, P_pages)
    # index maps receive (s, k, p, *scalar_prefetch_refs)
    q_spec = pl.BlockSpec((None, None, Q * G, D),
                          lambda s, k, p, pt, sp: (s, k, 0, 0))
    k_spec = pl.BlockSpec((None, page_size, None, None, D),
                          lambda s, k, p, pt, sp: (pt[s, p], 0, 0, k, 0))
    v_spec = pl.BlockSpec((None, page_size, None, None, D),
                          lambda s, k, p, pt, sp: (pt[s, p], 0, 1, k, 0))
    o_spec = pl.BlockSpec((None, None, Q * G, D),
                          lambda s, k, p, pt, sp: (s, k, 0, 0))

    if has_scale:
        # scale sidecar [P+1, page, 2, K] -> [page, 1] block per (p, k):
        # the same page-table indirection as k/v, 2-D refs (Mosaic
        # rejects in-kernel gathers; the BlockSpec DMA does the gather)
        ks_spec = pl.BlockSpec((None, page_size, None, 1),
                               lambda s, k, p, pt, sp: (pt[s, p], 0, 0, k))
        vs_spec = pl.BlockSpec((None, page_size, None, 1),
                               lambda s, k, p, pt, sp: (pt[s, p], 0, 1, k))
        in_specs = [q_spec, k_spec, ks_spec, v_spec, vs_spec]
        inputs = (qg, kv_arr, kv_layer.scale, kv_arr, kv_layer.scale)
    else:
        in_specs = [q_spec, k_spec, v_spec]
        inputs = (qg, kv_arr, kv_arr)
    if has_alibi:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(K, 1, G)
        sl_spec = pl.BlockSpec((None, 1, G),
                               lambda s, k, p, pt, sp: (k, 0, 0))
        in_specs = [sl_spec] + in_specs
        inputs = (slopes,) + inputs

    kernel = functools.partial(
        _decode_kernel, page_size=page_size, num_pages_per_seq=P_pages,
        sm_scale=scale, has_alibi=has_alibi, has_scale=has_scale,
        window=window, q_len=Q, groups=G)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=o_spec,
            scratch_shapes=[
                pltpu.VMEM((Q * G, 1), jnp.float32),
                pltpu.VMEM((Q * G, 1), jnp.float32),
                pltpu.VMEM((Q * G, D), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((S, K, Q * G, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), start_pos.astype(jnp.int32), *inputs)
    out = out.reshape(S, K, Q, G, D).transpose(0, 2, 1, 3, 4)
    return out.reshape(S, Q, H, D)


def rope_write_kv(kv_layer: jax.Array, k_new: jax.Array, v_new: jax.Array,
                  sin: jax.Array, cos: jax.Array, page_table: jax.Array,
                  start_pos: jax.Array, q_lens: jax.Array) -> jax.Array:
    """Fused rotary-embed + cache write (reference
    ``linear_blocked_kv_rotary``, inference/v2/kernels/ragged_ops/
    linear_blocked_kv_copy): one traced region XLA fuses into a single
    rotate-and-scatter, so the rotated K never round-trips HBM."""
    from ..models.transformer import apply_rope
    return write_kv(kv_layer, apply_rope(k_new, sin, cos), v_new,
                    page_table, start_pos, q_lens)


def gather_last(x: jax.Array, q_lens: jax.Array) -> jax.Array:
    """Last valid token's hidden state per slot: [S, Q, E] -> [S, E]
    (reference ``logits_gather`` kernel)."""
    idx = jnp.maximum(q_lens - 1, 0)
    return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]


def attention_reference(q, k_ctx, v_ctx, start_pos, q_lens,
                        window=None) -> jax.Array:
    """Dense ground-truth for tests: same masking over an unpaged
    [S, C, K, D] context."""
    S, Q, H, D = q.shape
    K = k_ctx.shape[2]
    qg = q.reshape(S, Q, K, H // K, D)
    scores = jnp.einsum("sqkgd,sckd->skgqc", qg, k_ctx).astype(jnp.float32)
    scores = scores / np.sqrt(D)
    C = k_ctx.shape[1]
    pos = token_positions(start_pos, Q)
    mask = jnp.arange(C)[None, None, :] <= pos[:, :, None]
    if window is not None:
        mask &= jnp.arange(C)[None, None, :] > pos[:, :, None] - window
    scores = jnp.where(mask[:, None, None, :, :], scores, MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1).astype(v_ctx.dtype)
    out = jnp.einsum("skgqc,sckd->sqkgd", probs, v_ctx)
    return out.reshape(S, Q, H, D)


def paged_context(kv_layer: jax.Array, page_table: jax.Array
                  ) -> Tuple[jax.Array, jax.Array]:
    """Materialize a slot's context (testing helper); a quantized layer
    dequantizes to fp32."""
    if isinstance(kv_layer, KVPages):
        pages = dequantize_kv_blocks(kv_layer.payload[page_table],
                                     kv_layer.scale[page_table])
    else:
        pages = kv_layer[page_table]
    S, P, page_size = pages.shape[:3]
    k = pages[..., 0, :, :].reshape(S, P * page_size, *pages.shape[4:])
    v = pages[..., 1, :, :].reshape(S, P * page_size, *pages.shape[4:])
    return k, v
