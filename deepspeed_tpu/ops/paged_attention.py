"""Paged (blocked-KV) attention for ragged inference batches.

TPU-native replacement for the FastGen ragged kernel set
(``inference/v2/kernels/ragged_ops/``: ``blocked_flash`` paged
attention, ``linear_blocked_kv_rotary`` fused KV-write+RoPE,
``logits_gather``).  The CUDA path splits sequences into "atoms" sized
to thread blocks; on TPU the ragged batch is instead padded to a static
``[S, Q]`` grid (see ragged/batch.py) and the three kernels become:

* ``write_kv``        — scatter new K/V into cache pages (null page 0
                        absorbs padding writes, keeping shapes static).
* ``paged_attention`` — gather each slot's pages and run masked GQA
                        attention over ``[S, C]`` context; everything is
                        dense einsum -> MXU, raggedness lives in masks.
* ``gather_last``     — last-token hidden-state gather for logits.

A Pallas kernel specializes the decode path (Q=1) to avoid
materializing the gathered ``[S, C, K, D]`` context in HBM; the jnp
formulation below is the semantics ground truth and the CPU/CI path.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)


def token_positions(start_pos: jax.Array, q_len_max: int) -> jax.Array:
    """pos[s, i] = start_pos[s] + i  (int32, [S, Q])."""
    return start_pos[:, None] + jnp.arange(q_len_max, dtype=jnp.int32)[None, :]


def write_kv(kv_layer: jax.Array, k_new: jax.Array, v_new: jax.Array,
             page_table: jax.Array, start_pos: jax.Array,
             q_lens: jax.Array) -> jax.Array:
    """Scatter new KV into the cache pages of one layer.

    kv_layer : [num_pages+1, page_size, 2, K, D]
    k_new/v_new : [S, Q, K, D]
    Returns the updated kv_layer (functional; donate at jit boundary).
    """
    S, Q = k_new.shape[:2]
    page_size = kv_layer.shape[1]
    pos = token_positions(start_pos, Q)                     # [S, Q]
    valid = jnp.arange(Q, dtype=jnp.int32)[None, :] < q_lens[:, None]
    page_idx_in_seq = pos // page_size
    slot = pos % page_size
    pages = jnp.take_along_axis(page_table, page_idx_in_seq, axis=1)
    pages = jnp.where(valid, pages, 0)                      # null page
    pages_f = pages.reshape(-1)
    slot_f = slot.reshape(-1)
    kv_new = jnp.stack([k_new, v_new], axis=2)              # [S,Q,2,K,D]
    kv_f = kv_new.reshape((S * Q,) + kv_new.shape[2:]).astype(kv_layer.dtype)
    return kv_layer.at[pages_f, slot_f].set(kv_f, mode="drop")


def paged_attention(q: jax.Array, kv_layer: jax.Array,
                    page_table: jax.Array, start_pos: jax.Array,
                    q_lens: jax.Array, *,
                    sm_scale: float | None = None) -> jax.Array:
    """Masked GQA attention of [S, Q] new tokens over their paged context.

    q        : [S, Q, H, D]    (H = K * groups)
    kv_layer : [num_pages+1, page_size, 2, K, D] (new KV already written)
    Returns  : [S, Q, H, D]
    """
    S, Q, H, D = q.shape
    page_size = kv_layer.shape[1]
    K = kv_layer.shape[3]
    G = H // K
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)

    pages = kv_layer[page_table]                # [S, P, page, 2, K, D]
    P = pages.shape[1]
    C = P * page_size
    k = pages[..., 0, :, :].reshape(S, C, K, D)
    v = pages[..., 1, :, :].reshape(S, C, K, D)

    qg = q.reshape(S, Q, K, G, D)
    scores = jnp.einsum("sqkgd,sckd->skgqc", qg, k).astype(jnp.float32) * scale

    pos = token_positions(start_pos, Q)                     # [S, Q]
    ctx = jnp.arange(C, dtype=jnp.int32)
    # context element c visible to query (s, i) iff c <= pos[s, i]; the
    # page gather places context position c at row c of the flattened
    # pages exactly (pages are filled in order).
    mask = ctx[None, None, :] <= pos[:, :, None]            # [S, Q, C]
    # null-page / unallocated-page rows beyond the sequence never pass
    # the causal check since pos < allocated capacity * page_size.
    scores = jnp.where(mask[:, None, None, :, :], scores, MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("skgqc,sckd->sqkgd", probs, v)
    return out.reshape(S, Q, H, D)


def gather_last(x: jax.Array, q_lens: jax.Array) -> jax.Array:
    """Last valid token's hidden state per slot: [S, Q, E] -> [S, E]
    (reference ``logits_gather`` kernel)."""
    idx = jnp.maximum(q_lens - 1, 0)
    return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]


def attention_reference(q, k_ctx, v_ctx, start_pos, q_lens) -> jax.Array:
    """Dense ground-truth for tests: same masking over an unpaged
    [S, C, K, D] context."""
    S, Q, H, D = q.shape
    K = k_ctx.shape[2]
    qg = q.reshape(S, Q, K, H // K, D)
    scores = jnp.einsum("sqkgd,sckd->skgqc", qg, k_ctx).astype(jnp.float32)
    scores = scores / np.sqrt(D)
    C = k_ctx.shape[1]
    pos = token_positions(start_pos, Q)
    mask = jnp.arange(C)[None, None, :] <= pos[:, :, None]
    scores = jnp.where(mask[:, None, None, :, :], scores, MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1).astype(v_ctx.dtype)
    out = jnp.einsum("skgqc,sckd->sqkgd", probs, v_ctx)
    return out.reshape(S, Q, H, D)


def paged_context(kv_layer: jax.Array, page_table: jax.Array
                  ) -> Tuple[jax.Array, jax.Array]:
    """Materialize a slot's context (testing helper)."""
    pages = kv_layer[page_table]
    S, P, page_size = pages.shape[:3]
    k = pages[..., 0, :, :].reshape(S, P * page_size, *pages.shape[4:])
    v = pages[..., 1, :, :].reshape(S, P * page_size, *pages.shape[4:])
    return k, v
