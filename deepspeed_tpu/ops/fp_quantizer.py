"""Floating-point (FP8 / FP6 / FP4) blockwise quantization.

TPU-native replacement for the reference FP quantizer
(``csrc/fp_quantizer/quantize.cu`` + ``deepspeed/ops/fp_quantizer/
quantize.py:32`` ``FP_Quantize``): symmetric per-block scaling into a
low-precision *floating point* grid, used for weight-only quantized
inference and ZeRO++-style compressed communication.

Where the CUDA path hand-packs 6/12-bit words, TPU v5e+ has native fp8
arithmetic and XLA has native conversions for every ml_dtypes format, so
quantization here is literally ``scale -> convert_element_type`` (RNE in
hardware) and storage is a real fp8/fp4 buffer:

* ``fp8_e4m3`` / ``fp8_e5m2`` — native storage and native dot support.
* ``fp4_e2m1``                — native storage (jnp.float4_e2m1fn)
  when the installed JAX exposes it; else snapped-to-grid fp8 storage.
* ``fp6_e3m2`` / ``fp6_e2m3`` — JAX has no fp6 buffer type; values are
  snapped to the exact fp6 grid but stored as fp8_e4m3 (every fp6 value
  is exactly representable there).  Numerics match the reference's fp6;
  storage is 8 bits rather than the reference's packed 6+12-bit scheme.

Scales are fp32 per block of ``group_size`` elements, chosen so the
block absmax lands on the format's max normal — the same policy as the
reference kernel (q_range / absmax).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# format -> (storage dtype, max normal magnitude, (exp_bits, man_bits))
_FORMATS = {
    "fp8_e4m3": (jnp.float8_e4m3fn, 448.0, (4, 3)),
    "fp8_e5m2": (jnp.float8_e5m2, 57344.0, (5, 2)),
    "fp6_e3m2": (jnp.float8_e4m3fn, 28.0, (3, 2)),
    "fp6_e2m3": (jnp.float8_e4m3fn, 7.5, (2, 3)),
    # storage is native fp4 when this JAX exposes it; otherwise values
    # snap to the exact e2m1 grid but store as fp8_e4m3 (every fp4 value
    # is exactly representable there — same fallback the fp6 formats use)
    "fp4_e2m1": (getattr(jnp, "float4_e2m1fn", jnp.float8_e4m3fn),
                 6.0, (2, 1)),
}

#: formats whose values must be grid-snapped because their storage dtype
#: is WIDER than the format (fp6 always; fp4 when jnp lacks a 4-bit type)
_SNAP_FORMATS = tuple(
    f for f in ("fp6_e3m2", "fp6_e2m3", "fp4_e2m1")
    if f in _FORMATS and jnp.finfo(_FORMATS[f][0]).bits > 6)

#: formats quantize_channelwise/quantize accept (int8 is handled inline)
SUPPORTED_FORMATS = ("int8",) + tuple(_FORMATS)

# reference FP_Quantize keys formats by q_bits (quantize.py:46)
_BITS_TO_FORMAT = {8: "fp8_e4m3", 6: "fp6_e3m2", 12: "fp8_e4m3",
                   4: "fp4_e2m1"}


def _fp6_grid(fmt: str) -> np.ndarray:
    """All non-negative representable values of an fp6 format."""
    exp_bits, man_bits = _FORMATS[fmt][2]
    bias = 2 ** (exp_bits - 1) - 1
    vals = [0.0]
    for e in range(2 ** exp_bits):
        for m in range(2 ** man_bits):
            if e == 0:  # subnormals
                v = (m / 2 ** man_bits) * 2.0 ** (1 - bias)
            else:
                v = (1 + m / 2 ** man_bits) * 2.0 ** (e - bias)
            vals.append(v)
    return np.unique(np.asarray(vals, np.float64)).astype(np.float32)


@functools.lru_cache(maxsize=None)
def _fp6_grid_cached(fmt: str) -> np.ndarray:
    return _fp6_grid(fmt)


def _snap_to_grid(x: jax.Array, grid: np.ndarray) -> jax.Array:
    """Round-to-nearest onto a symmetric grid (sign handled separately)."""
    mags = jnp.asarray(grid)
    mids = jnp.asarray((grid[1:] + grid[:-1]) / 2.0)
    idx = jnp.searchsorted(mids, jnp.abs(x))
    return jnp.sign(x) * mags[idx]


def quantize(x: jax.Array, group_size: int = 512,
             q_bits: Optional[int] = None,
             fmt: str = "fp8_e4m3") -> Tuple[jax.Array, jax.Array, int]:
    """Blockwise FP quantization.

    Returns ``(q, scales, pad)``: q is ``[rows, group_size]`` in the
    format's storage dtype, scales are fp32 ``[rows]`` such that
    ``q * scales`` reconstructs, pad is trailing elements added.
    """
    if q_bits is not None:
        fmt = _BITS_TO_FORMAT[q_bits]
    store_dtype, max_mag, _ = _FORMATS[fmt]
    flat = x.ravel().astype(jnp.float32)
    n = flat.shape[0]
    pad = (-n) % group_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    rows = flat.shape[0] // group_size
    x2 = flat.reshape(rows, group_size)
    absmax = jnp.max(jnp.abs(x2), axis=1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / max_mag
    y = x2 / scale
    if fmt in _SNAP_FORMATS:
        y = _snap_to_grid(y, _fp6_grid_cached(fmt))
    q = y.astype(store_dtype)
    return q, scale[:, 0], pad


def dequantize(q: jax.Array, scales: jax.Array, pad: int, shape,
               dtype=jnp.bfloat16) -> jax.Array:
    out = (q.astype(jnp.float32) * scales[:, None]).ravel()
    if pad:
        out = out[:-pad]
    return out.reshape(shape).astype(dtype)


def selective_dequantize(q: jax.Array, scales: jax.Array,
                         rows: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    """Dequantize only the requested block rows (reference
    ``selective_dequantize``, fp_quantizer/quantize.py:98 — used to fetch
    a slice of a quantized buffer without expanding it all)."""
    return (q[rows].astype(jnp.float32)
            * scales[rows][:, None]).astype(dtype)


def quantize_dequantize(x: jax.Array, group_size: int = 512,
                        q_bits: Optional[int] = None,
                        fmt: str = "fp8_e4m3") -> jax.Array:
    q, s, pad = quantize(x, group_size, q_bits, fmt)
    return dequantize(q, s, pad, x.shape, x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def quantize_dequantize_st(x: jax.Array, group_size: int = 512,
                           fmt: str = "fp8_e4m3") -> jax.Array:
    """Straight-through FP fake-quant: forward snaps to the fp grid,
    gradient passes through — the qwZ-style training-time use."""
    return quantize_dequantize(x, group_size, fmt=fmt)


def _qdq_fwd(x, group_size, fmt):
    return quantize_dequantize(x, group_size, fmt=fmt), None


def _qdq_bwd(group_size, fmt, _res, ct):
    return (ct,)


quantize_dequantize_st.defvjp(_qdq_fwd, _qdq_bwd)


def fp8_einsum(spec: str, x: jax.Array, q: jax.Array, scales: jax.Array,
               pad: int, w_shape, dtype=jnp.bfloat16) -> jax.Array:
    """Matmul against an fp8-quantized weight: dequantize blockwise into
    the contraction — XLA fuses the convert+scale into the MXU feed, so
    the bf16 weight never materializes in HBM (weight-only W8A16)."""
    w = dequantize(q, scales, pad, w_shape, dtype)
    return jnp.einsum(spec, x, w)


def quantize_channelwise(w: jax.Array, fmt: str = "fp8_e4m3",
                         batch_dims: int = 0) -> dict:
    """Weight-only quantization preserving shape: values stored in the
    low-precision dtype, one fp32 scale per last-axis channel (kept with
    singleton reduced dims so ``q * scale`` broadcasts for any rank).

    ``batch_dims`` leading dims (a scan-stacked layers dim, experts)
    each get their own scales rather than sharing one.

    The W8A16/W6A16 layout for inference (reference inference v2
    core_ops FP6 quantized GEMM, ``inference/v2/kernels/core_ops/``):
    the dequant fuses into the consuming matmul's operand feed, so the
    full-precision weight never materializes in HBM — weights stream at
    1 byte/elem (fp8) instead of 2, the lever that matters for
    HBM-bandwidth-bound decode."""
    axes = tuple(range(batch_dims, w.ndim - 1))
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes,
                     keepdims=True)
    if fmt == "int8":
        scale = jnp.maximum(absmax, 1e-12) / 127.0
        q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                     -127, 127).astype(jnp.int8)
        return {"q": q, "scale": scale.astype(jnp.float32)}
    store_dtype, max_mag, _ = _FORMATS[fmt]
    scale = jnp.maximum(absmax, 1e-12) / max_mag
    y = w.astype(jnp.float32) / scale
    if fmt in _SNAP_FORMATS:
        y = _snap_to_grid(y, _fp6_grid_cached(fmt))
    return {"q": y.astype(store_dtype), "scale": scale.astype(jnp.float32)}


def dequantize_channelwise(packed: dict, dtype=jnp.bfloat16) -> jax.Array:
    return (packed["q"].astype(jnp.float32)
            * packed["scale"]).astype(dtype)


class QuantizedTensor:
    """Self-describing quantized buffer: values + scales + original
    shape/dtype.  The reference packs scales into the tail of the int8
    buffer when ``return_meta_tensor=False`` (quantize.py:71); a small
    struct is the honest JAX equivalent."""

    __slots__ = ("q", "scales", "pad", "shape", "dtype")

    def __init__(self, q, scales, pad, shape, dtype):
        self.q, self.scales, self.pad = q, scales, pad
        self.shape, self.dtype = shape, dtype


class FP_Quantize:
    """Object API mirroring reference ``deepspeed.ops.fp_quantizer
    .FP_Quantize`` (quantize.py:32) for drop-in config compatibility."""

    def __init__(self, group_size: int = 512):
        self.group_size = group_size

    def quantize(self, x, q_bits: int = 8, return_meta_tensor: bool = False):
        q, s, pad = quantize(x, self.group_size, q_bits=q_bits)
        if return_meta_tensor:
            return q, s
        return QuantizedTensor(q, s, pad, x.shape, x.dtype)

    def dequantize(self, q, scale=None, q_bits: int = 8, shape=None,
                   dtype=jnp.bfloat16):
        if isinstance(q, QuantizedTensor):
            return dequantize(q.q, q.scales, q.pad, q.shape, q.dtype)
        if scale is None:
            raise ValueError(
                "dequantize needs either a QuantizedTensor (from "
                "quantize(return_meta_tensor=False)) or explicit scale")
        return dequantize(q, scale, 0 if shape is None else
                          int(np.prod(q.shape)) - int(np.prod(shape)),
                          shape if shape is not None else q.shape, dtype)
