"""Adam ops (reference ``deepspeed/ops/adam``)."""

from .cpu_adam import DeepSpeedCPUAdam, DeepSpeedCPUAdagrad, DeepSpeedCPULion  # noqa: F401
