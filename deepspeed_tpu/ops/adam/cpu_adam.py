"""Host Adam(W) on offloaded fp32 shards.

TPU-native analogue of ``deepspeed/ops/adam/cpu_adam.py``
(``DeepSpeedCPUAdam``): the optimizer step runs on the host CPU over numpy
views of pinned shard buffers while the device computes.  Used by the
ZeRO-Offload path (states live on host; only bf16 params travel back).
"""

from __future__ import annotations

import ctypes
from typing import Dict, Optional

import numpy as np

from ..op_builder import CPUAdamBuilder


def _f32ptr(a: np.ndarray):
    assert a.dtype == np.float32 and a.flags["C_CONTIGUOUS"]
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdam:
    """Fused multi-threaded SIMD Adam(W) over flat numpy shards."""

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 bias_correction: bool = True):
        self.lr = float(lr)
        self.betas = (float(betas[0]), float(betas[1]))
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.adamw_mode = bool(adamw_mode)
        self.bias_correction = bool(bias_correction)
        self._lib = CPUAdamBuilder().load()
        self._steps: Dict[int, int] = {}
        self._state: Dict[int, Dict[str, np.ndarray]] = {}

    def state_for(self, key: int, n: int) -> Dict[str, np.ndarray]:
        if key not in self._state:
            self._state[key] = {
                "exp_avg": np.zeros(n, np.float32),
                "exp_avg_sq": np.zeros(n, np.float32),
            }
            self._steps[key] = 0
        return self._state[key]

    def step(self, key: int, params: np.ndarray, grads: np.ndarray,
             lr: Optional[float] = None) -> None:
        """In-place update of ``params`` (flat fp32) given flat fp32 grads."""
        assert params.shape == grads.shape and params.ndim == 1
        state = self.state_for(key, params.size)
        self._steps[key] += 1
        self._lib.ds_cpu_adam_step(
            _f32ptr(params), _f32ptr(grads), _f32ptr(state["exp_avg"]),
            _f32ptr(state["exp_avg_sq"]), params.size, self._steps[key],
            lr if lr is not None else self.lr, self.betas[0], self.betas[1],
            self.eps, self.weight_decay, int(self.adamw_mode),
            int(self.bias_correction))

    def state_dict(self):
        return {"steps": dict(self._steps),
                "state": {k: {n: v.copy() for n, v in s.items()}
                          for k, s in self._state.items()}}

    def load_state_dict(self, sd):
        self._steps = dict(sd["steps"])
        self._state = {k: {n: np.asarray(v, np.float32)
                           for n, v in s.items()}
                       for k, s in sd["state"].items()}


class DeepSpeedCPUAdagrad:
    """Host Adagrad (reference ``ops/adagrad/cpu_adagrad.py``)."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0):
        from ..op_builder import CPUAdagradBuilder
        self.lr, self.eps, self.weight_decay = float(lr), float(eps), float(weight_decay)
        self._lib = CPUAdagradBuilder().load()
        self._state: Dict[int, np.ndarray] = {}

    def step(self, key: int, params: np.ndarray, grads: np.ndarray,
             lr: Optional[float] = None) -> None:
        if key not in self._state:
            self._state[key] = np.zeros(params.size, np.float32)
        self._lib.ds_cpu_adagrad_step(
            _f32ptr(params), _f32ptr(grads), _f32ptr(self._state[key]),
            params.size, lr if lr is not None else self.lr, self.eps,
            self.weight_decay)


class DeepSpeedCPULion:
    """Host Lion (reference ``ops/lion/cpu_lion.py``)."""

    def __init__(self, lr: float = 1e-4, betas=(0.9, 0.99),
                 weight_decay: float = 0.0):
        from ..op_builder import CPULionBuilder
        self.lr = float(lr)
        self.betas = (float(betas[0]), float(betas[1]))
        self.weight_decay = float(weight_decay)
        self._lib = CPULionBuilder().load()
        self._state: Dict[int, np.ndarray] = {}

    def step(self, key: int, params: np.ndarray, grads: np.ndarray,
             lr: Optional[float] = None) -> None:
        if key not in self._state:
            self._state[key] = np.zeros(params.size, np.float32)
        self._lib.ds_cpu_lion_step(
            _f32ptr(params), _f32ptr(grads), _f32ptr(self._state[key]),
            params.size, lr if lr is not None else self.lr, self.betas[0],
            self.betas[1], self.weight_decay)
