"""Host Adam(W)/Adagrad/Lion on offloaded fp32 shards.

TPU-native analogue of ``deepspeed/ops/adam/cpu_adam.py``
(``DeepSpeedCPUAdam``), ``ops/adagrad/cpu_adagrad.py`` and
``ops/lion/cpu_lion.py``: the optimizer step runs on the host CPU over
numpy views of pinned shard buffers while the device computes.  Used by
the ZeRO-Offload path (states live on host; only bf16 params travel back).
"""

from __future__ import annotations

import ctypes
from typing import Dict, Optional

import numpy as np

from ..op_builder import CPUAdamBuilder


def _f32ptr(a: np.ndarray):
    # hard checks (not assert): a wrong-dtype buffer reinterpreted as fp32
    # by the C kernel corrupts training state silently
    if a.dtype != np.float32 or not a.flags["C_CONTIGUOUS"]:
        raise ValueError(
            f"host optimizer buffers must be C-contiguous float32, got "
            f"{a.dtype} contiguous={a.flags['C_CONTIGUOUS']}")
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class _HostOptimizer:
    """Shared scaffolding: per-key dict-of-slots fp32 state + step counts,
    so offload swappers/checkpointing treat every host optimizer uniformly.
    Subclasses define SLOTS and ``_apply(key, params, grads, lr)``."""

    SLOTS: tuple = ()

    def __init__(self):
        self._steps: Dict[int, int] = {}
        self._state: Dict[int, Dict[str, np.ndarray]] = {}

    def state_for(self, key: int, n: int) -> Dict[str, np.ndarray]:
        if key not in self._state:
            self._state[key] = {slot: np.zeros(n, np.float32)
                                for slot in self.SLOTS}
            self._steps.setdefault(key, 0)
        return self._state[key]

    def step(self, key: int, params: np.ndarray, grads: np.ndarray,
             lr: Optional[float] = None) -> None:
        """In-place update of ``params`` (flat fp32) given flat fp32 grads."""
        if params.shape != grads.shape or params.ndim != 1:
            raise ValueError(
                f"expected matching flat shards, got params {params.shape} "
                f"grads {grads.shape}")
        state = self.state_for(key, params.size)
        self._steps[key] = self._steps.get(key, 0) + 1
        self._apply(state, params, grads,
                    lr if lr is not None else self.lr, self._steps[key])

    def _apply(self, state, params, grads, lr, step_count) -> None:
        raise NotImplementedError

    def state_dict(self):
        return {"steps": dict(self._steps),
                "state": {k: {n: v.copy() for n, v in s.items()}
                          for k, s in self._state.items()}}

    def load_state_dict(self, sd):
        self._steps = {int(k): int(v)
                       for k, v in sd.get("steps", {}).items()}
        self._state = {int(k): {n: np.asarray(v, np.float32)
                                for n, v in s.items()}
                       for k, s in sd["state"].items()}


class DeepSpeedCPUAdam(_HostOptimizer):
    """Fused multi-threaded SIMD Adam(W) over flat numpy shards."""

    SLOTS = ("exp_avg", "exp_avg_sq")

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 bias_correction: bool = True):
        super().__init__()
        self.lr = float(lr)
        self.betas = (float(betas[0]), float(betas[1]))
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.adamw_mode = bool(adamw_mode)
        self.bias_correction = bool(bias_correction)
        self._lib = CPUAdamBuilder().load()

    def _apply(self, state, params, grads, lr, step_count):
        self._lib.ds_cpu_adam_step(
            _f32ptr(params), _f32ptr(grads), _f32ptr(state["exp_avg"]),
            _f32ptr(state["exp_avg_sq"]), params.size, step_count,
            lr, self.betas[0], self.betas[1], self.eps, self.weight_decay,
            int(self.adamw_mode), int(self.bias_correction))


class DeepSpeedCPUAdagrad(_HostOptimizer):
    """Host Adagrad (reference ``ops/adagrad/cpu_adagrad.py``)."""

    SLOTS = ("exp_avg_sq",)

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0):
        from ..op_builder import CPUAdagradBuilder
        super().__init__()
        self.lr, self.eps, self.weight_decay = \
            float(lr), float(eps), float(weight_decay)
        self._lib = CPUAdagradBuilder().load()

    def _apply(self, state, params, grads, lr, step_count):
        self._lib.ds_cpu_adagrad_step(
            _f32ptr(params), _f32ptr(grads), _f32ptr(state["exp_avg_sq"]),
            params.size, lr, self.eps, self.weight_decay)


class DeepSpeedCPULion(_HostOptimizer):
    """Host Lion (reference ``ops/lion/cpu_lion.py``)."""

    SLOTS = ("exp_avg",)

    def __init__(self, lr: float = 1e-4, betas=(0.9, 0.99),
                 weight_decay: float = 0.0):
        from ..op_builder import CPULionBuilder
        super().__init__()
        self.lr = float(lr)
        self.betas = (float(betas[0]), float(betas[1]))
        self.weight_decay = float(weight_decay)
        self._lib = CPULionBuilder().load()

    def _apply(self, state, params, grads, lr, step_count):
        self._lib.ds_cpu_lion_step(
            _f32ptr(params), _f32ptr(grads), _f32ptr(state["exp_avg"]),
            params.size, lr, self.betas[0], self.betas[1], self.weight_decay)
