"""Async NVMe I/O (reference ``deepspeed/ops/aio`` + ``csrc/aio``)."""

from .async_io import AsyncIOError, AsyncIOHandle  # noqa: F401
