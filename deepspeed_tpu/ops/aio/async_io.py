"""ctypes wrapper over the native async file-I/O thread pool.

TPU-native analogue of ``deepspeed_py_aio_handle`` (reference
``csrc/aio/py_lib``): submit pread/pwrite of numpy buffers against NVMe
paths, overlap with device compute, wait on completion.  The swap-tensor
layer (``runtime/swap_tensor``) builds its param/optimizer swappers on this.
"""

from __future__ import annotations

import ctypes
from typing import Dict, Optional

import numpy as np

from ..op_builder import AsyncIOBuilder


class AsyncIOError(OSError):
    pass


class AsyncIOHandle:
    """A pool of I/O threads servicing async reads/writes of numpy buffers.

    The caller must keep a submitted buffer alive until its request is
    waited on; the handle tracks buffers to enforce that.
    """

    def __init__(self, num_threads: int = 4, block_size: int = 1 << 20,
                 queue_depth: int = 128, use_direct: bool = False):
        """Reference aio config surface (``aio`` block: thread_count,
        block_size, queue_depth, single_submit/overlap via the async
        API itself).  Large requests are striped into ``block_size``
        parts serviced by all threads concurrently; ``queue_depth``
        bounds outstanding parts (submit blocks when full);
        ``use_direct`` requests O_DIRECT when alignment permits."""
        self._lib = AsyncIOBuilder().load()
        self._handle = self._lib.ds_aio_create2(
            int(num_threads), int(block_size), int(queue_depth),
            1 if use_direct else 0)
        if not self._handle:
            raise AsyncIOError("failed to create aio handle")
        # request id -> (buffer keep-alive, expected bytes, is_read)
        self._inflight: Dict[int, tuple] = {}

    def _buf_ptr(self, arr: np.ndarray):
        if not arr.flags["C_CONTIGUOUS"]:
            raise ValueError("aio buffers must be C-contiguous")
        return ctypes.cast(arr.ctypes.data, ctypes.c_char_p)

    def pwrite(self, arr: np.ndarray, path: str, offset: int = 0) -> int:
        """Async write of the whole buffer; returns a request id."""
        req = self._lib.ds_aio_pwrite(self._handle, path.encode(),
                                      self._buf_ptr(arr), arr.nbytes, offset)
        if req < 0:  # submit-time failure (open): req is -errno
            raise AsyncIOError(-req, f"aio submit failed for {path!r}")
        self._inflight[req] = (arr, arr.nbytes, False)
        return req

    def pread(self, arr: np.ndarray, path: str, offset: int = 0) -> int:
        """Async read filling the whole buffer; returns a request id."""
        req = self._lib.ds_aio_pread(self._handle, path.encode(),
                                     self._buf_ptr(arr), arr.nbytes, offset)
        if req < 0:  # submit-time failure (open): req is -errno
            raise AsyncIOError(-req, f"aio submit failed for {path!r}")
        self._inflight[req] = (arr, arr.nbytes, True)
        return req

    def wait(self, request_id: int) -> int:
        """Block until one request completes; returns bytes moved."""
        rc = self._lib.ds_aio_wait(self._handle, request_id)
        _, expected, is_read = self._inflight.pop(
            request_id, (None, None, False))
        if rc < 0:
            raise AsyncIOError(-rc, f"aio request {request_id} failed")
        if is_read and expected is not None and rc < expected:
            # EOF short read: a truncated file would leave uninitialized
            # tail bytes in the destination buffer — surface it
            raise AsyncIOError(
                f"short read: got {rc} of {expected} bytes "
                f"(request {request_id}; truncated or missing file?)")
        return rc

    def wait_all(self) -> None:
        """Drain every inflight request (short-read checked per request)."""
        first_err: Optional[AsyncIOError] = None
        for req in list(self._inflight):
            try:
                self.wait(req)
            except AsyncIOError as e:
                first_err = first_err or e
        if first_err is not None:
            raise first_err

    # -------- sync conveniences (used by checkpoint/swap fallbacks) ------
    def sync_pwrite(self, arr: np.ndarray, path: str, offset: int = 0) -> int:
        return self.wait(self.pwrite(arr, path, offset))

    def sync_pread(self, arr: np.ndarray, path: str, offset: int = 0) -> int:
        return self.wait(self.pread(arr, path, offset))

    def close(self) -> None:
        if self._handle:
            self._lib.ds_aio_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
