"""Blockwise quantization kernels + quantized collectives.

Reference: ``csrc/quantization/`` (block int4/int8 quant/dequant, fused
dequant-reduce for ZeRO++ qgZ), ``csrc/fp_quantizer/`` (FP8/FP6/FP4), and
``runtime/comm/coalesced_collectives.py:31`` ``all_to_all_quant_reduce``.

TPU-native: symmetric per-block int8 quantization as a Pallas kernel
(scales in fp32, one block per row group), plus a *quantized gradient
psum* built from shard_map-level collectives (quantize -> all_to_all ->
local reduce -> requantize -> all_gather), the EQuARX-style recipe
(PAPERS.md: arXiv 2506.17615) that replaces ZeRO++'s CUDA qgZ pipeline.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.jax_compat import axis_size as _axis_size

BLOCK = 512  # quantization group size (reference default 512/2048)


def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)            # [rows, BLOCK]
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127)
    q_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale[:, 0]


def _dequant_kernel(q_ref, s_ref, o_ref):
    o_ref[:] = (q_ref[:].astype(jnp.float32)
                * s_ref[:][:, None]).astype(o_ref.dtype)


def quantize_blockwise(x: jax.Array, block: int = BLOCK,
                       interpret: Optional[bool] = None
                       ) -> Tuple[jax.Array, jax.Array, int]:
    """Flat fp tensor -> (int8 values [rows, block], fp32 scales [rows], pad)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    flat = x.ravel()
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    rows = flat.shape[0] // block
    x2 = flat.reshape(rows, block)
    q, s = pl.pallas_call(
        _quant_kernel,
        out_shape=[jax.ShapeDtypeStruct((rows, block), jnp.int8),
                   jax.ShapeDtypeStruct((rows,), jnp.float32)],
        interpret=interpret,
    )(x2)
    return q, s, pad


def dequantize_blockwise(q: jax.Array, s: jax.Array, pad: int,
                         shape, dtype=jnp.float32,
                         interpret: Optional[bool] = None) -> jax.Array:
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        _dequant_kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, dtype),
        interpret=interpret,
    )(q, s)
    flat = out.ravel()
    if pad:
        flat = flat[:-pad]
    return flat.reshape(shape)


def quantize_dequantize(x: jax.Array, block: int = BLOCK) -> jax.Array:
    """Fake-quant roundtrip (reference fake_quantizer.cu) — QAT + tests."""
    q, s, pad = quantize_blockwise(x, block)
    return dequantize_blockwise(q, s, pad, x.shape, x.dtype)


# ---------------------------------------------------------------------------
# quantized collectives (ZeRO++ qgZ / EQuARX recipe)
# ---------------------------------------------------------------------------

def quantized_psum_scatter(x: jax.Array, axis_name: str,
                           block: int = BLOCK) -> jax.Array:
    """int8-compressed reduce-scatter along mesh axis (shard_map context).

    Wire format: each rank quantizes its full buffer once (int8 + fp32
    scales = ~4.03 bits/elem wire cost vs 32), all_to_alls shards, then
    dequant-reduces locally — one quantization error per hop, matching
    ZeRO++'s 4x gradient-communication reduction.
    x: [N, ...] with N divisible by the axis size; returns [N/P, ...].
    """
    p = _axis_size(axis_name)
    shard = x.shape[0] // p
    q, s, pad = quantize_blockwise(x, block)
    # ship int8 payloads + scales to the owning rank
    rows_per_shard = q.shape[0] // p
    if q.shape[0] % p != 0:
        # fall back: unquantized psum_scatter when blocks straddle shards
        return lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    q_t = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=True)
    s_t = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0, tiled=True)
    # local dequant + reduce over the P received copies
    q_r = q_t.reshape(p, rows_per_shard, q.shape[1])
    s_r = s_t.reshape(p, rows_per_shard)
    vals = q_r.astype(jnp.float32) * s_r[..., None]
    red = vals.sum(axis=0).ravel()
    total = shard * int(np.prod(x.shape[1:]))
    red = red[:total]
    return red.reshape((shard,) + x.shape[1:]).astype(x.dtype)


def quantized_allreduce(x: jax.Array, axis_name, block: int = BLOCK
                        ) -> jax.Array:
    """int8-wire allreduce over a mesh axis (shard_map context):
    quantized reduce-scatter + quantized all-gather, each hop int8 +
    fp32 scales (~4.03 bits/elem/hop).  Shape-preserving."""
    p = _axis_size(axis_name)
    if p == 1:
        return x
    flat = x.ravel()
    n = flat.shape[0]
    # pad so every rank's payload is whole int8 blocks (otherwise
    # quantized_psum_scatter takes its unquantized fallback)
    pad = (-n) % (p * block)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = quantized_psum_scatter(flat.reshape(p, -1), axis_name,
                                   block=block)           # [1, n/p]
    full = quantized_all_gather(shard, axis_name, block=block)
    out = full.ravel()
    if pad:
        out = out[:-pad]
    return out.reshape(x.shape).astype(x.dtype)


def quantized_grad_reduce_shard(g: jax.Array, shard_dim: Optional[int],
                                scatter_axis: str = "fsdp",
                                replica_axes=("data",),
                                block: int = BLOCK) -> jax.Array:
    """ZeRO++ qgZ gradient wire (reference ``all_to_all_quant_reduce``,
    runtime/comm/coalesced_collectives.py:31) for one grad leaf inside a
    ``shard_map`` manual region.

    Hierarchical, every hop int8 on the wire:
      1. reduce-scatter over the ZeRO ``scatter_axis`` (fsdp): each rank
         ships int8 payloads and keeps its owned shard of ``shard_dim``;
      2. int8 allreduce over the pure-DP ``replica_axes`` so every data
         replica holds the identical reduced shard.

    ``shard_dim`` None means the leaf is not fsdp-sharded (replicated
    layout): the reduction still spans BOTH the replica and the scatter
    axes (batch shards live on both), via an exact psum for payloads too
    small to amortize int8 block padding, int8 allreduce otherwise.
    Returns the LOCAL shard (``shard_dim`` divided by the fsdp size) or
    the fully-reduced tensor when ``shard_dim`` is None.
    """
    replica_axes = tuple(a for a in replica_axes if _axis_size(a) > 1)
    f = _axis_size(scatter_axis)
    if shard_dim is None:
        axes = replica_axes + ((scatter_axis,) if f > 1 else ())
        if not axes:
            return g
        if g.size < block:
            # small leaf (bias/scalar): padded int8 wire would SHIP MORE
            # than exact fp32 (reference quantizes only bucketed large
            # payloads) — and correctness demands the full-axes reduce
            return lax.psum(g, axes)
        out = g
        for a in axes:
            out = quantized_allreduce(out, a, block=block)
        return out.astype(g.dtype)

    x = jnp.moveaxis(g, shard_dim, 0)
    lead = x.shape[0]
    rest = x.shape[1:]
    chunk = (lead // f) * int(np.prod(rest)) if rest else lead // f
    if f > 1 and chunk < block:
        # sharded but tiny: exact psum over all axes, keep own shard
        red = lax.psum(g, replica_axes + (scatter_axis,))
        idx = lax.axis_index(scatter_axis)
        return lax.dynamic_slice_in_dim(red, idx * (lead // f), lead // f,
                                        axis=shard_dim)
    out = g
    if f > 1:
        x2 = x.reshape(f, chunk)
        pad = (-chunk) % block  # whole int8 blocks per rank payload
        if pad:
            x2 = jnp.pad(x2, ((0, 0), (0, pad)))
        shard = quantized_psum_scatter(x2, scatter_axis, block=block)
        shard = shard.ravel()[:chunk]
        out = shard.reshape((lead // f,) + rest)
        out = jnp.moveaxis(out, 0, shard_dim)
    for a in replica_axes:
        out = quantized_allreduce(out, a, block=block)
    return out.astype(g.dtype)


def quantized_allreduce_ef(x: jax.Array, axis_names, world: int,
                           block: int = BLOCK
                           ) -> Tuple[jax.Array, jax.Array]:
    """Combined-axes int8 allreduce with first-hop error capture — the
    CollectiveScheduler's bucket wire (runtime/comm/collective_scheduler).

    Unlike :func:`quantized_allreduce` this reduces over ALL the listed
    mesh axes in ONE two-hop exchange (int8 reduce-scatter via all_to_all
    + int8 all_gather), so a data x fsdp mesh pays two quantizations per
    bucket instead of four, and it returns the local quantization error
    for persistent error feedback.

    ``x``: local flat bucket, ``x.size % (world * block) == 0`` (the
    bucket plan aligns boundaries).  ``world``: product of the axis
    sizes (static — ``lax.axis_size`` of a tuple is version-dependent).
    Returns ``(allreduced, error)`` where ``error = x - Q(x)`` is exactly
    the part of this rank's contribution the first hop did not ship (the
    second hop's error is shared post-reduction state, not locally
    correctable).
    """
    q, s, _ = quantize_blockwise(x, block)
    shipped = dequantize_blockwise(q, s, 0, x.shape, x.dtype)
    err = x - shipped
    rows = q.shape[0]
    per = rows // world
    # hop 1: int8 payload + fp32 scales to the owning rank, dequant-reduce
    qt = lax.all_to_all(q, axis_names, split_axis=0, concat_axis=0, tiled=True)
    st = lax.all_to_all(s, axis_names, split_axis=0, concat_axis=0, tiled=True)
    vals = (qt.reshape(world, per, block).astype(jnp.float32)
            * st.reshape(world, per)[..., None]).sum(axis=0)  # [per, block]
    # hop 2: requantize the reduced shard, int8 all-gather
    q2, s2, _ = quantize_blockwise(vals.ravel(), block)
    qg = lax.all_gather(q2, axis_names, axis=0, tiled=True)
    sg = lax.all_gather(s2, axis_names, axis=0, tiled=True)
    full = dequantize_blockwise(qg, sg, 0, (rows * block,), jnp.float32)
    return full.reshape(x.shape).astype(x.dtype), err


def quantized_all_gather(x: jax.Array, axis_name: str,
                         block: int = BLOCK) -> jax.Array:
    """int8-compressed all-gather (ZeRO++ qwZ weight gather)."""
    q, s, pad = quantize_blockwise(x, block)
    qg = lax.all_gather(q, axis_name, axis=0, tiled=True)
    sg = lax.all_gather(s, axis_name, axis=0, tiled=True)
    p = _axis_size(axis_name)
    flat = (qg.astype(jnp.float32) * sg[:, None]).ravel()
    n = x.size
    per = q.size  # padded elements per rank
    chunks = flat.reshape(p, per)[:, :n] if pad else flat.reshape(p, n)
    return chunks.reshape((p * x.shape[0],) + x.shape[1:]).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def quantized_all_gather_st(x: jax.Array, axis_name: str,
                            block: int = BLOCK) -> jax.Array:
    """Straight-through :func:`quantized_all_gather` (ZeRO++ qwZ):
    forward gathers int8-compressed shards; backward is the exact
    all-gather transpose (tiled psum-scatter of the cotangent), i.e. the
    quantization error is treated straight-through.  For use inside
    ``shard_map`` weight-gather paths."""
    return quantized_all_gather(x, axis_name, block)


def _qag_st_fwd(x, axis_name, block):
    return quantized_all_gather(x, axis_name, block), None


def _qag_st_bwd(axis_name, block, _res, ct):
    return (lax.psum_scatter(ct, axis_name, scatter_dimension=0,
                             tiled=True),)


quantized_all_gather_st.defvjp(_qag_st_fwd, _qag_st_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def quantize_dequantize_st(x: jax.Array, bits: int = 8,
                           block: int = BLOCK) -> jax.Array:
    """Straight-through blockwise fake quantization: forward snaps to the
    int8 grid (the numerics every qwZ-gathered weight sees), gradient
    passes through unchanged.  The engine uses this for
    ``zero_quantized_weights`` so training matches the reference's qwZ
    accuracy behavior; the wire-compressed gather itself is the
    ``quantized_all_gather_st`` op for shard_map paths."""
    return quantize_dequantize(x, block=block)


def _qdq_st_fwd(x, bits, block):
    return quantize_dequantize(x, block=block), None


def _qdq_st_bwd(bits, block, _res, ct):
    return (ct,)


quantize_dequantize_st.defvjp(_qdq_st_fwd, _qdq_st_bwd)
