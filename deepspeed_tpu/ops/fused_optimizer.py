"""Fused AdamW — Pallas multi-tensor-style optimizer kernel.

Reference: ``csrc/adam/multi_tensor_adam.cu`` (FusedAdam) + host
``csrc/adam/cpu_adam.cpp``.  The CUDA version exists to amortize kernel
launches over many small tensors; on TPU the same economics are achieved
by updating the *flattened shard* in one kernel: params/grads/moments are
raveled into one fp32 vector per dtype group and the whole Adam update is
a single elementwise pass (one HBM read/write per buffer).  XLA fuses the
optax chain nearly as well, so this kernel is an opt-in fast path
(``optimizer.type = "fusedadam"`` with ``tpu.fused_kernel=true``) and the
numerical ground truth for the optax path's tests.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 1024  # rows are reshaped to [n // _LANES, _LANES] for VPU tiling


def _adamw_kernel(p_ref, g_ref, m_ref, v_ref, sc_ref,
                  new_p_ref, new_m_ref, new_v_ref):
    """One elementwise pass: m, v, bias-corrected AdamW update.
    sc_ref (SMEM, [6]): lr, b1, b2, eps, wd, step."""
    lr = sc_ref[0]
    b1 = sc_ref[1]
    b2 = sc_ref[2]
    eps = sc_ref[3]
    wd = sc_ref[4]
    step = sc_ref[5]

    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    bc1 = 1.0 - jnp.power(b1, step)
    bc2 = 1.0 - jnp.power(b2, step)
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + wd * p
    new_p_ref[:] = (p - lr * update).astype(new_p_ref.dtype)
    new_m_ref[:] = m
    new_v_ref[:] = v


def fused_adamw_flat(p: jax.Array, g: jax.Array, m: jax.Array, v: jax.Array,
                     lr, b1: float, b2: float, eps: float, wd: float, step,
                     block_rows: int = 256, interpret: bool | None = None):
    """Apply fused AdamW to flat 1-D buffers; returns (p, m, v)."""
    n = p.shape[0]
    pad = (-n) % _LANES
    if pad:
        p, g, m, v = (jnp.pad(x, (0, pad)) for x in (p, g, m, v))
    rows = (n + pad) // _LANES
    shape2 = (rows, _LANES)
    p2, g2, m2, v2 = (x.reshape(shape2) for x in (p, g, m, v))
    scalars = jnp.asarray([lr, b1, b2, eps, wd, step], jnp.float32)

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_rows = min(block_rows, rows)
    grid = (pl.cdiv(rows, block_rows),)
    row_spec = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
    new_p, new_m, new_v = pl.pallas_call(
        _adamw_kernel,
        grid=grid,
        in_specs=[row_spec, row_spec, row_spec, row_spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct(shape2, p.dtype),
                   jax.ShapeDtypeStruct(shape2, jnp.float32),
                   jax.ShapeDtypeStruct(shape2, jnp.float32)],
        interpret=interpret,
    )(p2, g2, m2, v2, scalars)
    out = (new_p.ravel(), new_m.ravel(), new_v.ravel())
    if pad:
        out = tuple(x[:n] for x in out)
    return out


class FusedAdamState(NamedTuple):
    count: jax.Array
    mu: optax.Updates
    nu: optax.Updates


def fused_adamw(learning_rate, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8, weight_decay: float = 0.0
                ) -> optax.GradientTransformation:
    """optax transform whose update runs the Pallas kernel per leaf
    (leaves are raveled; shape restored afterwards)."""

    def init_fn(params):
        z = jax.tree.map(lambda p: jnp.zeros(p.size, jnp.float32), params)
        return FusedAdamState(count=jnp.zeros((), jnp.int32),
                              mu=z, nu=jax.tree.map(jnp.zeros_like, z))

    def update_fn(grads, state: FusedAdamState, params):
        if params is None:
            raise ValueError("fused_adamw requires params")
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate

        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        new_p, new_m, new_v = [], [], []
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            pf, mf, vf = fused_adamw_flat(
                p.ravel().astype(jnp.float32), g.ravel().astype(jnp.float32),
                m, v, lr, b1, b2, eps, weight_decay,
                count.astype(jnp.float32))
            new_p.append(pf.reshape(p.shape).astype(p.dtype))
            new_m.append(mf)
            new_v.append(vf)
        updates = jax.tree.unflatten(
            treedef, [np_ - p for np_, p in zip(new_p, flat_p)])
        return updates, FusedAdamState(
            count=count,
            mu=jax.tree.unflatten(treedef, new_m),
            nu=jax.tree.unflatten(treedef, new_v))

    return optax.GradientTransformation(init_fn, update_fn)
