"""Fused AdamW — Pallas multi-tensor-style optimizer kernel.

Reference: ``csrc/adam/multi_tensor_adam.cu`` (FusedAdam) + host
``csrc/adam/cpu_adam.cpp``.  The CUDA version exists to amortize kernel
launches over many small tensors; on TPU the same economics are achieved
by updating the *flattened shard* in one kernel: params/grads/moments are
raveled into one fp32 vector per dtype group and the whole Adam update is
a single elementwise pass (one HBM read/write per buffer).  XLA fuses the
optax chain nearly as well, so this kernel is an opt-in fast path
(``optimizer.type = "fusedadam"`` with ``tpu.fused_kernel=true``) and the
numerical ground truth for the optax path's tests.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 1024  # rows are reshaped to [n // _LANES, _LANES] for VPU tiling


def _adamw_kernel(p_ref, g_ref, m_ref, v_ref, sc_ref,
                  new_p_ref, new_m_ref, new_v_ref):
    """One elementwise pass: m, v, bias-corrected AdamW update.
    sc_ref (SMEM, [6]): lr, b1, b2, eps, wd, step."""
    lr = sc_ref[0]
    b1 = sc_ref[1]
    b2 = sc_ref[2]
    eps = sc_ref[3]
    wd = sc_ref[4]
    step = sc_ref[5]

    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    bc1 = 1.0 - jnp.power(b1, step)
    bc2 = 1.0 - jnp.power(b2, step)
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + wd * p
    new_p_ref[:] = (p - lr * update).astype(new_p_ref.dtype)
    new_m_ref[:] = m
    new_v_ref[:] = v


def fused_adamw_flat(p: jax.Array, g: jax.Array, m: jax.Array, v: jax.Array,
                     lr, b1: float, b2: float, eps: float, wd: float, step,
                     block_rows: int = 256, interpret: bool | None = None):
    """Apply fused AdamW to flat 1-D buffers; returns (p, m, v)."""
    n = p.shape[0]
    pad = (-n) % _LANES
    if pad:
        p, g, m, v = (jnp.pad(x, (0, pad)) for x in (p, g, m, v))
    rows = (n + pad) // _LANES
    shape2 = (rows, _LANES)
    p2, g2, m2, v2 = (x.reshape(shape2) for x in (p, g, m, v))
    scalars = jnp.asarray([lr, b1, b2, eps, wd, step], jnp.float32)

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_rows = min(block_rows, rows)
    grid = (pl.cdiv(rows, block_rows),)
    row_spec = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
    new_p, new_m, new_v = pl.pallas_call(
        _adamw_kernel,
        grid=grid,
        in_specs=[row_spec, row_spec, row_spec, row_spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct(shape2, p.dtype),
                   jax.ShapeDtypeStruct(shape2, jnp.float32),
                   jax.ShapeDtypeStruct(shape2, jnp.float32)],
        interpret=interpret,
    )(p2, g2, m2, v2, scalars)
    out = (new_p.ravel(), new_m.ravel(), new_v.ravel())
    if pad:
        out = tuple(x[:n] for x in out)
    return out


# ---------------------------------------------------------------------------
# Lion (reference csrc/lion/fused_lion* + cpu_lion.cpp)
# ---------------------------------------------------------------------------

def _lion_kernel(p_ref, g_ref, m_ref, sc_ref, new_p_ref, new_m_ref):
    """sign-momentum update: u = sign(b1*m + (1-b1)*g);
    p -= lr*(u + wd*p); m = b2*m + (1-b2)*g.
    sc_ref (SMEM, [4]): lr, b1, b2, wd."""
    lr = sc_ref[0]
    b1 = sc_ref[1]
    b2 = sc_ref[2]
    wd = sc_ref[3]
    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    m = m_ref[:]
    u = jnp.sign(b1 * m + (1.0 - b1) * g)
    new_p_ref[:] = (p - lr * (u + wd * p)).astype(new_p_ref.dtype)
    new_m_ref[:] = b2 * m + (1.0 - b2) * g


def fused_lion_flat(p, g, m, lr, b1: float, b2: float, wd: float,
                    block_rows: int = 256, interpret: bool | None = None):
    """Apply fused Lion to flat 1-D buffers; returns (p, m)."""
    n = p.shape[0]
    pad = (-n) % _LANES
    if pad:
        p, g, m = (jnp.pad(x, (0, pad)) for x in (p, g, m))
    rows = (n + pad) // _LANES
    shape2 = (rows, _LANES)
    p2, g2, m2 = (x.reshape(shape2) for x in (p, g, m))
    scalars = jnp.asarray([lr, b1, b2, wd], jnp.float32)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_rows = min(block_rows, rows)
    row_spec = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
    new_p, new_m = pl.pallas_call(
        _lion_kernel,
        grid=(pl.cdiv(rows, block_rows),),
        in_specs=[row_spec, row_spec, row_spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct(shape2, p.dtype),
                   jax.ShapeDtypeStruct(shape2, jnp.float32)],
        interpret=interpret,
    )(p2, g2, m2, scalars)
    out = (new_p.ravel(), new_m.ravel())
    if pad:
        out = tuple(x[:n] for x in out)
    return out


class FusedLionState(NamedTuple):
    count: jax.Array
    mu: optax.Updates


def fused_lion(learning_rate, b1: float = 0.9, b2: float = 0.99,
               weight_decay: float = 0.0) -> optax.GradientTransformation:
    """optax transform running the Pallas Lion kernel per (raveled) leaf
    — matches ``optax.lion`` numerics (decoupled decay)."""

    def init_fn(params):
        return FusedLionState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(lambda p: jnp.zeros(p.size, jnp.float32),
                            params))

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("fused_lion requires params")
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        new_p, new_m = [], []
        for p, g, m in zip(flat_p, flat_g, flat_m):
            pf, mf = fused_lion_flat(
                p.ravel().astype(jnp.float32),
                g.ravel().astype(jnp.float32), m,
                lr, b1, b2, weight_decay)
            new_p.append(pf.reshape(p.shape).astype(p.dtype))
            new_m.append(mf)
        updates = jax.tree.unflatten(
            treedef, [np_ - p for np_, p in zip(new_p, flat_p)])
        return updates, FusedLionState(
            count=count, mu=jax.tree.unflatten(treedef, new_m))

    return optax.GradientTransformation(init_fn, update_fn)


# ---------------------------------------------------------------------------
# LAMB (reference csrc/lamb/fused_lamb_cuda_kernel.cu: per-tensor trust
# ratio over the Adam-style update)
# ---------------------------------------------------------------------------

def _lamb_stage1_kernel(p_ref, g_ref, m_ref, v_ref, sc_ref,
                        u_ref, new_m_ref, new_v_ref, norms_ref):
    """Elementwise Adam-style update u (incl. decoupled wd term) + this
    block's partial squared norms of p and u (norms_ref [1, 2] per grid
    row; summed on the host side of the call).
    sc_ref (SMEM, [5]): b1, b2, eps, wd, step."""
    b1 = sc_ref[0]
    b2 = sc_ref[1]
    eps = sc_ref[2]
    wd = sc_ref[3]
    step = sc_ref[4]
    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    bc1 = 1.0 - jnp.power(b1, step)
    bc2 = 1.0 - jnp.power(b2, step)
    u = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + wd * p
    u_ref[:] = u
    new_m_ref[:] = m
    new_v_ref[:] = v
    norms_ref[0, 0] = jnp.sum(p * p)
    norms_ref[0, 1] = jnp.sum(u * u)


def fused_lamb_flat(p, g, m, v, lr, b1: float, b2: float, eps: float,
                    wd: float, step, block_rows: int = 256,
                    interpret: bool | None = None):
    """Fused LAMB on flat 1-D buffers; returns (p, m, v).

    Stage 1 (Pallas): moments + Adam-style update + per-block norm
    partials in one elementwise pass.  The per-TENSOR trust ratio
    ||p|| / ||u|| and the final axpy are O(1)+O(n) XLA ops fused into
    the surrounding program (the CUDA version's second kernel)."""
    n = p.shape[0]
    pad = (-n) % _LANES
    if pad:
        p, g, m, v = (jnp.pad(x, (0, pad)) for x in (p, g, m, v))
    rows = (n + pad) // _LANES
    shape2 = (rows, _LANES)
    p2, g2, m2, v2 = (x.reshape(shape2) for x in (p, g, m, v))
    scalars = jnp.asarray([b1, b2, eps, wd, step], jnp.float32)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_rows = min(block_rows, rows)
    nblocks = pl.cdiv(rows, block_rows)
    row_spec = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
    u, new_m, new_v, norms = pl.pallas_call(
        _lamb_stage1_kernel,
        grid=(nblocks,),
        in_specs=[row_spec, row_spec, row_spec, row_spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[row_spec, row_spec, row_spec,
                   pl.BlockSpec((1, 2), lambda i: (i, 0),
                                memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct(shape2, jnp.float32),
                   jax.ShapeDtypeStruct(shape2, jnp.float32),
                   jax.ShapeDtypeStruct(shape2, jnp.float32),
                   jax.ShapeDtypeStruct((nblocks, 2), jnp.float32)],
        interpret=interpret,
    )(p2, g2, m2, v2, scalars)
    pn = jnp.sqrt(norms[:, 0].sum())
    un = jnp.sqrt(norms[:, 1].sum())
    ratio = jnp.where((pn > 0) & (un > 0), pn / un, 1.0)
    new_p = (p2 - lr * ratio * u).astype(p.dtype)
    out = (new_p.ravel(), new_m.ravel(), new_v.ravel())
    if pad:
        out = tuple(x[:n] for x in out)
    return out


def fused_lamb(learning_rate, b1: float = 0.9, b2: float = 0.999,
               eps: float = 1e-6, weight_decay: float = 0.0
               ) -> optax.GradientTransformation:
    """optax transform running the Pallas LAMB kernel per leaf (the
    trust ratio is per PARAM TENSOR, reference FusedLamb semantics)."""

    def init_fn(params):
        z = jax.tree.map(lambda p: jnp.zeros(p.size, jnp.float32), params)
        return FusedAdamState(count=jnp.zeros((), jnp.int32),
                              mu=z, nu=jax.tree.map(jnp.zeros_like, z))

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("fused_lamb requires params")
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        new_p, new_m, new_v = [], [], []
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            pf, mf, vf = fused_lamb_flat(
                p.ravel().astype(jnp.float32),
                g.ravel().astype(jnp.float32), m, v,
                lr, b1, b2, eps, weight_decay, count.astype(jnp.float32))
            new_p.append(pf.reshape(p.shape).astype(p.dtype))
            new_m.append(mf)
            new_v.append(vf)
        updates = jax.tree.unflatten(
            treedef, [np_ - p for np_, p in zip(new_p, flat_p)])
        return updates, FusedAdamState(
            count=count,
            mu=jax.tree.unflatten(treedef, new_m),
            nu=jax.tree.unflatten(treedef, new_v))

    return optax.GradientTransformation(init_fn, update_fn)


class FusedAdamState(NamedTuple):
    count: jax.Array
    mu: optax.Updates
    nu: optax.Updates


def fused_adamw(learning_rate, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8, weight_decay: float = 0.0
                ) -> optax.GradientTransformation:
    """optax transform whose update runs the Pallas kernel per leaf
    (leaves are raveled; shape restored afterwards)."""

    def init_fn(params):
        z = jax.tree.map(lambda p: jnp.zeros(p.size, jnp.float32), params)
        return FusedAdamState(count=jnp.zeros((), jnp.int32),
                              mu=z, nu=jax.tree.map(jnp.zeros_like, z))

    def update_fn(grads, state: FusedAdamState, params):
        if params is None:
            raise ValueError("fused_adamw requires params")
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate

        flat_p, treedef = jax.tree.flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        new_p, new_m, new_v = [], [], []
        for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
            pf, mf, vf = fused_adamw_flat(
                p.ravel().astype(jnp.float32), g.ravel().astype(jnp.float32),
                m, v, lr, b1, b2, eps, weight_decay,
                count.astype(jnp.float32))
            new_p.append(pf.reshape(p.shape).astype(p.dtype))
            new_m.append(mf)
            new_v.append(vf)
        updates = jax.tree.unflatten(
            treedef, [np_ - p for np_, p in zip(new_p, flat_p)])
        return updates, FusedAdamState(
            count=count,
            mu=jax.tree.unflatten(treedef, new_m),
            nu=jax.tree.unflatten(treedef, new_v))

    return optax.GradientTransformation(init_fn, update_fn)
