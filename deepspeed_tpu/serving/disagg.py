"""Disaggregated prefill/decode serving (ISSUE 13, ROADMAP item 2).

Prefill is compute-bound and decode is bandwidth-bound; fusing them in
one engine forces one batch geometry and one compiled-program lattice
to serve both.  A :class:`DisaggPool` runs TWO engines in one process
(threaded like ``ReplicaPool.start()``): a **prefill pool**
(``serving.role = "prefill"``) that runs prompt chunks and produces
each request's FIRST token — so TTFT never waits on a transfer — and a
**decode pool** (``role = "decode"``) that carries the steady-state
token loop with the PR 2 async chained overlap and PR 10 speculation
untouched.

The handoff — after a request's first token lands, the prefill
scheduler parks it *handoff-ready* and the pool streams it across the
PR 8 page-transfer seam:

- ``FastGenScheduler.export_handoff(uids)`` →
  ``StateManager.export_state(seq_ids=...)``: the sequences' committed
  KV pages (each distinct page written once; full prefix pages ride
  with their chained blake2b digests) plus each request's residual
  state — the prompt incl. its partial-page tail tokens, committed
  tokens, sampling params, remaining TTL / token budget, spec
  counters.
- ``import_handoff(bundle)`` on the decode side merges into the LIVE
  engine: block tables remap onto freshly scattered pages, refcounts
  and prefix sharing are reconstructed, and any full page whose chain
  digest the decode pool's prefix cache already indexes is attached BY
  REFERENCE (``ds_disagg_pages_shared_total``) instead of streamed —
  prefix-cache hit rates survive the pool boundary.
- ``complete_handoff`` then flushes the prefill side, whose full
  prefix pages park in ITS cache, keeping later same-prefix prompts
  warm.

KV backpressure is structured: an import the decode pool cannot hold
yet raises ``KVAllocationError`` WITHOUT mutating, the pool defers and
retries while the decode pool drains (``ds_disagg_handoff_retry_
total``), and a request that could never fit an idle decode pool fails
with a structured "oom" verdict — nothing is ever lost silently.

Sampled continuations: with ``serving.keyed_sampling`` on BOTH engines
(and a shared base key), every sampled token's RNG derives from
(base, uid, position), so the two-pool output is tokenwise identical
to the fused single-engine run — greedy needs no flag.  Without keyed
sampling, sampled requests continue as valid draws from the decode
pool's own stream (committed prefixes always preserved verbatim).

Each pool's compiled-program lattice shrinks to its role
(``precompile(kinds=...)``): the decode pool drops every Q>1 prefill
bucket, the prefill pool drops the chain/spec families — a
compile-time and step-cache-pressure win ``ds_fastgen_step_cache_*``
can prove, and the substrate ROADMAP item 2 names for cross-process
KV streaming later (the bundle is already the PR 8 snapshot codec's
(meta, arrays) shape).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..inference.v2.ragged.blocked_allocator import KVAllocationError
from ..inference.v2.sampling import SamplingParams
from ..inference.v2.scheduler import FastGenScheduler, RequestError
from ..telemetry import journey as _journey
from ..telemetry import metrics as tm
from ..telemetry.flight_recorder import get_flight_recorder
from ..telemetry.tracer import set_component
from .pool import PoolRequest

#: deferred-import attempts against a BUSY decode pool before the pool
#: stops waiting for natural drain and fails the request structurally
#: (a busy pool frees pages as requests finish, so the common case
#: resolves in a few steps; the cap bounds pathological workloads)
_MAX_HANDOFF_RETRIES = 256


class DisaggPool:
    """One prefill engine + one decode engine behind a committed-page
    KV streaming handoff."""

    def __init__(self,
                 prefill_factory: Callable[[], FastGenScheduler],
                 decode_factory: Callable[[], FastGenScheduler],
                 on_token: Optional[Callable[[int, int], None]] = None,
                 handoff_every: int = 4,
                 manifest: Optional[Dict[str, list]] = None):
        """The factories build the two schedulers (engines must share
        model WEIGHTS for tokenwise-identical continuations and carry
        ``serving.role`` "prefill" / "decode" respectively — the role
        admission is what guarantees a misrouted request can never sit
        forever).  ``on_token`` taps the pool's stitched per-token
        delivery (bench/replay consumers).  ``handoff_every`` is the
        pump cadence in prefill steps: batching a few handoffs per
        import means fewer decode-membership changes, so the decode
        pool's async chain breaks once per BATCH instead of once per
        request (TTFT is unaffected — the first token already left the
        prefill pool; only that request's second token waits).
        ``manifest`` (ISSUE 14): a per-role compiled-key manifest
        (``{"prefill": [...], "decode": [...]}`` — the
        :meth:`compiled_manifest` of a previously-running pool); each
        engine precompiles its role's keys at birth, which against a
        warm persistent compile cache is a disk load, not a compile —
        a freshly spawned disagg pool serves its first handoff warm."""
        self.prefill = prefill_factory()
        self.decode = decode_factory()
        for sched, want in ((self.prefill, "prefill"),
                            (self.decode, "decode")):
            if sched.role != want:
                raise ValueError(
                    f"DisaggPool needs a role={want!r} scheduler, got "
                    f"role={sched.role!r} (set serving.role)")
        if manifest:
            # same gate as ReplicaPool._warm_new_replica: without an
            # active persistent compile cache the manifest would be
            # synchronous TRUE compiles at pool birth — stay lazy then
            from ..inference.v2.compile_cache import active_cache_dir
            if active_cache_dir() is None:
                from ..utils.logging import logger
                logger.info("DisaggPool: no active compile cache — "
                            "skipping the warm-birth manifest "
                            "precompile (engines compile lazily)")
            else:
                for sched, role in ((self.prefill, "prefill"),
                                    (self.decode, "decode")):
                    keys = manifest.get(role) or []
                    if keys:
                        sched._engine.precompile_keys(keys)
        self.prefill.enable_handoff_sink()
        self._on_token = on_token
        self._requests: Dict[int, PoolRequest] = {}
        self._retries: Dict[int, int] = {}
        self._lock = threading.RLock()          # pool ledger
        self._plock = threading.RLock()         # prefill scheduler
        self._dlock = threading.RLock()         # decode scheduler
        #: serializes a whole pump (export -> import -> complete): the
        #: per-scheduler locks drop between those phases, and two
        #: pumping threads (stepper + serve_until_idle driver) would
        #: otherwise export the same parked uids and collide at import
        self._pump_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._threads: List[threading.Thread] = []
        self._pace_s = 0.0
        #: optional per-handoff wall-time tap (bench/replay percentile
        #: collection on top of the ds_disagg_handoff_ms histogram)
        self._on_handoff_ms: Optional[Callable[[float], None]] = None
        #: wall seconds each pool spent INSIDE its own scheduler steps
        #: — the busy windows behind the per-pool MFU / HBM-rate
        #: numbers (pump time and the other pool's phases excluded:
        #: the claim is about what a specialized program mix does with
        #: its hardware while it runs, not about thread overlap)
        self.prefill_busy_s = 0.0
        self.decode_busy_s = 0.0
        self._handoff_every = max(int(handoff_every), 1)
        self._steps_since_pump = 0
        self._bind_backlog_gauge()
        get_flight_recorder().record(
            "disagg.build",
            prefill_pages=self.prefill._engine.model.kv_config.num_pages,
            decode_pages=self.decode._engine.model.kv_config.num_pages,
            keyed=bool(getattr(self.prefill._engine.model,
                               "keyed_sampling", False)))

    def compiled_manifest(self) -> Dict[str, list]:
        """Per-role compiled-key manifest of this pool — the
        ``manifest=`` input for spawning the next (warm-born) pool."""
        return {"prefill": [list(k) for k in
                            self.prefill._engine.compiled_keys()],
                "decode": [list(k) for k in
                           self.decode._engine.compiled_keys()]}

    def _bind_backlog_gauge(self) -> None:
        import weakref
        ref = weakref.ref(self.prefill)

        def _read(r=ref):
            sched = r()
            return sched.handoff_backlog if sched is not None else 0

        tm.DISAGG_HANDOFF_BACKLOG.bind(_read)

    # -- request lifecycle ---------------------------------------------------
    def submit(self, uid: int, prompt: Sequence[int],
               params: Optional[SamplingParams] = None,
               ttl_s: Optional[float] = None) -> Optional[RequestError]:
        """Same contract as ``FastGenScheduler.submit``: None on
        acceptance, else the structured rejection verdict (also kept
        in :attr:`errors`).  Every request enters through the prefill
        pool; the handoff is the pool's concern, not the caller's."""
        params = params or SamplingParams()
        req = PoolRequest(uid=uid,
                          prompt=np.asarray(prompt, dtype=np.int32),
                          params=params, replica="prefill")
        req.submit_mono = time.monotonic()
        req.journey = _journey.mint(uid)
        if req.journey is not None:
            # disagg placement is static (everything enters prefill),
            # but the segment still closes submit -> admission handed
            # to the prefill scheduler, mirroring the pool's router leg
            req.journey.mark("placement", at="router")
        if ttl_s:
            req.deadline = req.submit_mono + float(ttl_s)
        with self._lock:
            old = self._requests.get(uid)
            if old is not None and not old.finalized:
                raise ValueError(f"uid {uid} is already live in the pool")
            self._requests[uid] = req
        with self._plock:
            verdict = self.prefill.submit(uid, req.prompt, params,
                                          ttl_s=ttl_s,
                                          journey=req.journey)
        if verdict is not None:
            req.error = RequestError(uid=uid, code=verdict.code,
                                     message=verdict.message,
                                     tokens=[])
            req.finished_mono = time.monotonic()
        return verdict

    def _deliver(self, uid: int, tok: int) -> None:
        """The stitched per-token delivery both schedulers feed: the
        pool ledger is the authoritative full stream (prefill pool
        contributes the first token, decode pool the rest)."""
        req = self._requests.get(uid)
        if req is None or req.finalized:
            return
        req.tokens.append(int(tok))
        now = time.monotonic()
        if req.first_token_mono == 0.0:
            req.first_token_mono = now
        stop = req.params.stop_token
        if (len(req.tokens) >= req.params.max_new_tokens
                or (stop is not None and int(tok) == stop)):
            req.done = True
            req.finished_mono = now
        if self._on_token is not None:
            self._on_token(uid, int(tok))

    # -- the handoff pump ----------------------------------------------------
    def pump_handoffs(self) -> int:
        """Stream every handoff-ready request from the prefill pool to
        the decode pool; returns how many moved.  Import failures are
        backpressure, not errors: the batch splits to singles, singles
        defer while the decode pool still has work to drain, and only
        a request that cannot fit an IDLE decode pool (or exhausted
        the retry budget) fails with a structured verdict.  One pump
        runs at a time (export -> import -> complete is not atomic
        under the per-scheduler locks alone)."""
        with self._pump_lock:
            return self._pump_impl()

    def _pump_impl(self) -> int:
        with self._plock:
            # parked requests outlive the step loop (has_work excludes
            # them), so their TTL sweep runs here — a deadline passing
            # while awaiting collection still yields code="expired"
            self.prefill._expire_requests()
            uids = [u for u in self.prefill.handoff_ready_uids()
                    if not self._finalized(u)]
        if not uids:
            return 0
        moved = self._try_handoff(uids)
        if moved or len(uids) == 1:
            return moved
        # batch refused: try one by one so a single oversized request
        # can't wedge every other handoff behind it
        for u in uids:
            moved += self._try_handoff([u])
        return moved

    def _finalized(self, uid: int) -> bool:
        req = self._requests.get(uid)
        return req is not None and req.finalized

    def _try_handoff(self, uids: List[int]) -> int:
        t0 = time.perf_counter()
        with self._plock:
            uids = [u for u in uids
                    if u in self.prefill.handoff_ready_uids()]
            if not uids:
                return 0
            sm = self.prefill._engine.state_manager
            need = set()
            for u in uids:
                sd = sm.get_sequence(u)
                if sd is not None:
                    need.update(p for p in sd.pages if p)
        # cheap pre-check before the expensive export: a BUSY decode
        # pool whose schedulable page count can't possibly hold these
        # sequences defers WITHOUT re-copying their KV to host every
        # pump (optimistic — digest dedup only shrinks the need; an
        # idle pool, or an exhausted retry budget, always runs the
        # authoritative export+import, which fails structurally)
        with self._dlock:
            free = self.decode._engine.free_blocks
            decode_busy = self.decode.has_work
        if (decode_busy and len(need) > free
                and all(self._retries.get(u, 0) < _MAX_HANDOFF_RETRIES
                        for u in uids)):
            tm.DISAGG_HANDOFF_RETRY.inc()
            for u in uids:
                self._retries[u] = self._retries.get(u, 0) + 1
            return 0
        with self._plock:
            uids = [u for u in uids
                    if u in self.prefill.handoff_ready_uids()]
            if not uids:
                return 0
            bundle = self.prefill.export_handoff(uids)
        nbytes = sum(int(a.nbytes) for a in bundle["arrays"].values())
        try:
            with self._dlock:
                stats = self.decode.import_handoff(bundle)
        except KVAllocationError as e:
            tm.DISAGG_HANDOFF_RETRY.inc()
            self._defer_or_fail(uids, e)
            return 0
        with self._plock:
            self.prefill.complete_handoff(uids)
        for u in uids:
            self._retries.pop(u, None)
            req = self._requests.get(u)
            if req is not None:
                req.replica = "decode"
                req.migrations += 1
        ms = (time.perf_counter() - t0) * 1e3
        tm.DISAGG_HANDOFFS.inc(len(uids))
        tm.DISAGG_HANDOFF_BYTES.inc(nbytes)
        tm.DISAGG_HANDOFF_MS.observe(ms)
        if self._on_handoff_ms is not None:
            self._on_handoff_ms(ms)
        tm.DISAGG_PAGES_STREAMED.inc(int(stats.get("pages_streamed", 0)))
        tm.DISAGG_PAGES_SHARED.inc(int(stats.get("pages_shared", 0)))
        get_flight_recorder().record(
            "disagg.handoff", uids=len(uids), bytes=nbytes,
            ms=round(ms, 2),
            pages_streamed=int(stats.get("pages_streamed", 0)),
            pages_shared=int(stats.get("pages_shared", 0)))
        return len(uids)

    def _defer_or_fail(self, uids: List[int], exc: Exception) -> None:
        """A refused import: defer while the decode pool can still
        free pages by draining; fail structurally once it cannot (or
        the retry budget is spent) — the satellite guarantee that no
        request ever sits forever."""
        with self._dlock:
            decode_busy = self.decode.has_work
        for u in uids:
            self._retries[u] = self._retries.get(u, 0) + 1
        if decode_busy and all(self._retries[u] < _MAX_HANDOFF_RETRIES
                               for u in uids):
            return
        if len(uids) > 1:
            return      # pump retries one-by-one before any verdict
        u = uids[0]
        with self._plock:
            req = self.prefill._handoff_ready.get(u)
            if req is not None:
                self.prefill._fail_request(
                    req, "oom",
                    "handoff refused: decode pool cannot hold this "
                    f"sequence's KV ({exc}); "
                    f"{self._retries.get(u, 0)} attempts")
        self._retries.pop(u, None)

    # -- stepping ------------------------------------------------------------
    def _step_prefill(self) -> bool:
        set_component("prefill")
        with self._plock:
            if not self.prefill.has_work:
                return False
            t0 = time.perf_counter()
            self.prefill.step(on_token=self._deliver)
            self.prefill_busy_s += time.perf_counter() - t0
            return True

    def _step_decode(self) -> bool:
        set_component("decode")
        with self._dlock:
            if not self.decode.has_work:
                return False
            t0 = time.perf_counter()
            self.decode.step(on_token=self._deliver)
            self.decode_busy_s += time.perf_counter() - t0
            return True

    def _pump_due(self, stepped: bool) -> bool:
        """Cadence gate: pump every ``handoff_every`` prefill steps,
        or immediately once the prefill pool has nothing left to run
        (nothing to batch against — don't sit on the backlog)."""
        if stepped:
            self._steps_since_pump += 1
        if not self.prefill.handoff_backlog:
            return False
        if not stepped or self._steps_since_pump >= self._handoff_every:
            self._steps_since_pump = 0
            return True
        return False

    def step(self) -> None:
        """Single-threaded drive: one prefill step, the handoff pump
        (on its cadence), one decode step, error harvest."""
        stepped = self._step_prefill()
        if self._pump_due(stepped):
            self.pump_handoffs()
        self._step_decode()
        self._harvest_errors()

    @property
    def idle(self) -> bool:
        return (not self.prefill.has_work
                and self.prefill.handoff_backlog == 0
                and not self.decode.has_work
                and all(r.finalized for r in self._requests.values()))

    def run_to_completion(self, max_stalls: int = 512
                          ) -> Dict[int, List[int]]:
        """Step until every submitted request is finalized; returns
        ``{uid: tokens}`` for completed requests (structured errors in
        :attr:`errors`)."""
        stalls = 0
        while not self.idle:
            before = sum(len(r.tokens) for r in self._requests.values())
            self.step()
            after = sum(len(r.tokens) for r in self._requests.values())
            stalls = 0 if after > before else stalls + 1
            if stalls > max_stalls:
                raise RuntimeError(
                    "disagg pool stalled: "
                    f"{sum(not r.finalized for r in self._requests.values())} "
                    f"request(s) unfinalized with no progress "
                    f"(prefill backlog {self.prefill.backlog}, "
                    f"handoff-ready {self.prefill.handoff_backlog}, "
                    f"decode backlog {self.decode.backlog})")
        self.refresh_cost_gauges()
        return self.results()

    # -- threaded serve loop (the ReplicaPool.start pattern) -----------------
    def start(self, pace_s: float = 0.0) -> None:
        """One stepper thread per pool (JAX releases the GIL inside
        compiled steps, so prefill and decode genuinely overlap): the
        prefill thread also pumps handoffs after each step, so a
        finished prefill streams out while the NEXT prompt's chunks
        are already running."""
        self._stop_evt.clear()
        self._pace_s = float(pace_s)
        for name, loop in (("prefill", self._prefill_loop),
                           ("decode", self._decode_loop)):
            t = threading.Thread(target=loop, daemon=True,
                                 name=f"ds-disagg-{name}")
            self._threads.append(t)
            t.start()

    def _prefill_loop(self) -> None:
        set_component("prefill")
        while not self._stop_evt.is_set():
            stepped = self._step_prefill()
            if self._pump_due(stepped):
                self.pump_handoffs()
            self._harvest_errors()
            if not stepped:
                time.sleep(0.002)
            elif self._pace_s:
                time.sleep(self._pace_s)

    def _decode_loop(self) -> None:
        set_component("decode")
        while not self._stop_evt.is_set():
            stepped = self._step_decode()
            if not stepped:
                time.sleep(0.002)
            elif self._pace_s:
                time.sleep(self._pace_s)

    def serve_until_idle(self, timeout_s: float = 120.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.prefill.handoff_backlog:
                self.pump_handoffs()
            self._harvest_errors()
            if self.idle:
                self.refresh_cost_gauges()
                return True
            time.sleep(0.005)
        return False

    def stop(self) -> None:
        self._stop_evt.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads.clear()

    # -- read side -----------------------------------------------------------
    def _harvest_errors(self) -> None:
        """Mirror both schedulers' structured terminal errors into the
        pool ledger, with the FULL stitched token stream (a scheduler
        record only holds the tokens generated on ITS side)."""
        for sched in (self.prefill, self.decode):
            if not sched.errors:
                continue
            for uid, err in list(sched.errors.items()):
                req = self._requests.get(uid)
                if req is None or req.finalized:
                    continue
                req.error = RequestError(uid=uid, code=err.code,
                                         message=err.message,
                                         tokens=list(req.tokens))
                req.finished_mono = time.monotonic()

    def refresh_cost_gauges(self) -> Dict[str, float]:
        """Publish (and return) the per-pool cost facts (ISSUE 9
        accounting, read per engine over each pool's BUSY window):
        prefill-pool MFU and decode-pool HBM GB/s — the two numbers
        the disaggregation thesis stands on.  The ONE implementation
        behind both the ``ds_disagg_*`` gauges and the bench/replay
        report."""
        from ..inference.v2.model import serving_peak_flops
        pre = self.prefill._engine.cost_summary()
        dec = self.decode._engine.cost_summary()
        peak = serving_peak_flops()
        out = {
            "prefill_mfu": (float(pre.get("flops_dispatched", 0.0))
                            / max(self.prefill_busy_s, 1e-9) / peak),
            "decode_hbm_gb_s": (float(dec.get("bytes_dispatched", 0.0))
                                / max(self.decode_busy_s, 1e-9) / 1e9),
        }
        tm.DISAGG_PREFILL_MFU.set(out["prefill_mfu"])
        tm.DISAGG_DECODE_HBM_GB_S.set(out["decode_hbm_gb_s"])
        return out

    @property
    def errors(self) -> Dict[int, RequestError]:
        self._harvest_errors()
        return {uid: r.error for uid, r in self._requests.items()
                if r.error is not None}

    def results(self) -> Dict[int, List[int]]:
        return {uid: list(r.tokens)
                for uid, r in self._requests.items() if r.done}

    def request(self, uid: int) -> Optional[PoolRequest]:
        return self._requests.get(uid)

    def stats(self) -> Dict:
        reqs = list(self._requests.values())
        self.refresh_cost_gauges()
        return {
            "requests": len(reqs),
            "completed": sum(r.done for r in reqs),
            "errors": sum(r.error is not None for r in reqs),
            "inflight": sum(not r.finalized for r in reqs),
            "handed_off": sum(r.replica == "decode" for r in reqs),
            "handoff_backlog": self.prefill.handoff_backlog,
            "prefill_backlog": self.prefill.backlog,
            "decode_backlog": self.decode.backlog,
            "prefill_cost": self.prefill._engine.cost_summary(),
            "decode_cost": self.decode._engine.cost_summary(),
        }
