"""Replica-pool serving controller (ISSUE 12, ROADMAP item 1).

One ``FastGenScheduler`` is an engine; a :class:`ReplicaPool` is a
*service*: N scheduler replicas behind a :class:`PrefixAffinityRouter`,
scaled and rebalanced by the PR 11 SLO evaluator's advice, with live
migration so membership changes never lose a request.

Placement — every submit is routed by prefix-cache affinity: replicas
periodically publish a bounded top-K slice of their chained page-digest
index (``engine.export_digests``) and the router sends each prompt to
the replica holding the longest cumulative-digest prefix match, falling
back to least-backlog (``FastGenScheduler.backlog`` — the same quantity
the ``ds_fastgen_queue_depth``/``_running``/``_preempted`` gauges
export).  Same-prefix requests therefore pile onto the replica that
already holds the pages, which multiplies the PR 3 prefix cache across
the fleet instead of diluting it 1/N under round-robin.

Migration — two paths, both keeping partial tokens:

- **drain-and-migrate** (``scale_down``): the victim closes admission,
  ``snapshot()`` drains its in-flight step to committed state (tokens
  delivered through the pool's own ``on_token``, so nothing is lost at
  the drain boundary) and serializes its requests; the pool then
  redistributes each serialized request to a peer as
  ``prompt' = prompt + committed_tokens`` with
  ``max_new' = max_new - len(committed_tokens)`` and the remaining TTL.
  The pool stitches the token stream, so the request's COMMITTED prefix
  is preserved verbatim (tokenwise identical); for greedy decode the
  continuation is deterministic, so the full stream matches the
  uninterrupted run.
- **death absorption** (``kill`` / an ``InjectedPreemptionFault``
  escaping a replica's step — the ``serving.preempt`` chaos site): the
  replica vanishes WITHOUT a drain, exactly like a preempted spot VM.
  The pool resubmits every tracked in-flight request from its own
  delivered-token ledger; tokens that were committed but not yet
  host-visible are regenerated (greedy: identical) on the new home.

Autoscaling — the pool consumes the PR 11 SLO evaluator's verdicts:
``attach_slo()`` binds an evaluator and the step/serve loops poll its
``current()`` block, applying page-verdict advice (``scale_up`` spawns
a fresh replica via the factory, ``scale_down`` drains and migrates
the emptiest replica, ``rebalance`` pins the hottest digest group to
the coldest replica) under a cooldown; ``handle_advice(action)`` is
the same entry point for a controller tailing ``slo.advice`` flight
events (e.g. the scale-DOWN advice that only rides the flight
recorder).

Modes — in-process replicas (this module: full routing + migration;
the federation's in-process-registry pattern) are the first mode;
``tools/fleet_replica.py`` subprocesses are the second, scraped over
HTTP: their engines publish the same digest hints on
``/snapshot?digests=1`` (``router.fetch_remote_hints``) and their
backlog gauges ride ``/snapshot``, so the same router places against
subprocess replicas while lifecycle (spawn/kill) is process management
— ``tools/fleetctl.py``'s pool subcommands drive that mode.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..inference.v2.sampling import SamplingParams
from ..inference.v2.scheduler import FastGenScheduler, RequestError
from ..runtime.fault_injection import InjectedPreemptionFault
from ..telemetry import journey as _journey
from ..telemetry import metrics as tm
from ..telemetry.flight_recorder import get_flight_recorder
from ..telemetry.tracer import set_component
from .router import PrefixAffinityRouter, RouteDecision


@dataclasses.dataclass
class PoolRequest:
    """Pool-side view of one request: the authoritative token ledger
    across migrations (each scheduler only ever sees the tokens IT
    generated; the pool stitches the full stream)."""
    uid: int
    prompt: np.ndarray
    params: SamplingParams
    tokens: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    error: Optional[RequestError] = None
    replica: str = ""
    migrations: int = 0
    matched_pages: int = 0
    #: monotonic stamps for the pool's own TTFT accounting
    submit_mono: float = 0.0
    first_token_mono: float = 0.0
    finished_mono: float = 0.0
    #: absolute monotonic deadline (None = no TTL); survives migration
    #: as a remaining budget
    deadline: Optional[float] = None
    #: journey (ISSUE 19): ONE trace context for the request's whole
    #: life — every scheduler Request it is (re)submitted as shares
    #: this same object, so segments from before and after a migration
    #: land in one chain
    journey: Optional[object] = None

    @property
    def finalized(self) -> bool:
        return self.done or self.error is not None


class _Replica:
    """One in-process replica: scheduler + engine + its step lock (a
    scheduler is single-threaded; the lock serializes its own stepper
    thread against pool submits/migrations)."""

    def __init__(self, label: str, scheduler: FastGenScheduler,
                 pool: "ReplicaPool"):
        self.label = label
        self.scheduler = scheduler
        self.engine = scheduler._engine
        self.lock = threading.RLock()
        self.alive = True
        self.steps = 0
        self._pool = pool

    def deliver(self, uid: int, tok: int) -> None:
        """The pool's per-token delivery (passed as ``on_token`` to
        every step/snapshot drain): appends to the POOL ledger and
        applies the original request's termination rule (the scheduler
        applies it to its own residual view after a migration)."""
        req = self._pool._requests.get(uid)
        if req is None or req.finalized:
            return
        req.tokens.append(int(tok))
        now = time.monotonic()
        if req.first_token_mono == 0.0:
            req.first_token_mono = now
        stop = req.params.stop_token
        if (len(req.tokens) >= req.params.max_new_tokens
                or (stop is not None and int(tok) == stop)):
            req.done = True
            req.finished_mono = now


class ReplicaPool:
    """N FastGenScheduler replicas behind a prefix-affinity router."""

    def __init__(self, factory: Callable[[str], FastGenScheduler],
                 replicas: int = 2,
                 policy: str = "affinity",
                 hint_top_k: int = 64,
                 hint_every: int = 4,
                 min_replicas: int = 1,
                 max_replicas: int = 8,
                 warm_spawn: bool = True,
                 page_fetch_margin: int = -1):
        """``factory(label)`` builds one fresh replica (engine +
        scheduler) — also the ``scale_up`` spawn path, so it must
        return an INDEPENDENT engine per call.  With ``warm_spawn``
        (ISSUE 14) every later spawn precompiles the union of the live
        replicas' compiled-key manifests — exactly the programs fleet
        traffic actually forms — before joining the pool; against a
        warm persistent compile cache
        (``serving_optimization.compile_cache_dir``) those are disk
        loads, so a scale_up replica is born warm instead of eating
        its first requests as compile stalls."""
        self._factory = factory
        self._warm_spawn = bool(warm_spawn)
        self._hint_top_k = int(hint_top_k)
        self._hint_every = max(int(hint_every), 1)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self._lock = threading.RLock()
        self._replicas: Dict[str, _Replica] = {}
        self._requests: Dict[int, PoolRequest] = {}
        #: uids whose home died while the pool had no live replica —
        #: re-routed on the next scale_up / step with live members
        self._orphans: List[int] = []
        self._next_label = 0
        self._router: Optional[PrefixAffinityRouter] = None
        self._policy = policy
        #: ISSUE 16 cross-replica page fetch: when >= 0, an affinity
        #: match losing to least-backlog by more than this margin
        #: streams its matched pages to the chosen replica instead of
        #: recomputing the prefill (-1 = off, pure PR 12 affinity)
        self._page_fetch_margin = int(page_fetch_margin)
        # -- SLO subscription (PR 11 evaluator) ------------------------------
        self._slo = None
        self._slo_cooldown_s = 5.0
        self._last_action_mono = 0.0
        # -- threaded serve loop ---------------------------------------------
        self._stop_evt = threading.Event()
        self._threads: Dict[str, threading.Thread] = {}
        self._pace_s = 0.0
        for _ in range(max(int(replicas), 1)):
            self._add_replica(count_scale_up=False)
        get_flight_recorder().record(
            "pool.build", replicas=len(self._replicas), policy=policy)

    # -- membership ----------------------------------------------------------
    @property
    def router(self) -> PrefixAffinityRouter:
        return self._router

    def _live(self) -> List[_Replica]:
        with self._lock:
            return [r for r in self._replicas.values() if r.alive]

    @property
    def labels(self) -> List[str]:
        return sorted(r.label for r in self._live())

    def _add_replica(self, label: Optional[str] = None,
                     count_scale_up: bool = True) -> _Replica:
        with self._lock:
            if label is None:
                label = f"r{self._next_label}"
            self._next_label += 1
        sched = self._factory(label)
        if self._warm_spawn:
            self._warm_new_replica(sched)
        rep = _Replica(label, sched, self)
        with self._lock:
            self._replicas[label] = rep
            if self._router is None:
                # page size is an engine fact; the first replica fixes it
                self._router = PrefixAffinityRouter(
                    rep.engine.model.kv_config.page_size,
                    top_k=self._hint_top_k, policy=self._policy,
                    fetch_backlog_margin=self._page_fetch_margin)
            tm.POOL_REPLICAS.set(len(self._live()))
        if count_scale_up:
            tm.POOL_SCALE_UP.inc()
        get_flight_recorder().record("pool.replica_add", label=label,
                                     scale_up=count_scale_up)
        self._flush_orphans()
        return rep

    def compiled_manifest(self) -> List[tuple]:
        """Union of the live replicas' compiled-key manifests — the
        programs fleet traffic actually formed, in a stable order."""
        keys = set()
        for rep in self._live():
            try:
                keys.update(rep.engine.compiled_keys())
            except Exception:   # noqa: BLE001 — a dying replica is fine
                continue
        return sorted(keys, key=repr)

    def _warm_new_replica(self, sched: FastGenScheduler) -> None:
        """Precompile the fleet's compiled-key manifest on a
        just-spawned replica (ISSUE 14): a warm persistent compile
        cache turns these into disk loads, so the spawn joins the pool
        recompile-proof.  Without an active compile cache the manifest
        would be TRUE compiles paid synchronously inside scale_up —
        at exactly the moment the SLO is burning — so cache-less pools
        keep the lazy prior behavior (join immediately, compile the
        keys traffic actually forms).  Best-effort — a failure warns
        and the replica joins cold rather than not at all."""
        from ..inference.v2.compile_cache import active_cache_dir
        if active_cache_dir() is None:
            return
        manifest = self.compiled_manifest()
        if not manifest:
            return
        try:
            n = sched._engine.precompile_keys(manifest)
        except Exception as e:  # noqa: BLE001
            from ..utils.logging import logger
            logger.warning("pool: warm spawn precompile failed "
                           "(%s: %s) — replica joins cold",
                           type(e).__name__, e)
            return
        get_flight_recorder().record("pool.warm_spawn",
                                     manifest_keys=len(manifest),
                                     compiled=n)

    def scale_up(self, label: Optional[str] = None) -> Optional[str]:
        """Spawn one fresh replica (the SLO ``scale_up`` action).
        Refuses past ``max_replicas``; returns the new label."""
        if len(self._live()) >= self.max_replicas:
            return None
        return self._add_replica(label).label

    # -- placement -----------------------------------------------------------
    def _backlogs(self, exclude: Optional[str] = None) -> Dict[str, int]:
        return {r.label: r.scheduler.backlog for r in self._live()
                if r.label != exclude}

    def _place(self, req: PoolRequest, prompt: np.ndarray,
               params: SamplingParams, ttl_s: Optional[float],
               exclude: Optional[str] = None
               ) -> Optional[RequestError]:
        """Route + submit one (possibly migrated) request.  Returns the
        scheduler's immediate-rejection verdict or None; a rejection
        finalizes the pool request with its partial tokens."""
        backlogs = self._backlogs(exclude)
        if not backlogs:
            with self._lock:
                if req.uid not in self._orphans:
                    self._orphans.append(req.uid)
            return None     # parked until a replica exists
        decision: RouteDecision = self._router.decide(prompt, backlogs)
        rep = self._replicas.get(decision.label)
        if rep is None or not rep.alive:
            return self._place(req, prompt, params, ttl_s, exclude)
        tm.POOL_ROUTED.inc()
        if decision.reason in ("affinity", "pin"):
            tm.POOL_AFFINITY_ROUTED.inc()
        req.replica = decision.label
        req.matched_pages = decision.matched_pages
        if req.journey is not None:
            req.journey.mark("placement", at="router")
        if decision.fetch_from:
            self._fetch_pages(rep, decision)
            if req.journey is not None:
                req.journey.mark("page_fetch", at=decision.label)
        with rep.lock:
            verdict = rep.scheduler.submit(req.uid, prompt, params,
                                           ttl_s=ttl_s,
                                           journey=req.journey)
        if verdict is not None:
            req.error = RequestError(uid=req.uid, code=verdict.code,
                                     message=verdict.message,
                                     tokens=list(req.tokens))
            req.finished_mono = time.monotonic()
        return verdict

    def _fetch_pages(self, rep: _Replica,
                     decision: RouteDecision) -> None:
        """Stream the matched committed prefix pages replica-to-replica
        (ISSUE 16 tentpole c) through the same (meta, named numpy
        arrays) codec as the disagg handoff: export under the peer's
        lock, import under the target's — two SEPARATE critical
        sections, never nested, so opposite-direction fetches can't
        deadlock.  Best-effort: any failure (dead peer, stale hint,
        full target pool) just means the request prefills its prefix
        like a cold placement."""
        src = self._replicas.get(decision.fetch_from)
        if src is None or not src.alive:
            return
        t0 = time.monotonic()
        try:
            with src.lock:
                exported = src.engine.export_prefix(
                    decision.fetch_digests)
            if exported is None:
                return      # stale hint: the peer evicted the pages
            meta, arrays = exported
            with rep.lock:
                stats = rep.engine.import_prefix(meta, arrays)
        except Exception as e:  # noqa: BLE001 — the fetch is an
            # optimization; the recompute path is always correct
            from ..utils.logging import logger
            logger.warning(
                "pool: page fetch %s -> %s failed (%s: %s) — request "
                "prefills cold", decision.fetch_from, rep.label,
                type(e).__name__, e)
            return
        elapsed_ms = (time.monotonic() - t0) * 1000.0
        pages = int(stats.get("pages_imported", 0))
        nbytes = sum(int(a.nbytes) for a in arrays.values())
        tm.POOL_PAGE_FETCHES.inc()
        tm.POOL_PAGE_FETCH_PAGES.inc(pages)
        tm.POOL_PAGE_FETCH_BYTES.inc(nbytes)
        tm.POOL_PAGE_FETCH_MS.observe(elapsed_ms)
        get_flight_recorder().record(
            "pool.page_fetch", src=decision.fetch_from, dst=rep.label,
            pages=pages, skipped=int(stats.get("pages_skipped", 0)),
            bytes=nbytes)

    def submit(self, uid: int, prompt: Sequence[int],
               params: Optional[SamplingParams] = None,
               ttl_s: Optional[float] = None) -> Optional[RequestError]:
        """Route one request into the pool; same contract as
        ``FastGenScheduler.submit`` (None = accepted, else the
        structured rejection, also kept in :attr:`errors`)."""
        params = params or SamplingParams()
        req = PoolRequest(uid=uid,
                          prompt=np.asarray(prompt, dtype=np.int32),
                          params=params)
        req.submit_mono = time.monotonic()
        req.journey = _journey.mint(uid)
        if ttl_s:
            req.deadline = req.submit_mono + float(ttl_s)
        with self._lock:
            old = self._requests.get(uid)
            if old is not None and not old.finalized:
                raise ValueError(f"uid {uid} is already live in the pool")
            self._requests[uid] = req
        return self._place(req, req.prompt, params, ttl_s)

    # -- hint publication ----------------------------------------------------
    def _publish_hints(self, rep: _Replica) -> None:
        # under the replica's step lock: export_digests iterates the
        # prefix-cache index, which that replica's stepper thread
        # mutates mid-step (scale_down refreshes PEER hints from the
        # caller's thread while peers keep serving)
        with rep.lock:
            digests = rep.engine.export_digests(self._hint_top_k)
        self._router.publish(rep.label, digests)

    def publish_hints(self) -> None:
        """Force an immediate hint publish from every live replica
        (the step loop otherwise publishes every ``hint_every`` steps
        per replica)."""
        for rep in self._live():
            self._publish_hints(rep)

    # -- stepping ------------------------------------------------------------
    def _step_replica(self, rep: _Replica) -> bool:
        """One scheduler step on one replica (under its lock).  A
        preemption fault escaping the step kills the replica like a
        preempted spot VM; the pool absorbs it."""
        died = publish = False
        set_component(rep.label)
        with rep.lock:
            if not rep.alive or not rep.scheduler.has_work:
                return False
            try:
                rep.scheduler.step(on_token=rep.deliver)
                rep.steps += 1
                publish = rep.steps % self._hint_every == 0
            except InjectedPreemptionFault:
                rep.alive = False
                died = True
        if died:
            self._absorb_death(rep, reason="preempted")
            return True
        if publish:
            self._publish_hints(rep)
        self._harvest_errors(rep)
        return True

    def step(self) -> None:
        """Single-threaded drive: one step on every live replica, then
        orphan re-routing and SLO advice polling."""
        for rep in self._live():
            self._step_replica(rep)
        self._flush_orphans()
        self._poll_advice()

    @property
    def idle(self) -> bool:
        return (not self._orphans
                and all(not r.scheduler.has_work for r in self._live())
                and all(r.finalized for r in self._requests.values()))

    def run_to_completion(self, max_stalls: int = 256
                          ) -> Dict[int, List[int]]:
        """Step until every submitted request is finalized; returns
        {uid: tokens} for completed requests (errors in
        :attr:`errors`)."""
        stalls = 0
        while not self.idle:
            before = sum(len(r.tokens) for r in self._requests.values())
            self.step()
            after = sum(len(r.tokens) for r in self._requests.values())
            stalls = 0 if after > before else stalls + 1
            if stalls > max_stalls:
                raise RuntimeError(
                    f"pool stalled: {sum(not r.finalized for r in self._requests.values())} "
                    f"request(s) unfinalized with no progress "
                    f"({len(self._live())} live replicas, "
                    f"{len(self._orphans)} orphans)")
        return self.results()

    # -- threaded serve loop -------------------------------------------------
    def start(self, pace_s: float = 0.0) -> None:
        """Launch one stepper thread per live replica (JAX releases the
        GIL inside compiled steps, so replicas genuinely overlap on a
        multi-core host; ``pace_s`` sleeps between steps — the demo's
        simulated per-step device budget).  Replicas added later get
        threads from :meth:`serve_until_idle`'s driver loop."""
        self._stop_evt.clear()
        self._pace_s = float(pace_s)
        self._ensure_threads()

    def _ensure_threads(self) -> None:
        for rep in self._live():
            t = self._threads.get(rep.label)
            if t is None or not t.is_alive():
                t = threading.Thread(target=self._thread_loop,
                                     args=(rep,), daemon=True,
                                     name=f"ds-pool-{rep.label}")
                self._threads[rep.label] = t
                t.start()

    def _thread_loop(self, rep: _Replica) -> None:
        set_component(rep.label)
        while not self._stop_evt.is_set() and rep.alive:
            if not self._step_replica(rep):
                time.sleep(0.002)
            elif self._pace_s:
                time.sleep(self._pace_s)

    def serve_until_idle(self, timeout_s: float = 120.0) -> bool:
        """Driver loop for the threaded mode: keeps threads covering
        the (possibly changing) membership, re-routes orphans, polls
        SLO advice; returns True once idle (False on timeout)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self._ensure_threads()
            self._flush_orphans()
            self._poll_advice()
            if self.idle:
                return True
            time.sleep(0.01)
        return False

    def stop(self) -> None:
        self._stop_evt.set()
        for t in self._threads.values():
            t.join(timeout=5.0)
        self._threads.clear()

    # -- migration -----------------------------------------------------------
    def _resubmit(self, req: PoolRequest,
                  exclude: Optional[str] = None) -> None:
        """Re-home one in-flight request with its committed prefix
        kept: the peer continues from ``prompt + tokens`` with the
        remaining token and TTL budgets.  Greedy continuations are
        tokenwise identical to the uninterrupted run; the committed
        prefix is preserved verbatim for every sampling mode."""
        stop = req.params.stop_token
        if (len(req.tokens) >= req.params.max_new_tokens
                or (stop is not None and req.tokens
                    and req.tokens[-1] == stop)):
            req.done = True       # finished exactly at the boundary
            req.finished_mono = req.finished_mono or time.monotonic()
            # the dead home never got to flush this journey (it
            # finished AT the migration boundary, with no survivor
            # scheduler to close it) — the pool is the only owner left
            if req.journey is not None:
                req.journey.mark("decode")
                _journey.get_journey_log().publish(req.journey, "ok")
            return
        prompt2 = (np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
            if req.tokens else req.prompt)
        params2 = dataclasses.replace(
            req.params,
            max_new_tokens=req.params.max_new_tokens - len(req.tokens))
        ttl = (max(req.deadline - time.monotonic(), 0.001)
               if req.deadline is not None else None)
        req.migrations += 1
        tm.POOL_MIGRATED.inc()
        # close the outage window (death/drain -> re-home) as one
        # "migrate" segment before the new home starts queue_wait
        if req.journey is not None:
            req.journey.mark("migrate")
        self._place(req, prompt2, params2, ttl, exclude=exclude)

    def scale_down(self, label: Optional[str] = None) -> Optional[str]:
        """Drain-and-migrate the emptiest replica (the SLO
        ``scale_down`` action): close admission, drain to committed
        state (tokens delivered through the pool ledger), serialize its
        requests via ``snapshot()``, redistribute each to a peer with
        partial tokens kept, and drop the replica.  Refuses below
        ``min_replicas`` or with fewer than two live replicas (the
        last replica has no peer to migrate into)."""
        live = self._live()
        if len(live) <= max(self.min_replicas, 1):
            return None
        if label is None:
            rep = min(live, key=lambda r: (r.scheduler.backlog, r.label))
        else:
            rep = self._replicas.get(label)
            if rep is None or not rep.alive:
                return None
        # survivors' hints must be fresh BEFORE re-homing: the whole
        # point of affinity migration is landing each request on the
        # peer already holding its prefix
        for peer in live:
            if peer.label != rep.label:
                self._publish_hints(peer)
        with rep.lock:
            rep.scheduler.close()
            bundle = rep.scheduler.snapshot(on_token=rep.deliver)
            rep.alive = False
        serialized = bundle["meta"]["requests"]
        moved = 0
        for rec in (serialized["pending"] + serialized["running"]
                    + serialized["preempted"]):
            req = self._requests.get(int(rec["uid"]))
            if req is None or req.finalized:
                continue
            self._resubmit(req, exclude=rep.label)
            moved += 1
        self._drop_replica(rep)
        tm.POOL_SCALE_DOWN.inc()
        get_flight_recorder().record("pool.scale_down", label=rep.label,
                                     migrated=moved)
        return rep.label

    def kill(self, label: str) -> None:
        """Abrupt replica death (test/demo control — the same path an
        ``InjectedPreemptionFault`` escaping a step takes): no drain,
        no snapshot; the pool resubmits every tracked request from its
        own token ledger."""
        rep = self._replicas.get(label)
        if rep is None or not rep.alive:
            return
        with rep.lock:
            rep.alive = False
        self._absorb_death(rep, reason="killed")

    def _absorb_death(self, rep: _Replica, reason: str) -> None:
        tm.POOL_REPLICA_DEATHS.inc()
        victims = [r for r in self._requests.values()
                   if r.replica == rep.label and not r.finalized]
        self._drop_replica(rep)
        get_flight_recorder().record("pool.replica_death",
                                     label=rep.label, reason=reason,
                                     inflight=len(victims))
        for req in victims:
            self._resubmit(req, exclude=rep.label)

    def _drop_replica(self, rep: _Replica) -> None:
        with self._lock:
            self._replicas.pop(rep.label, None)
            self._threads.pop(rep.label, None)
            if self._router is not None:
                self._router.forget(rep.label)
            tm.POOL_REPLICAS.set(len(self._live()))

    def _flush_orphans(self) -> None:
        with self._lock:
            if not self._orphans or not self._live():
                return
            orphans, self._orphans = self._orphans, []
        for uid in orphans:
            req = self._requests.get(uid)
            if req is not None and not req.finalized:
                self._resubmit(req)

    def _harvest_errors(self, rep: _Replica) -> None:
        """Mirror a replica's structured terminal errors into the pool
        ledger (shed/expired/poisoned/oom...), tokens = the FULL pool
        stream (the scheduler record only has post-migration tokens)."""
        if not rep.scheduler.errors:
            return
        for uid, err in list(rep.scheduler.errors.items()):
            req = self._requests.get(uid)
            if req is None or req.finalized or req.replica != rep.label:
                continue
            req.error = RequestError(uid=uid, code=err.code,
                                     message=err.message,
                                     tokens=list(req.tokens))
            req.finished_mono = time.monotonic()

    # -- SLO subscription (PR 11) --------------------------------------------
    def attach_slo(self, evaluator, cooldown_s: float = 5.0) -> None:
        """Subscribe to an :class:`~..telemetry.slo.SLOEvaluator`: the
        step/serve loops poll its ``current()`` verdicts and apply
        page-verdict advice through :meth:`handle_advice` under a
        cooldown.  (Scale-DOWN advice is edge-triggered into the
        flight recorder only — a controller tailing ``slo.advice``
        events calls ``handle_advice("scale_down")`` itself.)"""
        self._slo = evaluator
        self._slo_cooldown_s = float(cooldown_s)

    def _poll_advice(self) -> None:
        ev = self._slo
        if ev is None:
            return
        cur = ev.current()
        if not cur.get("configured"):
            return
        for v in cur.get("objectives", {}).values():
            if v.get("status") == "page" and v.get("advice"):
                self.handle_advice(v["advice"])

    def handle_advice(self, action: str) -> Optional[str]:
        """Apply one SLO advice action (``scale_up`` / ``scale_down`` /
        ``rebalance``) under the cooldown; returns what changed (new /
        removed label, pinned root) or None when the action was a
        no-op (cooldown, bounds, nothing to do)."""
        now = time.monotonic()
        if now - self._last_action_mono < self._slo_cooldown_s:
            return None
        result: Optional[str] = None
        if action == "scale_up":
            result = self.scale_up()
        elif action == "scale_down":
            result = self.scale_down()
        elif action == "rebalance":
            result = self.rebalance()
        if result is not None:
            self._last_action_mono = now
            get_flight_recorder().record("pool.advice_applied",
                                         action=action, result=result)
        return result

    def rebalance(self) -> Optional[str]:
        """Re-home the hottest digest group: pin the root digest most
        often routed to the most-loaded replica onto the least-loaded
        one (which warms its own cache on first arrival).  Returns the
        pinned root or None when the pool is already balanced."""
        # one backlog snapshot is the membership view — a replica dying
        # between two _live() reads must not KeyError the advice path
        backlogs = self._backlogs()
        if len(backlogs) < 2:
            return None
        hot = max(backlogs, key=lambda lb: (backlogs[lb], lb))
        cold = min(backlogs, key=lambda lb: (backlogs[lb], lb))
        if hot == cold:
            return None
        root = self._router.hottest_group(hot)
        if root is None:
            return None
        self._router.pin(root, cold)
        tm.POOL_REBALANCE.inc()
        get_flight_recorder().record("pool.rebalance", root=root,
                                     src=hot, dst=cold)
        return root

    # -- read side -----------------------------------------------------------
    @property
    def errors(self) -> Dict[int, RequestError]:
        return {uid: r.error for uid, r in self._requests.items()
                if r.error is not None}

    def results(self) -> Dict[int, List[int]]:
        return {uid: list(r.tokens)
                for uid, r in self._requests.items() if r.done}

    def request(self, uid: int) -> Optional[PoolRequest]:
        return self._requests.get(uid)

    def stats(self) -> Dict:
        reqs = list(self._requests.values())
        return {
            "replicas": self.labels,
            "requests": len(reqs),
            "completed": sum(r.done for r in reqs),
            "errors": sum(r.error is not None for r in reqs),
            "inflight": sum(not r.finalized for r in reqs),
            "migrated": sum(r.migrations > 0 for r in reqs),
            "orphans": len(self._orphans),
            "backlogs": self._backlogs(),
        }
