"""Replica-pool serving (ISSUE 12, ROADMAP item 1): N FastGenScheduler
replicas behind a prefix-affinity router with live migration and
SLO-driven autoscaling — plus disaggregated prefill/decode pools with
committed-page KV streaming (ISSUE 13, ROADMAP item 2)."""

from .disagg import DisaggPool
from .pool import PoolRequest, ReplicaPool
from .router import (POLICIES, PrefixAffinityRouter, RouteDecision,
                     fetch_remote_hints)

__all__ = ["ReplicaPool", "PoolRequest", "PrefixAffinityRouter",
           "RouteDecision", "POLICIES", "fetch_remote_hints",
           "DisaggPool"]
