"""Prefix-affinity request router (ISSUE 12).

Placement rule: replicas periodically publish a bounded top-K slice of
their prefix cache's chained page-digest index
(``PrefixCache.export_digests`` — hex digests only, never page
contents).  The router hashes an incoming prompt's FULL pages with the
same chained blake2b scheme (``PrefixCache.chain``) and walks the chain
from the root: for each replica, the match length is the number of
leading cumulative digests the replica's published hint contains.  The
request goes to the replica with the LONGEST digest-prefix match —
that replica already holds the matched pages, so admission there
prefills only the uncached suffix (warm TTFT) and the pool's aggregate
prefix hit rate is maximized.  Cold prompts (no replica matches) fall
back to least-backlog placement; ties break by label order so routing
is deterministic under equal state.

Because the digest is *cumulative* (digest_i commits to all tokens of
pages 0..i), a match of length k is exact evidence that the replica's
cache indexed this very k-page prefix at publish time — two prompts
sharing page 3's tokens but differing in page 0 can never cross-match.
Hints go stale between publishes; staleness only costs warmth, never
correctness (a stale match routes to a replica whose cache may have
evicted the pages — admission simply prefills more).

``pin(root_digest, label)`` overrides affinity for one digest GROUP
(every prompt whose first full page hashes to that root) — the
rebalance action: the pool re-homes the hottest group to the coldest
replica and the pinned replica warms its own cache on first arrival.

Policies: ``affinity`` (default), ``least_backlog`` (ignore hints),
``round_robin`` (ignore hints AND backlogs — the control arm the bench
compares affinity's hit rate against).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import Counter
from typing import Dict, List, Optional, Sequence

import numpy as np

POLICIES = ("affinity", "least_backlog", "round_robin")


@dataclasses.dataclass
class RouteDecision:
    """One placement verdict: the chosen replica, how many leading
    prompt pages its published hints matched (0 = cold placement), and
    why (``affinity`` / ``pin`` / ``backlog`` / ``round_robin``)."""
    label: str
    matched_pages: int = 0
    reason: str = "backlog"
    #: cross-replica page fetch hint (ISSUE 16): when affinity lost to
    #: least-backlog, the peer that DID match — the chosen replica can
    #: stream the matched committed pages from it instead of
    #: recomputing the prefill
    fetch_from: Optional[str] = None
    #: the leading cumulative digest chain (hex, root first) the peer
    #: matched — exactly what ``StateManager.export_prefix`` consumes
    fetch_digests: List[str] = dataclasses.field(default_factory=list)


class PrefixAffinityRouter:
    """Route prompts to the replica already holding their prefix."""

    def __init__(self, page_size: int, top_k: int = 64,
                 policy: str = "affinity",
                 fetch_backlog_margin: int = -1):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if policy not in POLICIES:
            raise ValueError(f"policy {policy!r} not in {POLICIES}")
        self.page_size = int(page_size)
        self.top_k = int(top_k)
        self.policy = policy
        #: ISSUE 16: when >= 0, an affinity match whose replica is
        #: backlogged more than ``margin`` requests past the
        #: least-loaded replica LOSES the placement — the request goes
        #: to least-backlog carrying a ``fetch_from`` hint so the
        #: matched pages stream over instead of being recomputed.
        #: -1 keeps the pure affinity-first rule (PR 12 behavior)
        self.fetch_backlog_margin = int(fetch_backlog_margin)
        self._lock = threading.RLock()
        #: label -> published digest hints (set for O(1) chain walk)
        self._hints: Dict[str, set] = {}
        #: root digest (hex) -> pinned label (rebalance overrides)
        self._pins: Dict[str, str] = {}
        #: root digest -> Counter(label) of affinity placements — the
        #: heat map rebalancing reads to find the hottest group
        self._heat: Dict[str, Counter] = {}
        self._rr = 0

    # -- hint publication ----------------------------------------------------
    def publish(self, label: str, digests: Sequence[str]) -> None:
        """Replace ``label``'s published hint slice (most recent first,
        as ``export_digests`` returns it; order is irrelevant to the
        chain walk, the bound is what matters)."""
        with self._lock:
            self._hints[label] = set(digests[:self.top_k])

    def forget(self, label: str) -> None:
        """Drop a removed/dead replica: its hints, pins, and heat."""
        with self._lock:
            self._hints.pop(label, None)
            self._pins = {d: lb for d, lb in self._pins.items()
                          if lb != label}
            for c in self._heat.values():
                c.pop(label, None)

    def pin(self, root_digest: str, label: str) -> None:
        """Force every prompt of one digest group (same first full
        page) onto ``label`` — the rebalance re-homing action."""
        with self._lock:
            self._pins[root_digest] = label

    def unpin(self, root_digest: str) -> None:
        with self._lock:
            self._pins.pop(root_digest, None)

    # -- the placement rule --------------------------------------------------
    def prompt_digests(self, prompt) -> List[str]:
        """The prompt's cumulative full-page digest chain as hex —
        EXACTLY the scheme the prefix cache indexes under
        (:meth:`~..inference.v2.ragged.prefix_cache.PrefixCache.chain`),
        so router matches and cache hits agree by construction."""
        from ..inference.v2.ragged.prefix_cache import PrefixCache
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        ps = self.page_size
        out: List[str] = []
        d = b""
        for i in range(len(prompt) // ps):
            d = PrefixCache.chain(d, prompt[i * ps:(i + 1) * ps])
            out.append(d.hex())
        return out

    def _match_len(self, digests: List[str], hints: set) -> int:
        n = 0
        for d in digests:
            if d not in hints:
                break
            n += 1
        return n

    def decide(self, prompt, backlogs: Dict[str, int]) -> RouteDecision:
        """Place one prompt among the live replicas (``backlogs`` maps
        every live label to its current request backlog).  Raises on an
        empty pool — the caller owns spawn-on-empty semantics."""
        if not backlogs:
            raise ValueError("no live replicas to route to")
        labels = sorted(backlogs)
        if self.policy == "round_robin":
            with self._lock:
                label = labels[self._rr % len(labels)]
                self._rr += 1
            return RouteDecision(label, 0, "round_robin")
        # hash OUTSIDE the lock: the chain is O(prompt) blake2b work
        # over no shared state, and holding the lock across it would
        # serialize every concurrent decide()/publish() on it
        digests = (self.prompt_digests(prompt)
                   if self.policy == "affinity" else [])
        with self._lock:
            if digests:
                pinned = self._pins.get(digests[0])
                if pinned in backlogs:
                    self._note_heat(digests[0], pinned)
                    return RouteDecision(
                        pinned,
                        self._match_len(digests,
                                        self._hints.get(pinned, set())),
                        "pin")
                best, best_match = None, 0
                for label in labels:
                    m = self._match_len(digests,
                                        self._hints.get(label, set()))
                    if m > best_match or (m == best_match and m > 0
                                          and best is not None
                                          and backlogs[label]
                                          < backlogs[best]):
                        best, best_match = label, m
                if best is not None and best_match > 0:
                    least = min(labels,
                                key=lambda lb: (backlogs[lb], lb))
                    if (self.fetch_backlog_margin >= 0
                            and least != best
                            and backlogs[best] - backlogs[least]
                            > self.fetch_backlog_margin):
                        # affinity loses to least-backlog (ISSUE 16):
                        # place on the idle replica, but hand it the
                        # matched peer + digest chain so the pool can
                        # FETCH the pages instead of recomputing them
                        self._note_heat(digests[0], least)
                        return RouteDecision(
                            least, 0, "backlog", fetch_from=best,
                            fetch_digests=digests[:best_match])
                    self._note_heat(digests[0], best)
                    return RouteDecision(best, best_match, "affinity")
            label = min(labels, key=lambda lb: (backlogs[lb], lb))
            if digests:
                self._note_heat(digests[0], label)
            return RouteDecision(label, 0, "backlog")

    def _note_heat(self, root: str, label: str) -> None:
        self._heat.setdefault(root, Counter())[label] += 1

    def hottest_group(self, label: str) -> Optional[str]:
        """The root digest most often routed to ``label`` (None when
        nothing was) — the rebalance victim-group selector."""
        with self._lock:
            best, best_n = None, 0
            for root, counts in self._heat.items():
                n = counts.get(label, 0)
                if n > best_n:
                    best, best_n = root, n
            return best


def fetch_remote_hints(target: str, top_k: int = 64,
                       timeout_s: float = 2.0) -> Dict:
    """Scrape one replica's ``/snapshot?digests=1`` affinity hint (the
    subprocess-mode hint source — ``tools/fleet_replica.py`` children
    publish theirs automatically at engine build).  Returns
    ``{"page_size", "digests"}``; raises on an unreachable replica."""
    import json
    import urllib.request
    t = target if target.startswith(("http://", "https://")) \
        else "http://" + target
    url = f"{t.rstrip('/')}/snapshot?digests=1&top_k={int(top_k)}"
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return json.loads(r.read().decode())
