"""Per-architecture injection policies.

TPU-native analogue of ``deepspeed/module_inject/replace_policy.py`` +
``module_inject/containers/`` (policy classes per arch: llama, llama2,
bloom, gptj, gptneox, opt, bert, megatron, internlm, clip...).  A policy
resolves a HuggingFace architecture to:

* a :class:`~deepspeed_tpu.models.transformer.TransformerConfig`,
* a weight-loading function (HF state_dict -> our param tree),
* which makes "kernel injection" implicit — the functional transformer
  runs the Pallas flash kernel on the causal TPU path
  (models/transformer.py flash_dot_product_attention) and leaves
  RMSNorm/RoPE/bias-act to XLA fusion, covering what the reference's
  ``DeepSpeedTransformerInference`` containers swap in.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from ..checkpoint import hf as hf_ckpt
from ..utils.logging import logger


class InjectionPolicy:
    """Base policy (reference ``DSPolicy``/``TransformerPolicy``)."""

    #: HF ``model_type`` strings this policy claims
    MODEL_TYPES: Tuple[str, ...] = ()

    @classmethod
    def config_from_hf(cls, hf_cfg) -> Any:
        raise NotImplementedError

    @classmethod
    def load(cls, state_dict: Dict[str, Any], cfg, dtype) -> Any:
        raise NotImplementedError


class LlamaPolicy(InjectionPolicy):
    """Llama/Llama-2/Mistral family (reference containers/llama.py,
    llama2.py; mistral shares the rotary+GQA+SwiGLU shape)."""
    MODEL_TYPES = ("llama", "mistral")

    @classmethod
    def config_from_hf(cls, hf_cfg):
        return hf_ckpt.llama_config_from_hf(hf_cfg)

    @classmethod
    def load(cls, state_dict, cfg, dtype):
        return hf_ckpt.load_llama(state_dict, cfg, dtype=dtype)


class Qwen2Policy(InjectionPolicy):
    """Qwen2/Qwen2.5 (reference v2 model_implementations/qwen_v2):
    llama shape + attention qkv biases."""
    MODEL_TYPES = ("qwen2",)

    @classmethod
    def config_from_hf(cls, hf_cfg):
        return hf_ckpt.qwen2_config_from_hf(hf_cfg)

    @classmethod
    def load(cls, state_dict, cfg, dtype):
        return hf_ckpt.load_qwen2(state_dict, cfg, dtype=dtype)


class MixtralPolicy(InjectionPolicy):
    """Mixtral sparse-MoE (reference v2 model_implementations/mixtral):
    llama attention + top-k routed stacked experts."""
    MODEL_TYPES = ("mixtral",)

    @classmethod
    def config_from_hf(cls, hf_cfg):
        return hf_ckpt.mixtral_config_from_hf(hf_cfg)

    @classmethod
    def load(cls, state_dict, cfg, dtype):
        return hf_ckpt.load_mixtral(state_dict, cfg, dtype=dtype)


class GPTNeoXPolicy(InjectionPolicy):
    """GPT-NeoX/Pythia (reference containers/gptneox.py): parallel
    residual, partial rotary, fused-QKV with biases."""
    MODEL_TYPES = ("gpt_neox", "gptneox")

    @classmethod
    def config_from_hf(cls, hf_cfg):
        return hf_ckpt.gpt_neox_config_from_hf(hf_cfg)

    @classmethod
    def load(cls, state_dict, cfg, dtype):
        return hf_ckpt.load_gpt_neox(state_dict, cfg, dtype=dtype)


class GPT2Policy(InjectionPolicy):
    """GPT-2 family (reference containers/gpt2.py, distil_bert-style
    learned-position models load the same way)."""
    MODEL_TYPES = ("gpt2",)

    @classmethod
    def config_from_hf(cls, hf_cfg):
        return hf_ckpt.gpt2_config_from_hf(hf_cfg)

    @classmethod
    def load(cls, state_dict, cfg, dtype):
        return hf_ckpt.load_gpt2(state_dict, cfg, dtype=dtype)


class FalconPolicy(InjectionPolicy):
    """Falcon 7b/40b/falcon2 (reference v2 model_implementations/falcon
    + containers/falcon): MQA/GQA fused-QKV, parallel attn+mlp."""
    MODEL_TYPES = ("falcon", "refinedweb", "refinedwebmodel")

    @classmethod
    def config_from_hf(cls, hf_cfg):
        return hf_ckpt.falcon_config_from_hf(hf_cfg)

    @classmethod
    def load(cls, state_dict, cfg, dtype):
        # (num_heads, kv_heads) in cfg fully determine the fused-QKV
        # grouping — no HF arch flags needed, load stays stateless
        return hf_ckpt.load_falcon(state_dict, cfg, dtype=dtype)


class OPTPolicy(InjectionPolicy):
    """OPT (reference v2 model_implementations/opt + containers/opt.py):
    learned positions, relu MLP, biases everywhere."""
    MODEL_TYPES = ("opt",)

    @classmethod
    def config_from_hf(cls, hf_cfg):
        return hf_ckpt.opt_config_from_hf(hf_cfg)

    @classmethod
    def load(cls, state_dict, cfg, dtype):
        return hf_ckpt.load_opt(state_dict, cfg, dtype=dtype)


class PhiPolicy(InjectionPolicy):
    """Phi-1/1.5/2 (reference v2 model_implementations/phi): parallel
    residual off one LN, partial rotary, lm_head bias."""
    MODEL_TYPES = ("phi",)

    @classmethod
    def config_from_hf(cls, hf_cfg):
        return hf_ckpt.phi_config_from_hf(hf_cfg)

    @classmethod
    def load(cls, state_dict, cfg, dtype):
        return hf_ckpt.load_phi(state_dict, cfg, dtype=dtype)


class Phi3Policy(InjectionPolicy):
    """Phi-3 (reference v2 model_implementations/phi3): llama-shaped
    with fused qkv/gate_up projections."""
    MODEL_TYPES = ("phi3",)

    @classmethod
    def config_from_hf(cls, hf_cfg):
        return hf_ckpt.phi3_config_from_hf(hf_cfg)

    @classmethod
    def load(cls, state_dict, cfg, dtype):
        return hf_ckpt.load_phi3(state_dict, cfg, dtype=dtype)


class BloomPolicy(InjectionPolicy):
    """BLOOM (reference containers/bloom.py): ALiBi + post-embedding
    layernorm + per-head fused QKV."""
    MODEL_TYPES = ("bloom",)

    @classmethod
    def config_from_hf(cls, hf_cfg):
        return hf_ckpt.bloom_config_from_hf(hf_cfg)

    @classmethod
    def load(cls, state_dict, cfg, dtype):
        return hf_ckpt.load_bloom(state_dict, cfg, dtype=dtype)


class GPTJPolicy(InjectionPolicy):
    """GPT-J (reference containers/gptj.py): parallel residual off one
    ln, native interleaved partial rotary."""
    MODEL_TYPES = ("gptj",)

    @classmethod
    def config_from_hf(cls, hf_cfg):
        return hf_ckpt.gptj_config_from_hf(hf_cfg)

    @classmethod
    def load(cls, state_dict, cfg, dtype):
        return hf_ckpt.load_gptj(state_dict, cfg, dtype=dtype)


_POLICIES = [LlamaPolicy, Qwen2Policy, MixtralPolicy, GPTNeoXPolicy,
             GPT2Policy, FalconPolicy, OPTPolicy, PhiPolicy, Phi3Policy,
             BloomPolicy, GPTJPolicy]


def replace_policy_for(model_type: str) -> InjectionPolicy:
    """Resolve arch -> policy (reference ``replace_policy`` registry)."""
    for pol in _POLICIES:
        if model_type.lower() in pol.MODEL_TYPES:
            return pol
    raise ValueError(
        f"no injection policy for architecture {model_type!r}; supported: "
        f"{sorted(t for p in _POLICIES for t in p.MODEL_TYPES)}")


def register_policy(policy: type) -> None:
    """Register a custom policy class (reference ``injection_policy`` arg
    of ``init_inference``)."""
    _POLICIES.insert(0, policy)
