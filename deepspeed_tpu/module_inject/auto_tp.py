"""AutoTP: automatic tensor-parallel sharding of checkpoint weights.

TPU-native analogue of ``deepspeed/module_inject/auto_tp.py`` (``AutoTP``
:191, ``tp_parser`` :283, ``ReplaceWithTensorSlicing`` :32) and the
inference-v2 sharding lib (``inference/v2/model_implementations/sharding/``).

The reference walks an ``nn.Module`` tree and physically slices ``Linear``
weights row/col per rank.  Under GSPMD nothing is sliced by hand: AutoTP
here *parses* a parameter tree (by logical-axis boxes when present, else by
name heuristics over HF-style keys) into a ``PartitionSpec`` tree, and one
``jax.device_put`` distributes every weight; XLA inserts the matching
all-reduce after row-parallel matmuls automatically.

Heuristic classes (reference ``tp_parser`` logic):
* **column-parallel** (shard output dim): q/k/v/query/key/value, gate/up,
  fused qkv, first MLP linear, embedding vocab dim;
* **row-parallel** (shard input dim): attention output / o_proj / dense,
  second MLP linear (down_proj / fc2 / w2);
* indivisible dims stay replicated (reference keeps unsliceable modules
  unsharded rather than failing).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.logging import logger

# name-pattern -> (role, shard_dim) over the LAST path component(s).
# dims: 0 = rows = output features for [out, in] torch layout; our arrays
# are [in, out] (jax dense convention), handled by layout below.
COLUMN_PATTERNS = [
    r"q_proj", r"k_proj", r"v_proj", r"query", r"key", r"value", r"\bwq\b",
    r"\bwk\b", r"\bwv\b", r"qkv", r"gate_proj", r"up_proj", r"\bw1\b",
    r"\bw3\b", r"fc1", r"c_fc", r"dense_h_to_4h", r"wi", r"intermediate",
]
ROW_PATTERNS = [
    r"o_proj", r"out_proj", r"\bwo\b", r"attn[._]out", r"attention[._]output",
    r"down_proj", r"\bw2\b", r"fc2", r"c_proj", r"dense_4h_to_h", r"wo\b",
    r"dense$",
]
EMBED_PATTERNS = [r"embed_tokens", r"\bwte\b", r"word_embeddings",
                  r"lm_head", r"embed_out", r"tokens$", r"unembed"]

_COL = re.compile("|".join(COLUMN_PATTERNS))
_ROW = re.compile("|".join(ROW_PATTERNS))
_EMB = re.compile("|".join(EMBED_PATTERNS))


def classify(name: str) -> Optional[str]:
    """Classify one parameter path: 'column' | 'row' | 'embed' | None."""
    lower = name.lower()
    if _EMB.search(lower):
        return "embed"
    if _COL.search(lower):
        return "column"
    if _ROW.search(lower):
        return "row"
    return None


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        parts.append(str(key))
    return ".".join(parts)


class AutoTP:
    """Parse a param tree into TP PartitionSpecs + place it on a mesh."""

    def __init__(self, mesh: Mesh, tp_axis: str = "tensor",
                 weight_layout: str = "in_out"):
        """``weight_layout``: 'in_out' (jax dense [in, out]) or 'out_in'
        (torch Linear [out, in]) — decides which dim 'column'/'row' hit."""
        self.mesh = mesh
        self.tp_axis = tp_axis
        self.layout = weight_layout
        self.tp_size = mesh.shape.get(tp_axis, 1)

    # ------------------------------------------------------------ parsing
    def tp_parser(self, params: Any) -> Any:
        """PartitionSpec tree for ``params`` by name heuristics
        (reference ``AutoTP.tp_parser`` + ``_replace``)."""
        def spec_for(path, leaf) -> P:
            name = _path_str(path)
            role = classify(name)
            shape = np.shape(leaf)
            if role is None or len(shape) == 0:
                return P()
            if len(shape) == 1:
                # bias: column-parallel biases shard with outputs; row biases
                # are replicated (they're added after the all-reduce)
                if role == "column" and shape[0] % self.tp_size == 0:
                    return P(self.tp_axis)
                return P()
            out_dim = len(shape) - 1 if self.layout == "in_out" else len(shape) - 2
            in_dim = len(shape) - 2 if self.layout == "in_out" else len(shape) - 1
            dim = {"column": out_dim, "row": in_dim, "embed": out_dim}[role]
            if role == "embed":
                # embedding tables: [vocab, hidden] — shard vocab (dim -2 in
                # both layouts, it's not a matmul weight)
                dim = len(shape) - 2
            if shape[dim] % self.tp_size != 0:
                logger.debug("AutoTP: %s dim %d (%d) not divisible by tp=%d"
                             " — replicated", name, dim, shape[dim],
                             self.tp_size)
                return P()
            entries: List[Optional[str]] = [None] * len(shape)
            entries[dim] = self.tp_axis
            return P(*entries)

        return jax.tree_util.tree_map_with_path(spec_for, params)

    # ------------------------------------------------------------ placing
    def shard(self, params: Any) -> Any:
        """Distribute weights onto the mesh per the parsed specs
        (the ``ReplaceWithTensorSlicing`` analogue — one device_put)."""
        specs = self.tp_parser(params)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            params, specs)

    def replication_report(self, params: Any) -> Dict[str, str]:
        """name -> spec string, for debugging which weights got sharded."""
        specs = self.tp_parser(params)
        flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
        flat_s = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
        return {_path_str(path): str(spec)
                for (path, _), spec in zip(flat_p, flat_s)}
