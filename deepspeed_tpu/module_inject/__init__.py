"""Module injection / AutoTP (reference ``deepspeed/module_inject/``)."""

from .auto_tp import AutoTP, classify  # noqa: F401
from .policies import (  # noqa: F401
    GPT2Policy,
    InjectionPolicy,
    LlamaPolicy,
    register_policy,
    replace_policy_for,
)
