"""OptimizedLinear: LoRA + sharded/quantized frozen base weights.

TPU-native analogue of ``deepspeed/linear/optimized_linear.py:18``
(``OptimizedLinear``/``LoRAOptimizedLinear`` :76) and
``linear/quantization.py`` (quantized frozen base): a linear layer whose
frozen base weight can be (a) sharded over the mesh and (b) stored
int8-blockwise (dequantized on the fly inside the matmul program), while
only the low-rank A/B adapters train.

Functional API: ``init`` builds the param dict, ``apply`` is the forward,
``trainable_mask`` feeds ``optax.masked`` so the engine's optimizer only
touches adapters — the reference achieves the same by setting
``requires_grad=False`` on the base.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.quantization import dequantize_blockwise, quantize_blockwise
from ..utils.logging import logger


@dataclasses.dataclass
class LoRAConfig:
    """Reference ``deepspeed.linear.LoRAConfig``."""
    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1  # shard base over this many ranks ('fsdp')


@dataclasses.dataclass
class QuantizationConfig:
    """Reference ``deepspeed.linear.QuantizationConfig``.

    ``q_dtype='int8'`` keeps the Pallas int8 blockwise path;
    ``'fp8_e4m3'`` / ``'fp8_e5m2'`` / ``'fp6_e3m2'`` / ``'fp4_e2m1'``
    store the frozen base in a real low-precision FLOAT buffer via
    ops/fp_quantizer (the reference's FP_Quantize path,
    linear/quantization.py:52 — TPU v5e+ fp8 is a native dtype)."""
    q_bits: int = 8
    mantissa_bits: int = 3   # accepted for config parity (fp6/fp8 path)
    group_size: int = 512
    q_dtype: str = "int8"

    def resolved_dtype(self) -> str:
        """int8 covers only q_bits=8; a reference-style q_bits=6/4 config
        (reference keys format by q_bits, fp_quantizer/quantize.py:46)
        resolves to the matching FP format rather than being ignored."""
        if self.q_dtype == "int8" and self.q_bits != 8:
            from ..ops.fp_quantizer import _BITS_TO_FORMAT
            return _BITS_TO_FORMAT[self.q_bits]
        return self.q_dtype


class OptimizedLinear:
    """Factory for one linear layer's params + forward.

    >>> lin = OptimizedLinear(256, 512, lora_config=LoRAConfig(lora_r=8))
    >>> params = lin.init(jax.random.key(0))
    >>> y = lin.apply(params, x)
    """

    def __init__(self, input_dim: int, output_dim: int,
                 lora_config: Optional[LoRAConfig] = None,
                 quantization_config: Optional[QuantizationConfig] = None,
                 bias: bool = False,
                 dtype=jnp.bfloat16):
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.lora = lora_config
        self.quant = quantization_config
        self.bias = bias
        self.dtype = dtype
        if self.lora is not None and self.lora.lora_r > min(
                input_dim, output_dim):
            raise ValueError(
                f"lora_r {self.lora.lora_r} exceeds min(in,out)="
                f"{min(input_dim, output_dim)}")

    # ----------------------------------------------------------- params
    def init(self, rng: jax.Array,
             base_weight: Optional[jax.Array] = None) -> Dict[str, Any]:
        k_base, k_a = jax.random.split(rng)
        if base_weight is None:
            scale = 1.0 / jnp.sqrt(self.input_dim)
            base_weight = jax.random.uniform(
                k_base, (self.input_dim, self.output_dim),
                jnp.float32, -scale, scale)
        base_weight = jnp.asarray(base_weight)
        params: Dict[str, Any] = {}
        if self.quant is not None:
            if self.quant.resolved_dtype() != "int8":
                from ..ops import fp_quantizer
                q, s, pad = fp_quantizer.quantize(
                    base_weight, group_size=self.quant.group_size,
                    fmt=self.quant.resolved_dtype())
            else:
                q, s, pad = quantize_blockwise(base_weight,
                                               block=self.quant.group_size)
            # pad is shape-derived and static — keeping it OUT of the param
            # tree keeps apply() jittable and the optimizer tree clean
            assert pad == self._static_pad(), (pad, self._static_pad())
            params["base_q"] = q
            params["base_scale"] = s
        else:
            params["base"] = base_weight.astype(self.dtype)
        if self.lora is not None:
            # reference init: A ~ kaiming, B = 0 so the adapter starts as a
            # no-op around the frozen base
            params["lora_a"] = (jax.random.normal(
                k_a, (self.input_dim, self.lora.lora_r), jnp.float32)
                / jnp.sqrt(self.input_dim)).astype(self.dtype)
            params["lora_b"] = jnp.zeros(
                (self.lora.lora_r, self.output_dim), self.dtype)
        if self.bias:
            params["bias"] = jnp.zeros((self.output_dim,), self.dtype)
        return params

    # ---------------------------------------------------------- forward
    def _static_pad(self) -> int:
        n = self.input_dim * self.output_dim
        block = self.quant.group_size
        return (block - n % block) % block

    def _base_weight(self, params: Dict[str, Any]) -> jax.Array:
        if "base_q" in params:
            if self.quant.resolved_dtype() != "int8":
                from ..ops import fp_quantizer
                return fp_quantizer.dequantize(
                    params["base_q"], params["base_scale"],
                    self._static_pad(),
                    (self.input_dim, self.output_dim), dtype=self.dtype)
            return dequantize_blockwise(
                params["base_q"], params["base_scale"], self._static_pad(),
                (self.input_dim, self.output_dim),
                dtype=self.dtype)
        return params["base"]

    def apply(self, params: Dict[str, Any], x: jax.Array) -> jax.Array:
        w = self._base_weight(params).astype(self.dtype)
        y = x.astype(self.dtype) @ w
        if self.lora is not None:
            scaling = self.lora.lora_alpha / self.lora.lora_r
            y = y + (x.astype(self.dtype) @ params["lora_a"]
                     ) @ params["lora_b"] * scaling
        if self.bias:
            y = y + params["bias"]
        return y

    __call__ = apply

    # -------------------------------------------------------- train mask
    def trainable_mask(self, params: Dict[str, Any]) -> Dict[str, bool]:
        """True only for adapter (and bias) leaves — base is frozen
        (reference: base.requires_grad=False)."""
        return {k: k in ("lora_a", "lora_b", "bias") for k in params}

    def merge(self, params: Dict[str, Any]) -> jax.Array:
        """Fold the adapter into a dense weight (for export/inference)."""
        w = self._base_weight(params).astype(jnp.float32)
        if self.lora is not None:
            scaling = self.lora.lora_alpha / self.lora.lora_r
            w = w + params["lora_a"].astype(jnp.float32) @ \
                params["lora_b"].astype(jnp.float32) * scaling
        return w


def lora_trainable_mask(params: Any) -> Any:
    """Tree-wide mask: only ``lora_a``/``lora_b``/``bias`` leaves train.
    Feed to ``optax.masked`` for whole-model LoRA fine-tuning."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, _ in flat:
        name = str(getattr(path[-1], "key", path[-1])) if path else ""
        out.append(name in ("lora_a", "lora_b", "bias"))
    return jax.tree.unflatten(treedef, out)
