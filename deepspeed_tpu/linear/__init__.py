"""LoRA / OptimizedLinear (reference ``deepspeed/linear/``)."""

from .optimized_linear import (  # noqa: F401
    LoRAConfig,
    OptimizedLinear,
    QuantizationConfig,
    lora_trainable_mask,
)
