"""Device-mesh topology: the TPU-native replacement for process groups.

The reference builds parallel "grids" out of torch.distributed process
groups (``deepspeed/utils/groups.py``, ``runtime/pipe/topology.py``:
``ProcessTopology`` / ``PipeModelDataParallelTopology``).  On TPU the same
roles are played by named axes of a single ``jax.sharding.Mesh``; XLA then
lowers per-axis collectives onto ICI/DCN.  This module owns the canonical
axis names and the arithmetic that maps a DeepSpeed-style parallel config
(dp/tp/pp/sp/ep sizes) onto a mesh.

Axis roles (ordered outermost -> innermost; innermost axes get
ICI-adjacent devices, so the most communication-hungry axes go last):

  pipe    pipeline-parallel stages           (reference: PP axis 'pipe')
  data    pure data parallelism (replicas)   (reference: DP axis 'data')
  expert  expert parallelism for MoE         (reference: EP groups)
  fsdp    ZeRO parameter/optimizer sharding  (reference: ZeRO partitioning
                                              inside the DP group)
  seq     sequence (Ulysses) parallelism     (reference: SP groups)
  tensor  tensor (Megatron) parallelism      (reference: MP/'model' axis)

DeepSpeed equivalences:
  * dp_world (grad-reduction group)  == data x expert x fsdp x seq
    (sequence ranks see different tokens, so they are also gradient
    replicas, matching reference engine.py:320-326 SP grad allreduce)
  * ZeRO stage 1/2/3 partition_count == size of 'fsdp'
  * MoE expert-data-parallel group   == 'data' (+ 'fsdp' when ep covers it)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.logging import logger

# Canonical axis order, outermost first.
MESH_AXES: Tuple[str, ...] = ("pipe", "data", "expert", "fsdp", "hpz", "seq", "tensor")

# Composite axis groups used for common shardings.
BATCH_AXES = ("data", "expert", "fsdp", "hpz")  # batch dim of inputs
GRAD_REDUCE_AXES = ("data", "expert", "fsdp", "hpz", "seq")  # dp_world for grad psum


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    """Sizes of each mesh axis.  -1 means "absorb remaining devices"."""
    pipe: int = 1
    data: int = -1
    expert: int = 1
    fsdp: int = 1
    # ZeRO++ hpZ secondary partition: an INNER shard axis placed on
    # ICI-adjacent devices; stage-3 per-layer gathers ride only this axis
    # while optimizer state shards over fsdp x hpz (see zero/partitioner).
    hpz: int = 1
    seq: int = 1
    tensor: int = 1

    def resolve(self, n_devices: int) -> "TopologyConfig":
        sizes = {a: getattr(self, a) for a in MESH_AXES}
        free = [a for a, s in sizes.items() if s == -1]
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if n_devices % fixed != 0:
            raise ValueError(
                f"mesh axes {sizes} do not divide device count {n_devices}")
        rem = n_devices // fixed
        if not free:
            if fixed != n_devices:
                raise ValueError(
                    f"mesh axes {sizes} (product {fixed}) != device count {n_devices}")
        elif len(free) == 1:
            sizes[free[0]] = rem
        else:
            # First free axis absorbs everything, the rest get 1.
            sizes[free[0]] = rem
            for a in free[1:]:
                sizes[a] = 1
        return TopologyConfig(**sizes)


class MeshTopology:
    """A resolved device mesh plus DeepSpeed-style group arithmetic."""

    def __init__(self,
                 config: Optional[TopologyConfig] = None,
                 devices: Optional[Sequence[jax.Device]] = None):
        self.devices = list(devices) if devices is not None else jax.devices()
        cfg = (config or TopologyConfig()).resolve(len(self.devices))
        self.config = cfg
        shape = tuple(getattr(cfg, a) for a in MESH_AXES)
        dev_array = np.asarray(self.devices).reshape(shape)
        self.mesh = Mesh(dev_array, MESH_AXES)
        logger.info("MeshTopology: %s over %d devices",
                    {a: s for a, s in zip(MESH_AXES, shape) if s > 1} or "{single}",
                    len(self.devices))

    # -- DeepSpeed-compatible size accessors ------------------------------
    @property
    def world_size(self) -> int:
        return len(self.devices)

    def axis_size(self, axis: str) -> int:
        return getattr(self.config, axis)

    @property
    def pp_world_size(self) -> int:
        return self.config.pipe

    @property
    def tp_world_size(self) -> int:
        return self.config.tensor

    @property
    def sp_world_size(self) -> int:
        return self.config.seq

    @property
    def ep_world_size(self) -> int:
        return self.config.expert

    @property
    def fsdp_world_size(self) -> int:
        return self.config.fsdp

    @property
    def hpz_world_size(self) -> int:
        return self.config.hpz

    @property
    def dp_world_size(self) -> int:
        """Gradient-reduction world size (reference dp group size)."""
        return math.prod(self.axis_size(a) for a in GRAD_REDUCE_AXES)

    @property
    def batch_shard_size(self) -> int:
        """Number of distinct micro-batch shards along the batch dim."""
        return math.prod(self.axis_size(a) for a in BATCH_AXES)

    # -- sharding helpers -------------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_spec(self, seq_sharded: bool = True) -> P:
        """PartitionSpec for [batch, seq, ...] input arrays."""
        if seq_sharded and self.config.seq > 1:
            return P(BATCH_AXES, "seq")
        return P(BATCH_AXES)

    def batch_sharding(self, seq_sharded: bool = True) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec(seq_sharded))

    def __repr__(self) -> str:
        sizes = {a: self.axis_size(a) for a in MESH_AXES}
        return f"MeshTopology({sizes})"


def single_device_topology() -> MeshTopology:
    return MeshTopology(TopologyConfig(data=1), devices=jax.devices()[:1])


def ambient_mesh():
    """The physical Mesh active at trace time, or None.

    Single lookup point for trace-time mesh discovery (used by the
    transformer's sharding constraints and comm.get_world_group).  Tries
    the current private location first, then the deprecated public alias
    — when JAX removes both, this one site needs the update."""
    for locate in (
        lambda: __import__("jax._src.mesh", fromlist=["thread_resources"]
                           ).thread_resources.env.physical_mesh,
        lambda: __import__("jax.interpreters.pxla", fromlist=["thread_resources"]
                           ).thread_resources.env.physical_mesh,
    ):
        try:
            m = locate()
        except Exception:
            continue
        if m is not None and not m.empty:
            return m
        return None
    return None
