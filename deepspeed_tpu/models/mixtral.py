"""Mixtral — sparse-MoE LLaMA-style decoder (BASELINE config #4:
Mixtral-8x7B expert parallel; reference inference impl
``inference/v2/model_implementations/mixtral/``, training MoE via
``deepspeed/moe/``)."""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from flax.core import meta

from ..moe.layer import MoEConfig, init_moe_params, moe_forward
from .transformer import (CausalLM, TransformerConfig, cross_entropy_loss,
                          forward, init_params)


def mixtral_config(size: str = "8x7b", **overrides) -> TransformerConfig:
    presets = {
        "8x7b": dict(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                     num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=4096),
        "tiny": dict(vocab_size=512, hidden_size=128, intermediate_size=256,
                     num_layers=4, num_heads=4, num_kv_heads=2, max_seq_len=256),
        "debug": dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=64),
    }
    base = dict(norm="rmsnorm", norm_eps=1e-5, activation="silu_gated",
                pos_emb="rope", causal=True, tie_embeddings=False,
                use_bias=False, dtype=jnp.bfloat16)
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


class MixtralForCausalLM(CausalLM):
    """LLaMA backbone with the dense MLP swapped for a top-2 MoE."""

    def __init__(self, size: str = "8x7b", num_experts: int = 8,
                 top_k: int = 2, moe_overrides: Dict[str, Any] = None,
                 **overrides):
        super().__init__(mixtral_config(size, **overrides))
        moe_kw = dict(num_experts=num_experts, top_k=top_k,
                      activation=self.cfg.activation)
        moe_kw.update(moe_overrides or {})
        self.moe_cfg = MoEConfig(**moe_kw)

    def init_params(self, rng):
        params = init_params(self.cfg, rng)
        # swap each layer's dense mlp for MoE params (stacked over layers)
        L = self.cfg.num_layers
        moe_rngs = [jax.random.fold_in(rng, 10_000 + i) for i in range(L)]
        per_layer = [init_moe_params(self.moe_cfg, self.cfg.hidden_size,
                                     self.cfg.intermediate_size, r)
                     for r in moe_rngs]
        if self.cfg.scan_layers:
            stacked = jax.tree.map(
                lambda *xs: meta.Partitioned(
                    jnp.stack([x.value for x in xs]),
                    names=("layers",) + xs[0].names),
                *per_layer,
                is_leaf=lambda x: isinstance(x, meta.Partitioned))
            params["layers"]["mlp"] = stacked
        else:
            for i in range(L):
                params["layers"][f"layer_{i}"]["mlp"] = per_layer[i]
        return params

    def logits_and_aux(self, params, batch, rng=None, is_training=True):
        # rng threads into gate noise (noisy_gate_policy); shared across
        # layers within a step (independent per micro-batch via the engine)
        def mlp_fn(cfg, mlp_params, x):
            return moe_forward(self.moe_cfg, mlp_params, x, rng=rng,
                               is_training=is_training)
        return forward(self.cfg, params, batch["input_ids"],
                       positions=batch.get("positions"),
                       attention_mask=batch.get("attention_mask"),
                       mlp_fn=mlp_fn, return_aux=True)

    def logits(self, params, batch, rng=None):
        return self.logits_and_aux(params, batch, rng)[0]

    def _loss(self, params, batch, rng, is_training):
        logits, aux = self.logits_and_aux(params, batch, rng, is_training)
        if "labels" in batch:
            ce = cross_entropy_loss(logits, batch["labels"],
                                    batch.get("attention_mask"))
        else:
            labels = batch["input_ids"][:, 1:]
            mask = batch.get("attention_mask")
            ce = cross_entropy_loss(logits[:, :-1], labels,
                                    mask[:, 1:] if mask is not None else None)
        return ce + aux.astype(ce.dtype)

    def loss(self, params, batch, rng=None):
        return self._loss(params, batch, rng, is_training=True)

    def eval_loss(self, params, batch, rng=None):
        """Eval path: eval_capacity_factor, no gate noise (the engine's
        eval step prefers this method when present)."""
        return self._loss(params, batch, None, is_training=False)
