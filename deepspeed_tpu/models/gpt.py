"""GPT-2/GPT-3-style family (BASELINE config #3: GPT-1.3B pipeline)."""

from __future__ import annotations

import jax.numpy as jnp

from .transformer import CausalLM, TransformerConfig


def gpt_config(size: str = "1.3b", **overrides) -> TransformerConfig:
    presets = {
        "125m": dict(vocab_size=50257, hidden_size=768, intermediate_size=3072,
                     num_layers=12, num_heads=12, max_seq_len=1024),
        "350m": dict(vocab_size=50257, hidden_size=1024, intermediate_size=4096,
                     num_layers=24, num_heads=16, max_seq_len=1024),
        "1.3b": dict(vocab_size=50257, hidden_size=2048, intermediate_size=8192,
                     num_layers=24, num_heads=16, max_seq_len=2048),
        "2.7b": dict(vocab_size=50257, hidden_size=2560, intermediate_size=10240,
                     num_layers=32, num_heads=32, max_seq_len=2048),
        "debug": dict(vocab_size=128, hidden_size=64, intermediate_size=256,
                      num_layers=2, num_heads=4, max_seq_len=64),
    }
    base = dict(norm="layernorm", norm_eps=1e-5, activation="gelu",
                pos_emb="learned", causal=True, tie_embeddings=True,
                use_bias=True, dtype=jnp.bfloat16)
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


class GPTForCausalLM(CausalLM):
    def __init__(self, size: str = "1.3b", **overrides):
        super().__init__(gpt_config(size, **overrides))
