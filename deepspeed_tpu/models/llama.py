"""LLaMA family — the flagship model (BASELINE config #2: Llama-2-7B
ZeRO-3; reference inference impl at
``inference/v2/model_implementations/llama_v2/model.py:22``)."""

from __future__ import annotations

import jax.numpy as jnp

from .transformer import CausalLM, TransformerConfig


def llama_config(size: str = "7b", **overrides) -> TransformerConfig:
    presets = {
        # Llama-2 family
        "7b": dict(vocab_size=32000, hidden_size=4096, intermediate_size=11008,
                   num_layers=32, num_heads=32, num_kv_heads=32, max_seq_len=4096),
        "13b": dict(vocab_size=32000, hidden_size=5120, intermediate_size=13824,
                    num_layers=40, num_heads=40, num_kv_heads=40, max_seq_len=4096),
        "70b": dict(vocab_size=32000, hidden_size=8192, intermediate_size=28672,
                    num_layers=80, num_heads=64, num_kv_heads=8, max_seq_len=4096),
        # small configs for tests / benches
        "1b": dict(vocab_size=32000, hidden_size=2048, intermediate_size=5504,
                   num_layers=16, num_heads=16, num_kv_heads=16, max_seq_len=2048),
        # MXU-friendly ~2.1B bench config (head_dim 128, dims % 128 == 0)
        "2b": dict(vocab_size=32000, hidden_size=2560, intermediate_size=6912,
                   num_layers=24, num_heads=20, num_kv_heads=20, max_seq_len=2048),
        "tiny": dict(vocab_size=512, hidden_size=128, intermediate_size=352,
                     num_layers=4, num_heads=4, num_kv_heads=2, max_seq_len=256),
        "debug": dict(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=64),
    }
    base = dict(norm="rmsnorm", norm_eps=1e-5, activation="silu_gated",
                pos_emb="rope", causal=True, tie_embeddings=False,
                use_bias=False, dtype=jnp.bfloat16)
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


class LlamaForCausalLM(CausalLM):
    def __init__(self, size: str = "7b", **overrides):
        super().__init__(llama_config(size, **overrides))
