"""BERT family — bidirectional encoder + MLM head (BASELINE config #1:
BERT-base ZeRO-1 DP; reference training kernels target this class of model,
csrc/transformer/).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .transformer import (TransformerConfig, cross_entropy_loss, forward,
                          init_params)


def bert_config(size: str = "base", **overrides) -> TransformerConfig:
    presets = {
        "base": dict(vocab_size=30522, hidden_size=768, intermediate_size=3072,
                     num_layers=12, num_heads=12, max_seq_len=512),
        "large": dict(vocab_size=30522, hidden_size=1024, intermediate_size=4096,
                      num_layers=24, num_heads=16, max_seq_len=512),
        "debug": dict(vocab_size=128, hidden_size=64, intermediate_size=256,
                      num_layers=2, num_heads=4, max_seq_len=64),
    }
    base = dict(norm="layernorm", norm_eps=1e-12, activation="gelu",
                pos_emb="learned", causal=False, tie_embeddings=True,
                use_bias=True, dtype=jnp.bfloat16)
    base.update(presets[size])
    base.update(overrides)
    return TransformerConfig(**base)


class BertForMaskedLM:
    """Engine-protocol masked-LM.  Batch: {'input_ids', 'labels'
    (-100/-1 = unmasked positions), optional 'attention_mask'}."""

    def __init__(self, size: str = "base", **overrides):
        self.cfg = bert_config(size, **overrides)

    def init_params(self, rng):
        return init_params(self.cfg, rng)

    def logits(self, params, batch, rng=None):
        return forward(self.cfg, params, batch["input_ids"],
                       attention_mask=batch.get("attention_mask"))

    def loss(self, params, batch, rng=None):
        logits = self.logits(params, batch, rng)
        labels = batch["labels"]
        labels = jnp.where(labels == -100, -1, labels)  # HF convention
        return cross_entropy_loss(logits, labels, batch.get("attention_mask"))
