"""Model protocol + test fixtures.

The engine consumes any object exposing:
  init_params(rng) -> params pytree (optionally flax-Partitioned-boxed
                      with logical axis names for TP/EP sharding)
  loss(params, batch, rng) -> scalar loss

``FlaxModelAdapter`` wraps a flax linen module + criterion into this
protocol.  ``SimpleModel`` / ``SimpleMoEModel`` mirror the reference test
fixtures (``tests/unit/simple_model.py:20,80``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Protocol, runtime_checkable

import jax
import jax.numpy as jnp


@runtime_checkable
class Model(Protocol):
    def init_params(self, rng) -> Any: ...
    def loss(self, params, batch, rng) -> jax.Array: ...


class FlaxModelAdapter:
    """Adapts a flax linen module to the engine protocol."""

    def __init__(self, module, loss_fn: Callable, example_batch: Any,
                 input_keys=("input",), mutable: bool = False):
        self.module = module
        self._criterion = loss_fn
        self._example = example_batch
        self._input_keys = input_keys

    def init_params(self, rng):
        inputs = [self._example[k] for k in self._input_keys]
        variables = self.module.init(rng, *inputs)
        return variables["params"]

    def apply(self, params, *inputs, rngs=None):
        return self.module.apply({"params": params}, *inputs, rngs=rngs)

    def loss(self, params, batch, rng):
        inputs = [batch[k] for k in self._input_keys]
        rngs = {"dropout": rng, "params": rng} if rng is not None else None
        out = self.module.apply({"params": params}, *inputs, rngs=rngs)
        return self._criterion(out, batch)


class SimpleModel:
    """MLP regression fixture (reference tests/unit/simple_model.py:20
    ``SimpleModel``: Linear stack + cross entropy; here an MLP + MSE over a
    dict batch {'x': [B, H], 'y': [B, H]})."""

    def __init__(self, hidden_dim: int = 64, nlayers: int = 2, seed: int = 0):
        self.hidden_dim = hidden_dim
        self.nlayers = nlayers

    def init_params(self, rng):
        keys = jax.random.split(rng, self.nlayers)
        h = self.hidden_dim
        return {
            f"layer_{i}": {
                "w": jax.random.normal(keys[i], (h, h), jnp.float32) / jnp.sqrt(h),
                "b": jnp.zeros((h,), jnp.float32),
            }
            for i in range(self.nlayers)
        }

    def forward(self, params, x):
        for i in range(self.nlayers):
            p = params[f"layer_{i}"]
            x = x @ p["w"] + p["b"]
            if i < self.nlayers - 1:
                x = jax.nn.relu(x)
        return x

    def loss(self, params, batch, rng):
        pred = self.forward(params, batch["x"])
        return jnp.mean((pred - batch["y"].astype(pred.dtype)) ** 2)


def random_dataset(total_samples: int, hidden_dim: int, seed: int = 42):
    """Reference ``random_dataset`` (simple_model.py:266)."""
    import numpy as np
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(total_samples, hidden_dim)).astype(np.float32)
    ys = rng.normal(size=(total_samples, hidden_dim)).astype(np.float32)
    return [{"x": xs[i], "y": ys[i]} for i in range(total_samples)]
