"""Functional transformer core — shared implementation behind the model
families (LLaMA, GPT, BERT, Mixtral presets in sibling modules).

TPU-native design choices (cf. reference per-arch containers in
``deepspeed/module_inject/containers/`` and inference-v2 model
implementations ``inference/v2/model_implementations/``):

* **Pure functions over pytrees** — params are nested dicts of arrays
  boxed with ``flax.core.meta.Partitioned`` logical axis names
  ('embed', 'heads', 'kv', 'mlp', 'vocab', 'layers', 'norm'); the ZeRO
  partitioner maps names -> mesh axes per parallelism config.
* **Stacked layers + lax.scan** — all transformer layers live in one
  stacked tree (leading 'layers' dim).  One compile of the layer body,
  O(1) HLO size in depth, and ``jax.checkpoint`` on the body is the
  activation-checkpointing unit (reference
  ``runtime/activation_checkpointing/checkpointing.py`` becomes a remat
  policy).
* **Sequence parallelism as sharding constraints** — Ulysses' two
  all-to-alls (reference ``sequence/layer.py:65`` DistributedAttention)
  are expressed by resharding activations seq-sharded -> head-sharded
  around attention; XLA inserts the all-to-alls on the 'seq' axis.
* **bf16 compute, fp32 softmax/normalization accumulations.**
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax.core import meta
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ..parallel.topology import BATCH_AXES as BATCH  # batch-dim mesh axes


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None  # None -> MHA
    head_dim: Optional[int] = None      # None -> hidden/heads
    max_seq_len: int = 4096
    norm: str = "rmsnorm"              # rmsnorm | layernorm
    norm_eps: float = 1e-5
    # silu_gated | gelu (tanh approx) | gelu_exact | gelu_gated | relu
    activation: str = "silu_gated"
    pos_emb: str = "rope"              # rope | learned | alibi | none
    # layernorm over the token embeddings (BLOOM word_embeddings_layernorm)
    embed_layernorm: bool = False
    rope_theta: float = 10000.0
    rope_pct: float = 1.0              # partial rotary (GPT-NeoX/phi)
    causal: bool = True
    # Mistral/Mixtral sliding-window attention (HF sliding_window): each
    # position attends to the last `sliding_window` positions only.
    # None = full causal.  Served by the flash kernel's banded block
    # bounds on TPU and the dense mask on the einsum path; inference v2
    # masks (and skips out-of-window pages in the decode kernel) — KV
    # pages are still retained for the full context, so size num_pages
    # for O(context), not O(window).
    sliding_window: Optional[int] = None
    # attention-only biases (Qwen2: qkv bias, no o/mlp bias); use_bias
    # adds biases everywhere (GPT-2/NeoX style)
    qkv_bias: bool = False
    # x + attn(ln1 x) + mlp(ln2 x) (GPT-NeoX use_parallel_residual)
    parallel_residual: bool = False
    # MoE geometry (mixtral): >0 means the mlp block holds stacked
    # expert weights and forward needs a routed mlp_fn
    moe_num_experts: int = 0
    moe_top_k: int = 2
    tie_embeddings: bool = False
    use_bias: bool = False
    dropout: float = 0.0
    scan_layers: bool = True
    remat: bool = True
    remat_policy: str = "nothing_saveable"
    # reference activation_checkpointing.partition_activations
    # (checkpointing.py:487): saved layer-boundary residuals are sharded
    # along the sequence dim over the model-parallel axes, 1/(sp*tp)
    # memory per device; XLA re-gathers at recompute
    partition_activations: bool = False
    # auto: Pallas flash kernel whenever the mask is pure-causal (TPU;
    # jnp reference off-TPU) | flash: force | einsum: dense path
    attention_impl: str = "auto"
    # sequence-parallel mechanism when the mesh has a 'seq' axis:
    # "ulysses" reshards tokens->heads around attention (two
    # all-to-alls); "ring" keeps tokens seq-sharded and circulates K/V
    # blocks over ppermute (context parallelism — O(S/P) activation
    # memory with no head-divisibility requirement).  Wired from engine
    # config sequence_parallel.mode.
    sp_mode: str = "ulysses"
    flash_block_q: int = 512
    flash_block_k: int = 512
    # sparse embedding gradients (reference engine.py:2535 sparse
    # allreduce): backward ships the [B*S,E] cotangent, not [V,E]
    sparse_gradients: bool = False
    dtype: Any = jnp.bfloat16

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def dims_per_head(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    def n_params(self) -> int:
        e, f, l, v = self.hidden_size, self.intermediate_size, self.num_layers, self.vocab_size
        h, k, d = self.num_heads, self.kv_heads, self.dims_per_head
        attn = e * h * d + 2 * e * k * d + h * d * e
        mlp = e * f * (3 if "gated" in self.activation else 2)
        return l * (attn + mlp) + v * e * (1 if self.tie_embeddings else 2)


# ---------------------------------------------------------------------------
# param construction
# ---------------------------------------------------------------------------

def _boxed(value: jax.Array, names: Tuple[Optional[str], ...]):
    return meta.Partitioned(value, names=names)


def _dense_init(rng, shape, fan_in, dtype=jnp.float32):
    return jax.random.normal(rng, shape, dtype) * (fan_in ** -0.5)


def init_params(cfg: TransformerConfig, rng: jax.Array) -> Dict[str, Any]:
    """Initialize (boxed) parameters; stacked over layers when scanning."""
    e, f, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    h, k, d = cfg.num_heads, cfg.kv_heads, cfg.dims_per_head
    L = cfg.num_layers
    keys = jax.random.split(rng, 12)

    def stack(init_one):
        """init per-layer then stack (scan) or keep list-of-dicts."""
        ps = [init_one(jax.random.fold_in(keys[0], i)) for i in range(L)]
        if cfg.scan_layers:
            return jax.tree.map(
                lambda *xs: _boxed(jnp.stack([x.value for x in xs]),
                                   ("layers",) + xs[0].names),
                *ps,
                is_leaf=lambda x: isinstance(x, meta.Partitioned))
        return {f"layer_{i}": p for i, p in enumerate(ps)}

    def layer_init(key):
        ks = jax.random.split(key, 8)
        p = {
            "attn": {
                "wq": _boxed(_dense_init(ks[0], (e, h, d), e), ("embed", "heads", None)),
                "wk": _boxed(_dense_init(ks[1], (e, k, d), e), ("embed", "kv", None)),
                "wv": _boxed(_dense_init(ks[2], (e, k, d), e), ("embed", "kv", None)),
                "wo": _boxed(_dense_init(ks[3], (h, d, e), h * d), ("heads", None, "embed")),
            },
            "mlp": {
                "wi": _boxed(_dense_init(ks[4], (e, f), e), ("embed", "mlp")),
                "wo": _boxed(_dense_init(ks[5], (f, e), f), ("mlp", "embed")),
            },
            "norm1": _norm_init(cfg, e),
            "norm2": _norm_init(cfg, e),
        }
        if "gated" in cfg.activation:
            p["mlp"]["wg"] = _boxed(_dense_init(ks[6], (e, f), e), ("embed", "mlp"))
        if cfg.use_bias or cfg.qkv_bias:
            p["attn"]["bq"] = _boxed(jnp.zeros((h, d)), ("heads", None))
            p["attn"]["bk"] = _boxed(jnp.zeros((k, d)), ("kv", None))
            p["attn"]["bv"] = _boxed(jnp.zeros((k, d)), ("kv", None))
        if cfg.use_bias:
            p["attn"]["bo"] = _boxed(jnp.zeros((e,)), ("embed",))
            p["mlp"]["bi"] = _boxed(jnp.zeros((f,)), ("mlp",))
            p["mlp"]["bo"] = _boxed(jnp.zeros((e,)), ("embed",))
        return p

    params: Dict[str, Any] = {
        "embed": {"tokens": _boxed(
            jax.random.normal(keys[1], (v, e)) * 0.02, ("vocab", "embed"))},
        "layers": stack(layer_init),
        "final_norm": _norm_init(cfg, e),
    }
    if cfg.pos_emb == "learned":
        params["embed"]["positions"] = _boxed(
            jax.random.normal(keys[2], (cfg.max_seq_len, e)) * 0.02, (None, "embed"))
    if cfg.embed_layernorm:
        params["embed"]["norm"] = _norm_init(cfg, e)
    if not cfg.tie_embeddings:
        params["lm_head"] = _boxed(_dense_init(keys[3], (e, v), e), ("embed", "vocab"))
    return params


def _norm_init(cfg: TransformerConfig, dim: int):
    p = {"scale": _boxed(jnp.ones((dim,)), ("norm",))}
    if cfg.norm == "layernorm":
        p["bias"] = _boxed(jnp.zeros((dim,)), ("norm",))
    return p


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _constrain(x: jax.Array, *spec) -> jax.Array:
    """Sharding constraint that degrades to no-op outside a mesh context.

    Inside a ``shard_map`` region (e.g. the CollectiveScheduler's
    batch-axes-manual backward), entries naming manually-bound axes are
    pruned — those dims are already physically sharded by the region —
    while entries over still-automatic axes (tensor/seq under
    partial-auto) keep guiding GSPMD."""
    from ..utils.jax_compat import manual_axis_names
    manual = manual_axis_names()
    if manual:
        def prune(entry):
            if entry is None:
                return None
            axes = entry if isinstance(entry, tuple) else (entry,)
            kept = tuple(a for a in axes if a not in manual)
            return kept if len(kept) > 1 else (kept[0] if kept else None)
        spec = tuple(prune(e) for e in spec)
        if all(e is None for e in spec):
            return x
    try:
        return jax.lax.with_sharding_constraint(x, P(*spec))
    except (ValueError, RuntimeError):
        return x


def _wval(p, dtype) -> jax.Array:
    """Weight leaf -> compute-dtype array.  Channel-quantized leaves
    ({'q', 'scale'} from ops/fp_quantizer.quantize_channelwise) dequant
    lazily — XLA fuses the cast+scale into the consuming einsum."""
    if isinstance(p, dict) and "q" in p:
        from ..ops.fp_quantizer import dequantize_channelwise
        return dequantize_channelwise(p, dtype)
    return p.astype(dtype)


def _norm_apply(cfg: TransformerConfig, p, x: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    if cfg.norm == "rmsnorm":
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + cfg.norm_eps) * p["scale"].astype(jnp.float32)
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + cfg.norm_eps)
        y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype)


def rope_table(cfg: TransformerConfig, positions: jax.Array) -> Tuple[jax.Array, jax.Array]:
    d = int(cfg.dims_per_head * cfg.rope_pct)
    d -= d % 2
    freqs = cfg.rope_theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,d/2]
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(x: jax.Array, sin: jax.Array, cos: jax.Array) -> jax.Array:
    """x: [B,S,H,D]; interleaved-pair rotation in fp32.  When the rope
    table covers fewer than D/2 frequencies (partial rotary,
    ``rope_pct < 1``), only the leading ``2*n_freq`` dims rotate and the
    tail passes through (GPT-NeoX ``rotary_pct`` semantics)."""
    rot = 2 * sin.shape[-1]
    head = x[..., :rot].astype(jnp.float32)
    x1, x2 = head[..., 0::2], head[..., 1::2]
    sin, cos = sin[:, :, None, :], cos[:, :, None, :]
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    out = jnp.stack([r1, r2], axis=-1).reshape(head.shape).astype(x.dtype)
    if rot == x.shape[-1]:
        return out
    return jnp.concatenate([out, x[..., rot:]], axis=-1)


def _activation(cfg: TransformerConfig, gate, up):
    if cfg.activation == "silu_gated":
        return jax.nn.silu(gate) * up
    if cfg.activation == "gelu_gated":
        return jax.nn.gelu(gate) * up
    if cfg.activation == "relu":
        return jax.nn.relu(up)
    if cfg.activation == "gelu_exact":  # HF "gelu" = erf, not tanh approx
        return jax.nn.gelu(up, approximate=False)
    return jax.nn.gelu(up)


def _ambient_mesh():
    """The Mesh active at trace time (None when single-device/absent)."""
    from ..parallel.topology import ambient_mesh
    m = ambient_mesh()
    return m if m is not None and m.devices.size > 1 else None


def flash_dot_product_attention(cfg: TransformerConfig, q, kv_k, kv_v) -> jax.Array:
    """Causal attention via the Pallas flash kernel (ops/flash_attention.py).

    q: [B,S,H,D], k/v: [B,S,K,D] -> [B,S,H,D].  Replaces the reference's
    fused attention kernels (csrc/transformer/ softmax+attention CUDA) on
    the training path: no [B,H,S,S] score tensor ever reaches HBM.

    GQA folds kv heads up to H per shard.  Under a >1-device mesh the
    kernel runs inside shard_map (batch over the batch axes, heads over
    'seq'+'tensor' — the Ulysses layout), since GSPMD cannot partition a
    pallas_call on its own.
    """
    from ..ops.flash_attention import flash_attention

    qf = q.transpose(0, 2, 1, 3)      # [B,H,S,D]
    kf = kv_k.transpose(0, 2, 1, 3)   # [B,K,S,D]
    vf = kv_v.transpose(0, 2, 1, 3)

    def per_shard(qs, ks, vs):
        groups = qs.shape[1] // ks.shape[1]
        if groups > 1:
            ks = jnp.repeat(ks, groups, axis=1)
            vs = jnp.repeat(vs, groups, axis=1)
        return flash_attention(qs, ks, vs, causal=True,
                               block_q=cfg.flash_block_q,
                               block_k=cfg.flash_block_k,
                               window=cfg.sliding_window)

    mesh = _ambient_mesh()
    if mesh is not None:
        from ..utils.jax_compat import shard_map
        batch_axes = tuple(a for a in BATCH if a in mesh.axis_names)
        head_axes = tuple(a for a in ("seq", "tensor") if a in mesh.axis_names)
        head_shards = 1
        for a in head_axes:
            head_shards *= mesh.shape[a]
        if kf.shape[1] % max(head_shards, 1) != 0:
            # GQA with fewer kv heads than head shards (e.g. 2 kv heads
            # over seq*tensor = 4): repeat kv up to the q heads BEFORE
            # the manual region so the head split divides — same
            # semantics, and flash still beats the einsum fallback for
            # any nontrivial sequence length
            groups = qf.shape[1] // kf.shape[1]
            kf = jnp.repeat(kf, groups, axis=1)
            vf = jnp.repeat(vf, groups, axis=1)
        spec = P(batch_axes or None, head_axes or None, None, None)
        out = shard_map(per_shard, mesh=mesh,
                        in_specs=(spec, spec, spec), out_specs=spec,
                        check_vma=False)(qf, kf, vf)
    else:
        out = per_shard(qf, kf, vf)
    return out.transpose(0, 2, 1, 3)


def ring_dot_product_attention(cfg: TransformerConfig, q, kv_k, kv_v
                               ) -> jax.Array:
    """Causal attention with tokens kept SEQ-SHARDED: K/V blocks travel
    the 'seq' ring via ppermute (sequence/ring.py) while queries stay
    put — context parallelism as the reference-parity alternative to the
    Ulysses all-to-all sandwich.  q: [B,S,H,D], k/v: [B,S,K,D]."""
    from ..sequence.ring import ring_attention

    qf = q.transpose(0, 2, 1, 3)      # [B,H,S,D]
    kf = kv_k.transpose(0, 2, 1, 3)
    vf = kv_v.transpose(0, 2, 1, 3)
    groups = qf.shape[1] // kf.shape[1]
    if groups > 1:  # ring attends full heads; lift GQA before the ring
        kf = jnp.repeat(kf, groups, axis=1)
        vf = jnp.repeat(vf, groups, axis=1)

    mesh = _ambient_mesh()
    from ..utils.jax_compat import shard_map
    batch_axes = tuple(a for a in BATCH if a in mesh.axis_names)
    head_axes = _divisible_head_axes(qf.shape[1], ("tensor",))
    spec = P(batch_axes or None, head_axes or None, "seq", None)
    out = shard_map(
        functools.partial(ring_attention, axis_name="seq", causal=True,
                          window=cfg.sliding_window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)(qf, kf, vf)
    return out.transpose(0, 2, 1, 3)


def _ring_ok(cfg: TransformerConfig, seq_len: int,
             batch: Optional[int] = None) -> bool:
    """Trace-time check for the ring layout: a real 'seq' axis whose size
    divides the sequence, plus exact batch divisibility (shard_map)."""
    mesh = _ambient_mesh()
    if mesh is None or mesh.shape.get("seq", 1) <= 1:
        return False
    if seq_len % mesh.shape["seq"] != 0:
        return False
    if batch is not None:
        batch_shards = 1
        for a in BATCH:
            if a in mesh.axis_names:
                batch_shards *= mesh.shape[a]
        if batch % batch_shards != 0:
            return False
    return True


def _flash_ok(cfg: TransformerConfig, n_heads: int, n_kv: int,
              batch: Optional[int] = None) -> bool:
    """Trace-time check that the flash layout divides the active mesh.

    Unlike the einsum path (where GSPMD pads awkward shapes), shard_map
    requires exact divisibility of both the head layout over
    ('seq','tensor') and — when known — the batch over the batch axes."""
    mesh = _ambient_mesh()
    if mesh is None:
        return True
    head_shards = 1
    for a in ("seq", "tensor"):
        if a in mesh.axis_names:
            head_shards *= mesh.shape[a]
    if batch is not None:
        batch_shards = 1
        for a in BATCH:
            if a in mesh.axis_names:
                batch_shards *= mesh.shape[a]
        if batch % batch_shards != 0:
            return False
    # kv heads that don't divide the shards are repeated up to n_heads
    # before the manual region (flash_dot_product_attention), so q-head
    # divisibility is the only hard constraint
    return n_heads % head_shards == 0 and head_shards <= n_heads


def _divisible_head_axes(n: int, axes=("seq", "tensor")) -> tuple:
    """Maximal prefix of ``axes`` (present in the mesh) whose sizes all
    divide ``n`` exactly — GSPMD pads non-divisible shardings, which
    costs an involuntary full rematerialization per transition."""
    mesh = _ambient_mesh()
    if mesh is None:
        return ()
    out = []
    for a in axes:
        size = mesh.shape.get(a, 1)
        if size > 1:
            if n % size != 0:
                break
            out.append(a)
            n //= size
    return tuple(out)


def alibi_slopes(n_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes (geometric in 2^(-8/n), with the standard
    interleave extension for non-power-of-two head counts)."""
    def pow2(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return [start ** (i + 1) for i in range(n)]
    k = 2 ** int(np.floor(np.log2(n_heads)))
    slopes = pow2(k)
    if k < n_heads:
        slopes += pow2(2 * k)[0::2][: n_heads - k]
    return np.asarray(slopes, np.float32)


def dot_product_attention(cfg: TransformerConfig, q, kv_k, kv_v,
                          mask: Optional[jax.Array],
                          attn_bias: Optional[jax.Array] = None) -> jax.Array:
    """Grouped-query attention, fp32 softmax.  q: [B,S,H,D], k/v: [B,S,K,D].

    Hot op #1 (reference csrc/transformer softmax/attention kernels).
    This dense einsum formulation serves arbitrary masks and non-TPU CI;
    the pure-causal training path uses flash_dot_product_attention.

    GQA sharding: the head dim splits into (k, g); when the Ulysses head
    shards exceed the kv-head count, k takes the axes that divide it and
    g takes the remainder, keeping every intermediate exactly-sharded
    (no GSPMD padding -> no involuntary remat in fwd or transpose).
    """
    b, s, hq, dd = q.shape
    k_heads = kv_k.shape[2]
    groups = hq // k_heads
    k_axes = _divisible_head_axes(k_heads)
    g_axes = _divisible_head_axes(
        groups, tuple(a for a in ("seq", "tensor") if a not in k_axes))
    q = q.reshape(b, s, k_heads, groups, dd)
    q = _constrain(q, BATCH, None, k_axes or None, g_axes or None, None)
    kv_k = _constrain(kv_k, BATCH, None, k_axes or None, None)
    kv_v = _constrain(kv_v, BATCH, None, k_axes or None, None)
    scores = jnp.einsum("bskgd,btkd->bkgst", q, kv_k) / np.sqrt(dd)
    scores = scores.astype(jnp.float32)
    if attn_bias is not None:  # ALiBi: [B,H,T] additive, per q-head
        scores = scores + attn_bias.reshape(
            b, k_heads, groups, 1, attn_bias.shape[-1])
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    scores = _constrain(scores, BATCH, k_axes or None, g_axes or None,
                        None, None)
    probs = jax.nn.softmax(scores, axis=-1).astype(kv_v.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, kv_v)
    out = _constrain(out, BATCH, None, k_axes or None, g_axes or None, None)
    return out.reshape(b, s, hq, dd)


def _attention_block(cfg: TransformerConfig, p, x, sin, cos, mask,
                     use_flash: bool = False, attn_bias=None,
                     use_ring: bool = False):
    dtype = cfg.dtype
    wq, wk, wv, wo = (p["wq"].astype(dtype), p["wk"].astype(dtype),
                      p["wv"].astype(dtype), p["wo"].astype(dtype))
    q = jnp.einsum("bse,ehd->bshd", x, wq)
    k = jnp.einsum("bse,ekd->bskd", x, wk)
    v = jnp.einsum("bse,ekd->bskd", x, wv)
    if cfg.use_bias or cfg.qkv_bias:
        q = q + p["bq"].astype(dtype)
        k = k + p["bk"].astype(dtype)
        v = v + p["bv"].astype(dtype)
    if cfg.pos_emb == "rope":
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
    if use_ring:
        # ring CP: tokens STAY seq-sharded; no head resharding at all
        q = _constrain(q, BATCH, "seq", None, None)
        k = _constrain(k, BATCH, "seq", None, None)
        v = _constrain(v, BATCH, "seq", None, None)
        out = ring_dot_product_attention(cfg, q, k, v)
        out = checkpoint_name(out, "attn_out")
        out = jnp.einsum("bshd,hde->bse", out, wo)
        if cfg.use_bias:
            out = out + p["bo"].astype(dtype)
        return _constrain(out, BATCH, "seq", None)
    # Ulysses resharding: tokens seq-sharded -> heads ('seq'+'tensor')-sharded.
    # XLA materializes this as the two all-to-alls of reference
    # sequence/layer.py:65, but fused into the surrounding program.
    # kv heads take only the axes that DIVIDE them (GQA may have fewer kv
    # heads than head shards; padding a non-divisible sharding costs an
    # involuntary full remat per transition).
    q_axes = _divisible_head_axes(cfg.num_heads)
    kv_axes = _divisible_head_axes(cfg.kv_heads)
    # staged like the return leg below: S-over-seq + H-over-tensor first,
    # then full head sharding — each hop is a plannable all-to-all, and
    # the TRANSPOSE of this staging keeps the backward cotangents off the
    # replicate-repartition fallback too
    if _divisible_head_axes(q.shape[1], ("seq",)):
        t_q = _divisible_head_axes(cfg.num_heads, ("tensor",))
        t_kv = _divisible_head_axes(cfg.kv_heads, ("tensor",))
        q = _constrain(q, BATCH, "seq", t_q or None, None)
        k = _constrain(k, BATCH, "seq", t_kv or None, None)
        v = _constrain(v, BATCH, "seq", t_kv or None, None)
    q = _constrain(q, BATCH, None, q_axes or None, None)
    k = _constrain(k, BATCH, None, kv_axes or None, None)
    v = _constrain(v, BATCH, None, kv_axes or None, None)
    if use_flash:
        out = flash_dot_product_attention(cfg, q, k, v)
    else:
        out = dot_product_attention(cfg, q, k, v, mask, attn_bias)
    # named for the save_attn_out remat policy: saving attention outputs
    # (cheap: [B,S,H,D]) lets the backward skip re-running the flash
    # kernel while everything else still rematerializes
    out = checkpoint_name(out, "attn_out")
    # Ulysses return leg, staged: go heads-(seq+tensor) -> (S over seq,
    # H over tensor) FIRST — a single plannable all-to-all — so the wo
    # einsum below is Megatron row-parallel (psum over 'tensor') with an
    # S-sharded output.  Without the stage, GSPMD sees heads-sharded ->
    # seq-sharded directly and falls back to an involuntary full
    # rematerialization (replicate + repartition) of the [B,S,H,D]
    # activation every layer.
    stage_axes = _divisible_head_axes(out.shape[2], ("tensor",))
    if _divisible_head_axes(out.shape[1], ("seq",)):
        out = _constrain(out, BATCH, "seq", stage_axes or None, None)
    out = jnp.einsum("bshd,hde->bse", out, wo)
    if cfg.use_bias:
        out = out + p["bo"].astype(dtype)
    return _constrain(out, BATCH, "seq", None)


def _mlp_block(cfg: TransformerConfig, p, x):
    dtype = cfg.dtype
    up = jnp.einsum("bse,ef->bsf", x, _wval(p["wi"], dtype))
    if cfg.use_bias:
        up = up + p["bi"].astype(dtype)
    gate = jnp.einsum("bse,ef->bsf", x, _wval(p["wg"], dtype)) \
        if "wg" in p else None
    h = _activation(cfg, gate, up) if gate is not None else _activation(cfg, None, up)
    h = _constrain(h, BATCH, "seq", "tensor")
    out = jnp.einsum("bsf,fe->bse", h, _wval(p["wo"], dtype))
    if cfg.use_bias:
        out = out + p["bo"].astype(dtype)
    return _constrain(out, BATCH, "seq", None)


def _layer_body(cfg: TransformerConfig, layer_params, x, sin, cos, mask,
                mlp_fn=None, use_flash: bool = False, attn_bias=None,
                use_ring: bool = False):
    """Returns (x, aux) — aux is 0 for dense MLPs, the load-balancing loss
    for MoE mlp_fns (accumulated through the layer scan)."""
    h = _norm_apply(cfg, layer_params["norm1"], x)
    attn_out = _attention_block(cfg, layer_params["attn"], h, sin, cos,
                                mask, use_flash=use_flash,
                                attn_bias=attn_bias, use_ring=use_ring)
    if cfg.parallel_residual:
        # GPT-NeoX: mlp sees ln2(x), both branches add to the SAME input
        h2 = _norm_apply(cfg, layer_params["norm2"], x)
        mlp_out = (mlp_fn or _mlp_block)(cfg, layer_params["mlp"], h2)
        aux = jnp.zeros((), jnp.float32)
        if isinstance(mlp_out, tuple):
            mlp_out, aux = mlp_out
        return x + attn_out + mlp_out, aux
    x = x + attn_out
    h = _norm_apply(cfg, layer_params["norm2"], x)
    mlp_out = (mlp_fn or _mlp_block)(cfg, layer_params["mlp"], h)
    aux = jnp.zeros((), jnp.float32)
    if isinstance(mlp_out, tuple):
        mlp_out, aux = mlp_out
    return x + mlp_out, aux


_REMAT_POLICIES = {
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    # save per-layer attention outputs only: the backward never re-runs
    # the (expensive) flash kernel, everything else rematerializes —
    # trades B*S*E per layer of HBM for ~30% of the recompute FLOPs
    "save_attn_out": jax.checkpoint_policies.save_only_these_names(
        "attn_out"),
}


def resolve_remat_policy(name: str):
    """Remat-policy lookup incl. the host-offload variants backing the
    reference's ``cpu_checkpointing`` (checkpointing.py:487): checkpoints
    are saved to pinned host memory and fetched back for the backward,
    trading HBM for PCIe/host traffic exactly like the CUDA path."""
    if name in _REMAT_POLICIES:
        return _REMAT_POLICIES[name]
    if name == "offload_attn_out":
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=["attn_out"],
            offload_src="device", offload_dst="pinned_host")
    if name == "offload_dots":
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    raise ValueError(
        f"unknown remat policy {name!r}; known: "
        f"{sorted(_REMAT_POLICIES) + ['offload_attn_out', 'offload_dots']}")


def forward(cfg: TransformerConfig, params, input_ids: jax.Array,
            positions: Optional[jax.Array] = None,
            attention_mask: Optional[jax.Array] = None,
            mlp_fn=None, return_aux: bool = False) -> jax.Array:
    """Token ids [B,S] -> logits [B,S,V] (fp32); with ``return_aux``,
    returns (logits, accumulated MoE aux loss)."""
    params = meta.unbox(params) if _has_boxes(params) else params
    b, s = input_ids.shape

    # Flash is valid only for the standard dense-causal case: default
    # positions (no packing) and no padding mask.  Decided at trace time.
    pure_causal = (cfg.causal and attention_mask is None
                   and positions is None and cfg.pos_emb != "alibi"
                   and s > 1)
    # ring CP replaces the Ulysses reshard entirely when configured
    use_ring = (cfg.sp_mode == "ring" and pure_causal
                and _ring_ok(cfg, s, batch=b))
    use_flash = (not use_ring
                 and cfg.attention_impl != "einsum"
                 and pure_causal
                 and _flash_ok(cfg, cfg.num_heads, cfg.kv_heads, batch=b))
    if cfg.attention_impl == "flash" and not (use_flash or use_ring):
        raise ValueError(
            "attention_impl='flash' requires causal attention with default "
            "positions, no attention_mask, and a mesh the head layout divides")

    if positions is None:
        if cfg.pos_emb == "learned" and attention_mask is not None:
            # padded batches: positions count only attended tokens
            # (HF OPTLearnedPositionalEmbedding cumsum semantics — left
            # or right padding yields the same logits as transformers)
            am = attention_mask.astype(jnp.int32)
            positions = jnp.clip(jnp.cumsum(am, axis=-1) - 1, 0)
        else:
            positions = jnp.broadcast_to(jnp.arange(s), (b, s))

    # Gather from an explicitly replicated table: the ZeRO JIT all-gather
    # of [V,E] happens once, the gather output is then born replicated and
    # the batch/seq constraint below is a cheap local slice (letting XLA
    # derive the output sharding from a vocab/fsdp-sharded table instead
    # triggers an involuntary full remat of the gathered activations).
    table = _constrain(params["embed"]["tokens"].astype(cfg.dtype))
    if cfg.sparse_gradients:
        from ..runtime.sparse_tensor import embedding_lookup
        x = embedding_lookup(table, input_ids)
    else:
        x = table[input_ids]
    if cfg.pos_emb == "learned":
        x = x + params["embed"]["positions"].astype(cfg.dtype)[positions]
    if cfg.embed_layernorm:  # BLOOM word_embeddings_layernorm
        x = _norm_apply(cfg, params["embed"]["norm"], x)
    x = _constrain(x, BATCH, "seq", None)

    # mask: [B, S(q), S(k)]  (not needed on the flash path — the kernel
    # applies causality blockwise)
    if use_flash or use_ring:
        mask = None
    elif cfg.causal:
        mask = positions[:, :, None] >= positions[:, None, :]
    else:
        mask = jnp.ones((b, s, s), bool)
    if mask is not None and cfg.sliding_window is not None:
        mask = mask & ((positions[:, :, None] - positions[:, None, :])
                       < cfg.sliding_window)
    if attention_mask is not None and mask is not None:
        mask = mask & attention_mask[:, None, :].astype(bool)

    sin, cos = rope_table(cfg, positions) if cfg.pos_emb == "rope" else (None, None)

    # ALiBi: additive per-head bias that depends only on the KEY position
    # (softmax is shift-invariant along each query row, so slope*(t-s)
    # and slope*t are equivalent under the causal mask)
    attn_bias = None
    if cfg.pos_emb == "alibi":
        slopes = jnp.asarray(alibi_slopes(cfg.num_heads))
        attn_bias = slopes[None, :, None] * positions[:, None, :].astype(
            jnp.float32)                                      # [B,H,T]

    body = functools.partial(_layer_body, cfg, mlp_fn=mlp_fn,
                             use_flash=use_flash, attn_bias=attn_bias,
                             use_ring=use_ring)

    # partition_activations: the layer-boundary residual (what the scan
    # carry chain / checkpoint saves) is sharded along seq over the
    # model-parallel axes — 1/(sp*tp) activation memory per device
    part_axes = (_divisible_head_axes(s, ("seq", "tensor"))
                 if cfg.partition_activations else ())

    def bound(y):
        return _constrain(y, BATCH, part_axes, None) if part_axes else y

    aux_total = jnp.zeros((), jnp.float32)
    if cfg.scan_layers:
        def scan_body(carry, layer_params):
            x, aux_acc = carry
            y, aux = body(layer_params, x, sin, cos, mask)
            return (bound(y), aux_acc + aux), None
        if cfg.remat:
            policy = resolve_remat_policy(cfg.remat_policy)
            scan_body = jax.checkpoint(scan_body, policy=policy,
                                       prevent_cse=False)
        (x, aux_total), _ = jax.lax.scan(scan_body, (bound(x), aux_total),
                                         params["layers"])
    else:
        for i in range(cfg.num_layers):
            lp = params["layers"][f"layer_{i}"]
            fn = body
            if cfg.remat:
                fn = jax.checkpoint(body, policy=resolve_remat_policy(cfg.remat_policy),
                                    prevent_cse=False)
            x, aux = fn(lp, bound(x), sin, cos, mask)
            aux_total = aux_total + aux

    x = _norm_apply(cfg, params["final_norm"], x)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bse,ve->bsv", x, params["embed"]["tokens"].astype(cfg.dtype))
    else:
        logits = jnp.einsum("bse,ev->bsv", x, params["lm_head"].astype(cfg.dtype))
    if "lm_head_bias" in params:  # phi family ships an lm_head bias
        logits = logits + params["lm_head_bias"].astype(cfg.dtype)
    logits = _constrain(logits, BATCH, "seq", "tensor")
    logits = logits.astype(jnp.float32)
    if return_aux:
        return logits, aux_total
    return logits


def _has_boxes(params) -> bool:
    found = False
    for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, meta.Partitioned)):
        if isinstance(leaf, meta.Partitioned):
            found = True
        break
    return found


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       mask: Optional[jax.Array] = None) -> jax.Array:
    """Token-level CE in fp32; labels < 0 are ignored."""
    valid = labels >= 0 if mask is None else (mask.astype(bool) & (labels >= 0))
    safe_labels = jnp.maximum(labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)


class CausalLM:
    """Engine-protocol causal LM over the transformer core.  Batch dict:
    {'input_ids': [B,S] int32, optional 'labels' (default: shifted inputs),
    optional 'attention_mask'}."""

    def __init__(self, cfg: TransformerConfig):
        self.cfg = cfg

    def init_params(self, rng):
        return init_params(self.cfg, rng)

    def logits(self, params, batch, rng=None):
        return forward(self.cfg, params, batch["input_ids"],
                       positions=batch.get("positions"),
                       attention_mask=batch.get("attention_mask"))

    def loss(self, params, batch, rng=None):
        logits = self.logits(params, batch, rng)
        if "labels" in batch:
            labels = batch["labels"]
            return cross_entropy_loss(logits, labels,
                                      batch.get("attention_mask"))
        # next-token prediction: shift
        labels = batch["input_ids"][:, 1:]
        mask = batch.get("attention_mask")
        return cross_entropy_loss(logits[:, :-1], labels,
                                  mask[:, 1:] if mask is not None else None)
