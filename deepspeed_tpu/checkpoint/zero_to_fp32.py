"""Offline fp32 reconstruction from a (sharded) checkpoint directory.

Reference: ``deepspeed/utils/zero_to_fp32.py`` — stitches per-rank zero
shard files back into a consolidated fp32 state dict, offline.  Here
checkpoints are Orbax/tensorstore directories whose array storage is
already logically whole (shards are an Orbax storage detail), so
"reconstruction" is a host-side restore of the ``params`` subtree; no
per-rank shard walking is needed, and any (dp, tp, pp) topology change
between save and load is absorbed by restore-time sharding (the
universal-checkpoint property, reference ``deepspeed/checkpoint/``).

CLI:  python -m deepspeed_tpu.checkpoint.zero_to_fp32 <ckpt_dir> <out.npz>
"""

from __future__ import annotations

import os
import sys
from typing import Any, Dict, Optional

import numpy as np

from .engine import LATEST_FILE


def get_fp32_state_dict_from_zero_checkpoint(
        ckpt_dir: str, tag: Optional[str] = None) -> Dict[str, Any]:
    """Load the consolidated fp32 param tree from a checkpoint dir on
    host memory (no engine, no mesh required)."""
    import orbax.checkpoint as ocp

    if tag is None:
        latest = os.path.join(ckpt_dir, LATEST_FILE)
        if not os.path.exists(latest):
            raise FileNotFoundError(
                f"no tag given and no '{LATEST_FILE}' file in {ckpt_dir}")
        with open(latest) as f:
            tag = f.read().strip()
    path = os.path.abspath(os.path.join(ckpt_dir, tag, "state"))
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint state dir not found: {path}")
    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    state = ckptr.restore(path)
    params = state["params"] if isinstance(state, dict) else state.params
    return _tree_to_host_fp32(params)


def _tree_to_host_fp32(tree: Any) -> Any:
    import jax
    return jax.tree.map(
        lambda x: np.asarray(x, dtype=np.float32), tree)


def _key_of(entry) -> str:
    """Uniform rendering of one pytree path entry: DictKey('a'),
    GetAttrKey('count') (namedtuple field) and SequenceKey(0) all become
    bare names, so a namedtuple and the dict Orbax restores it as produce
    the same flat key."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def flatten_state_dict(tree: Any, prefix: str = "",
                       sep: str = ".") -> Dict[str, np.ndarray]:
    """Any pytree -> flat {'a.b.c': array} (torch-state-dict style keys;
    ``sep='/'`` gives the universal-checkpoint atom key scheme)."""
    import jax
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out: Dict[str, np.ndarray] = {}
    for path, leaf in flat:
        out[prefix + sep.join(_key_of(p) for p in path)] = np.asarray(leaf)
    return out


def convert_zero_checkpoint_to_fp32_state_dict(
        ckpt_dir: str, output_file: str, tag: Optional[str] = None) -> None:
    params = get_fp32_state_dict_from_zero_checkpoint(ckpt_dir, tag)
    flat = flatten_state_dict(params)
    np.savez(output_file, **flat)
    total = sum(v.size for v in flat.values())
    print(f"saved {len(flat)} tensors / {total:,} params -> {output_file}")


def main(argv=None):
    # Host-side reconstruction needs no accelerator: pin the CPU platform
    # BEFORE any backend init so the CLI never blocks on a busy TPU (the
    # sitecustomize-pinned platform would otherwise claim the chip).
    import jax
    jax.config.update("jax_platforms", "cpu")
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) not in (2, 3):
        print("usage: python -m deepspeed_tpu.checkpoint.zero_to_fp32 "
              "<checkpoint_dir> <output.npz> [tag]")
        return 1
    convert_zero_checkpoint_to_fp32_state_dict(
        argv[0], argv[1], argv[2] if len(argv) == 3 else None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
