"""HuggingFace checkpoint import — HF weights -> our param pytree.

Reference: ``inference/v2/checkpoint/huggingface_engine.py`` (streams HF
safetensors into the inference param layer) and the v1 checkpoint
loaders (``module_inject/load_checkpoint.py``).  Here one converter
serves training and inference since both share the transformer core's
param tree (models/transformer.py).

Supported families: LLaMA/Mistral-style (rmsnorm + gated silu + rope)
and GPT-2 style (layernorm + gelu + learned positions, fused c_attn).

RoPE convention: models/transformer.py rotates interleaved pairs
(Meta/original convention).  HF checkpoints store q/k projections
permuted for the half-split ("rotate_half") convention, so the import
applies the inverse permutation to q/k weight rows.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from ..models.transformer import TransformerConfig


def _np(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    try:  # torch tensor
        return t.detach().to("cpu").float().numpy()
    except AttributeError:
        return np.asarray(t)


def _unpermute_rope(w: np.ndarray, n_heads: int, head_dim: int) -> np.ndarray:
    """Invert the HF conversion permute: [H*D, E] rows from half-split
    order back to interleaved order."""
    E = w.shape[1]
    w = w.reshape(n_heads, 2, head_dim // 2, E)
    w = np.transpose(w, (0, 2, 1, 3))  # (H, D/2, 2, E)
    return w.reshape(n_heads * head_dim, E)


def llama_config_from_hf(hf_cfg) -> TransformerConfig:
    """Map a transformers LlamaConfig/MistralConfig to TransformerConfig."""
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        hidden_size=hf_cfg.hidden_size,
        intermediate_size=hf_cfg.intermediate_size,
        num_layers=hf_cfg.num_hidden_layers,
        num_heads=hf_cfg.num_attention_heads,
        num_kv_heads=getattr(hf_cfg, "num_key_value_heads",
                             hf_cfg.num_attention_heads),
        max_seq_len=getattr(hf_cfg, "max_position_embeddings", 4096),
        norm="rmsnorm", norm_eps=hf_cfg.rms_norm_eps,
        activation="silu_gated", pos_emb="rope",
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        tie_embeddings=getattr(hf_cfg, "tie_word_embeddings", False),
        use_bias=False, dtype=jnp.bfloat16)


def load_llama(state_dict: Dict[str, Any], cfg: TransformerConfig,
               dtype=jnp.float32) -> Dict[str, Any]:
    """HF LLaMA/Mistral state dict -> our (unboxed) param tree."""
    sd = {k: _np(v) for k, v in state_dict.items()}
    E = cfg.hidden_size
    H, K, D = cfg.num_heads, cfg.kv_heads, cfg.dims_per_head

    def key(*names):
        for n in names:
            if n in sd:
                return sd[n]
        raise KeyError(f"none of {names} in checkpoint "
                       f"(have e.g. {list(sd)[:5]})")

    layers = []
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        wq = _unpermute_rope(key(p + "self_attn.q_proj.weight"), H, D)
        wk = _unpermute_rope(key(p + "self_attn.k_proj.weight"), K, D)
        wv = key(p + "self_attn.v_proj.weight")
        wo = key(p + "self_attn.o_proj.weight")
        layers.append({
            "attn": {
                "wq": wq.T.reshape(E, H, D),
                "wk": wk.T.reshape(E, K, D),
                "wv": wv.T.reshape(E, K, D),
                "wo": wo.T.reshape(H, D, E),
            },
            "mlp": {
                "wg": key(p + "mlp.gate_proj.weight").T,
                "wi": key(p + "mlp.up_proj.weight").T,
                "wo": key(p + "mlp.down_proj.weight").T,
            },
            "norm1": {"scale": key(p + "input_layernorm.weight")},
            "norm2": {"scale": key(p + "post_attention_layernorm.weight")},
        })

    params: Dict[str, Any] = {
        "embed": {"tokens": key("model.embed_tokens.weight")},
        "layers": _stack(layers) if cfg.scan_layers
        else {f"layer_{i}": l for i, l in enumerate(layers)},
        "final_norm": {"scale": key("model.norm.weight")},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = key("lm_head.weight").T
    return _cast(params, dtype)


def gpt2_config_from_hf(hf_cfg) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        hidden_size=hf_cfg.n_embd,
        intermediate_size=4 * hf_cfg.n_embd,
        num_layers=hf_cfg.n_layer,
        num_heads=hf_cfg.n_head,
        num_kv_heads=hf_cfg.n_head,
        max_seq_len=hf_cfg.n_positions,
        norm="layernorm", norm_eps=hf_cfg.layer_norm_epsilon,
        activation="gelu", pos_emb="learned",
        tie_embeddings=True, use_bias=True, dtype=jnp.bfloat16)


def load_gpt2(state_dict: Dict[str, Any], cfg: TransformerConfig,
              dtype=jnp.float32) -> Dict[str, Any]:
    """HF GPT-2 state dict -> our param tree.  GPT-2's Conv1D stores
    weights [in, out] (already our orientation)."""
    sd = {k.removeprefix("transformer."): _np(v)
          for k, v in state_dict.items()}
    E, H, D = cfg.hidden_size, cfg.num_heads, cfg.dims_per_head
    layers = []
    for i in range(cfg.num_layers):
        p = f"h.{i}."
        w_qkv = sd[p + "attn.c_attn.weight"]      # [E, 3E]
        b_qkv = sd[p + "attn.c_attn.bias"]        # [3E]
        wq, wk, wv = np.split(w_qkv, 3, axis=1)
        bq, bk, bv = np.split(b_qkv, 3)
        layers.append({
            "attn": {
                "wq": wq.reshape(E, H, D), "wk": wk.reshape(E, H, D),
                "wv": wv.reshape(E, H, D),
                "wo": sd[p + "attn.c_proj.weight"].reshape(H, D, E),
                "bq": bq.reshape(H, D), "bk": bk.reshape(H, D),
                "bv": bv.reshape(H, D),
                "bo": sd[p + "attn.c_proj.bias"],
            },
            "mlp": {
                "wi": sd[p + "mlp.c_fc.weight"],
                "bi": sd[p + "mlp.c_fc.bias"],
                "wo": sd[p + "mlp.c_proj.weight"],
                "bo": sd[p + "mlp.c_proj.bias"],
            },
            "norm1": {"scale": sd[p + "ln_1.weight"],
                      "bias": sd[p + "ln_1.bias"]},
            "norm2": {"scale": sd[p + "ln_2.weight"],
                      "bias": sd[p + "ln_2.bias"]},
        })
    params = {
        "embed": {"tokens": sd["wte.weight"],
                  "positions": sd["wpe.weight"]},
        "layers": _stack(layers) if cfg.scan_layers
        else {f"layer_{i}": l for i, l in enumerate(layers)},
        "final_norm": {"scale": sd["ln_f.weight"],
                       "bias": sd["ln_f.bias"]},
    }
    return _cast(params, dtype)


def from_pretrained(model_or_path, dtype=jnp.float32
                    ) -> Tuple[TransformerConfig, Dict[str, Any]]:
    """Convert a transformers model instance or local checkpoint dir."""
    if isinstance(model_or_path, str):
        import transformers
        model = transformers.AutoModelForCausalLM.from_pretrained(
            model_or_path, local_files_only=True)
    else:
        model = model_or_path
    arch = model.config.model_type
    sd = model.state_dict()
    if arch in ("llama", "mistral"):
        cfg = llama_config_from_hf(model.config)
        return cfg, load_llama(sd, cfg, dtype)
    if arch == "gpt2":
        cfg = gpt2_config_from_hf(model.config)
        return cfg, load_gpt2(sd, cfg, dtype)
    raise ValueError(f"unsupported HF architecture: {arch!r}")


def _stack(layers):
    import jax
    return jax.tree.map(lambda *xs: np.stack(xs), *layers)


def _cast(tree, dtype):
    import jax
    return jax.tree.map(lambda x: jnp.asarray(x, dtype), tree)
